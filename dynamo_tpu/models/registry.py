"""Model registry: name -> ModelAdapter the engine can drive.

The engine is model-family-agnostic (same role as the reference being
engine-agnostic at a higher level): an adapter exposes init/forward/kv-init
over the paged cache contract. New families (Qwen2, Mixtral/MoE) register
here.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, replace
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from dynamo_tpu.models import llama as llama_mod
from dynamo_tpu.models import qwen2vl as qwen2vl_mod
from dynamo_tpu.models.llama import KVPages, LlamaConfig

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class ModelAdapter:
    name: str
    config: Any
    vocab_size: int
    init_params: Callable[[jax.Array], Any]
    forward: Callable[..., tuple[jax.Array, KVPages]]  # (params, tokens, positions, valid, kv, pt) -> (logits, kv)
    forward_hidden: Callable[..., tuple[jax.Array, KVPages]]  # same in, (hidden, kv) out
    compute_logits: Callable[[Any, jax.Array], jax.Array]  # (params, hidden) -> logits
    #: (num_pages, page_size, kv_quantize=None) -> KVPages; families
    #: without quantized pages raise on kv_quantize != None
    init_kv: Callable[..., KVPages]
    param_specs: Callable[[], Any]
    kv_spec: Callable[[], Any]
    #: (quantized=False) -> the same tree as param_specs but with
    #: logical AxisNames leaves (parallel/logical.py) — the model's
    #: single layout declaration; param_specs is this resolved through
    #: the rule table. /v1/debug/mesh groups params by these names.
    logical_axes: Optional[Callable[[], Any]] = None
    load_params: Optional[Callable[[str], Any]] = None  # from a checkpoint dir
    #: where weights live when the model name itself identifies them
    #: (an HF checkpoint dir or a .gguf file); engines load from here when
    #: no explicit checkpoint_path is given
    default_checkpoint: Optional[str] = None
    #: weight-only quantization transform for this family's param layout
    #: (None = family doesn't support it); the engine calls it for
    #: EngineConfig.quantize="int8"
    quantize_params: Optional[Callable[[Any], Any]] = None
    #: random-init straight into the quantized layout, one layer at a
    #: time — init_params + quantize_params peaks at full-model dtype
    #: size, which for 8B+ configs exceeds a single chip's HBM
    init_params_quantized: Optional[Callable[[jax.Array], Any]] = None


def _kv_pages_spec(kv_quantize=None, shard_heads: bool = True):
    """Partition specs matching init_kv_pages' pytree: head-sharded KV
    pools, scale planes (when quantized) sharded on the same Hkv axis —
    both resolved through the logical-axis rule table."""
    from dynamo_tpu.parallel.logical import L, resolve
    from dynamo_tpu.parallel.shardings import kv_cache_spec

    scale = (
        resolve(L(
            "layers", "kv_pages", "kv_seq",
            "kv_heads" if shard_heads else None,
        ))
        if kv_quantize
        else None
    )
    return KVPages(
        k=kv_cache_spec(shard_heads),
        v=kv_cache_spec(shard_heads),
        k_scale=scale,
        v_scale=scale,
    )


_LLAMA_PRESETS: dict[str, Callable[[], LlamaConfig]] = {
    "tiny": LlamaConfig.tiny,
    "llama3-1b": LlamaConfig.llama3_1b,
    # speculation draft for the llama3 family (same 128256 vocab);
    # serveable standalone but meant for EngineConfig.spec_draft_model
    "llama3-draft": LlamaConfig.llama3_draft,
    "llama3-8b": LlamaConfig.llama3_8b,
    "llama3-70b": LlamaConfig.llama3_70b,
    # DeepSeek-R1-Distill-Llama-8B is architecturally Llama-3-8B.
    "deepseek-r1-distill-llama-8b": LlamaConfig.llama3_8b,
    # Qwen2 family = Llama + qkv bias (models/llama.py attention_bias).
    "qwen2-7b": LlamaConfig.qwen2_7b,
    "qwen2-0.5b": LlamaConfig.qwen2_05b,
    # Gemma family = GeGLU + (1+w) RMSNorm + scaled embeddings + tied head.
    "gemma-2b": LlamaConfig.gemma_2b,
    "gemma-7b": LlamaConfig.gemma_7b,
    # Gemma2 adds sliding/global alternation, logit softcaps, post-norms.
    "gemma2-2b": LlamaConfig.gemma2_2b,
    # Gemma3: 5:1 local/global pattern, dual rope theta, qk-norm,
    # no softcaps (text model; the 4B+ vision tower is not served).
    "gemma3-1b": LlamaConfig.gemma3_1b,
    "gemma3-4b-text": LlamaConfig.gemma3_4b_text,
    # Mistral = Llama + sliding-window attention on every layer.
    "mistral-7b": LlamaConfig.mistral_7b,
    # Qwen3 = Llama + per-head q/k RMSNorm (no attention bias).
    "qwen3-8b": LlamaConfig.qwen3_8b,
    # Phi-3/Phi-4 = Llama with fused qkv/gate_up in the checkpoint.
    "phi3-mini": LlamaConfig.phi3_mini,
    "phi4": LlamaConfig.phi4,
}


# Qwen2-VL language models (Qwen2 + m-RoPE; the vision tower rides the
# multimodal encode worker, models/qwen2vl.vision_forward).
_LLAMA_PRESETS.update(
    {
        "qwen2-vl-tiny": qwen2vl_mod.text_tiny,
        "qwen2-vl-2b": qwen2vl_mod.text_2b,
        "qwen2-vl-7b": qwen2vl_mod.text_7b,
        "qwen2.5-vl-3b": qwen2vl_mod.text_25_3b,
        "qwen2.5-vl-7b": qwen2vl_mod.text_25_7b,
    }
)


def _llama_adapter(
    name: str, cfg: LlamaConfig, mesh=None
) -> ModelAdapter:
    from dynamo_tpu.parallel.shardings import llama_param_specs

    def forward(params, tokens, positions, valid, kv, page_tables):
        return llama_mod.forward(params, cfg, tokens, positions, valid, kv, page_tables)

    def forward_hidden(
        params, tokens, positions, valid, kv, page_tables, **mm
    ):
        return llama_mod.forward_hidden(
            params, cfg, tokens, positions, valid, kv, page_tables,
            mesh=mesh, **mm
        )

    return ModelAdapter(
        name=name,
        config=cfg,
        vocab_size=cfg.vocab_size,
        init_params=lambda key: llama_mod.init_params(key, cfg),
        forward=forward,
        forward_hidden=forward_hidden,
        compute_logits=lambda params, h: llama_mod.compute_logits(params, cfg, h),
        init_kv=lambda num_pages, page_size, kv_quantize=None: (
            llama_mod.init_kv_pages(
                cfg, num_pages, page_size, kv_quantize=kv_quantize
            )
        ),
        param_specs=lambda quantized=False: llama_param_specs(
            cfg, quantized=quantized
        ),
        kv_spec=lambda kv_quantize=None: _kv_pages_spec(kv_quantize),
        logical_axes=lambda quantized=False: llama_mod.llama_logical_axes(
            cfg, quantized=quantized
        ),
        load_params=lambda path: _load_llama_checkpoint(path, cfg),
        quantize_params=llama_mod.quantize_params_int8,
        init_params_quantized=lambda key: llama_mod.init_params_int8(
            key, cfg
        ),
    )


def _load_llama_checkpoint(path: str, cfg: LlamaConfig):
    """Load HF-format weights (safetensors/bin) from a local dir."""
    import torch
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(
        path, torch_dtype=torch.float32, low_cpu_mem_usage=True
    )
    return llama_mod.params_from_torch_state_dict(model.state_dict(), cfg)


def _mla_init_kv(cfg, num_pages: int, page_size: int, kv_quantize):
    from dynamo_tpu.models import mla as mla_mod

    if kv_quantize:
        # The shared-latent cache IS the attention input (no per-head
        # rows to scale); refuse rather than serve silently degraded.
        raise ValueError(
            "kv_quantize is not supported for MLA (shared-latent cache) "
            "models — run with kv_quantize=None"
        )
    return mla_mod.init_kv_pages(cfg, num_pages, page_size)


def _mla_adapter(name: str, cfg, mesh=None) -> ModelAdapter:
    from dynamo_tpu.models import mla as mla_mod

    def fwd(params, tokens, positions, valid, kv, pt):
        return mla_mod.forward(params, cfg, tokens, positions, valid, kv, pt)

    def fwd_hidden(params, tokens, positions, valid, kv, pt, **mm):
        return mla_mod.forward_hidden(
            params, cfg, tokens, positions, valid, kv, pt, mesh=mesh, **mm
        )

    def load(path):
        import torch
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(
            path, torch_dtype=torch.float32, low_cpu_mem_usage=True,
            trust_remote_code=False,
        )
        return mla_mod.params_from_torch_state_dict(model.state_dict(), cfg)

    return ModelAdapter(
        name=name,
        config=cfg,
        vocab_size=cfg.vocab_size,
        init_params=lambda key: mla_mod.init_params(key, cfg),
        forward=fwd,
        forward_hidden=fwd_hidden,
        compute_logits=lambda params, h: mla_mod.compute_logits(
            params, cfg, h
        ),
        init_kv=lambda num_pages, page_size, kv_quantize=None: (
            _mla_init_kv(cfg, num_pages, page_size, kv_quantize)
        ),
        param_specs=lambda quantized=False: mla_mod.mla_param_specs(
            cfg, quantized=quantized
        ),
        # one shared latent per token: the cache replicates over tp (MQA
        # shape) — reuse the generic spec with no head axis to shard
        kv_spec=lambda kv_quantize=None: _kv_pages_spec(
            kv_quantize, shard_heads=False
        ),
        logical_axes=lambda quantized=False: mla_mod.mla_logical_axes(
            cfg, quantized=quantized
        ),
        load_params=load,
        quantize_params=mla_mod.quantize_params_int8,
        init_params_quantized=lambda key: mla_mod.init_params_int8(
            key, cfg
        ),
    )


def _moe_adapter(name: str, moe_cfg, mesh=None) -> ModelAdapter:
    from dynamo_tpu.models import moe as moe_mod

    cfg = moe_cfg

    def fwd(params, tokens, positions, valid, kv, pt):
        return moe_mod.forward(params, cfg, tokens, positions, valid, kv, pt)

    def fwd_hidden(params, tokens, positions, valid, kv, pt, **mm):
        return moe_mod.forward_hidden(
            params, cfg, tokens, positions, valid, kv, pt, mesh=mesh, **mm
        )

    def load(path):
        import torch
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(
            path, torch_dtype=torch.float32, low_cpu_mem_usage=True
        )
        return moe_mod.params_from_torch_state_dict(model.state_dict(), cfg)

    return ModelAdapter(
        name=name,
        config=cfg,
        vocab_size=cfg.base.vocab_size,
        init_params=lambda key: moe_mod.init_params(key, cfg),
        forward=fwd,
        forward_hidden=fwd_hidden,
        compute_logits=lambda params, h: llama_mod.compute_logits(
            params, cfg.base, h
        ),
        init_kv=lambda num_pages, page_size, kv_quantize=None: (
            llama_mod.init_kv_pages(
                cfg.base, num_pages, page_size, kv_quantize=kv_quantize
            )
        ),
        param_specs=lambda quantized=False: moe_mod.moe_param_specs(
            cfg, quantized=quantized
        ),
        kv_spec=lambda kv_quantize=None: _kv_pages_spec(kv_quantize),
        logical_axes=lambda quantized=False: moe_mod.moe_logical_axes(
            cfg, quantized=quantized
        ),
        load_params=load,
        quantize_params=moe_mod.quantize_params_int8,
    )


def _moe_presets() -> dict:
    from dynamo_tpu.models.moe import MoeConfig

    return {
        "mixtral-8x7b": MoeConfig.mixtral_8x7b,
        "moe-tiny": MoeConfig.tiny,
        "qwen3-moe-30b": MoeConfig.qwen3_moe_30b,
        "llama4-scout-text": MoeConfig.llama4_scout_text,
        "llama4-tiny": MoeConfig.llama4_tiny,
        "gpt-oss-20b": MoeConfig.gpt_oss_20b,
        "gpt-oss-tiny": MoeConfig.gpt_oss_tiny,
    }


def _mla_presets() -> dict:
    from dynamo_tpu.models.mla import MlaConfig

    return {
        "deepseek-v2-lite": MlaConfig.deepseek_v2_lite,
        "mla-tiny": MlaConfig.tiny,
        "mla-tiny-moe": MlaConfig.tiny_moe,
    }


def list_presets() -> list[str]:
    """Every serveable preset id (llama + MoE + MLA families) — the
    iteration surface for `scripts/dryrun_70b.py --check-rules`, which
    dry-resolves each one's logical axes through the rule table."""
    return sorted(_LLAMA_PRESETS) + sorted(_moe_presets()) + sorted(
        _mla_presets()
    )


def get_model(
    name: str,
    dtype: Optional[str] = None,
    attention_impl: Optional[str] = None,
    mesh=None,
) -> ModelAdapter:
    """Resolve a model name: preset id, or a local HF checkpoint dir."""
    from dynamo_tpu.models.mla import MlaConfig
    from dynamo_tpu.models.moe import MoeConfig

    key = name.lower()
    moe_presets = _moe_presets()
    mla_presets = _mla_presets()
    moe_cfg = None
    mla_cfg = None
    gguf_path = None
    qwen2vl_dir = False
    if key in _LLAMA_PRESETS:
        cfg = _LLAMA_PRESETS[key]()
    elif key.endswith(".gguf") and os.path.isfile(name):
        from dynamo_tpu.gguf import read_gguf

        g = read_gguf(name)
        arch = g.architecture()
        if arch not in ("llama", "qwen2", "qwen3", "gemma", "gemma2",
                        "gemma3"):
            raise ValueError(
                f"unsupported GGUF architecture {arch!r} for {name}"
            )
        cfg = g.to_llama_config()
        gguf_path = name
    elif key in moe_presets:
        moe_cfg = moe_presets[key]()
    elif key in mla_presets:
        mla_cfg = mla_presets[key]()
    elif os.path.isdir(name) and os.path.exists(os.path.join(name, "config.json")):
        with open(os.path.join(name, "config.json")) as f:
            hf = json.load(f)
        arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0]
        if (
            "mixtral" in arch.lower()
            or arch in (
                "Qwen3MoeForCausalLM", "Llama4ForCausalLM",
                "GptOssForCausalLM",
            )
            or hf.get("model_type") in ("qwen3_moe", "llama4_text", "gpt_oss")
        ):
            moe_cfg = MoeConfig.from_hf_config(hf)
        elif (
            arch in ("DeepseekV2ForCausalLM", "DeepseekV3ForCausalLM")
            or hf.get("model_type") in ("deepseek_v2", "deepseek_v3")
        ):
            mla_cfg = MlaConfig.from_hf_config(hf)
        elif (
            arch in (
                "Qwen2VLForConditionalGeneration",
                "Qwen2_5_VLForConditionalGeneration",
            )
            or hf.get("model_type") in ("qwen2_vl", "qwen2_5_vl")
        ):
            from dynamo_tpu.models import qwen2vl

            cfg = qwen2vl.config_from_hf(hf)
            qwen2vl_dir = True
        elif (
            "llama" in arch.lower()
            or "qwen2" in arch.lower()
            or arch in (
                "GemmaForCausalLM", "Gemma2ForCausalLM",
                "Gemma3ForCausalLM", "MistralForCausalLM",
                "Qwen3ForCausalLM", "Phi3ForCausalLM",
            )
            or hf.get("model_type") in (
                "gemma", "gemma2", "gemma3_text", "mistral", "qwen3",
                "phi3",
            )
            # Multimodal Gemma3 dumps (model_type "gemma3") and
            # RecurrentGemma remain refused rather than served
            # silently wrong (text-only Gemma3ForCausalLM is covered).
        ):
            cfg = LlamaConfig.from_hf_config(hf)
        else:
            raise ValueError(f"unsupported architecture {arch} for {name}")
    else:
        raise ValueError(
            f"unknown model {name!r}; presets: "
            f"{sorted(_LLAMA_PRESETS) + sorted(moe_presets) + sorted(mla_presets)} "
            "or a local HF checkpoint directory"
        )
    if mla_cfg is not None:
        if dtype is not None:
            mla_cfg = _with_dtype(mla_cfg, dtype)
        if attention_impl not in (None, "auto", "xla"):
            # MLA's absorbed-latent attention only has the XLA path; the
            # flash kernels assume per-head K/V pages. An explicit request
            # gets a WARNING: an operator benchmarking kernels must not
            # read xla numbers believing they measured pallas.
            logger.warning(
                "%s: attention_impl=%s requested but MLA only has the XLA "
                "path -> serving with attention_impl=xla",
                name, attention_impl,
            )
        mla_adapter = _mla_adapter(name, mla_cfg, mesh=mesh)
        if os.path.isdir(name):
            mla_adapter = replace(mla_adapter, default_checkpoint=name)
        return mla_adapter
    if moe_cfg is not None:
        if dtype is not None:
            moe_cfg = replace(moe_cfg, base=_with_dtype(moe_cfg.base, dtype))
        if attention_impl is not None:
            moe_cfg = replace(
                moe_cfg,
                base=replace(moe_cfg.base, attention_impl=attention_impl),
            )
        moe_adapter = _moe_adapter(name, moe_cfg, mesh=mesh)
        if os.path.isdir(name):
            moe_adapter = replace(moe_adapter, default_checkpoint=name)
        return moe_adapter
    if dtype is not None:
        cfg = _with_dtype(cfg, dtype)
    if attention_impl is not None:
        cfg = replace(cfg, attention_impl=attention_impl)
    if cfg.attention_impl in ("pallas", "hybrid") and (
        cfg.sliding_window
        or cfg.attn_logit_softcap
        or (
            cfg.query_pre_attn_scalar is not None
            and cfg.query_pre_attn_scalar != cfg.head_dim
        )
    ):
        # Gemma2's sliding-window / softcapped / rescaled attention isn't
        # implemented in the flash kernels (they scale by 1/sqrt(head_dim))
        # — serve it on the XLA path rather than fail ("auto" on TPU would
        # otherwise pick pallas and raise at trace). Explicit requests get
        # a WARNING (see the MLA coercion above).
        log = (
            logger.warning
            if attention_impl in ("pallas", "hybrid")
            else logger.info
        )
        log(
            "%s: sliding-window/softcap/rescaled attention has no flash "
            "kernel -> serving with attention_impl=xla",
            name,
        )
        cfg = replace(cfg, attention_impl="xla")
    adapter = _llama_adapter(name, cfg, mesh=mesh)
    if gguf_path is not None:
        from dynamo_tpu.gguf import read_gguf

        def load_from_gguf(path=gguf_path, cfg=cfg):
            return llama_mod.params_from_gguf(read_gguf(path), cfg)

        adapter = replace(
            adapter, load_params=load_from_gguf, default_checkpoint=gguf_path
        )
    elif os.path.isdir(name):
        adapter = replace(adapter, default_checkpoint=name)
        if qwen2vl_dir:
            # Qwen2-VL dirs hold a conditional-generation model;
            # AutoModelForCausalLM refuses them, and the language weights
            # live under `model.language_model.*`.
            adapter = replace(
                adapter,
                load_params=lambda path: _load_qwen2vl_checkpoint(path, cfg),
            )
    return adapter


def _load_qwen2vl_checkpoint(path: str, cfg: LlamaConfig):
    import torch

    from dynamo_tpu.models.qwen2vl import remap_language_state_dict

    with open(os.path.join(path, "config.json")) as f:
        mt = json.load(f).get("model_type")
    if mt == "qwen2_5_vl":
        from transformers import Qwen2_5_VLForConditionalGeneration as cls
    else:
        from transformers import Qwen2VLForConditionalGeneration as cls
    model = cls.from_pretrained(
        path, torch_dtype=torch.float32, low_cpu_mem_usage=True
    )
    return llama_mod.params_from_torch_state_dict(
        remap_language_state_dict(model.state_dict()), cfg
    )


def _with_dtype(cfg: LlamaConfig, dtype) -> LlamaConfig:
    if isinstance(dtype, str):
        table = {
            "bfloat16": jnp.bfloat16,
            "float32": jnp.float32,
            "float64": jnp.float64,
        }
        if dtype not in table:
            raise ValueError(
                f"unsupported dtype {dtype!r}; use one of {sorted(table)}"
            )
        dtype = table[dtype]
    return replace(cfg, dtype=dtype)

"""Llama-family decoder in pure functional JAX with a paged KV cache.

This is the flagship engine model (the reference serves Llama via external
GPU engines — vLLM/TRT-LLM; here the engine is first-class, SURVEY.md §2.9).
Design choices are TPU-first:

- One `forward` covers prefill AND decode: T is just the chunk length (1 for
  decode). Attention always runs against the paged KV cache gathered through
  the page table, so chunked prefill, prefix-cache continuation, and decode
  are the same compiled program shape-family.
- Layers are scanned (`lax.scan` over stacked layer params), so compile time
  is O(1) in depth and XLA sees one fused layer body.
- Weights live in bf16; softmax/norm accumulate in f32 (MXU-friendly).
- All shapes are static: (B, T, MAX_PAGES) come from the scheduler's bucket,
  padding is masked. No data-dependent control flow under jit.

Parity notes: replaces the model execution the reference delegates to
vLLM/SGLang/TRT-LLM subprocesses (/root/reference launch/dynamo-run/src/
subprocess/vllm_inc.py etc.); paged-KV semantics match the vLLM-style paged
attention contract (page table per sequence, block == token-block of the
router, so KV routing hashes align with engine pages).
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    # Llama-3.1-style NTK rope scaling (None disables).
    rope_scaling_factor: Optional[float] = None
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    dtype: Any = jnp.bfloat16
    #: "xla" (gather path, any T) | "pallas" (flash kernels: page-walk DMA
    #: decode for T=1, VMEM-tiled causal flash for first-chunk prefill;
    #: history-chunk prefill still takes the XLA gather path) | "hybrid"
    #: (pallas write discipline + flash prefill, but decode attention
    #: switches to the XLA gather past pallas_decode_max_batch — the
    #: page-walk kernel issues O(B x pages) DMA descriptors per layer,
    #: which is latency-optimal at small B and descriptor-bound at large)
    attention_impl: str = "xla"
    #: "hybrid" decode: largest batch bucket still served by the pallas
    #: page-walk kernel (bigger buckets use the XLA gather)
    pallas_decode_max_batch: int = 32
    #: q/k/v projection bias — the Qwen2 family's one architectural delta
    attention_bias: bool = False
    #: Qwen3: per-head RMSNorm on q and k (head_dim-wide), applied after
    #: the projections, before rope
    qk_norm: bool = False
    #: MLP activation: "silu" (Llama/Qwen GLU) or "gelu_tanh" (Gemma GeGLU)
    hidden_act: str = "silu"
    #: Gemma-style RMSNorm: scale by (1 + weight) instead of weight
    rms_norm_unit_offset: bool = False
    #: Gemma scales token embeddings by sqrt(hidden_size)
    scale_embeddings: bool = False
    #: Gemma2: attention scores pass cap*tanh(s/cap) before masking
    attn_logit_softcap: Optional[float] = None
    #: Gemma2: final lm_head logits pass cap*tanh(l/cap)
    final_logit_softcap: Optional[float] = None
    #: Local attention: affected layers attend only the last
    #: `sliding_window` positions; 0 disables
    sliding_window: int = 0
    #: which layers are local: layer_idx % every == 0. 2 = Gemma2's
    #: local/global alternation; 1 = every layer (Mistral)
    sliding_window_every: int = 2
    #: Gemma2: query scale is query_pre_attn_scalar**-0.5 (None: head_dim)
    query_pre_attn_scalar: Optional[float] = None
    #: Gemma2 block: extra post-attention / post-feedforward RMSNorms
    post_block_norms: bool = False
    #: Gemma3: layer is GLOBAL iff (layer+1) % this == 0, all others are
    #: local (the 5:1 pattern with 6). 0 = use sliding_window_every's
    #: "every Nth layer is local" semantics instead (Gemma2/Mistral).
    sliding_global_every: int = 0
    #: Gemma3: LOCAL-attention layers rope with this theta (10k) while
    #: global layers use rope_theta (1M). None = one theta everywhere.
    rope_local_theta: Optional[float] = None
    #: Gemma3 4B+: linear rope position scaling on GLOBAL layers only
    #: (positions effectively divided by this factor)
    rope_linear_factor: Optional[float] = None
    #: Llama-4: rope rotates interleaved pairs (x0,x1),(x2,x3)… (the
    #: complex freqs_cis convention) instead of the HF half-split
    rope_interleaved: bool = False
    #: Llama-4 NoPE: every Nth layer ((layer+1) % N == 0) skips rope and
    #: attends globally; 0 = rope everywhere
    nope_every: int = 0
    #: Llama-4: weightless L2 q/k norm after rope (rope layers only)
    qk_l2_norm: bool = False
    #: Llama-4: scale NoPE-layer queries by
    #: log1p(floor((pos+1)/floor_scale)) * attn_scale + 1
    attn_temperature_tuning: bool = False
    attn_floor_scale: float = 8192.0
    attn_scale_coef: float = 0.1
    #: Llama-4 chunked attention on rope layers: token attends only
    #: within its `attention_chunk`-sized block (0 = off). Equivalent to
    #: a per-query window of (pos % chunk) + 1.
    attention_chunk: int = 0
    #: YaRN rope scaling (GPT-OSS): interpolation factor; None = off.
    #: Uses rope_original_max_position as the pretraining context and
    #: scales cos/sin by the paper's 0.1·ln(factor)+1 attention factor.
    rope_yarn_factor: Optional[float] = None
    rope_yarn_beta_fast: float = 32.0
    rope_yarn_beta_slow: float = 1.0
    rope_yarn_truncate: bool = True
    #: explicit cos/sin scale override (HF rope_scaling.attention_factor);
    #: None = the paper's 0.1·ln(factor)+1
    rope_yarn_attention_factor: Optional[float] = None
    #: GPT-OSS attention sinks: a learned per-head logit joins every
    #: softmax (params key "sinks" [Hq] per layer)
    attn_sinks: bool = False
    #: GPT-OSS: the o projection carries a bias too (params key "bo")
    attention_out_bias: bool = False
    #: Qwen2-VL m-RoPE: head_dim/2 frequency slots partitioned into
    #: (temporal, height, width) sections — e.g. (16, 24, 24) for D=128.
    #: Rope positions may then be [3, B, T] (one stream per axis); plain
    #: [B, T] positions still work and equal the (p, p, p) case exactly,
    #: which is why text-only serving needs no special path.
    mrope_section: Optional[tuple[int, ...]] = None

    def __post_init__(self):
        if self.rope_local_theta is not None and not self.sliding_global_every:
            # the per-layer theta selection keys off the global-layer
            # period; without it the modulus is by zero (undefined under
            # XLA) and every layer's theta would be silently arbitrary
            raise ValueError(
                "rope_local_theta requires sliding_global_every > 0 "
                "(the dual-theta selection follows the Gemma3 "
                "local/global layer pattern)"
            )

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    @property
    def kv_head_dim(self) -> int:
        """head_dim as stored in the paged KV cache. The Pallas decode
        kernel DMAs one [page_size, D] block per page, and Mosaic requires
        DMA slice shapes aligned to the (8,128) lane tile — so for
        head_dim-64 models (Llama-3.2-1B, Qwen2.5-0.5B) the cache keeps D
        padded up to 128 zero lanes when the kernel is active. Padding is
        invisible outside the cache: q·k over zero lanes adds nothing and
        the attention output is sliced back to head_dim."""
        if (
            self.attention_impl in ("pallas", "hybrid")
            and self.head_dim % 128 != 0
        ):
            return -(-self.head_dim // 128) * 128
        return self.head_dim

    # -- canned configs ----------------------------------------------------

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()  # defaults above are Llama-3-8B

    @staticmethod
    def llama3_70b() -> "LlamaConfig":
        return LlamaConfig(
            hidden_size=8192, intermediate_size=28672, num_layers=80,
            num_heads=64, num_kv_heads=8,
        )

    @staticmethod
    def llama3_1b() -> "LlamaConfig":
        """Llama-3.2-1B-shaped config — fits a single v5e chip with headroom."""
        return LlamaConfig(
            hidden_size=2048, intermediate_size=8192, num_layers=16,
            num_heads=32, num_kv_heads=8, head_dim=64,
            tie_word_embeddings=True,
            rope_scaling_factor=32.0,
        )

    @staticmethod
    def llama3_draft() -> "LlamaConfig":
        """Draft-sized Llama sharing the Llama-3 vocabulary (128256):
        ~8% of llama3-1b's non-embedding FLOPs — the speculation draft
        (`EngineConfig.spec_draft_model="llama3-draft"`) for the 1B/8B
        targets. Random-init unless a distilled checkpoint is loaded
        via spec_draft_checkpoint; a random draft accepts at chance and
        the engine's acceptance cooldown keeps it out of the hot path."""
        return LlamaConfig(
            hidden_size=512, intermediate_size=2048, num_layers=4,
            num_heads=8, num_kv_heads=4, head_dim=64,
            tie_word_embeddings=True,
            rope_scaling_factor=32.0,
        )

    @staticmethod
    def tiny(vocab_size: int = 256) -> "LlamaConfig":
        """For unit tests (CPU) — small enough to compare against torch."""
        return LlamaConfig(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            rope_theta=10000.0, dtype=jnp.float32,
        )

    @staticmethod
    def qwen2_7b() -> "LlamaConfig":
        """Qwen2/2.5-7B: Llama architecture + qkv bias."""
        return LlamaConfig(
            vocab_size=152064, hidden_size=3584, intermediate_size=18944,
            num_layers=28, num_heads=28, num_kv_heads=4, head_dim=128,
            rope_theta=1000000.0, rms_norm_eps=1e-6, attention_bias=True,
        )

    @staticmethod
    def qwen2_05b() -> "LlamaConfig":
        """Qwen2.5-0.5B — single-chip smoke size for the family."""
        return LlamaConfig(
            vocab_size=151936, hidden_size=896, intermediate_size=4864,
            num_layers=24, num_heads=14, num_kv_heads=2, head_dim=64,
            rope_theta=1000000.0, rms_norm_eps=1e-6, attention_bias=True,
            tie_word_embeddings=True,
        )

    @staticmethod
    def gemma_2b() -> "LlamaConfig":
        """Gemma-2B: GeGLU MLP, (1+w) RMSNorm, sqrt(H)-scaled embeddings,
        tied lm_head, MQA (1 kv head), head_dim 256."""
        return LlamaConfig(
            vocab_size=256000, hidden_size=2048, intermediate_size=16384,
            num_layers=18, num_heads=8, num_kv_heads=1, head_dim=256,
            rope_theta=10000.0, rms_norm_eps=1e-6, tie_word_embeddings=True,
            hidden_act="gelu_tanh", rms_norm_unit_offset=True,
            scale_embeddings=True,
        )

    @staticmethod
    def gemma_7b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=256000, hidden_size=3072, intermediate_size=24576,
            num_layers=28, num_heads=16, num_kv_heads=16, head_dim=256,
            rope_theta=10000.0, rms_norm_eps=1e-6, tie_word_embeddings=True,
            hidden_act="gelu_tanh", rms_norm_unit_offset=True,
            scale_embeddings=True,
        )

    @staticmethod
    def qwen3_8b() -> "LlamaConfig":
        """Qwen3-8B: Llama architecture + per-head q/k RMSNorm, no bias."""
        return LlamaConfig(
            vocab_size=151936, hidden_size=4096, intermediate_size=12288,
            num_layers=36, num_heads=32, num_kv_heads=8, head_dim=128,
            rope_theta=1000000.0, rms_norm_eps=1e-6, qk_norm=True,
        )

    @staticmethod
    def phi3_mini() -> "LlamaConfig":
        """Phi-3-mini-4k: Llama architecture with fused qkv/gate_up
        projections in the checkpoint (split at load); 128k longrope
        variants are refused."""
        return LlamaConfig(
            vocab_size=32064, hidden_size=3072, intermediate_size=8192,
            num_layers=32, num_heads=32, num_kv_heads=32, head_dim=96,
            rope_theta=10000.0, rms_norm_eps=1e-5,
        )

    @staticmethod
    def phi4() -> "LlamaConfig":
        """Phi-4 (14B): the same phi3 architecture (fused qkv/gate_up
        split at load, tests/test_model_phi3.py) at 40 layers with GQA
        and a 250k rope base."""
        return LlamaConfig(
            vocab_size=100352, hidden_size=5120, intermediate_size=17920,
            num_layers=40, num_heads=40, num_kv_heads=10, head_dim=128,
            rope_theta=250000.0, rms_norm_eps=1e-5,
        )

    @staticmethod
    def mistral_7b() -> "LlamaConfig":
        """Mistral-7B-v0.1: Llama architecture + sliding-window attention
        on every layer (window 4096)."""
        return LlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
            rope_theta=10000.0, rms_norm_eps=1e-5,
            sliding_window=4096, sliding_window_every=1,
        )

    @staticmethod
    def gemma2_2b() -> "LlamaConfig":
        """Gemma-2-2B: Gemma base + sliding/global layer alternation,
        attn+final logit soft-capping, post-block norms."""
        return LlamaConfig(
            vocab_size=256000, hidden_size=2304, intermediate_size=9216,
            num_layers=26, num_heads=8, num_kv_heads=4, head_dim=256,
            rope_theta=10000.0, rms_norm_eps=1e-6, tie_word_embeddings=True,
            hidden_act="gelu_tanh", rms_norm_unit_offset=True,
            scale_embeddings=True, attn_logit_softcap=50.0,
            final_logit_softcap=30.0, sliding_window=4096,
            query_pre_attn_scalar=256.0, post_block_norms=True,
        )

    @staticmethod
    def gemma3_1b() -> "LlamaConfig":
        """Gemma-3-1B: Gemma2 block structure minus the soft-caps, plus
        qk-norm, 5:1 local/global layer pattern, and dual rope theta
        (1M global / 10k local)."""
        return LlamaConfig(
            vocab_size=262144, hidden_size=1152, intermediate_size=6912,
            num_layers=26, num_heads=4, num_kv_heads=1, head_dim=256,
            rope_theta=1_000_000.0, rope_local_theta=10_000.0,
            rms_norm_eps=1e-6, tie_word_embeddings=True,
            hidden_act="gelu_tanh", rms_norm_unit_offset=True,
            scale_embeddings=True, qk_norm=True, sliding_window=512,
            sliding_global_every=6, query_pre_attn_scalar=256.0,
            post_block_norms=True,
        )

    @staticmethod
    def gemma3_4b_text() -> "LlamaConfig":
        """Gemma-3-4B language model (text weights of the multimodal
        checkpoint): 1B recipe + linear rope scaling x8 on global
        layers."""
        return LlamaConfig(
            vocab_size=262208, hidden_size=2560, intermediate_size=10240,
            num_layers=34, num_heads=8, num_kv_heads=4, head_dim=256,
            rope_theta=1_000_000.0, rope_local_theta=10_000.0,
            rope_linear_factor=8.0, rms_norm_eps=1e-6,
            tie_word_embeddings=True, hidden_act="gelu_tanh",
            rms_norm_unit_offset=True, scale_embeddings=True, qk_norm=True,
            sliding_window=1024, sliding_global_every=6,
            query_pre_attn_scalar=256.0, post_block_norms=True,
        )

    @staticmethod
    def from_hf_config(hf: dict) -> "LlamaConfig":
        """Map a HuggingFace `config.json` dict onto LlamaConfig (covers the
        Llama, Qwen2 (= Llama + qkv bias), Gemma, Gemma2, and Gemma3-text
        families)."""
        arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0]
        gemma3 = (
            hf.get("model_type") == "gemma3_text"
            or arch == "Gemma3ForCausalLM"
        )
        rope_scaling = hf.get("rope_scaling") or {}
        factor = None
        linear_factor = None
        yarn = {}
        rs_type = rope_scaling.get("rope_type", rope_scaling.get("type"))
        if rs_type == "llama3":
            factor = float(rope_scaling["factor"])
        elif gemma3 and rs_type == "linear":
            linear_factor = float(rope_scaling["factor"])
        elif rs_type == "yarn":
            if rope_scaling.get("mscale") or rope_scaling.get(
                "mscale_all_dim"
            ):
                # DeepSeek-style mscale yarn lives in models/mla.py;
                # refuse rather than scale attention silently wrong here
                raise ValueError(
                    "yarn mscale/mscale_all_dim is only implemented for "
                    "the DeepSeek MLA family"
                )
            att = rope_scaling.get("attention_factor")
            yarn = dict(
                rope_yarn_factor=float(rope_scaling["factor"]),
                rope_yarn_beta_fast=float(
                    rope_scaling.get("beta_fast") or 32.0
                ),
                rope_yarn_beta_slow=float(
                    rope_scaling.get("beta_slow") or 1.0
                ),
                rope_yarn_truncate=bool(rope_scaling.get("truncate", True)),
                rope_yarn_attention_factor=(
                    float(att) if att is not None else None
                ),
            )
        elif rope_scaling:
            # refuse rather than run long-context positions unscaled
            raise ValueError(
                f"unsupported rope_scaling type {rs_type!r} for this "
                "family (llama3 NTK, Gemma3 linear, and yarn are "
                "implemented)"
            )
        head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
        global_every = 0
        if gemma3:
            lt = hf.get("layer_types") or []
            global_every = (
                lt.index("full_attention") + 1
                if "full_attention" in lt
                else 6
            )
            want = [
                "full_attention"
                if (i + 1) % global_every == 0
                else "sliding_attention"
                for i in range(len(lt))
            ]
            if lt and lt != want:
                # refuse rather than run a non-periodic pattern silently
                # wrong (only the every-Nth-global layout is implemented)
                raise ValueError(
                    f"unsupported gemma3 layer_types pattern {lt!r}: only "
                    f"periodic every-{global_every}th-global is implemented"
                )
        gemma2 = hf.get("model_type") == "gemma2" or arch == "Gemma2ForCausalLM"
        gemma = (
            hf.get("model_type") == "gemma"
            or arch == "GemmaForCausalLM"
            or gemma2
            or gemma3
        )
        llama4 = (
            hf.get("model_type") == "llama4_text"
            or arch == "Llama4ForCausalLM"
        )
        gpt_oss = (
            hf.get("model_type") == "gpt_oss" or arch == "GptOssForCausalLM"
        )
        nope_every = 0
        if llama4:
            nrl = hf.get("no_rope_layers") or []
            if not nrl:
                # HF serializes an empty list to mean "the default
                # pattern" (every no_rope_layer_interval-th layer NoPE)
                nope_every = int(hf.get("no_rope_layer_interval") or 4)
            elif 0 in nrl:
                nope_every = nrl.index(0) + 1
                want = [
                    0 if (i + 1) % nope_every == 0 else 1
                    for i in range(len(nrl))
                ]
                if nrl != want:
                    raise ValueError(
                        f"unsupported llama4 no_rope_layers pattern "
                        f"{nrl!r}: only periodic every-{nope_every}th-NoPE "
                        "is implemented"
                    )
        mistral = (
            hf.get("model_type") == "mistral" or arch == "MistralForCausalLM"
        )
        qwen3 = hf.get("model_type") in ("qwen3", "qwen3_moe") or arch in (
            "Qwen3ForCausalLM",
            "Qwen3MoeForCausalLM",
        )

        hidden_act = hf.get("hidden_activation") or hf.get("hidden_act", "silu")
        if hidden_act in ("gelu_pytorch_tanh", "gelu_tanh", "gelu"):
            hidden_act = "gelu_tanh"
        elif hidden_act == "silu":
            hidden_act = "silu"
        else:
            # refuse rather than run a numerically wrong model
            raise ValueError(
                f"unsupported hidden_act {hidden_act!r} in HF config"
            )
        return LlamaConfig(
            attention_bias=bool(
                hf.get("attention_bias", arch == "Qwen2ForCausalLM")
            ),
            qk_norm=qwen3 or gemma3,
            hidden_act=hidden_act,
            rms_norm_unit_offset=gemma,
            scale_embeddings=gemma,
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=head_dim,
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
            tie_word_embeddings=bool(hf.get("tie_word_embeddings", gemma)),
            rope_scaling_factor=factor,
            rope_low_freq_factor=float(rope_scaling.get("low_freq_factor", 1.0)),
            rope_high_freq_factor=float(rope_scaling.get("high_freq_factor", 4.0)),
            rope_original_max_position=int(
                rope_scaling.get("original_max_position_embeddings")
                # HF's yarn falls back to the model's max positions, NOT
                # a fixed constant — the correction range depends on it
                or (hf.get("max_position_embeddings") if rs_type == "yarn"
                    else None)
                or 8192
            ),
            attn_logit_softcap=(
                hf.get("attn_logit_softcapping") if gemma2 else None
            ),
            final_logit_softcap=(
                hf.get("final_logit_softcapping") if gemma2 else None
            ),
            sliding_window=(
                int(hf.get("sliding_window") or 0)
                if (gemma2 or gemma3 or mistral or gpt_oss)
                else 0
            ),
            sliding_window_every=2 if (gemma2 or gpt_oss) else 1,
            sliding_global_every=global_every,
            rope_local_theta=(
                float(hf.get("rope_local_base_freq", 10_000.0))
                if gemma3
                else None
            ),
            rope_linear_factor=linear_factor,
            query_pre_attn_scalar=(
                float(hf["query_pre_attn_scalar"])
                if (gemma2 or gemma3) and hf.get("query_pre_attn_scalar")
                else None
            ),
            post_block_norms=gemma2 or gemma3,
            rope_interleaved=llama4,
            nope_every=nope_every,
            qk_l2_norm=bool(llama4 and hf.get("use_qk_norm", True)),
            attn_temperature_tuning=bool(
                llama4 and hf.get("attn_temperature_tuning", True)
            ),
            attn_floor_scale=float(hf.get("floor_scale", 8192.0)),
            attn_scale_coef=float(hf.get("attn_scale", 0.1)),
            attention_chunk=(
                int(hf.get("attention_chunk_size") or 0) if llama4 else 0
            ),
            attn_sinks=gpt_oss,
            attention_out_bias=gpt_oss,
            **yarn,
        )


class KVPages(NamedTuple):
    """Paged KV cache: one page pool shared by all sequences of a worker.

    k, v: [num_layers, num_pages, page_size, num_kv_heads, head_dim]
    Page-major: one (layer, page) slice is a contiguous [S, Hkv, D] block —
    a single dense DMA descriptor covering every kv head (the Pallas decode
    kernel reads one page per DMA and computes all heads from it), and a
    token's row [Hkv, D] is contiguous so the Pallas write kernel can land
    it with one descriptor; writes for one sequence across ALL layers are a
    single strided DMA (stride = the page axis). tp shards the kv-heads
    axis. Page 0 is the null page: padding writes land there and no real
    page table ever references it.

    Quantized pages (`kv_quantize="int8"|"fp8"`): k/v hold the narrow
    dtype and k_scale/v_scale carry per-(page, slot, kv-head) f32 scale
    planes of shape [L, P, S, Hkv] — each page travels with its own
    [S, Hkv] scale plane. A token's row [D] quantizes symmetrically
    against its own amax on write, so pages filling incrementally never
    need re-scaling, and readers dequantize in VMEM right after the page
    DMA lands — no fp copy of the cache ever exists in HBM.
    """

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None  # [L, P, S, Hkv] f32, quantized only
    v_scale: Optional[jax.Array] = None

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


#: kv_quantize mode -> (storage dtype, symmetric max representable)
def kv_quant_spec(mode: str):
    if mode == "int8":
        return jnp.int8, 127.0
    if mode == "fp8":
        fp8 = getattr(jnp, "float8_e4m3fn", None)
        if fp8 is None:
            raise ValueError(
                "kv_quantize='fp8' needs jnp.float8_e4m3fn (newer jax); "
                "use 'int8'"
            )
        return fp8, 448.0
    raise ValueError(f"unknown kv_quantize mode {mode!r}; use int8|fp8")


def quantize_kv_rows(x: jax.Array, mode: str = "int8"):
    """Per-token, per-kv-head symmetric quantization of KV rows:
    x [..., D] -> (q [..., D] narrow dtype, scale [...] f32). The scale is
    each row's amax/qmax — decode writes one token at a time, so row-local
    scales are exact under incremental page fill (a page-wide scale would
    clip tokens written after it was fixed)."""
    dtype, qmax = kv_quant_spec(mode)
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / qmax, 1e-8)
    q = xf / scale[..., None]
    if dtype == jnp.int8:
        q = jnp.round(q)
    return q.astype(dtype), scale


def dequantize_kv_rows(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Inverse of quantize_kv_rows: q [..., D] x scale [...] -> dtype."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def init_kv_pages(
    cfg: LlamaConfig,
    num_pages: int,
    page_size: int,
    dtype=None,
    kv_quantize: Optional[str] = None,
) -> KVPages:
    shape = (
        cfg.num_layers, num_pages, page_size, cfg.num_kv_heads, cfg.kv_head_dim
    )
    if kv_quantize:
        qdtype, _ = kv_quant_spec(kv_quantize)
        scale_shape = shape[:-1]
        return KVPages(
            k=jnp.zeros(shape, qdtype),
            v=jnp.zeros(shape, qdtype),
            k_scale=jnp.zeros(scale_shape, jnp.float32),
            v_scale=jnp.zeros(scale_shape, jnp.float32),
        )
    dtype = dtype or cfg.dtype
    return KVPages(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def kv_page_bytes(
    cfg, page_size: int, kv_quantize: Optional[str] = None, dtype=None
) -> int:
    """Bytes ONE page costs across all layers (k + v + scale planes) —
    the capacity-planning arithmetic for sizing num_pages against an HBM
    budget before an engine exists (the live gauges, kv_pool_bytes /
    kv_pool_bytes_dense_equiv, are computed from the actual pool arrays
    at engine init instead — that also covers MLA's asymmetric caches).
    `cfg` is a LlamaConfig (MoE passes cfg.base); quantized pages pay
    1 byte/elem + 4-byte f32 row scales, i.e. ~(1 + 4/D)/itemsize of
    the dense cost. Pinned by tests/test_kv_quant.py."""
    d = cfg.kv_head_dim
    elems = cfg.num_layers * page_size * cfg.num_kv_heads
    if kv_quantize:
        qdtype, _ = kv_quant_spec(kv_quantize)
        per = d * jnp.dtype(qdtype).itemsize + 4  # row + f32 scale
    else:
        per = d * jnp.dtype(dtype or cfg.dtype).itemsize
    return 2 * elems * per  # k and v


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def init_params(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Random-init params, layer-stacked for lax.scan."""
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    qd, kvd = cfg.num_heads * cfg.head_dim, cfg.num_kv_heads * cfg.head_dim
    L = cfg.num_layers
    keys = jax.random.split(key, 10)

    def norm_init(shape):
        return jnp.ones(shape, cfg.dtype)

    def dense(key, shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    params = {
        "embed": dense(keys[0], (v, h), h),
        "layers": {
            "attn_norm": norm_init((L, h)),
            "wq": dense(keys[1], (L, h, qd), h),
            "wk": dense(keys[2], (L, h, kvd), h),
            "wv": dense(keys[3], (L, h, kvd), h),
            "wo": dense(keys[4], (L, qd, h), qd),
            "mlp_norm": norm_init((L, h)),
            "w_gate": dense(keys[5], (L, h, i), h),
            "w_up": dense(keys[6], (L, h, i), h),
            "w_down": dense(keys[7], (L, i, h), i),
        },
        "final_norm": norm_init((h,)),
    }
    if cfg.attention_bias:
        params["layers"]["bq"] = jnp.zeros((L, qd), cfg.dtype)
        params["layers"]["bk"] = jnp.zeros((L, kvd), cfg.dtype)
        params["layers"]["bv"] = jnp.zeros((L, kvd), cfg.dtype)
    if cfg.qk_norm:
        params["layers"]["q_norm"] = norm_init((L, cfg.head_dim))
        params["layers"]["k_norm"] = norm_init((L, cfg.head_dim))
    if cfg.post_block_norms:
        params["layers"]["post_attn_norm"] = norm_init((L, h))
        params["layers"]["post_mlp_norm"] = norm_init((L, h))
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense(keys[8], (h, v), h)
    return params


def llama_logical_axes(cfg: LlamaConfig, quantized: bool = False) -> dict:
    """Logical axis names for every param, declared ONCE beside the
    shapes `init_params` builds (parallel/logical.py resolves them to
    PartitionSpecs through the one rule table):

    - "heads"/"kv_heads": packed q/kv head output dims of wq/wk/wv;
      wo's INPUT dim carries "heads" so the following matmul produces
      partial sums and XLA inserts the one per-layer psum,
    - "mlp": ffn intermediate dim (w_down's input, like wo),
    - "embed": the embedding table's hidden dim; the vocab dim of the
      TABLE stays unnamed (lookup is a gather — sharding the hidden dim
      is the cheap one), while an untied lm_head names its output dim
      "vocab",
    - "layers": the lax.scan stack dim, never sharded,
    - int8 scales [L, 1, out] ride their weight's OUTPUT dim
      (contraction-sharded wo/w_down keep unsharded scales, which
      commute with the partial-sum).
    """
    from dynamo_tpu.parallel.logical import L

    axes = {
        "embed": L(None, "embed"),
        "layers": {
            "attn_norm": L("layers", None),
            "wq": L("layers", None, "heads"),
            "wk": L("layers", None, "kv_heads"),
            "wv": L("layers", None, "kv_heads"),
            "wo": L("layers", "heads", None),
            "mlp_norm": L("layers", None),
            "w_gate": L("layers", None, "mlp"),
            "w_up": L("layers", None, "mlp"),
            "w_down": L("layers", "mlp", None),
        },
        "final_norm": L(None),
    }
    if cfg.attention_bias:
        # biases shard with their projection's output dim
        axes["layers"]["bq"] = L("layers", "heads")
        axes["layers"]["bk"] = L("layers", "kv_heads")
        axes["layers"]["bv"] = L("layers", "kv_heads")
    if getattr(cfg, "qk_norm", False):
        # per-head-dim norms apply identically on every sharded head
        axes["layers"]["q_norm"] = L("layers", None)
        axes["layers"]["k_norm"] = L("layers", None)
    if getattr(cfg, "post_block_norms", False):
        # Gemma2 post-sublayer norms act on the replicated hidden dim
        axes["layers"]["post_attn_norm"] = L("layers", None)
        axes["layers"]["post_mlp_norm"] = L("layers", None)
    if quantized:
        axes["layers"]["wq_scale"] = L("layers", None, "heads")
        axes["layers"]["wk_scale"] = L("layers", None, "kv_heads")
        axes["layers"]["wv_scale"] = L("layers", None, "kv_heads")
        axes["layers"]["w_gate_scale"] = L("layers", None, "mlp")
        axes["layers"]["w_up_scale"] = L("layers", None, "mlp")
        axes["layers"]["wo_scale"] = L("layers", None, None)
        axes["layers"]["w_down_scale"] = L("layers", None, None)
    if not cfg.tie_word_embeddings:
        axes["lm_head"] = L(None, "vocab")
    return axes


def params_from_torch_state_dict(state_dict, cfg: LlamaConfig) -> dict:
    """Convert a HuggingFace Llama state_dict (torch tensors) to our pytree.

    HF stores projections as [out, in]; we use [in, out] so matmuls read
    x @ W. Layer tensors are stacked along a leading L axis for lax.scan.
    """
    import numpy as np

    def t(name):
        w = state_dict[name]
        return np.asarray(w.to("cpu").float().numpy())

    L = cfg.num_layers

    if "model.layers.0.self_attn.qkv_proj.weight" in state_dict:
        # Phi-3 fuses qkv and gate_up; split into the canonical leaves so
        # one forward serves the family (HF Phi3Attention chunks in
        # q/k/v order, Phi3MLP in gate/up order).
        qd = cfg.num_heads * cfg.head_dim
        kvd = cfg.num_kv_heads * cfg.head_dim
        for l in range(L):
            qkv = state_dict[f"model.layers.{l}.self_attn.qkv_proj.weight"]
            state_dict[f"model.layers.{l}.self_attn.q_proj.weight"] = qkv[:qd]
            state_dict[f"model.layers.{l}.self_attn.k_proj.weight"] = (
                qkv[qd : qd + kvd]
            )
            state_dict[f"model.layers.{l}.self_attn.v_proj.weight"] = (
                qkv[qd + kvd :]
            )
            gu = state_dict[f"model.layers.{l}.mlp.gate_up_proj.weight"]
            half = gu.shape[0] // 2
            state_dict[f"model.layers.{l}.mlp.gate_proj.weight"] = gu[:half]
            state_dict[f"model.layers.{l}.mlp.up_proj.weight"] = gu[half:]

    def stack(fmt, transpose=True):
        ws = [t(fmt.format(l)) for l in range(L)]
        ws = [w.T if transpose else w for w in ws]
        return jnp.asarray(np.stack(ws), cfg.dtype)

    # Gemma2 renames the pre-MLP norm: post_attention_layernorm becomes a
    # POST-attention branch norm and pre_feedforward_layernorm takes the
    # pre-MLP role the Llama name implies.
    mlp_norm_name = (
        "model.layers.{}.pre_feedforward_layernorm.weight"
        if cfg.post_block_norms
        else "model.layers.{}.post_attention_layernorm.weight"
    )
    params = {
        "embed": jnp.asarray(t("model.embed_tokens.weight"), cfg.dtype),
        "layers": {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight", transpose=False),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            "mlp_norm": stack(mlp_norm_name, transpose=False),
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight"),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight"),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight"),
        },
        "final_norm": jnp.asarray(t("model.norm.weight"), cfg.dtype),
    }
    if cfg.qk_norm:
        params["layers"]["q_norm"] = stack(
            "model.layers.{}.self_attn.q_norm.weight", transpose=False
        )
        params["layers"]["k_norm"] = stack(
            "model.layers.{}.self_attn.k_norm.weight", transpose=False
        )
    if cfg.post_block_norms:
        params["layers"]["post_attn_norm"] = stack(
            "model.layers.{}.post_attention_layernorm.weight", transpose=False
        )
        params["layers"]["post_mlp_norm"] = stack(
            "model.layers.{}.post_feedforward_layernorm.weight",
            transpose=False,
        )
    if cfg.attention_bias:
        params["layers"]["bq"] = stack(
            "model.layers.{}.self_attn.q_proj.bias", transpose=False
        )
        params["layers"]["bk"] = stack(
            "model.layers.{}.self_attn.k_proj.bias", transpose=False
        )
        params["layers"]["bv"] = stack(
            "model.layers.{}.self_attn.v_proj.bias", transpose=False
        )
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(t("lm_head.weight").T, cfg.dtype)
    return params


def params_from_gguf(gguf_file, cfg: LlamaConfig) -> dict:
    """Load unquantized GGUF tensors into our layer-stacked pytree.

    GGUF (llama.cpp) names: token_embd, blk.{l}.{attn_norm, attn_q, attn_k,
    attn_v, attn_output, ffn_norm, ffn_gate, ffn_up, ffn_down},
    output_norm, output. Projections stored [out, in] -> transposed to
    [in, out] like params_from_torch_state_dict.

    llama-arch GGUFs carry q/k projections in llama.cpp's interleaved rope
    row order (the HF->GGUF converter permutes them); apply_rope here uses
    the HF half-split pairing, so those rows are permuted back on load.
    qwen2-arch GGUFs are not permuted by the converter.
    """
    import numpy as np

    g = gguf_file
    L = cfg.num_layers
    permute_qk = g.architecture() == "llama"

    def unpermute_rows(w: np.ndarray, n_head: int) -> np.ndarray:
        # inverse of convert_hf_to_gguf's permute():
        #   reshape(h, 2, d/2, in).swapaxes(1, 2)
        out, inn = w.shape
        d = out // n_head
        return (
            w.reshape(n_head, d // 2, 2, inn)
            .swapaxes(1, 2)
            .reshape(out, inn)
        )

    def t(name, transpose=True, rope_heads: Optional[int] = None):
        w = np.asarray(g.load_tensor(name), np.float32)
        if rope_heads is not None and permute_qk:
            w = unpermute_rows(w, rope_heads)
        return w.T if transpose else w

    def stack(fmt, transpose=True, rope_heads: Optional[int] = None):
        return jnp.asarray(
            np.stack(
                [t(fmt.format(l), transpose, rope_heads) for l in range(L)]
            ),
            cfg.dtype,
        )

    params = {
        "embed": jnp.asarray(t("token_embd.weight", transpose=False), cfg.dtype),
        "layers": {
            "attn_norm": stack("blk.{}.attn_norm.weight", transpose=False),
            "wq": stack("blk.{}.attn_q.weight", rope_heads=cfg.num_heads),
            "wk": stack("blk.{}.attn_k.weight", rope_heads=cfg.num_kv_heads),
            "wv": stack("blk.{}.attn_v.weight"),
            "wo": stack("blk.{}.attn_output.weight"),
            "mlp_norm": stack("blk.{}.ffn_norm.weight", transpose=False),
            "w_gate": stack("blk.{}.ffn_gate.weight"),
            "w_up": stack("blk.{}.ffn_up.weight"),
            "w_down": stack("blk.{}.ffn_down.weight"),
        },
        "final_norm": jnp.asarray(
            t("output_norm.weight", transpose=False), cfg.dtype
        ),
    }
    if cfg.attention_bias:  # qwen2-family GGUFs carry qkv biases
        params["layers"]["bq"] = stack("blk.{}.attn_q.bias", transpose=False)
        params["layers"]["bk"] = stack("blk.{}.attn_k.bias", transpose=False)
        params["layers"]["bv"] = stack("blk.{}.attn_v.bias", transpose=False)
    if cfg.qk_norm:  # qwen3/gemma3-family GGUFs carry per-head q/k norms
        params["layers"]["q_norm"] = stack(
            "blk.{}.attn_q_norm.weight", transpose=False
        )
        params["layers"]["k_norm"] = stack(
            "blk.{}.attn_k_norm.weight", transpose=False
        )
    if cfg.post_block_norms:  # gemma2/3 sandwich norms
        params["layers"]["post_attn_norm"] = stack(
            "blk.{}.post_attention_norm.weight", transpose=False
        )
        params["layers"]["post_mlp_norm"] = stack(
            "blk.{}.post_ffw_norm.weight", transpose=False
        )
    if "output.weight" in g.tensors:
        params["lm_head"] = jnp.asarray(t("output.weight"), cfg.dtype)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


#: the per-layer dense weights weight-only quantization covers
QUANTIZED_DENSE_NAMES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def init_params_int8(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Random-init directly into the int8 weight-only layout.

    `init_params` + `quantize_params_int8` materializes the full
    model-dtype weights first — 16GB for an 8B config, more than one
    v5e chip's HBM. Here every quantized dense weight is generated and
    quantized one layer at a time under lax.map, so peak transient
    memory is a single fp32 layer (~235MB for 8B); embeddings, norms and
    biases keep the base init. Output layout == quantize_params_int8's.
    """
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    qd, kvd = cfg.num_heads * cfg.head_dim, cfg.num_kv_heads * cfg.head_dim
    L = cfg.num_layers
    keys = jax.random.split(key, 10)

    def qdense(k, in_dim, out_dim):
        def one(kl):
            w = jax.random.normal(
                kl, (in_dim, out_dim), jnp.float32
            ) / math.sqrt(in_dim)
            return quantize_channelwise_int8(w)

        return jax.lax.map(one, jax.random.split(k, L))

    def dense(k, shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (
            jax.random.normal(k, shape, jnp.float32) * scale
        ).astype(cfg.dtype)

    layers: dict = {
        "attn_norm": jnp.ones((L, h), cfg.dtype),
        "mlp_norm": jnp.ones((L, h), cfg.dtype),
    }
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, cfg.head_dim), cfg.dtype)
        layers["k_norm"] = jnp.ones((L, cfg.head_dim), cfg.dtype)
    for name, k, din, dout in (
        ("wq", keys[1], h, qd), ("wk", keys[2], h, kvd),
        ("wv", keys[3], h, kvd), ("wo", keys[4], qd, h),
        ("w_gate", keys[5], h, i), ("w_up", keys[6], h, i),
        ("w_down", keys[7], i, h),
    ):
        q, s = qdense(k, din, dout)
        layers[name] = q
        layers[name + "_scale"] = s
    if cfg.attention_bias:
        layers["bq"] = jnp.zeros((L, qd), cfg.dtype)
        layers["bk"] = jnp.zeros((L, kvd), cfg.dtype)
        layers["bv"] = jnp.zeros((L, kvd), cfg.dtype)
    params = {
        "embed": dense(keys[0], (v, h), h),
        "layers": layers,
        "final_norm": jnp.ones((h,), cfg.dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense(keys[8], (h, v), h)
    return params


def quantize_params_int8(params: dict) -> dict:
    """Weight-only int8 quantization with per-output-channel symmetric
    scales, applied to the seven layer matmul weights (embed / lm_head /
    norms / biases stay in the model dtype). Decode on TPU is
    HBM-bandwidth-bound on weight reads; int8 halves that traffic vs
    bf16 — XLA streams the int8->bf16 convert + scale into the dot's
    operand read. Matmul helpers (`_mm`) dequantize transparently, so the
    same forward serves both layouts."""
    quant_one = quantize_channelwise_int8

    out = dict(params)
    layers = dict(params["layers"])
    if any(layers[n].dtype == jnp.int8 for n in QUANTIZED_DENSE_NAMES):
        raise ValueError(
            "params are already int8-quantized; re-quantizing would "
            "recompute scales from quantized values and corrupt the model"
        )
    for name in QUANTIZED_DENSE_NAMES:
        # lax.map over the stacked layer axis keeps the fp32 temporary at
        # one layer's size (a whole-tensor astype would briefly double the
        # biggest weight on one device before sharding).
        q, scale = jax.lax.map(quant_one, layers[name])
        layers[name] = q
        layers[name + "_scale"] = scale
    out["layers"] = layers
    return out


def _mm(x: jax.Array, lp: dict, name: str, dtype) -> jax.Array:
    """x @ lp[name], dequantizing int8 weights on the fly."""
    w = lp[name]
    if w.dtype == jnp.int8:
        return (x @ w.astype(dtype)) * lp[name + "_scale"][0].astype(dtype)
    return x @ w


def _w(lp: dict, name: str, dtype) -> jax.Array:
    """lp[name], dequantized when int8 — for weights consumed by einsum
    (the scale varies over non-factorable axes, so dequant first; XLA
    fuses the convert+scale into the consumer's operand read). Shared by
    every family (mla/moe expert stacks, wkv_b)."""
    w = lp[name]
    if w.dtype == jnp.int8:
        return w.astype(dtype) * lp[name + "_scale"].astype(dtype)
    return w.astype(dtype)


def quantize_channelwise_int8(w: jax.Array):
    """THE int8 scheme, shared by every family's quantize/init path:
    per-output-channel symmetric max-abs scales over a [in, out] weight.
    Returns (int8 weight, [1, out] f32 scale)."""
    wf = w.astype(jnp.float32)
    scale = jnp.maximum(
        jnp.max(jnp.abs(wf), axis=0, keepdims=True) / 127.0, 1e-8
    )
    return jnp.round(wf / scale).astype(jnp.int8), scale


def _l2_norm(x: jax.Array, eps: float) -> jax.Array:
    """Weightless RMS normalization (Llama-4's q/k norm)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype)


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, unit_offset: bool = False
) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if unit_offset:  # Gemma stores norm weights as deltas around 1
        w = w + 1.0
    return (out * w).astype(x.dtype)


def _rope_inv_freq(
    cfg: LlamaConfig,
    theta: Optional[float] = None,
    linear_factor: Optional[float] = None,
) -> jax.Array:
    """`theta` overrides cfg.rope_theta (Gemma3 local layers — the NTK
    path below never applies to an override); `linear_factor` divides
    every frequency, i.e. linear position scaling."""
    d = cfg.head_dim
    base = cfg.rope_theta if theta is None else theta
    inv_freq = 1.0 / (
        base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    )
    if linear_factor is not None:
        inv_freq = inv_freq / linear_factor
    if theta is not None:
        return inv_freq
    if cfg.rope_yarn_factor is not None:
        # YaRN (2309.00071): interpolate low-frequency slots by `factor`,
        # keep high-frequency slots, ramp between the correction bounds.
        f = cfg.rope_yarn_factor
        orig = cfg.rope_original_max_position

        def corr_dim(rot):
            return (
                d * math.log(orig / (rot * 2 * math.pi))
            ) / (2 * math.log(base))

        low = corr_dim(cfg.rope_yarn_beta_fast)
        high = corr_dim(cfg.rope_yarn_beta_slow)
        if cfg.rope_yarn_truncate:
            low, high = math.floor(low), math.ceil(high)
        low, high = max(low, 0), min(high, d - 1)
        if low == high:
            high += 0.001
        ramp = jnp.clip(
            (jnp.arange(d // 2, dtype=jnp.float32) - low) / (high - low),
            0.0, 1.0,
        )
        extrapolation_w = 1.0 - ramp
        return (inv_freq / f) * (1.0 - extrapolation_w) + (
            inv_freq * extrapolation_w
        )
    if cfg.rope_scaling_factor is not None:
        # Llama-3.1 NTK-by-parts scaling.
        low = cfg.rope_original_max_position / cfg.rope_low_freq_factor
        high = cfg.rope_original_max_position / cfg.rope_high_freq_factor
        wavelen = 2.0 * jnp.pi / inv_freq
        smooth = (cfg.rope_original_max_position / wavelen - cfg.rope_low_freq_factor) / (
            cfg.rope_high_freq_factor - cfg.rope_low_freq_factor
        )
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = inv_freq / cfg.rope_scaling_factor
        blended = (1.0 - smooth) * scaled + smooth * inv_freq
        inv_freq = jnp.where(wavelen > low, scaled, jnp.where(wavelen < high, inv_freq, blended))
    return inv_freq


def apply_rope(
    x: jax.Array,
    positions: jax.Array,
    cfg: LlamaConfig,
    inv_freq: Optional[jax.Array] = None,
) -> jax.Array:
    """x: [B, T, H, D]; positions: [B, T] absolute positions — or
    [3, B, T] m-RoPE streams (temporal, height, width) when
    cfg.mrope_section is set (Qwen2-VL; reference reaches this family
    only through vLLM — /root/reference examples/multimodal).
    `inv_freq` overrides the frequency table (Gemma3's per-layer-type
    selection, attention_block)."""
    default_table = inv_freq is None
    if inv_freq is None:
        inv_freq = _rope_inv_freq(cfg)
    if positions.ndim == 3:
        if not cfg.mrope_section:
            raise ValueError("[3,B,T] rope positions need cfg.mrope_section")
        # Each frequency section takes its angles from one position
        # stream; equal streams reduce to standard rope exactly.
        angles3 = positions[..., None].astype(jnp.float32) * inv_freq
        parts, off = [], 0
        for j, sec in enumerate(cfg.mrope_section):
            parts.append(angles3[j, ..., off : off + sec])
            off += sec
        angles = jnp.concatenate(parts, axis=-1)  # [B,T,D/2]
    else:
        angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,T,1,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    if default_table and cfg.rope_yarn_factor is not None:
        # YaRN attention factor scales the rotated vectors (HF convention:
        # cos/sin multiplied, so q·k scores scale by the factor squared)
        s = (
            cfg.rope_yarn_attention_factor
            if cfg.rope_yarn_attention_factor is not None
            else 0.1 * math.log(cfg.rope_yarn_factor) + 1.0
        )
        cos = cos * s
        sin = sin * s
    xf = x.astype(jnp.float32)
    if cfg.rope_interleaved:
        # Llama-4 / original-Llama pairing: (x[2i], x[2i+1]) rotate by
        # angle i (torch.view_as_complex semantics)
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        out = jnp.stack(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        ).reshape(x.shape)
        return out.astype(x.dtype)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def paged_scatter(
    cache: jax.Array,  # [L, P, S, ...] — the FULL stacked cache
    layer: jax.Array,  # scalar int32 layer index
    new: jax.Array,  # [B, T, ...] (KV rows [B,T,Hkv,D] or scale [B,T,Hkv])
    page_tables: jax.Array,  # [B, MP] int32
    positions: jax.Array,  # [B, T] int32
    valid: jax.Array,  # [B, T] bool
) -> jax.Array:
    """Write new KV for absolute `positions` into cache[layer]'s pages
    (the XLA fallback path; the Pallas impl stages writes and lands them
    with one DMA kernel per step instead — ops/kv_update.py). Trailing
    dims are generic: the same scatter lands KV rows and their quantized
    scale planes.

    Invalid (padding) slots are redirected to the null page 0 slot 0.

    The full cache goes in and comes out so the layer loop can carry it
    through `lax.scan`: a carried buffer is updated in place by the XLA
    while loop, so per-step HBM traffic is proportional to the tokens
    written — NOT to the cache size. (Emitting per-layer caches as scan
    outputs instead forces XLA to rewrite the entire pool every step —
    measured 2.6× slower at 512 pages and linear in num_pages. The
    slice-layer → 4D scatter → dynamic_update structure below keeps the
    carry aliasable; a direct 5D advanced-index scatter with the scalar
    layer index broke XLA's in-place update.)
    """
    page_size = cache.shape[2]
    page_of = positions // page_size  # [B,T] index into page table
    slot_of = positions % page_size
    page_ids = jnp.take_along_axis(page_tables, page_of, axis=1)  # [B,T]
    page_ids = jnp.where(valid, page_ids, 0)
    slot_of = jnp.where(valid, slot_of, 0)
    flat_pages = page_ids.reshape(-1)
    flat_slots = slot_of.reshape(-1)
    flat_new = new.reshape(-1, *new.shape[2:])  # [N, ...]
    layer_cache = lax.dynamic_index_in_dim(cache, layer, 0, keepdims=False)
    layer_cache = layer_cache.at[flat_pages, flat_slots].set(
        flat_new, mode="drop"
    )
    return lax.dynamic_update_index_in_dim(cache, layer_cache, layer, 0)


def paged_scatter_kv(
    kv: KVPages,
    layer: jax.Array,
    k_new: jax.Array,  # [B, T, Hkv, D] model-dtype rows
    v_new: jax.Array,
    page_tables: jax.Array,
    positions: jax.Array,
    valid: jax.Array,
) -> KVPages:
    """paged_scatter over the whole pool, quantizing on write when the
    pool is quantized (scales land next to their rows)."""
    if not kv.quantized:
        return kv._replace(
            k=paged_scatter(
                kv.k, layer, k_new.astype(kv.k.dtype), page_tables,
                positions, valid,
            ),
            v=paged_scatter(
                kv.v, layer, v_new.astype(kv.v.dtype), page_tables,
                positions, valid,
            ),
        )
    mode = "int8" if kv.k.dtype == jnp.int8 else "fp8"
    kq, ks = quantize_kv_rows(k_new, mode)
    vq, vs = quantize_kv_rows(v_new, mode)
    args = (page_tables, positions, valid)
    return KVPages(
        k=paged_scatter(kv.k, layer, kq, *args),
        v=paged_scatter(kv.v, layer, vq, *args),
        k_scale=paged_scatter(kv.k_scale, layer, ks, *args),
        v_scale=paged_scatter(kv.v_scale, layer, vs, *args),
    )


def paged_gather(
    cache: jax.Array, layer: jax.Array, page_tables: jax.Array
) -> jax.Array:
    """[L, P, S, ...] × [B, MP] -> [B, MP*S, ...], position-ordered.
    Trailing dims are generic (KV rows or their scale planes)."""
    g = jax.lax.dynamic_index_in_dim(
        cache, layer, axis=0, keepdims=False
    )[page_tables]  # [B, MP, S, ...]
    b, mp, s = g.shape[:3]
    return g.reshape(b, mp * s, *g.shape[3:])


def paged_gather_kv(
    kv: KVPages, layer: jax.Array, page_tables: jax.Array, dtype
) -> tuple[jax.Array, jax.Array]:
    """Gather + dequantize the paged history densely (the XLA fallback
    read path): returns (k, v) [B, MP*S, Hkv, D] in `dtype`. Quantized
    pools dequantize row-by-row against their gathered scale planes, so
    the xla/hybrid impls see exactly the values the flash kernels see."""
    k = paged_gather(kv.k, layer, page_tables)
    v = paged_gather(kv.v, layer, page_tables)
    if kv.quantized:
        ks = paged_gather(kv.k_scale, layer, page_tables)  # [B, K, Hkv]
        vs = paged_gather(kv.v_scale, layer, page_tables)
        return (
            dequantize_kv_rows(k, ks, dtype),
            dequantize_kv_rows(v, vs, dtype),
        )
    return k.astype(dtype), v.astype(dtype)


def paged_attention(
    q: jax.Array,  # [B, T, Hq, D] (post-rope)
    k_pages: jax.Array,  # [B, K, Hkv, D] gathered, position-ordered
    v_pages: jax.Array,  # [B, K, Hkv, D]
    q_positions: jax.Array,  # [B, T]
    cfg: LlamaConfig,
    key_positions: Optional[jax.Array] = None,  # [B, K]; default arange(K)
    window: Optional[jax.Array] = None,  # scalar: keys within (q_pos-w, q_pos]
    sinks: Optional[jax.Array] = None,  # [Hq] per-head sink logits
) -> jax.Array:
    """Reference paged attention (XLA path; the Pallas decode kernel in
    dynamo_tpu.ops replaces this for T=1 when cfg.attention_impl="pallas").

    Causality over the whole paged history: key at gathered index i has
    absolute position i (or key_positions when given), so the mask is
    simply key_pos <= q_pos. Unallocated page-table slots sit at positions
    >= seq_len and are masked by the same comparison. `window` (a traced
    scalar — Gemma2's per-layer local attention) additionally drops keys
    older than q_pos - window + 1.
    """
    b, t, hq, d = q.shape
    kk = k_pages.shape[1]
    g = cfg.q_per_kv
    qg = q.reshape(b, t, cfg.num_kv_heads, g, d)
    scale = 1.0 / math.sqrt(cfg.query_pre_attn_scalar or d)
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg.astype(jnp.float32), k_pages.astype(jnp.float32)
    ) * scale
    if cfg.attn_logit_softcap:
        c = cfg.attn_logit_softcap
        scores = c * jnp.tanh(scores / c)
    if key_positions is None:
        key_pos = jnp.arange(kk)[None, None, None, None, :]
    else:
        key_pos = key_positions[:, None, None, None, :]
    q_pos = q_positions[:, None, None, :, None]
    mask = key_pos <= q_pos
    if window is not None:
        if getattr(window, "ndim", 0) == 2:
            # per-query window [B, T] (Llama-4 chunked attention)
            window = window[:, None, None, :, None]
        mask = mask & (key_pos > q_pos - window)
    scores = jnp.where(mask, scores, -1e30)
    if sinks is not None:
        # GPT-OSS attention sinks: a learned per-head logit joins the
        # softmax denominator (equivalently: softmax over [scores, sink]
        # with the sink column dropped)
        sk = sinks.astype(jnp.float32).reshape(cfg.num_kv_heads, g)[
            None, :, :, None, None
        ]
        m = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), sk)
        e = jnp.exp(scores - m)
        probs = e / (jnp.sum(e, axis=-1, keepdims=True) + jnp.exp(sk - m))
    else:
        probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v_pages.astype(jnp.float32))
    return out.reshape(b, t, hq * d).astype(q.dtype)


def _chunk_only_attention(q, k, v, positions, valid, cfg, dpad, mesh=None,
                          window=None, sinks=None):
    """First-chunk fast path: no history exists, so attend over the
    in-register chunk only — skips the O(MP·S) page gather and the
    attention over its padding. Invalid (padding) keys are pushed past
    every query position.

    Long-context: under a mesh with an sp axis, the chunk's causal
    attention runs as ring attention over the sequence shards (ICI
    ppermute of K/V blocks — parallel/context.py), so a prompt too long
    for one chip's attention memory prefills across the sp group. Valid
    first-chunk positions are contiguous from 0, so index-causal masking
    equals position masking; padding sits past every valid query.

    Under attention_impl="pallas" (and no sp ring), the chunk runs the
    flash kernel (ops/flash_prefill.py): online softmax in VMEM instead
    of materializing [B, H, T, T] fp32 scores in HBM."""
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    t = q.shape[1]
    if sp > 1 and t % sp == 0 and t > 1:
        if window is not None or sinks is not None:
            raise ValueError(
                "sliding-window / sink attention (Gemma2, GPT-OSS) is not "
                "implemented for the sp ring-attention path — run with sp=1"
            )
        if dpad:
            k = k[..., : cfg.head_dim]
            v = v[..., : cfg.head_dim]
        from dynamo_tpu.parallel.context import ring_attention

        out = ring_attention(
            q, k, v, mesh=mesh, causal=True,
            batch_axis="dp" if mesh.shape.get("dp", 1) > 1 else None,
            head_axis="tp" if mesh.shape.get("tp", 1) > 1 else None,
        )
        b, _, hq, d = q.shape
        return out.reshape(b, t, hq * d)
    if cfg.attention_impl in ("pallas", "hybrid"):
        from dynamo_tpu.ops.flash_prefill import flash_prefill_attention

        qp = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, dpad))) if dpad else q
        valid_len = jnp.sum(valid, axis=1).astype(jnp.int32)
        out = flash_prefill_attention(
            qp, k, v, valid_len, scale_dim=cfg.head_dim, mesh=mesh
        )
        if dpad:
            out = out[..., : cfg.head_dim]
        b, _, hq, d = q.shape
        return out.reshape(b, t, hq * cfg.head_dim).astype(q.dtype)
    if dpad:
        k = k[..., : cfg.head_dim]
        v = v[..., : cfg.head_dim]
    cur_pos = jnp.where(valid, positions, jnp.int32(1 << 30))
    return paged_attention(
        q, k, v, positions, cfg, key_positions=cur_pos, window=window,
        sinks=sinks,
    )


#: route decode to the XLA gather past this kernel VMEM estimate rather
#: than letting Mosaic fail allocation (v5e VMEM is 16 MiB; leave head-
#: room for Mosaic's own buffers)
_PALLAS_DECODE_VMEM_BUDGET = 12 << 20
#: shapes whose explicit-pallas VMEM reroute was already warned about
_warned_vmem_reroute: set = set()


def maybe_decode_work(cfg, tokens, positions, kv, page_tables):
    """The decode kernel's (sequence, page) work list is LAYER-INVARIANT:
    build it once per step, outside the layer scan (XLA won't reliably
    hoist the sort out of the loop). Shared by the Llama and MoE forward
    passes; None whenever the step can't take the kernel path."""
    if tokens.shape[1] != 1 or cfg.attention_impl not in (
        "pallas", "hybrid"
    ):
        return None
    from dynamo_tpu.ops.paged_attention import decode_work_list

    return decode_work_list(page_tables, positions[:, 0], kv.k.shape[2])


def attention_block(
    q: jax.Array,  # [B, T, Hq, D] pre-rope
    k: jax.Array,  # [B, T, Hkv, D] pre-rope
    v: jax.Array,  # [B, T, Hkv, D]
    kv: KVPages,  # full stacked cache (+ scale planes when quantized)
    layer: jax.Array,  # scalar int32
    page_tables: jax.Array,  # [B, MP] int32
    positions: jax.Array,  # [B, T] int32
    valid: jax.Array,  # [B, T] bool
    cfg: LlamaConfig,
    first_chunk: bool = False,
    mesh=None,
    decode_work=None,  # precomputed ops.paged_attention.decode_work_list
    rope_positions=None,  # [3,B,T] m-RoPE streams; None = positions
    sinks=None,  # [Hq] GPT-OSS per-head sink logits
):
    """rope → paged attention, in one of two write disciplines:

    - "xla": scatter this layer's KV into the cache, then gather + dense
      attention. Works on any backend and under any mesh.
    - "pallas": the cache is READ-ONLY here (history); this layer's KV is
      returned as `staged` for the layer scan to stack, and the engine step
      lands all layers with one DMA kernel (ops/kv_update.paged_write).
      Decode (T==1) runs the flash kernel + exact current-token merge;
      prefill attends to history pages + the in-register current chunk.

    Quantized pools (kv.quantized): the xla discipline quantizes on
    scatter and dequantizes on gather; the pallas discipline stages
    model-dtype KV (the write kernel quantizes) and the flash kernels
    dequantize each page in VMEM right after its DMA lands.

    Returns (attn [B,T,Hq*head_dim], kv, staged) where staged is None
    (xla) or ([B,T,Hkv,Dpad], [B,T,Hkv,Dpad]).
    Handles the cache's lane padding (cfg.kv_head_dim) transparently.
    """
    b, t = q.shape[0], q.shape[1]
    rp = positions if rope_positions is None else rope_positions
    # Gemma3's every-Nth-layer-global predicate, shared by the rope theta
    # selection and the window selection below (`layer` is a traced scan
    # carry, so this is a traced scalar bool)
    is_global = (
        (layer + 1) % cfg.sliding_global_every == 0
        if cfg.sliding_global_every
        else None
    )
    # Llama-4 NoPE: every Nth layer skips rope entirely (traced bool)
    use_rope = (
        (layer + 1) % cfg.nope_every != 0 if cfg.nope_every else None
    )
    if cfg.rope_local_theta is not None:
        # Gemma3: global layers rope at rope_theta (with optional linear
        # scaling), local layers at rope_local_theta — select between the
        # two tiny [D/2] frequency tables, one rope application each.
        inv_freq = jnp.where(
            is_global,
            _rope_inv_freq(cfg, linear_factor=cfg.rope_linear_factor),
            _rope_inv_freq(cfg, theta=cfg.rope_local_theta),
        )
        q = apply_rope(q, rp, cfg, inv_freq=inv_freq)
        k = apply_rope(k, rp, cfg, inv_freq=inv_freq)
    else:
        rq = apply_rope(q, rp, cfg)
        rk = apply_rope(k, rp, cfg)
        if cfg.qk_l2_norm:
            # Llama-4: weightless L2 norm AFTER rope, rope layers only
            rq = _l2_norm(rq, cfg.rms_norm_eps)
            rk = _l2_norm(rk, cfg.rms_norm_eps)
        if use_rope is None:
            q, k = rq, rk
        else:
            q = jnp.where(use_rope, rq, q)
            k = jnp.where(use_rope, rk, k)
            if cfg.attn_temperature_tuning:
                # arXiv 2501.19399 temperature tuning on NoPE layers
                scales = (
                    jnp.log1p(
                        jnp.floor(
                            (positions.astype(jnp.float32) + 1.0)
                            / cfg.attn_floor_scale
                        )
                    )
                    * cfg.attn_scale_coef
                    + 1.0
                )  # [B, T]
                q = jnp.where(
                    use_rope,
                    q,
                    (q.astype(jnp.float32) * scales[..., None, None]).astype(
                        q.dtype
                    ),
                )
    dpad = cfg.kv_head_dim - cfg.head_dim
    if dpad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, 0), (0, dpad)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, dpad)))

    # Local attention (Gemma2 alternation / Mistral all-layers): affected
    # layers see only the trailing window. A traced scalar per scan step —
    # the mask comparison absorbs it with no extra program variants.
    window = None
    if cfg.sliding_window:
        if is_global is not None:
            # Gemma3: every Nth layer is GLOBAL, the rest are local
            window = jnp.where(
                is_global,
                jnp.int32(1 << 30), jnp.int32(cfg.sliding_window),
            )
        else:
            window = jnp.where(
                layer % cfg.sliding_window_every == 0,
                jnp.int32(cfg.sliding_window), jnp.int32(1 << 30),
            )
    elif cfg.attention_chunk:
        # Llama-4 chunked attention ≡ a PER-QUERY window of
        # (pos % chunk) + 1 on rope layers; NoPE layers attend globally
        wq = positions % cfg.attention_chunk + 1  # [B, T]
        if use_rope is not None:
            wq = jnp.where(use_rope, wq, jnp.int32(1 << 30))
        window = wq
    if cfg.attention_impl in ("pallas", "hybrid") and (
        cfg.sliding_window
        or cfg.attn_logit_softcap
        or cfg.attention_chunk
        or cfg.nope_every
        or cfg.attn_sinks
        or (
            cfg.query_pre_attn_scalar is not None
            and cfg.query_pre_attn_scalar != cfg.head_dim
        )
    ):
        raise ValueError(
            "sliding-window / softcap / rescaled / chunked / NoPE / "
            "sink attention (Gemma2, Llama-4, GPT-OSS) requires "
            "attention_impl='xla' — the flash kernels don't implement them"
        )

    if cfg.attention_impl not in ("pallas", "hybrid"):
        kv = paged_scatter_kv(
            kv, layer, k, v, page_tables, positions, valid
        )
        if first_chunk and t > 1:
            attn = _chunk_only_attention(
                q, k, v, positions, valid, cfg, dpad, mesh=mesh,
                window=window, sinks=sinks,
            )
            return attn, kv, None
        k_all, v_all = paged_gather_kv(kv, layer, page_tables, cfg.dtype)
        if dpad:
            k_all = k_all[..., : cfg.head_dim]
            v_all = v_all[..., : cfg.head_dim]
        attn = paged_attention(
            q, k_all, v_all, positions, cfg, window=window, sinks=sinks
        )
        return attn, kv, None

    from dynamo_tpu.ops.paged_attention import (
        decode_vmem_bytes,
        paged_decode_attention,
    )

    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    kernel_vmem = decode_vmem_bytes(
        b, cfg.num_heads // tp, cfg.kv_head_dim, kv.k.shape[2],
        cfg.num_kv_heads // tp or 1, jnp.dtype(kv.k.dtype).itemsize,
        quantized=kv.quantized,
    )
    if t == 1 and (
        (cfg.attention_impl == "hybrid" and b > cfg.pallas_decode_max_batch)
        or kernel_vmem > _PALLAS_DECODE_VMEM_BUDGET
    ):
        # Two routes to the dense gather: (a) hybrid's large-batch policy
        # (the gather reads ~the same HBM bytes in a handful of fused XLA
        # ops), (b) the flattened kernel's whole-batch VMEM blocks would
        # overflow — route instead of letting Mosaic fail allocation.
        if (
            cfg.attention_impl == "pallas"
            and kernel_vmem > _PALLAS_DECODE_VMEM_BUDGET
            and (key := (b, cfg.num_heads // tp, kv.k.shape[2]))
            not in _warned_vmem_reroute
        ):
            # An explicit pallas request silently running the XLA gather
            # is the measured-the-wrong-kernel hazard: say so at trace
            # time (same severity as the registry coercions). Once per
            # shape, not once per layer per retrace.
            _warned_vmem_reroute.add(key)
            logging.getLogger(__name__).warning(
                "attention_impl='pallas' rerouted to the XLA gather: "
                "decode kernel needs ~%.1f MiB VMEM (budget %.0f MiB) at "
                "b=%d heads=%d S=%d — shrink batch, page size, or "
                "heads-per-chip (tp) to keep the Pallas path",
                kernel_vmem / 2**20, _PALLAS_DECODE_VMEM_BUDGET / 2**20,
                b, cfg.num_heads // tp, kv.k.shape[2],
            )
        attn = _xla_history_attention(
            q, k, v, kv, layer, page_tables, positions, valid, cfg, dpad,
        )
    elif t == 1:
        hist = positions[:, 0]  # tokens already in the cache
        qd = q[:, 0]
        if dpad:
            qd = jnp.pad(qd, ((0, 0), (0, 0), (0, dpad)))
        acc, m, l = paged_decode_attention(
            qd, kv.k, kv.v, layer, page_tables, hist,
            scale_dim=cfg.head_dim, mesh=mesh, work_list=decode_work,
            k_scale=kv.k_scale, v_scale=kv.v_scale,
        )  # acc [B,Hq,Dpad] unnormalized, m/l [B,Hq]
        # Exact merge of the current (unwritten) token: self-attention
        # score s = q·k_cur/√d folded into the flash running state.
        g = cfg.q_per_kv
        kv_of = jnp.arange(cfg.num_heads) // g  # [Hq]
        k_sel = k[:, 0, kv_of]  # [B, Hq, Dpad]
        v_sel = v[:, 0, kv_of].astype(jnp.float32)
        scale = 1.0 / math.sqrt(cfg.head_dim)
        s_self = jnp.sum(
            qd.astype(jnp.float32) * k_sel.astype(jnp.float32), axis=-1
        ) * scale  # [B, Hq]
        m_star = jnp.maximum(m, s_self)
        alpha = jnp.exp(m - m_star)
        beta = jnp.exp(s_self - m_star)
        out = (alpha[..., None] * acc + beta[..., None] * v_sel) / (
            alpha * l + beta
        )[..., None]
        out = out.astype(cfg.dtype)
        if dpad:
            out = out[..., : cfg.head_dim]
        attn = out.reshape(b, cfg.num_heads * cfg.head_dim)[:, None, :]
    elif first_chunk:
        attn = _chunk_only_attention(
            q, k, v, positions, valid, cfg, dpad, mesh=mesh
        )
    elif t <= 1024:
        # Prefill chunk with history: paged pages (positions < chunk
        # start) + the current chunk, one online softmax — the flash
        # kernel walks pages with double-buffered DMA instead of
        # materializing the gathered history densely in HBM. The kernel
        # holds the whole current chunk's K/V in VMEM per grid cell, so
        # very large chunks (t > 1024) take the XLA path below instead of
        # oversubscribing VMEM.
        from dynamo_tpu.ops.flash_prefill import paged_prefill_attention

        qp = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, dpad))) if dpad else q
        start = positions[:, 0]
        hist_lens = jnp.where(valid[:, 0], start, 0).astype(jnp.int32)
        cur_lens = jnp.sum(valid, axis=1).astype(jnp.int32)
        out = paged_prefill_attention(
            qp, k, v, kv.k, kv.v, layer, page_tables,
            hist_lens, cur_lens, scale_dim=cfg.head_dim, mesh=mesh,
            k_scale=kv.k_scale, v_scale=kv.v_scale,
        )
        if dpad:
            out = out[..., : cfg.head_dim]
        attn = out.reshape(b, t, cfg.num_heads * cfg.head_dim).astype(q.dtype)
    else:
        attn = _xla_history_attention(
            q, k, v, kv, layer, page_tables, positions, valid, cfg, dpad,
        )
    return attn, kv, (k, v)


def _xla_history_attention(
    q, k, v, kv, layer, page_tables, positions, valid, cfg, dpad
):
    """Gather-then-attend fallback for history chunks too large for the
    flash kernel's VMEM budget (dequantizes quantized pools on gather)."""
    k_hist, v_hist = paged_gather_kv(kv, layer, page_tables, k.dtype)
    kk = k_hist.shape[1]
    start = positions[:, 0]
    hist_pos = jnp.arange(kk, dtype=jnp.int32)[None, :]
    # Mask unwritten (>= chunk start) gathered slots outright.
    hist_pos = jnp.where(
        hist_pos < start[:, None], hist_pos, jnp.int32(1 << 30)
    )
    cur_pos = jnp.where(valid, positions, jnp.int32(1 << 30))
    keys = jnp.concatenate([k_hist, k], axis=1)
    vals = jnp.concatenate([v_hist, v], axis=1)
    key_positions = jnp.concatenate([hist_pos, cur_pos], axis=1)
    if dpad:
        keys = keys[..., : cfg.head_dim]
        vals = vals[..., : cfg.head_dim]
    return paged_attention(
        q, keys, vals, positions, cfg, key_positions=key_positions
    )


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def forward_hidden(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, T] int32
    positions: jax.Array,  # [B, T] int32 absolute positions (padding: any)
    valid: jax.Array,  # [B, T] bool — which (b,t) are real tokens
    kv: KVPages,
    page_tables: jax.Array,  # [B, MP] int32
    mm_embeds: Optional[jax.Array] = None,  # [B, T, H] multimodal embeds
    mm_mask: Optional[jax.Array] = None,  # [B, T] bool — use mm_embeds here
    first_chunk: bool = False,  # static: every row starts at position 0
    mesh=None,  # tp mesh: the Pallas kernels shard_map over it
    rope_positions: Optional[jax.Array] = None,  # [3,B,T] m-RoPE streams
) -> tuple[jax.Array, KVPages]:
    """One model step over a token chunk; returns (hidden [B,T,H] post final
    norm, new kv). The engine applies `compute_logits` only at the positions
    it samples from — for a 512-token prefill chunk the full-chunk lm_head
    matmul would otherwise dominate the step's FLOPs.

    Covers prefill (T = chunk), decode (T = 1), and prefix-cache continuation
    (positions start past 0) uniformly. Multimodal (llava-style) prompts
    pass projected image embeddings in mm_embeds; where mm_mask is True
    they replace the token-id embedding lookup (the placeholder ids under
    the mask are ignored).

    The fused K-step decode window (EngineConfig.decode_kstep) calls
    this inside a lax.scan with per-iteration valid masks: rows frozen
    mid-window keep the same [B, 1] shapes and their paged_write lanes
    redirect to the null page (valid=False contract in ops/kv_update),
    so the whole window lowers to ONE XLA program with no host in the
    loop.
    """
    h = params["embed"][tokens].astype(cfg.dtype)  # [B,T,H]
    if mm_embeds is not None:
        h = jnp.where(mm_mask[..., None], mm_embeds.astype(cfg.dtype), h)
    if cfg.scale_embeddings:  # Gemma: normalizer cast to the model dtype
        h = h * jnp.asarray(math.sqrt(cfg.hidden_size), cfg.dtype)
    off = cfg.rms_norm_unit_offset
    if cfg.hidden_act == "silu":
        act = jax.nn.silu
    elif cfg.hidden_act == "gelu_tanh":
        act = partial(jax.nn.gelu, approximate=True)
    else:
        raise ValueError(f"unknown hidden_act {cfg.hidden_act!r}")

    decode_work = maybe_decode_work(cfg, tokens, positions, kv, page_tables)

    def layer(carry, xs):
        h, kvc = carry
        lp, li = xs
        x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps, off)
        b, t, _ = x.shape
        q = _mm(x, lp, "wq", cfg.dtype)
        k = _mm(x, lp, "wk", cfg.dtype)
        v = _mm(x, lp, "wv", cfg.dtype)
        if cfg.attention_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(b, t, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
        if cfg.qk_norm:  # Qwen3: head_dim-wide RMSNorm pre-rope
            q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps, off)
            k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps, off)
        attn, kvc, staged = attention_block(
            q, k, v, kvc, li, page_tables, positions, valid, cfg,
            first_chunk=first_chunk, mesh=mesh, decode_work=decode_work,
            rope_positions=rope_positions,
        )
        attn_out = _mm(attn, lp, "wo", cfg.dtype)
        if cfg.post_block_norms:  # Gemma2: norm the branch, then residual
            attn_out = rms_norm(
                attn_out, lp["post_attn_norm"], cfg.rms_norm_eps, off
            )
        h = h + attn_out
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps, off)
        gate = act(_mm(x, lp, "w_gate", cfg.dtype).astype(jnp.float32))
        up = _mm(x, lp, "w_up", cfg.dtype).astype(jnp.float32)
        mlp_out = _mm((gate * up).astype(cfg.dtype), lp, "w_down", cfg.dtype)
        if cfg.post_block_norms:
            mlp_out = rms_norm(
                mlp_out, lp["post_mlp_norm"], cfg.rms_norm_eps, off
            )
        h = h + mlp_out
        return (h, kvc), staged

    (h, kv_new), staged = lax.scan(
        layer,
        (h, kv),
        (params["layers"], jnp.arange(cfg.num_layers, dtype=jnp.int32)),
    )
    kv_new = land_staged_kv(
        kv_new, staged, page_tables, positions, valid, mesh=mesh
    )
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps, off)
    return h, kv_new


def land_staged_kv(
    kv: KVPages, staged, page_tables, positions, valid, mesh=None
) -> KVPages:
    """Land a layer scan's staged KV (pallas write discipline) in one DMA
    kernel call; no-op under the xla scatter discipline (staged is None).
    Quantized pools quantize inside the page writer (the staged arrays
    are model-dtype). Shared by the Llama and MoE forward passes."""
    if staged is None:
        return kv
    from dynamo_tpu.ops.kv_update import paged_write

    out = paged_write(
        kv.k, kv.v, staged[0], staged[1], page_tables, positions,
        valid, mesh=mesh, k_scale=kv.k_scale, v_scale=kv.v_scale,
    )
    if kv.quantized:
        return KVPages(*out)
    return kv._replace(k=out[0], v=out[1])


def compute_logits(params: dict, cfg: LlamaConfig, hidden: jax.Array) -> jax.Array:
    """Project hidden states [..., H] to vocab logits [..., V] in f32."""
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = params["embed"].T
    logits = (hidden @ lm_head).astype(jnp.float32)
    if cfg.final_logit_softcap:  # Gemma2
        c = cfg.final_logit_softcap
        logits = c * jnp.tanh(logits / c)
    return logits


def forward(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,
    positions: jax.Array,
    valid: jax.Array,
    kv: KVPages,
    page_tables: jax.Array,
    **kw,
) -> tuple[jax.Array, KVPages]:
    """forward_hidden + full-chunk logits (tests/tools; engine uses the
    split form to avoid the all-positions lm_head matmul)."""
    h, kv = forward_hidden(
        params, cfg, tokens, positions, valid, kv, page_tables, **kw
    )
    return compute_logits(params, cfg, h), kv

"""DeepSeek-V2-style MLA (multi-head latent attention) + DeepSeek MoE.

The reference's flagship scale example serves DeepSeek models through
SGLang with DeepEP (examples/sglang/dsr1-wideep.md); here the
architecture is first-class TPU, built on the same paged-cache contract
as the Llama family — with the cache holding the COMPRESSED latent:

  cache.k: [L, P, S, 1, kv_lora_rank]      c_kv  (latent KV, pre-norm'd)
  cache.v: [L, P, S, 1, qk_rope_head_dim]  k_pe  (shared rope key)

Per token the cache costs kv_lora+rope floats (576 for V2 shapes) —
~9x smaller than the equivalent MHA cache — and every generic subsystem
(page allocator, prefix caching, tiering, disagg transfer) carries it
unchanged because they treat KVPages as opaque pages.

Attention runs in the ABSORBED form (the deployment form from the
DeepSeek-V2 paper): q_nope is projected by W_UK^T into the latent space
so scores dot directly with the cached latent, and the value projection
W_UV is applied AFTER the probability-weighted latent sum — no per-token
decompression of the history, FLOPs independent of kv_b:

  q_lat  = q_nope @ W_UK          [B,T,H,c]
  score  = q_lat . c_hist + q_pe . k_pe_hist    (scale 1/sqrt(nope+rope))
  o_lat  = softmax(score) . c_hist
  attn   = (o_lat @ W_UV) reshaped @ W_O

RoPE here is the DeepSeek complex-interleaved pairing (adjacent elements
(x[2j], x[2j+1]) rotate together — modeling_deepseek_v2.apply_rotary_emb)
— NOT the Llama half-split. DeepSeek-YaRN rope scaling is implemented
(interp/extrap frequency ramp; the attention factor scales the rotary
cos/sin, and V3/R1 configs additionally scale the softmax by
yarn_mscale(factor, mscale_all_dim)^2 — both matching HF).

MoE layers follow HF DeepseekV2MoE semantics: softmax gate -> greedy
top-k (weights NOT renormalized unless norm_topk_prob) scaled by
routed_scaling_factor, plus always-on shared experts. Routed experts use
the same static-shape GShard dispatch/combine as models/moe.py, with the
expert axis sharded over the mesh's "ep" axis. The first
`first_k_dense_replace` layers use a dense MLP (V2-Lite: layer 0) — the
layer stack is two lax.scans (dense prefix, MoE suffix), keeping params
scan-stacked without per-layer Python unrolling.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.models.llama import (
    KVPages,
    _mm,
    _w,
    paged_gather,
    paged_scatter,
    quantize_channelwise_int8,
    rms_norm,
)

#: weight names quantized by quantize_params_int8 / init_params_int8
#: (w_router stays UNquantized in the base dtype — the gate matmul
#: upcasts it to f32; norms/embeds keep the base dtype too)
_QUANT_2D = (
    "wq", "wq_a", "wq_b", "wkv_a", "wkv_b", "wo",
    "w_gate", "w_up", "w_down", "ws_gate", "ws_up", "ws_down",
)
_QUANT_EXPERTS = ("we_gate", "we_up", "we_down")  # [L, E, in, out]


@dataclass(frozen=True)
class MlaConfig:
    vocab_size: int = 256
    hidden_size: int = 64
    intermediate_size: int = 128  # dense layers' MLP width
    num_layers: int = 2
    num_heads: int = 4
    q_lora_rank: Optional[int] = None  # None: direct q projection (V2-Lite)
    kv_lora_rank: int = 32
    qk_nope_head_dim: int = 16
    qk_rope_head_dim: int = 8
    v_head_dim: int = 16
    rope_theta: float = 10000.0
    #: DeepSeek-YaRN rope scaling (None disables): matches HF's
    #: _compute_yarn_parameters + the V2/V3 practice of scaling the
    #: rotary cos/sin by the attention factor
    rope_scaling_factor: Optional[float] = None
    rope_beta_fast: float = 32.0
    rope_beta_slow: float = 1.0
    rope_mscale: Optional[float] = None
    rope_mscale_all_dim: Optional[float] = None
    rope_original_max_position: int = 4096
    #: V3/R1: softmax scale additionally multiplies by
    #: yarn_mscale(factor, mscale_all_dim)^2 (DeepseekV3Attention); the
    #: integrated HF V2 port does NOT, so V2 configs default False — but
    #: deepseek-ai's ORIGINAL remote code applies it for V2 too; set True
    #: to match such a checkpoint's training-time semantics
    rope_mscale_softmax: bool = False
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    attention_impl: str = "xla"  # only the XLA path exists for MLA
    # -- MoE (None/0 experts = dense model) --------------------------------
    n_routed_experts: int = 0
    n_shared_experts: int = 0
    moe_intermediate_size: int = 0
    num_experts_per_tok: int = 2
    first_k_dense_replace: int = 1
    routed_scaling_factor: float = 1.0
    norm_topk_prob: bool = False
    capacity_factor: float = 2.0
    #: routed experts computed per lax.map step in the MoE FFN: bounds the
    #: f32 expert intermediates (xe/gate/up/down) AND the dequantized
    #: int8 expert weights to one group's worth instead of all E at once
    #: — the all-at-once temps (264M+192M+132M at V2-Lite decode shapes)
    #: OOM'd a v5e chip. 0 = auto-size groups to ~_MOE_CHUNK_BYTES.
    moe_expert_chunk: int = 0
    #: "greedy" (V2-Lite), "group_limited_greedy" (V2/V2-Chat), or
    #: "noaux_tc" (V3/R1: sigmoid scores + aux-loss-free bias-corrected
    #: group routing). Groups rank by max member (V2) / top-2 sum (V3) of
    #: the (bias-corrected, V3) scores; top-k selects within the winning
    #: groups; V3 weights come from the UNcorrected sigmoid scores
    topk_method: str = "greedy"
    n_group: int = 1
    topk_group: int = 1

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def softmax_scale(self) -> float:
        s = 1.0 / math.sqrt(self.qk_head_dim)
        if (
            self.rope_mscale_softmax
            and self.rope_scaling_factor
            and self.rope_scaling_factor > 1
            and self.rope_mscale_all_dim
        ):
            m = (
                0.1 * self.rope_mscale_all_dim
                * math.log(self.rope_scaling_factor) + 1.0
            )
            s *= m * m
        return s

    @property
    def cache_dim(self) -> int:
        return self.kv_lora_rank + self.qk_rope_head_dim

    @property
    def num_kv_heads(self) -> int:
        """MLA stores ONE shared latent per token (MQA-shaped cache)."""
        return 1

    @property
    def mqa_latent_cache(self) -> bool:
        """The cache REPLICATES over tp (kv_cache_spec(shard_heads=False))
        — the engine skips its kv-head tp-divisibility check for us."""
        return True

    @property
    def num_dense_layers(self) -> int:
        if not self.n_routed_experts:
            return self.num_layers
        return min(self.first_k_dense_replace, self.num_layers)

    @property
    def num_moe_layers(self) -> int:
        return self.num_layers - self.num_dense_layers

    @staticmethod
    def deepseek_v2_lite() -> "MlaConfig":
        """DeepSeek-V2-Lite (15.7B total / 2.4B active): MLA with direct q,
        layer 0 dense, 26 MoE layers of 64 routed (top-6, greedy) + 2
        shared experts. Plain-rope shape for random-weight benching; real
        checkpoints load their YaRN fields from config.json."""
        return MlaConfig(
            vocab_size=102400, hidden_size=2048, intermediate_size=10944,
            num_layers=27, num_heads=16, q_lora_rank=None,
            kv_lora_rank=512, qk_nope_head_dim=128, qk_rope_head_dim=64,
            v_head_dim=128, rope_theta=10000.0,
            n_routed_experts=64, n_shared_experts=2,
            moe_intermediate_size=1408, num_experts_per_tok=6,
            first_k_dense_replace=1,
        )

    @staticmethod
    def tiny(vocab_size: int = 256) -> "MlaConfig":
        return MlaConfig(vocab_size=vocab_size, dtype=jnp.float32)

    @staticmethod
    def tiny_moe(vocab_size: int = 256) -> "MlaConfig":
        return MlaConfig(
            vocab_size=vocab_size, dtype=jnp.float32, num_layers=3,
            n_routed_experts=4, n_shared_experts=1,
            moe_intermediate_size=32, num_experts_per_tok=2,
            first_k_dense_replace=1, capacity_factor=4.0,
        )

    @staticmethod
    def from_hf_config(hf: dict) -> "MlaConfig":
        rs = hf.get("rope_scaling") or {}
        if rs and rs.get("rope_type", rs.get("type")) != "yarn":
            raise ValueError(
                f"unsupported rope_scaling {rs!r} for DeepSeek (only "
                "yarn is implemented)"
            )
        if rs and rs.get("factor") is None:
            raise ValueError(
                "yarn rope_scaling needs an explicit 'factor'"
            )
        v3 = (
            hf.get("model_type") == "deepseek_v3"
            or "DeepseekV3ForCausalLM" in (hf.get("architectures") or [])
        )
        topk_method = hf.get("topk_method") or (
            "noaux_tc" if v3 else "greedy"
        )
        if topk_method not in (
            "greedy", "group_limited_greedy", "noaux_tc"
        ):
            raise ValueError(f"unsupported topk_method {topk_method!r}")
        if topk_method in ("group_limited_greedy", "noaux_tc"):
            ng = int(hf.get("n_group") or 1)
            tg = int(hf.get("topk_group") or 1)
            ne = int(hf.get("n_routed_experts") or 0)
            # fail at load with a named error, not at trace with a shape one
            if ne % max(ng, 1) or tg > ng:
                raise ValueError(
                    f"{topk_method} needs n_group ({ng}) dividing "
                    f"n_routed_experts ({ne}) and topk_group ({tg}) <= "
                    f"n_group"
                )
        return MlaConfig(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            q_lora_rank=hf.get("q_lora_rank"),
            kv_lora_rank=hf["kv_lora_rank"],
            qk_nope_head_dim=hf["qk_nope_head_dim"],
            qk_rope_head_dim=hf["qk_rope_head_dim"],
            v_head_dim=hf["v_head_dim"],
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rope_scaling_factor=(
                float(rs["factor"]) if rs else None
            ),
            rope_beta_fast=float(rs.get("beta_fast") or 32.0),
            rope_beta_slow=float(rs.get("beta_slow") or 1.0),
            rope_mscale=rs.get("mscale"),
            rope_mscale_all_dim=rs.get("mscale_all_dim"),
            rope_original_max_position=int(
                rs.get("original_max_position_embeddings")
                or hf.get("max_position_embeddings", 4096)
            ),
            # V3 applies the yarn mscale^2 term inside the softmax scale;
            # the integrated HF port of V2 does NOT (our golden tests match
            # that port), but V2 yarn checkpoints (factor=40,
            # mscale_all_dim=0.707) were TRAINED with it, so expose an
            # operator override: DYN_MLA_MSCALE_SOFTMAX=1 forces it on.
            # See docs/models.md "DeepSeek V2 yarn softmax scale".
            rope_mscale_softmax=(
                v3
                or os.environ.get("DYN_MLA_MSCALE_SOFTMAX", "") == "1"
            ),
            rms_norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
            tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
            n_routed_experts=int(hf.get("n_routed_experts") or 0),
            n_shared_experts=int(hf.get("n_shared_experts") or 0),
            moe_intermediate_size=int(hf.get("moe_intermediate_size") or 0),
            num_experts_per_tok=int(hf.get("num_experts_per_tok") or 2),
            first_k_dense_replace=int(hf.get("first_k_dense_replace", 1)),
            routed_scaling_factor=float(
                hf.get("routed_scaling_factor", 1.0)
            ),
            norm_topk_prob=bool(hf.get("norm_topk_prob", False)),
            topk_method=topk_method,
            n_group=int(hf.get("n_group") or 1),
            topk_group=int(hf.get("topk_group") or 1),
        )


def init_kv_pages(cfg: MlaConfig, num_pages: int, page_size: int) -> KVPages:
    """k holds the latent c_kv, v the shared rope key — see module doc."""
    return KVPages(
        k=jnp.zeros(
            (cfg.num_layers, num_pages, page_size, 1, cfg.kv_lora_rank),
            cfg.dtype,
        ),
        v=jnp.zeros(
            (cfg.num_layers, num_pages, page_size, 1, cfg.qk_rope_head_dim),
            cfg.dtype,
        ),
    )


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def _attn_layer_shapes(cfg: MlaConfig) -> dict:
    h = cfg.hidden_size
    shapes = {
        "attn_norm": (h,),
        "wkv_a": (h, cfg.cache_dim),
        "kv_a_norm": (cfg.kv_lora_rank,),
        "wkv_b": (cfg.kv_lora_rank, cfg.num_heads * (cfg.qk_nope_head_dim + cfg.v_head_dim)),
        "wo": (cfg.num_heads * cfg.v_head_dim, h),
        "mlp_norm": (h,),
    }
    if cfg.q_lora_rank:
        shapes["wq_a"] = (h, cfg.q_lora_rank)
        shapes["q_a_norm"] = (cfg.q_lora_rank,)
        shapes["wq_b"] = (cfg.q_lora_rank, cfg.num_heads * cfg.qk_head_dim)
    else:
        shapes["wq"] = (h, cfg.num_heads * cfg.qk_head_dim)
    return shapes


def init_params(key: jax.Array, cfg: MlaConfig) -> dict:
    h, v = cfg.hidden_size, cfg.vocab_size
    counter = iter(range(1 << 30))

    def dense(shape):
        # fold_in per tensor: no fixed key pool to exhaust (deepseek-v2-
        # lite alone has thousands of expert tensors)
        k = jax.random.fold_in(key, next(counter))
        scale = 1.0 / math.sqrt(shape[0])
        return (
            jax.random.normal(k, shape, jnp.float32) * scale
        ).astype(cfg.dtype)

    def norm(shape):
        return jnp.ones(shape, cfg.dtype)

    def group(n_layers: int, moe: bool) -> dict:
        if n_layers == 0:
            return {}
        lp = {}
        for name, shape in _attn_layer_shapes(cfg).items():
            init = norm if "norm" in name else dense
            lp[name] = jnp.stack([init(shape) for _ in range(n_layers)])
        if not moe:
            i = cfg.intermediate_size
            for nm, shape in (
                ("w_gate", (h, i)), ("w_up", (h, i)), ("w_down", (i, h)),
            ):
                lp[nm] = jnp.stack([dense(shape) for _ in range(n_layers)])
        else:
            e, mi = cfg.n_routed_experts, cfg.moe_intermediate_size
            si = mi * cfg.n_shared_experts
            lp["w_router"] = jnp.stack(
                [dense((h, e)) for _ in range(n_layers)]
            )
            if cfg.topk_method == "noaux_tc":
                lp["router_bias"] = jnp.zeros((n_layers, e), jnp.float32)
            for nm, shape in (
                ("we_gate", (e, h, mi)), ("we_up", (e, h, mi)),
                ("we_down", (e, mi, h)),
            ):
                lp[nm] = jnp.stack(
                    [
                        jnp.stack([dense(shape[1:]) for _ in range(e)])
                        for _ in range(n_layers)
                    ]
                )
            for nm, shape in (
                ("ws_gate", (h, si)), ("ws_up", (h, si)), ("ws_down", (si, h)),
            ):
                lp[nm] = jnp.stack([dense(shape) for _ in range(n_layers)])
        return lp

    params = {
        "embed": dense((v, h)),
        "dense_layers": group(cfg.num_dense_layers, moe=False),
        "moe_layers": group(cfg.num_moe_layers, moe=True),
        "final_norm": norm((h,)),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense((h, v))
    return params


def params_from_torch_state_dict(state_dict, cfg: MlaConfig) -> dict:
    """HF DeepseekV2ForCausalLM state_dict -> our two-scan pytree."""
    import numpy as np

    def t(name):
        return np.asarray(state_dict[name].to("cpu").float().numpy())

    def stack(layers, fmt, transpose=True):
        ws = [t(fmt.format(l)) for l in layers]
        return jnp.asarray(
            np.stack([w.T if transpose else w for w in ws]), cfg.dtype
        )

    def attn_group(layers) -> dict:
        lp = {
            "attn_norm": stack(
                layers, "model.layers.{}.input_layernorm.weight", False
            ),
            "wkv_a": stack(
                layers, "model.layers.{}.self_attn.kv_a_proj_with_mqa.weight"
            ),
            "kv_a_norm": stack(
                layers, "model.layers.{}.self_attn.kv_a_layernorm.weight",
                False,
            ),
            "wkv_b": stack(
                layers, "model.layers.{}.self_attn.kv_b_proj.weight"
            ),
            "wo": stack(layers, "model.layers.{}.self_attn.o_proj.weight"),
            "mlp_norm": stack(
                layers, "model.layers.{}.post_attention_layernorm.weight",
                False,
            ),
        }
        if cfg.q_lora_rank:
            lp["wq_a"] = stack(
                layers, "model.layers.{}.self_attn.q_a_proj.weight"
            )
            lp["q_a_norm"] = stack(
                layers, "model.layers.{}.self_attn.q_a_layernorm.weight",
                False,
            )
            lp["wq_b"] = stack(
                layers, "model.layers.{}.self_attn.q_b_proj.weight"
            )
        else:
            lp["wq"] = stack(
                layers, "model.layers.{}.self_attn.q_proj.weight"
            )
        return lp

    dense_idx = list(range(cfg.num_dense_layers))
    moe_idx = list(range(cfg.num_dense_layers, cfg.num_layers))

    dense_lp = attn_group(dense_idx) if dense_idx else {}
    if dense_idx:
        for nm, hf_nm in (
            ("w_gate", "gate_proj"), ("w_up", "up_proj"),
            ("w_down", "down_proj"),
        ):
            dense_lp[nm] = stack(
                dense_idx, "model.layers.{}.mlp." + hf_nm + ".weight"
            )

    moe_lp = attn_group(moe_idx) if moe_idx else {}
    if moe_idx:
        import numpy as np

        moe_lp["w_router"] = stack(
            moe_idx, "model.layers.{}.mlp.gate.weight"
        )  # HF gate.weight is [E, h]; transposed to [h, E]
        if cfg.topk_method == "noaux_tc":
            # keep FULL f32 precision: stack() would round-trip through
            # cfg.dtype (bf16) and lose the tie-breaking bias bits that
            # govern V3 expert selection
            moe_lp["router_bias"] = jnp.asarray(
                np.stack([
                    t(f"model.layers.{l}.mlp.gate.e_score_correction_bias")
                    for l in moe_idx
                ]),
                jnp.float32,
            )
        for nm, hf_nm in (
            ("we_gate", "gate_proj"), ("we_up", "up_proj"),
            ("we_down", "down_proj"),
        ):
            moe_lp[nm] = jnp.asarray(
                np.stack(
                    [
                        np.stack(
                            [
                                t(
                                    f"model.layers.{l}.mlp.experts.{e}."
                                    f"{hf_nm}.weight"
                                ).T
                                for e in range(cfg.n_routed_experts)
                            ]
                        )
                        for l in moe_idx
                    ]
                ),
                cfg.dtype,
            )
        for nm, hf_nm in (
            ("ws_gate", "gate_proj"), ("ws_up", "up_proj"),
            ("ws_down", "down_proj"),
        ):
            moe_lp[nm] = stack(
                moe_idx, "model.layers.{}.mlp.shared_experts." + hf_nm
                + ".weight"
            )

    params = {
        "embed": jnp.asarray(t("model.embed_tokens.weight"), cfg.dtype),
        "dense_layers": dense_lp,
        "moe_layers": moe_lp,
        "final_norm": jnp.asarray(t("model.norm.weight"), cfg.dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(t("lm_head.weight").T, cfg.dtype)
    return params


# ---------------------------------------------------------------------------
# Attention
# ---------------------------------------------------------------------------


def _yarn_inv_freq_and_factor(cfg: MlaConfig, d: int):
    """HF _compute_yarn_parameters: blend interpolated/extrapolated
    inverse frequencies with a linear ramp between the beta_fast/slow
    correction dims; the attention factor (mscale ratio, or
    0.1*ln(factor)+1) scales the rotary cos/sin — exactly how the HF
    DeepSeek rotary applies it (freqs_cis * attention_scaling)."""
    import numpy as np

    base, factor = cfg.rope_theta, cfg.rope_scaling_factor
    pos_freqs = base ** (np.arange(0, d, 2, dtype=np.float64) / d)
    extrap = 1.0 / pos_freqs
    interp = 1.0 / (factor * pos_freqs)

    def corr_dim(rot):
        return (
            d
            * math.log(cfg.rope_original_max_position / (rot * 2 * math.pi))
        ) / (2 * math.log(base))

    low = max(math.floor(corr_dim(cfg.rope_beta_fast)), 0)
    high = min(math.ceil(corr_dim(cfg.rope_beta_slow)), d - 1)
    if low == high:
        high += 0.001
    ramp = np.clip(
        (np.arange(d // 2, dtype=np.float64) - low) / (high - low), 0, 1
    )
    extrap_factor = 1.0 - ramp
    inv = interp * (1 - extrap_factor) + extrap * extrap_factor

    def get_mscale(scale, m=1.0):
        return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

    if cfg.rope_mscale and cfg.rope_mscale_all_dim:
        att = get_mscale(factor, cfg.rope_mscale) / get_mscale(
            factor, cfg.rope_mscale_all_dim
        )
    else:
        att = get_mscale(factor)
    return jnp.asarray(inv, jnp.float32), float(att)


def _interleaved_rope(x: jax.Array, positions: jax.Array, cfg: MlaConfig):
    """DeepSeek rope: adjacent pairs (x[2j], x[2j+1]) rotate as complex
    numbers (modeling_deepseek_v2.apply_rotary_emb) — unlike Llama's
    half-split pairing. x: [B, T, ..., D], positions [B, T]."""
    d = x.shape[-1]
    if cfg.rope_scaling_factor:
        inv, att = _yarn_inv_freq_and_factor(cfg, d)
    else:
        inv = 1.0 / (
            cfg.rope_theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
        )
        att = 1.0
    freqs = positions.astype(jnp.float32)[..., None] * inv  # [B,T,d/2]
    cos, sin = jnp.cos(freqs) * att, jnp.sin(freqs) * att
    extra = x.ndim - 3  # broadcast over any head axes between T and D
    for _ in range(extra):
        cos, sin = cos[..., None, :], sin[..., None, :]
    xf = x.astype(jnp.float32)
    x_even, x_odd = xf[..., 0::2], xf[..., 1::2]
    out_even = x_even * cos - x_odd * sin
    out_odd = x_even * sin + x_odd * cos
    return jnp.stack([out_even, out_odd], axis=-1).reshape(x.shape).astype(
        x.dtype
    )


def mla_attention(
    x: jax.Array,  # [B, T, H'] post-attn-norm
    lp: dict,
    cfg: MlaConfig,
    kv: tuple,  # (k_cache, v_cache) full stacked
    layer: jax.Array,
    page_tables: jax.Array,
    positions: jax.Array,
    valid: jax.Array,
):
    b, t, _ = x.shape
    hn, r, c = cfg.num_heads, cfg.qk_rope_head_dim, cfg.kv_lora_rank
    n, vd = cfg.qk_nope_head_dim, cfg.v_head_dim
    k_cache, v_cache = kv

    if cfg.q_lora_rank:
        qa = rms_norm(
            _mm(x, lp, "wq_a", cfg.dtype).astype(cfg.dtype),
            lp["q_a_norm"], cfg.rms_norm_eps,
        )
        q = _mm(qa, lp, "wq_b", cfg.dtype).reshape(
            b, t, hn, cfg.qk_head_dim
        )
    else:
        q = _mm(x, lp, "wq", cfg.dtype).reshape(b, t, hn, cfg.qk_head_dim)
    q_nope, q_pe = q[..., :n], q[..., n:]
    q_pe = _interleaved_rope(q_pe, positions, cfg)

    kv_a = _mm(x, lp, "wkv_a", cfg.dtype)  # [B,T,c+r]
    c_kv = rms_norm(
        kv_a[..., :c].astype(cfg.dtype), lp["kv_a_norm"], cfg.rms_norm_eps
    )
    k_pe = _interleaved_rope(kv_a[..., c:], positions, cfg)

    # Land this chunk's latent + rope key, then attend over the gathered
    # (history + current) cache — same scatter-then-gather discipline as
    # the Llama XLA path, so causality is pure position masking.
    k_cache = paged_scatter(
        k_cache, layer, c_kv[:, :, None, :], page_tables, positions, valid
    )
    v_cache = paged_scatter(
        v_cache, layer, k_pe.astype(cfg.dtype)[:, :, None, :], page_tables,
        positions, valid,
    )
    c_hist = paged_gather(k_cache, layer, page_tables)[:, :, 0]  # [B,K,c]
    pe_hist = paged_gather(v_cache, layer, page_tables)[:, :, 0]  # [B,K,r]

    wkv_b = _w(lp, "wkv_b", jnp.float32).reshape(c, hn, n + vd)
    w_uk, w_uv = wkv_b[..., :n], wkv_b[..., n:]

    scale = cfg.softmax_scale
    q_lat = jnp.einsum(
        "bthn,chn->bthc", q_nope.astype(jnp.float32),
        w_uk.astype(jnp.float32),
    )
    scores = (
        jnp.einsum("bthc,bkc->bhtk", q_lat, c_hist.astype(jnp.float32))
        + jnp.einsum(
            "bthr,bkr->bhtk", q_pe.astype(jnp.float32),
            pe_hist.astype(jnp.float32),
        )
    ) * scale
    kk = c_hist.shape[1]
    key_pos = jnp.arange(kk)[None, None, None, :]
    mask = key_pos <= positions[:, None, :, None]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o_lat = jnp.einsum("bhtk,bkc->bthc", probs, c_hist.astype(jnp.float32))
    out = jnp.einsum("bthc,chv->bthv", o_lat, w_uv.astype(jnp.float32))
    out = out.reshape(b, t, hn * vd).astype(cfg.dtype)
    return _mm(out, lp, "wo", cfg.dtype), k_cache, v_cache


# ---------------------------------------------------------------------------
# MoE FFN (DeepSeek semantics, GShard static dispatch)
# ---------------------------------------------------------------------------


#: auto expert-chunk byte budget for _routed_expert_ffn's per-group f32
#: temporaries + dequantized weights (v5e has ~16G HBM; keep the MoE FFN's
#: transient share well under the KV pool + params headroom)
_MOE_CHUNK_BYTES = 128 << 20


def _auto_expert_chunk(e: int, cap: int, h: int, i: int) -> int:
    """Largest divisor of `e` whose per-group transients fit the budget:
    per expert the FFN holds xe/down ([C, H] f32 each), gate/up ([C, I]
    f32 each) plus the dequantized f32 weight slices (3·H·I)."""
    per_expert = 4 * (cap * (2 * h + 2 * i) + 3 * h * i)
    g = max(1, min(e, _MOE_CHUNK_BYTES // max(per_expert, 1)))
    while e % g:
        g -= 1
    return g


def _routed_expert_ffn(
    xf: jax.Array,  # [N, H] f32 tokens
    dispatch: jax.Array,  # [N, E, C] f32 one-hot dispatch
    combine: jax.Array,  # [N, E, C] f32 weighted combine
    lp: dict,
    cfg: MlaConfig,
    cap: int,
) -> jax.Array:
    """The routed experts' gated FFN, chunked over expert groups.

    The fused all-experts einsum chain materializes xe [E, C, H] +
    gate/up [E, C, I] f32 (264M+192M+132M at V2-Lite decode shapes) plus
    — with int8 expert weights — the full [E, H, I] f32 dequants, which
    OOMs a single v5e chip. lax.map over groups of `moe_expert_chunk`
    experts rematerializes per group: same contractions, same f32
    accumulation within a group, peak transients divided by E/group
    (the cross-group sum reorders f32 adds — sub-ulp vs the fused path).
    """
    nt, e, _ = dispatch.shape
    h = xf.shape[1]
    i = cfg.moe_intermediate_size
    eg = cfg.moe_expert_chunk or _auto_expert_chunk(e, cap, h, i)
    eg = max(1, min(eg, e))
    while e % eg:
        eg -= 1

    def dequant(w, scale):
        if scale is None:
            return w.astype(jnp.float32)
        return w.astype(jnp.float32) * scale.astype(jnp.float32)

    if eg == e:  # one group — the original fused path, no map overhead
        xe = jnp.einsum("nec,nh->ech", dispatch, xf)
        gate = jax.nn.silu(
            jnp.einsum("ech,ehi->eci", xe, _w(lp, "we_gate", jnp.float32))
        )
        up = jnp.einsum("ech,ehi->eci", xe, _w(lp, "we_up", jnp.float32))
        down = jnp.einsum(
            "eci,eih->ech", gate * up, _w(lp, "we_down", jnp.float32)
        )
        return jnp.einsum("nec,ech->nh", combine, down)

    ng = e // eg
    quantized = lp["we_gate"].dtype == jnp.int8
    xs = {
        "disp": dispatch.reshape(nt, ng, eg, cap).transpose(1, 0, 2, 3),
        "comb": combine.reshape(nt, ng, eg, cap).transpose(1, 0, 2, 3),
    }
    for name in ("we_gate", "we_up", "we_down"):
        w = lp[name]
        xs[name] = w.reshape(ng, eg, *w.shape[1:])
        if quantized:
            s = lp[name + "_scale"]
            xs[name + "_s"] = s.reshape(ng, eg, *s.shape[1:])

    def group(g):
        wg = dequant(g["we_gate"], g.get("we_gate_s"))
        wu = dequant(g["we_up"], g.get("we_up_s"))
        wd = dequant(g["we_down"], g.get("we_down_s"))
        xe = jnp.einsum("nec,nh->ech", g["disp"], xf)  # [eg, C, H]
        gate = jax.nn.silu(jnp.einsum("ech,ehi->eci", xe, wg))
        up = jnp.einsum("ech,ehi->eci", xe, wu)
        down = jnp.einsum("eci,eih->ech", gate * up, wd)
        return jnp.einsum("nec,ech->nh", g["comb"], down)  # [N, H]

    return jnp.sum(lax.map(group, xs), axis=0)


def _deepseek_moe_ffn(x: jax.Array, lp: dict, cfg: MlaConfig) -> jax.Array:
    b, t, h = x.shape
    nt = b * t
    e, k = cfg.n_routed_experts, cfg.num_experts_per_tok
    xf = x.reshape(nt, h)

    logits = (xf.astype(jnp.float32)) @ lp["w_router"].astype(jnp.float32)

    def _group_mask(choice, rank_fn):
        g = cfg.n_group
        group_scores = rank_fn(choice.reshape(nt, g, e // g))
        _, gidx = lax.top_k(group_scores, cfg.topk_group)  # [N, tg]
        gmask = jnp.sum(
            jax.nn.one_hot(gidx, g, dtype=jnp.float32), axis=1
        )  # [N, g]
        return jnp.repeat(gmask, e // g, axis=-1)  # [N, E]

    if cfg.topk_method == "noaux_tc":
        # HF DeepseekV3TopkRouter: sigmoid scores; groups rank by the SUM
        # of their top-2 bias-corrected scores; selection uses corrected
        # scores, weights use the uncorrected ones.
        scores = jax.nn.sigmoid(logits)
        choice = scores + lp["router_bias"][None, :]
        choice = choice * _group_mask(
            choice,
            lambda gc: jnp.sum(lax.top_k(gc, min(2, e // cfg.n_group))[0],
                               axis=-1),
        )
        _, topi = lax.top_k(choice, k)
        topw = jnp.take_along_axis(scores, topi, axis=-1)
        if cfg.norm_topk_prob:
            topw = topw / (jnp.sum(topw, axis=-1, keepdims=True) + 1e-20)
    else:
        scores = jax.nn.softmax(logits, axis=-1)  # [N, E]
        if cfg.topk_method == "group_limited_greedy":
            # HF DeepseekV2MoEGate: groups rank by their max member score
            scores = scores * _group_mask(
                scores, lambda gc: jnp.max(gc, axis=-1)
            )
        topw, topi = lax.top_k(scores, k)
        if cfg.norm_topk_prob:
            topw = topw / jnp.sum(topw, axis=-1, keepdims=True)
    topw = topw * cfg.routed_scaling_factor

    cap = max(1, int(math.ceil(k * nt / e * cfg.capacity_factor)))
    # one-hot dispatch with per-expert capacity (same shape discipline as
    # models/moe.py — over-capacity tokens drop their expert contribution)
    onehot = jax.nn.one_hot(topi, e, dtype=jnp.float32)  # [N,k,E]
    pos_in_e = (
        jnp.cumsum(onehot.reshape(nt * k, e), axis=0).reshape(nt, k, e)
        - onehot
    )
    keep = pos_in_e < cap
    onehot = onehot * keep
    slot = jax.nn.one_hot(
        jnp.sum(pos_in_e, axis=-1, where=onehot > 0, initial=0.0).astype(
            jnp.int32
        ),
        cap,
        dtype=jnp.float32,
    )  # [N,k,C]
    dispatch = jnp.einsum("nke,nkc->nec", onehot, slot)  # [N,E,C]
    combine = jnp.einsum("nke,nkc,nk->nec", onehot, slot, topw)

    routed = _routed_expert_ffn(
        xf.astype(jnp.float32), dispatch, combine, lp, cfg, cap
    )

    shared_gate = jax.nn.silu(
        _mm(xf, lp, "ws_gate", cfg.dtype).astype(jnp.float32)
    )
    shared = _mm(
        (shared_gate * _mm(xf, lp, "ws_up", cfg.dtype).astype(jnp.float32))
        .astype(cfg.dtype),
        lp, "ws_down", cfg.dtype,
    )
    return (routed.astype(cfg.dtype) + shared).reshape(b, t, h)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def forward_hidden(
    params: dict,
    cfg: MlaConfig,
    tokens: jax.Array,
    positions: jax.Array,
    valid: jax.Array,
    kv: KVPages,
    page_tables: jax.Array,
    mm_embeds: Optional[jax.Array] = None,
    mm_mask: Optional[jax.Array] = None,
    first_chunk: bool = False,
    mesh=None,
) -> tuple[jax.Array, KVPages]:
    if mm_embeds is not None:
        raise ValueError("multimodal prompts are not supported for MLA yet")
    h = params["embed"][tokens].astype(cfg.dtype)
    k_cache, v_cache = kv.k, kv.v

    def dense_layer(carry, xs):
        h, kc, vc = carry
        lp, li = xs
        x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
        attn, kc, vc = mla_attention(
            x, lp, cfg, (kc, vc), li, page_tables, positions, valid
        )
        h = h + attn
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
        gate = jax.nn.silu(_mm(x, lp, "w_gate", cfg.dtype).astype(jnp.float32))
        up = _mm(x, lp, "w_up", cfg.dtype).astype(jnp.float32)
        h = h + _mm((gate * up).astype(cfg.dtype), lp, "w_down", cfg.dtype)
        return (h, kc, vc), None

    def moe_layer(carry, xs):
        h, kc, vc = carry
        lp, li = xs
        x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
        attn, kc, vc = mla_attention(
            x, lp, cfg, (kc, vc), li, page_tables, positions, valid
        )
        h = h + attn
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
        h = h + _deepseek_moe_ffn(x, lp, cfg)
        return (h, kc, vc), None

    nd = cfg.num_dense_layers
    carry = (h, k_cache, v_cache)
    if nd:
        carry, _ = lax.scan(
            dense_layer, carry,
            (params["dense_layers"], jnp.arange(nd, dtype=jnp.int32)),
        )
    if cfg.num_moe_layers:
        carry, _ = lax.scan(
            moe_layer, carry,
            (
                params["moe_layers"],
                jnp.arange(nd, cfg.num_layers, dtype=jnp.int32),
            ),
        )
    h, k_cache, v_cache = carry
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    return h, KVPages(k=k_cache, v=v_cache)


def compute_logits(params: dict, cfg: MlaConfig, hidden: jax.Array):
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = params["embed"].T
    return (hidden @ lm_head).astype(jnp.float32)


def forward(params, cfg: MlaConfig, tokens, positions, valid, kv, page_tables):
    h, kv = forward_hidden(
        params, cfg, tokens, positions, valid, kv, page_tables
    )
    return compute_logits(params, cfg, h), kv


def mla_logical_axes(cfg: MlaConfig, quantized: bool = False) -> dict:
    """Logical axis names (parallel/logical.py): attention heads carry
    "heads" (the packed head output axes of wq/wkv_b, wo's input),
    routed experts carry "expert" with DELIBERATELY unnamed
    intermediate dims — DeepSeek's many small experts shard on ep
    alone, tp-splitting a 1408-wide expert mlp would fragment the
    matmuls below MXU tile size. The latent projections and cache
    replicate (one shared latent — MQA-shaped). Quantized scale leaves
    ride their weight's OUTPUT dim (contraction-sharded wo/w_down keep
    replicated scales, which commute with the partial-sum)."""
    from dynamo_tpu.parallel.logical import L

    def attn_axes(moe: bool) -> dict:
        axes = {
            "attn_norm": L(),
            "wkv_a": L(),
            "kv_a_norm": L(),
            "wkv_b": L("layers", None, "heads"),
            "wo": L("layers", "heads", None),
            "mlp_norm": L(),
        }
        if cfg.q_lora_rank:
            axes.update(
                wq_a=L(), q_a_norm=L(), wq_b=L("layers", None, "heads")
            )
        else:
            axes["wq"] = L("layers", None, "heads")
        if not moe:
            axes.update(
                w_gate=L("layers", None, "mlp"),
                w_up=L("layers", None, "mlp"),
                w_down=L("layers", "mlp", None),
            )
        else:
            axes.update(
                w_router=L(),
                **(
                    {"router_bias": L()}
                    if cfg.topk_method == "noaux_tc"
                    else {}
                ),
                we_gate=L("layers", "expert", None, None),
                we_up=L("layers", "expert", None, None),
                we_down=L("layers", "expert", None, None),
                ws_gate=L("layers", None, "mlp"),
                ws_up=L("layers", None, "mlp"),
                ws_down=L("layers", "mlp", None),
            )
        if quantized:
            for name in list(axes):
                if name not in _QUANT_2D + _QUANT_EXPERTS:
                    continue
                waxes = tuple(axes[name])
                if name in _QUANT_EXPERTS:
                    # [L, E, 1, out]: scale rides the expert shard
                    axes[name + "_scale"] = L(
                        "layers", "expert", None, None
                    )
                elif waxes and waxes[-1] is not None:  # output-dim named
                    axes[name + "_scale"] = L("layers", None, waxes[-1])
                else:  # replicated or contraction-sharded: scale replicates
                    axes[name + "_scale"] = L()
        return axes

    axes = {
        "embed": L(),
        "dense_layers": attn_axes(moe=False) if cfg.num_dense_layers else {},
        "moe_layers": attn_axes(moe=True) if cfg.num_moe_layers else {},
        "final_norm": L(),
    }
    if not cfg.tie_word_embeddings:
        axes["lm_head"] = L(None, "vocab")
    return axes


def mla_param_specs(cfg: MlaConfig, quantized: bool = False, rules=None):
    """PartitionSpecs for MLA params: `mla_logical_axes` resolved
    through the logical-axis rule table (default table when `rules` is
    None)."""
    from dynamo_tpu.parallel.logical import resolve

    return resolve(mla_logical_axes(cfg, quantized=quantized), rules)


# ---------------------------------------------------------------------------
# Weight-only int8
# ---------------------------------------------------------------------------


def quantize_params_int8(params: dict) -> dict:
    """Per-output-channel symmetric int8 for every dense matmul weight
    (same scheme as llama.quantize_params_int8; w_router / norms / embed
    stay in the base dtype). Makes deepseek-v2-lite's 15.7B weights
    ~16GB — servable on one v5e chip."""

    quant_one = quantize_channelwise_int8

    out = dict(params)
    for gname in ("dense_layers", "moe_layers"):
        group = dict(params.get(gname) or {})
        if not group:
            continue
        if any(
            group.get(n) is not None and group[n].dtype == jnp.int8
            for n in _QUANT_2D + _QUANT_EXPERTS
        ):
            raise ValueError("params are already int8-quantized")
        for name in _QUANT_2D:
            if name in group:
                q, s = jax.lax.map(quant_one, group[name])
                group[name] = q
                group[name + "_scale"] = s
        for name in _QUANT_EXPERTS:
            if name in group:
                q, s = jax.lax.map(
                    lambda we: jax.lax.map(quant_one, we), group[name]
                )
                group[name] = q
                group[name + "_scale"] = s
        out[gname] = group
    return out


def init_params_int8(key: jax.Array, cfg: MlaConfig) -> dict:
    """Random-init straight into the int8 layout, one (layer, expert)
    tensor at a time — full-dtype init of deepseek-v2-lite (~31GB bf16)
    would blow a single chip's HBM before quantization could run."""
    counter = iter(range(1 << 30))

    def qdense(shape):
        k = jax.random.fold_in(key, next(counter))
        w = jax.random.normal(k, shape, jnp.float32) / math.sqrt(shape[0])
        return quantize_channelwise_int8(w)

    def dense(shape):
        k = jax.random.fold_in(key, next(counter))
        return (
            jax.random.normal(k, shape, jnp.float32) / math.sqrt(shape[0])
        ).astype(cfg.dtype)

    def norm(shape):
        return jnp.ones(shape, cfg.dtype)

    h = cfg.hidden_size

    def group(n_layers: int, moe: bool) -> dict:
        if n_layers == 0:
            return {}
        lp: dict = {}
        for name, shape in _attn_layer_shapes(cfg).items():
            if "norm" in name:
                lp[name] = jnp.stack([norm(shape)] * n_layers)
            elif name in _QUANT_2D:
                qs = [qdense(shape) for _ in range(n_layers)]
                lp[name] = jnp.stack([q for q, _ in qs])
                lp[name + "_scale"] = jnp.stack([s for _, s in qs])
            else:
                lp[name] = jnp.stack([dense(shape) for _ in range(n_layers)])
        if not moe:
            i = cfg.intermediate_size
            for nm, shape in (
                ("w_gate", (h, i)), ("w_up", (h, i)), ("w_down", (i, h)),
            ):
                qs = [qdense(shape) for _ in range(n_layers)]
                lp[nm] = jnp.stack([q for q, _ in qs])
                lp[nm + "_scale"] = jnp.stack([s for _, s in qs])
        else:
            e, mi = cfg.n_routed_experts, cfg.moe_intermediate_size
            si = mi * cfg.n_shared_experts
            lp["w_router"] = jnp.stack(
                [dense((h, e)) for _ in range(n_layers)]
            )
            if cfg.topk_method == "noaux_tc":
                lp["router_bias"] = jnp.zeros((n_layers, e), jnp.float32)
            for nm, shape in (
                ("we_gate", (e, h, mi)), ("we_up", (e, h, mi)),
                ("we_down", (e, mi, h)),
            ):
                # one compiled map over all (layer, expert) tensors —
                # eager per-expert dispatch would mean thousands of
                # round-trips and list-then-stack copies at v2-lite scale
                base = next(counter)

                def one(idx, _shape=shape[1:], _base=base):
                    k = jax.random.fold_in(key, _base + idx)
                    w = jax.random.normal(
                        k, _shape, jnp.float32
                    ) / math.sqrt(_shape[0])
                    return quantize_channelwise_int8(w)

                q, s = jax.lax.map(
                    one, jnp.arange(n_layers * e, dtype=jnp.int32)
                )
                for _ in range(n_layers * e - 1):
                    next(counter)  # keep the fold_in stream unique
                lp[nm] = q.reshape(n_layers, e, *shape[1:])
                lp[nm + "_scale"] = s.reshape(n_layers, e, 1, shape[2])
            for nm, shape in (
                ("ws_gate", (h, si)), ("ws_up", (h, si)),
                ("ws_down", (si, h)),
            ):
                qs = [qdense(shape) for _ in range(n_layers)]
                lp[nm] = jnp.stack([q for q, _ in qs])
                lp[nm + "_scale"] = jnp.stack([s for _, s in qs])
        return lp

    params = {
        "embed": dense((cfg.vocab_size, h)),
        "dense_layers": group(cfg.num_dense_layers, moe=False),
        "moe_layers": group(cfg.num_moe_layers, moe=True),
        "final_norm": norm((h,)),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense((h, cfg.vocab_size))
    return params

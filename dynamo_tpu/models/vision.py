"""ViT vision encoder for multimodal (llava-style) serving.

A CLIP-ViT-shaped encoder in JAX: patch embedding (as one big matmul —
MXU-friendly), pre-norm transformer blocks, and a two-layer projector to
the language model's hidden size. The encode worker (examples/multimodal)
runs this and ships the projected embeddings to the LLM worker over the
fabric data plane — the reference's encode/prefill/decode split with its
NIXL `connect` RDMA library (examples/multimodal/connect/__init__.py),
re-done as host/ICI tensor hand-off.

Dense [B, N, D] shapes throughout; no paging needed (images are encoded
in one shot).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    #: language model hidden size the projector maps into
    proj_dim: int = 4096
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def clip_vit_l_14() -> "VisionConfig":
        return VisionConfig()  # defaults are CLIP-ViT-L/14 @ 224

    @staticmethod
    def tiny(proj_dim: int = 64) -> "VisionConfig":
        return VisionConfig(
            image_size=16, patch_size=4, hidden_size=32,
            intermediate_size=64, num_layers=2, num_heads=2,
            proj_dim=proj_dim, dtype=jnp.float32,
        )


def init_params(key: jax.Array, cfg: VisionConfig) -> dict:
    h, i = cfg.hidden_size, cfg.intermediate_size
    patch_in = 3 * cfg.patch_size * cfg.patch_size
    L = cfg.num_layers
    keys = jax.random.split(key, 8)

    def dense(key, shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (
            jax.random.normal(key, shape, jnp.float32) * scale
        ).astype(cfg.dtype)

    return {
        "patch_embed": dense(keys[0], (patch_in, h), patch_in),
        "pos_embed": dense(keys[1], (cfg.num_patches, h), h),
        "layers": {
            "ln1": jnp.ones((L, h), cfg.dtype),
            "ln1_b": jnp.zeros((L, h), cfg.dtype),
            "wqkv": dense(keys[2], (L, h, 3 * h), h),
            "wo": dense(keys[3], (L, h, h), h),
            "ln2": jnp.ones((L, h), cfg.dtype),
            "ln2_b": jnp.zeros((L, h), cfg.dtype),
            "w1": dense(keys[4], (L, h, i), h),
            "w2": dense(keys[5], (L, i, h), i),
        },
        "final_ln": jnp.ones((h,), cfg.dtype),
        "final_ln_b": jnp.zeros((h,), cfg.dtype),
        "proj1": dense(keys[6], (h, cfg.proj_dim), h),
        "proj2": dense(keys[7], (cfg.proj_dim, cfg.proj_dim), cfg.proj_dim),
    }


def _layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def patchify(images: jax.Array, cfg: VisionConfig) -> jax.Array:
    """[B, H, W, 3] -> [B, N, patch_in] row-major patches."""
    b = images.shape[0]
    p = cfg.patch_size
    g = cfg.image_size // p
    x = images.reshape(b, g, p, g, p, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, g, g, p, p, 3]
    return x.reshape(b, g * g, p * p * 3)


def forward(params: dict, cfg: VisionConfig, images: jax.Array) -> jax.Array:
    """[B, image_size, image_size, 3] pixels -> [B, num_patches, proj_dim]
    projected patch embeddings (the tokens spliced into the LLM prompt)."""
    x = patchify(images.astype(cfg.dtype), cfg) @ params["patch_embed"]
    x = x + params["pos_embed"][None]

    def layer(x, lp):
        y = _layer_norm(x, lp["ln1"], lp["ln1_b"], cfg.layer_norm_eps)
        b, n, h = y.shape
        qkv = (y @ lp["wqkv"]).reshape(
            b, n, 3, cfg.num_heads, cfg.head_dim
        )
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scores = jnp.einsum(
            "bnhd,bmhd->bhnm", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / math.sqrt(cfg.head_dim)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "bhnm,bmhd->bnhd", probs, v.astype(jnp.float32)
        ).reshape(b, n, h).astype(x.dtype)
        x = x + attn @ lp["wo"]
        y = _layer_norm(x, lp["ln2"], lp["ln2_b"], cfg.layer_norm_eps)
        y = jax.nn.gelu((y @ lp["w1"]).astype(jnp.float32), approximate=True)
        return x + (y.astype(cfg.dtype) @ lp["w2"]), None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    x = _layer_norm(x, params["final_ln"], params["final_ln_b"], cfg.layer_norm_eps)
    # llava-style 2-layer MLP projector into the LM embedding space
    y = jax.nn.gelu((x @ params["proj1"]).astype(jnp.float32), approximate=True)
    return (y.astype(cfg.dtype) @ params["proj2"]).astype(cfg.dtype)

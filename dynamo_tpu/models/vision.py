"""ViT vision encoder for multimodal (llava-style) serving.

A CLIP-ViT-shaped encoder in JAX: patch embedding (as one big matmul —
MXU-friendly), pre-norm transformer blocks, and a two-layer projector to
the language model's hidden size. The encode worker (examples/multimodal)
runs this and ships the projected embeddings to the LLM worker over the
fabric data plane — the reference's encode/prefill/decode split with its
NIXL `connect` RDMA library (examples/multimodal/connect/__init__.py),
re-done as host/ICI tensor hand-off.

Dense [B, N, D] shapes throughout; no paging needed (images are encoded
in one shot).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    #: language model hidden size the projector maps into
    proj_dim: int = 4096
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    #: CLIP architectural switches (all on for real CLIP checkpoints;
    #: off = the lean encoder used before loader support existed)
    cls_token: bool = False
    pre_norm: bool = False
    attn_bias: bool = False
    mlp_bias: bool = False
    #: "gelu_tanh" | "quick_gelu" (original CLIP uses quick_gelu)
    hidden_act: str = "gelu_tanh"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def seq_len(self) -> int:
        return self.num_patches + (1 if self.cls_token else 0)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def clip_vit_l_14(proj_dim: int = 4096) -> "VisionConfig":
        """openai/clip-vit-large-patch14's vision tower (the llava encoder)."""
        return VisionConfig(
            proj_dim=proj_dim, cls_token=True, pre_norm=True,
            attn_bias=True, mlp_bias=True, hidden_act="quick_gelu",
        )

    @staticmethod
    def from_hf_config(
        hf: dict, proj_dim: int = 4096, dtype: Any = jnp.bfloat16
    ) -> "VisionConfig":
        """From an HF CLIPVisionConfig dict (or CLIPConfig['vision_config'])."""
        if "vision_config" in hf:
            hf = hf["vision_config"]
        return VisionConfig(
            dtype=dtype,
            image_size=hf.get("image_size", 224),
            patch_size=hf.get("patch_size", 14),
            hidden_size=hf.get("hidden_size", 1024),
            intermediate_size=hf.get("intermediate_size", 4096),
            num_layers=hf.get("num_hidden_layers", 24),
            num_heads=hf.get("num_attention_heads", 16),
            layer_norm_eps=hf.get("layer_norm_eps", 1e-5),
            proj_dim=proj_dim,
            cls_token=True, pre_norm=True, attn_bias=True, mlp_bias=True,
            hidden_act=hf.get("hidden_act", "quick_gelu"),
        )

    @staticmethod
    def tiny(proj_dim: int = 64) -> "VisionConfig":
        return VisionConfig(
            image_size=16, patch_size=4, hidden_size=32,
            intermediate_size=64, num_layers=2, num_heads=2,
            proj_dim=proj_dim, dtype=jnp.float32,
        )

    @staticmethod
    def tiny_clip(proj_dim: int = 64) -> "VisionConfig":
        """tiny() with every real-CLIP switch on (loader/golden tests)."""
        return VisionConfig(
            image_size=16, patch_size=4, hidden_size=32,
            intermediate_size=64, num_layers=2, num_heads=2,
            proj_dim=proj_dim, dtype=jnp.float32,
            cls_token=True, pre_norm=True, attn_bias=True, mlp_bias=True,
            hidden_act="quick_gelu",
        )


def init_params(key: jax.Array, cfg: VisionConfig) -> dict:
    h, i = cfg.hidden_size, cfg.intermediate_size
    patch_in = 3 * cfg.patch_size * cfg.patch_size
    L = cfg.num_layers
    keys = jax.random.split(key, 8)

    def dense(key, shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (
            jax.random.normal(key, shape, jnp.float32) * scale
        ).astype(cfg.dtype)

    params = {
        "patch_embed": dense(keys[0], (patch_in, h), patch_in),
        "pos_embed": dense(keys[1], (cfg.seq_len, h), h),
        "layers": {
            "ln1": jnp.ones((L, h), cfg.dtype),
            "ln1_b": jnp.zeros((L, h), cfg.dtype),
            "wqkv": dense(keys[2], (L, h, 3 * h), h),
            "wo": dense(keys[3], (L, h, h), h),
            "ln2": jnp.ones((L, h), cfg.dtype),
            "ln2_b": jnp.zeros((L, h), cfg.dtype),
            "w1": dense(keys[4], (L, h, i), h),
            "w2": dense(keys[5], (L, i, h), i),
        },
        "final_ln": jnp.ones((h,), cfg.dtype),
        "final_ln_b": jnp.zeros((h,), cfg.dtype),
        "proj1": dense(keys[6], (h, cfg.proj_dim), h),
        "proj2": dense(keys[7], (cfg.proj_dim, cfg.proj_dim), cfg.proj_dim),
    }
    if cfg.cls_token:
        params["cls_embed"] = jnp.zeros((h,), cfg.dtype)
    if cfg.pre_norm:
        params["pre_ln"] = jnp.ones((h,), cfg.dtype)
        params["pre_ln_b"] = jnp.zeros((h,), cfg.dtype)
    if cfg.attn_bias:
        params["layers"]["wqkv_b"] = jnp.zeros((L, 3 * h), cfg.dtype)
        params["layers"]["wo_b"] = jnp.zeros((L, h), cfg.dtype)
    if cfg.mlp_bias:
        params["layers"]["w1_b"] = jnp.zeros((L, i), cfg.dtype)
        params["layers"]["w2_b"] = jnp.zeros((L, h), cfg.dtype)
    return params


def params_from_torch_state_dict(sd, cfg: VisionConfig) -> dict:
    """HF CLIPVisionModel weights -> this module's pytree.

    Handles both bare CLIPVisionModel state dicts ("vision_model....") and
    CLIPModel ones (same keys). The patch conv [h, 3, p, p] becomes the
    row-major patch matmul weight [p*p*3, h] matching patchify()'s
    [p, p, 3] flattening. The projector gets a deterministic random init
    (bare CLIP carries none — see the inline note on llava projectors).
    Reference checkpoints: /root/reference examples/multimodal (llava's
    openai/clip-vit-large-patch14-336 tower)."""
    import numpy as np

    def t(name):
        key = name if name in sd else f"vision_model.{name}"
        return np.asarray(sd[key].detach().cpu().numpy(), np.float32)

    h, L = cfg.hidden_size, cfg.num_layers
    conv = t("embeddings.patch_embedding.weight")  # [h, 3, p, p]
    patch_w = conv.transpose(2, 3, 1, 0).reshape(-1, h)  # [p*p*3, h]

    def stack(fmt, transpose=False):
        ws = [t(fmt.format(i)) for i in range(L)]
        out = np.stack([w.T if transpose else w for w in ws])
        return jnp.asarray(out, cfg.dtype)

    def qkv_w(i):
        q = t(f"encoder.layers.{i}.self_attn.q_proj.weight")
        k = t(f"encoder.layers.{i}.self_attn.k_proj.weight")
        v = t(f"encoder.layers.{i}.self_attn.v_proj.weight")
        return np.concatenate([q.T, k.T, v.T], axis=1)  # [h, 3h]

    def qkv_b(i):
        return np.concatenate(
            [
                t(f"encoder.layers.{i}.self_attn.q_proj.bias"),
                t(f"encoder.layers.{i}.self_attn.k_proj.bias"),
                t(f"encoder.layers.{i}.self_attn.v_proj.bias"),
            ]
        )

    params = {
        "patch_embed": jnp.asarray(patch_w, cfg.dtype),
        "pos_embed": jnp.asarray(
            t("embeddings.position_embedding.weight"), cfg.dtype
        ),
        "layers": {
            "ln1": stack("encoder.layers.{}.layer_norm1.weight"),
            "ln1_b": stack("encoder.layers.{}.layer_norm1.bias"),
            "wqkv": jnp.asarray(
                np.stack([qkv_w(i) for i in range(L)]), cfg.dtype
            ),
            "wqkv_b": jnp.asarray(
                np.stack([qkv_b(i) for i in range(L)]), cfg.dtype
            ),
            "wo": stack(
                "encoder.layers.{}.self_attn.out_proj.weight", transpose=True
            ),
            "wo_b": stack("encoder.layers.{}.self_attn.out_proj.bias"),
            "ln2": stack("encoder.layers.{}.layer_norm2.weight"),
            "ln2_b": stack("encoder.layers.{}.layer_norm2.bias"),
            "w1": stack("encoder.layers.{}.mlp.fc1.weight", transpose=True),
            "w1_b": stack("encoder.layers.{}.mlp.fc1.bias"),
            "w2": stack("encoder.layers.{}.mlp.fc2.weight", transpose=True),
            "w2_b": stack("encoder.layers.{}.mlp.fc2.bias"),
        },
        "final_ln": jnp.asarray(t("post_layernorm.weight"), cfg.dtype),
        "final_ln_b": jnp.asarray(t("post_layernorm.bias"), cfg.dtype),
        "cls_embed": jnp.asarray(t("embeddings.class_embedding"), cfg.dtype),
        "pre_ln": jnp.asarray(t("pre_layrnorm.weight"), cfg.dtype),
        "pre_ln_b": jnp.asarray(t("pre_layrnorm.bias"), cfg.dtype),
    }
    # Projector: deterministic random init. A bare CLIP checkpoint carries
    # no projector; loading a trained llava projector is future work — it
    # requires the PRE-post-layernorm feature surface llava trains on
    # (vision_feature_layer=-2) plus its linear biases, not a weight copy.
    keys = jax.random.split(jax.random.key(0), 2)
    scale1 = 1.0 / math.sqrt(h)
    scale2 = 1.0 / math.sqrt(cfg.proj_dim)
    params["proj1"] = (
        jax.random.normal(keys[0], (h, cfg.proj_dim), jnp.float32) * scale1
    ).astype(cfg.dtype)
    params["proj2"] = (
        jax.random.normal(keys[1], (cfg.proj_dim, cfg.proj_dim), jnp.float32)
        * scale2
    ).astype(cfg.dtype)
    return params


def load_vision_checkpoint(
    path: str, proj_dim: int = 4096, dtype: Any = jnp.bfloat16
):
    """Load an HF CLIP checkpoint DIRECTORY: returns (cfg, params).

    Accepts CLIPVisionModel or CLIPModel checkpoints (config.json with or
    without a nested vision_config)."""
    import json
    import os

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    cfg = VisionConfig.from_hf_config(hf, proj_dim=proj_dim, dtype=dtype)
    from transformers import CLIPVisionModel

    model = CLIPVisionModel.from_pretrained(path)
    return cfg, params_from_torch_state_dict(model.state_dict(), cfg)


def _layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def patchify(images: jax.Array, cfg: VisionConfig) -> jax.Array:
    """[B, H, W, 3] -> [B, N, patch_in] row-major patches."""
    b = images.shape[0]
    p = cfg.patch_size
    g = cfg.image_size // p
    x = images.reshape(b, g, p, g, p, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, g, g, p, p, 3]
    return x.reshape(b, g * g, p * p * 3)


def _act(x: jax.Array, kind: str) -> jax.Array:
    if kind == "quick_gelu":  # original CLIP: x * sigmoid(1.702 x)
        return x * jax.nn.sigmoid(1.702 * x)
    if kind == "gelu":  # HF "gelu" is the EXACT erf form
        return jax.nn.gelu(x, approximate=False)
    return jax.nn.gelu(x, approximate=True)


def forward_features(
    params: dict, cfg: VisionConfig, images: jax.Array
) -> jax.Array:
    """[B, H, W, 3] pixels -> [B, seq_len, hidden] final-norm hidden states
    (HF CLIPVisionModel.last_hidden_state equivalent — the golden-test
    surface)."""
    x = patchify(images.astype(cfg.dtype), cfg) @ params["patch_embed"]
    if cfg.cls_token:
        cls = jnp.broadcast_to(
            params["cls_embed"], (x.shape[0], 1, cfg.hidden_size)
        ).astype(x.dtype)
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"][None]
    if cfg.pre_norm:
        x = _layer_norm(x, params["pre_ln"], params["pre_ln_b"], cfg.layer_norm_eps)

    def layer(x, lp):
        y = _layer_norm(x, lp["ln1"], lp["ln1_b"], cfg.layer_norm_eps)
        b, n, h = y.shape
        qkv = y @ lp["wqkv"]
        if cfg.attn_bias:
            qkv = qkv + lp["wqkv_b"]
        qkv = qkv.reshape(b, n, 3, cfg.num_heads, cfg.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scores = jnp.einsum(
            "bnhd,bmhd->bhnm", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / math.sqrt(cfg.head_dim)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "bhnm,bmhd->bnhd", probs, v.astype(jnp.float32)
        ).reshape(b, n, h).astype(x.dtype)
        attn = attn @ lp["wo"]
        if cfg.attn_bias:
            attn = attn + lp["wo_b"]
        x = x + attn
        y = _layer_norm(x, lp["ln2"], lp["ln2_b"], cfg.layer_norm_eps)
        y = y @ lp["w1"]
        if cfg.mlp_bias:
            y = y + lp["w1_b"]
        y = _act(y.astype(jnp.float32), cfg.hidden_act).astype(cfg.dtype)
        y = y @ lp["w2"]
        if cfg.mlp_bias:
            y = y + lp["w2_b"]
        return x + y, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return _layer_norm(
        x, params["final_ln"], params["final_ln_b"], cfg.layer_norm_eps
    )


def forward(params: dict, cfg: VisionConfig, images: jax.Array) -> jax.Array:
    """[B, image_size, image_size, 3] pixels -> [B, num_patches, proj_dim]
    projected patch embeddings (the tokens spliced into the LLM prompt).
    With a CLS token, the CLS position is dropped before projection
    (llava splices patch embeddings only)."""
    x = forward_features(params, cfg, images)
    if cfg.cls_token:
        x = x[:, 1:]
    # llava-style 2-layer MLP projector into the LM embedding space
    y = jax.nn.gelu((x @ params["proj1"]).astype(jnp.float32), approximate=True)
    return (y.astype(cfg.dtype) @ params["proj2"]).astype(cfg.dtype)

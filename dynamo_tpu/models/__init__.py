from dynamo_tpu.models.llama import (
    LlamaConfig,
    init_params,
    forward,
)

__all__ = ["LlamaConfig", "init_params", "forward"]

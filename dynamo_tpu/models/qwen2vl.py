"""Qwen2-VL: ViT vision tower + m-RoPE language model (BASELINE config 5).

The reference serves this family only through vLLM
(/root/reference examples/multimodal/ — no in-tree implementation);
here it is a first-class model family like the other Llama variants:

- **Vision tower**: a full-attention ViT over flattened conv patches
  (HF's Conv3d patch embed with stride == kernel is exactly one matmul),
  2D rotary position embedding per (h, w) patch coordinate, and the 2x2
  PatchMerger MLP projecting into the language model's hidden size.
  Patches arrive in the Qwen2-VL image-processor order (merge-group
  major), matching HF `pixel_values` bit for bit.
- **Language model**: the Qwen2 architecture (llama.py with qkv bias)
  plus m-RoPE — rope positions carry three streams (temporal, height,
  width) with the frequency dim partitioned by `mrope_section`
  (llama.apply_rope). Text-only prompts have all three streams equal,
  which reduces to standard rope EXACTLY — so text serving runs the
  stock engine path unchanged.
- **get_rope_index**: the position-stream builder (images; HF
  Qwen2VLModel.get_rope_index semantics) used by tests and the
  multimodal preprocessor.

Serving note: through the serving engine, image prompts splice vision
embeds llava-style at sequential positions (the unified multimodal
contract, models/vision.py). Native m-RoPE grid positions are exact at
this model API (`forward(..., rope_positions=[3,B,T])`) and golden-
tested against HF `Qwen2VLForConditionalGeneration`
(tests/test_model_qwen2vl.py).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.llama import LlamaConfig

__all__ = [
    "Qwen2VLVisionConfig",
    "text_config",
    "get_rope_index",
    "init_vision_params",
    "vision_forward",
    "vision_params_from_torch_state_dict",
    "remap_language_state_dict",
]


@dataclass(frozen=True)
class Qwen2VLVisionConfig:
    depth: int = 32
    embed_dim: int = 1280
    num_heads: int = 16
    in_channels: int = 3
    patch_size: int = 14
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    mlp_ratio: float = 4.0
    hidden_size: int = 1536  # language-model hidden size (merger output)
    dtype: jnp.dtype = jnp.float32
    #: "qwen2" (LayerNorm, QuickGELU MLP, full attention) or "qwen2_5"
    #: (RMSNorm, biased SwiGLU MLP, WINDOWED attention except
    #: fullatt_block_indexes, window-reordered merge units)
    variant: str = "qwen2"
    #: qwen2_5: window edge in PIXELS (112 = 8 patches = 4 merge units)
    window_size: int = 112
    #: qwen2_5: blocks that attend across the whole frame
    fullatt_block_indexes: tuple[int, ...] = ()
    #: qwen2_5: explicit MLP width (qwen2 uses mlp_ratio)
    intermediate_size: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def patch_dim(self) -> int:
        return (
            self.in_channels
            * self.temporal_patch_size
            * self.patch_size
            * self.patch_size
        )

    @property
    def mlp_dim(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        return int(self.embed_dim * self.mlp_ratio)

    @staticmethod
    def tiny(hidden_size: int = 64) -> "Qwen2VLVisionConfig":
        return Qwen2VLVisionConfig(
            depth=2, embed_dim=32, num_heads=4, patch_size=4,
            temporal_patch_size=2, spatial_merge_size=2, mlp_ratio=2.0,
            hidden_size=hidden_size,
        )

    @staticmethod
    def qwen2_vl(hidden_size: int) -> "Qwen2VLVisionConfig":
        """The production tower (same for 2B/7B/72B; only the merger's
        output dim differs)."""
        return Qwen2VLVisionConfig(hidden_size=hidden_size)

    @staticmethod
    def tiny_25(hidden_size: int = 64) -> "Qwen2VLVisionConfig":
        """Test-scale Qwen2.5-VL tower: 4 blocks (block 3 full-attention,
        the rest windowed at 2 merge units per side)."""
        return Qwen2VLVisionConfig(
            depth=4, embed_dim=32, num_heads=4, patch_size=4,
            temporal_patch_size=2, spatial_merge_size=2,
            hidden_size=hidden_size, variant="qwen2_5",
            window_size=16, fullatt_block_indexes=(3,),
            intermediate_size=48,
        )

    @staticmethod
    def qwen2_5_vl(hidden_size: int) -> "Qwen2VLVisionConfig":
        """Qwen2.5-VL production tower (3B/7B/72B share it)."""
        return Qwen2VLVisionConfig(
            depth=32, embed_dim=1280, num_heads=16, patch_size=14,
            hidden_size=hidden_size, variant="qwen2_5",
            window_size=112, fullatt_block_indexes=(7, 15, 23, 31),
            intermediate_size=3420,
        )


def text_config(
    *,
    vocab_size: int,
    hidden_size: int,
    intermediate_size: int,
    num_layers: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    rope_theta: float = 1_000_000.0,
    mrope_section: tuple[int, ...] = (16, 24, 24),
    dtype=jnp.bfloat16,
    tie_word_embeddings: bool = False,
) -> LlamaConfig:
    """Qwen2-VL language model = Qwen2 (qkv bias) + mrope_section."""
    if sum(mrope_section) != head_dim // 2:
        raise ValueError(
            f"mrope_section {mrope_section} must sum to head_dim/2 "
            f"({head_dim // 2})"
        )
    return LlamaConfig(
        vocab_size=vocab_size,
        hidden_size=hidden_size,
        intermediate_size=intermediate_size,
        num_layers=num_layers,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        head_dim=head_dim,
        rope_theta=rope_theta,
        attention_bias=True,
        rms_norm_eps=1e-6,
        mrope_section=mrope_section,
        dtype=dtype,
        tie_word_embeddings=tie_word_embeddings,
    )


def text_tiny() -> LlamaConfig:
    """Unit-test scale, comparable against HF on CPU."""
    return text_config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        rope_theta=10000.0, mrope_section=(2, 3, 3), dtype=jnp.float32,
    )


def text_2b() -> LlamaConfig:
    """Qwen2-VL-2B-Instruct language model."""
    return text_config(
        vocab_size=151936, hidden_size=1536, intermediate_size=8960,
        num_layers=28, num_heads=12, num_kv_heads=2, head_dim=128,
        tie_word_embeddings=True,
    )


def text_7b() -> LlamaConfig:
    """Qwen2-VL-7B-Instruct language model."""
    return text_config(
        vocab_size=152064, hidden_size=3584, intermediate_size=18944,
        num_layers=28, num_heads=28, num_kv_heads=4, head_dim=128,
    )


def text_25_3b() -> LlamaConfig:
    """Qwen2.5-VL-3B-Instruct language model."""
    return text_config(
        vocab_size=151936, hidden_size=2048, intermediate_size=11008,
        num_layers=36, num_heads=16, num_kv_heads=2, head_dim=128,
        tie_word_embeddings=True,
    )


def text_25_7b() -> LlamaConfig:
    """Qwen2.5-VL-7B-Instruct language model."""
    return text_config(
        vocab_size=152064, hidden_size=3584, intermediate_size=18944,
        num_layers=28, num_heads=28, num_kv_heads=4, head_dim=128,
    )


def config_from_hf(hf: dict) -> LlamaConfig:
    """LlamaConfig for a Qwen2-VL HF checkpoint config.json (text fields
    nest under `text_config` in new transformers; older dumps keep them
    top-level)."""
    t = hf.get("text_config") or hf
    rope = (t.get("rope_scaling") or {}).get("mrope_section") or (16, 24, 24)
    heads = t["num_attention_heads"]
    return text_config(
        vocab_size=t["vocab_size"],
        hidden_size=t["hidden_size"],
        intermediate_size=t["intermediate_size"],
        num_layers=t["num_hidden_layers"],
        num_heads=heads,
        num_kv_heads=t.get("num_key_value_heads", heads),
        head_dim=t.get("head_dim") or t["hidden_size"] // heads,
        rope_theta=t.get("rope_theta", 1_000_000.0),
        mrope_section=tuple(rope),
        tie_word_embeddings=t.get("tie_word_embeddings", False),
    )


# --- m-RoPE position streams ------------------------------------------------


def get_rope_index(
    tokens: Sequence[int],
    image_grid_thw: Sequence[tuple[int, int, int]],
    *,
    image_token_id: int,
    spatial_merge_size: int = 2,
) -> tuple[np.ndarray, int]:
    """Build the [3, T] (temporal, height, width) rope position streams
    for one sequence. Text runs advance all three streams together;
    each image's tokens get (t_base, h, w) grid positions; the following
    text resumes at max(previous positions) + 1. Returns (positions,
    delta) where delta = next_position - len(tokens) — decode continues
    at len(tokens) + step + delta on all three streams (HF
    `mrope_position_deltas` semantics)."""
    toks = np.asarray(tokens)
    pos = np.zeros((3, len(toks)), np.int32)
    img_i = 0
    st = 0  # next unpositioned token index
    base = 0  # next position value
    while st < len(toks):
        img_positions = np.nonzero(toks[st:] == image_token_id)[0]
        if img_positions.size == 0 or img_i >= len(image_grid_thw):
            n = len(toks) - st
            pos[:, st:] = base + np.arange(n)
            base += n
            st = len(toks)
            break
        img_at = st + int(img_positions[0])
        # text run before the image
        n_text = img_at - st
        if n_text:
            pos[:, st:img_at] = base + np.arange(n_text)
            base += n_text
        t, h, w = image_grid_thw[img_i]
        lh, lw = h // spatial_merge_size, w // spatial_merge_size
        n_img = t * lh * lw
        tt = np.repeat(np.arange(t), lh * lw)
        hh = np.tile(np.repeat(np.arange(lh), lw), t)
        ww = np.tile(np.arange(lw), t * lh)
        pos[0, img_at : img_at + n_img] = base + tt
        pos[1, img_at : img_at + n_img] = base + hh
        pos[2, img_at : img_at + n_img] = base + ww
        base += int(max(t, lh, lw))
        st = img_at + n_img
        img_i += 1
    return pos, base - len(toks)


# --- vision tower -----------------------------------------------------------


def _rot_pos_emb(cfg: Qwen2VLVisionConfig, grid_thw) -> np.ndarray:
    """Per-patch 2D rotary angles [N, head_dim/2]: the first half of the
    slots rotates by the patch's h coordinate, the second by w —
    coordinates emitted in the image processor's merge-group-major patch
    order (HF Qwen2VisionTransformer.rot_pos_emb)."""
    dim = cfg.head_dim // 2  # freqs per axis
    inv_freq = 1.0 / (
        10000.0 ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
    )
    m = cfg.spatial_merge_size
    out = []
    for t, h, w in grid_thw:
        hp = np.broadcast_to(np.arange(h)[:, None], (h, w))
        hp = (
            hp.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3).reshape(-1)
        )
        wp = np.broadcast_to(np.arange(w)[None, :], (h, w))
        wp = (
            wp.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3).reshape(-1)
        )
        ang_h = hp[:, None].astype(np.float64) * inv_freq
        ang_w = wp[:, None].astype(np.float64) * inv_freq
        per = np.concatenate([ang_h, ang_w], axis=-1)  # [h*w, head_dim/2]
        out.append(np.tile(per, (t, 1)))
    return np.concatenate(out, axis=0).astype(np.float32)


def _window_order(cfg: Qwen2VLVisionConfig, grid_thw):
    """Qwen2.5-VL window reordering, all static per grid: merge units are
    permuted window-major (HF get_window_index), and each PATCH gets a
    window-segment id in the reordered sequence. Returns
    (unit_order [Nu], patch_win_seg [N] in reordered order)."""
    m = cfg.spatial_merge_size
    win = cfg.window_size // m // cfg.patch_size  # merge units per side
    orders = []
    segs = []
    unit_base = 0
    seg_base = 0
    for t, h, w in grid_thw:
        lh, lw = h // m, w // m
        idx = np.arange(t * lh * lw).reshape(t, lh, lw)
        ph, pw = (-lh) % win, (-lw) % win
        padded = np.full((t, lh + ph, lw + pw), -1, np.int64)
        padded[:, :lh, :lw] = idx
        nh, nw = (lh + ph) // win, (lw + pw) // win
        padded = (
            padded.reshape(t, nh, win, nw, win)
            .transpose(0, 1, 3, 2, 4)
            .reshape(t * nh * nw, win * win)
        )
        for wi, row in enumerate(padded):
            units = row[row != -1]
            orders.append(units + unit_base)
            segs.append(np.full(len(units) * m * m, seg_base + wi))
        unit_base += t * lh * lw
        seg_base += t * nh * nw
    return np.concatenate(orders), np.concatenate(segs)


def init_vision_params(key: jax.Array, cfg: Qwen2VLVisionConfig) -> dict:
    ks = list(jax.random.split(key, 8))

    def dense(k, shape, fan_in):
        return (
            jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)
        ).astype(cfg.dtype)

    e, md = cfg.embed_dim, cfg.mlp_dim
    d = cfg.depth
    merged = e * cfg.spatial_merge_size**2
    if cfg.variant == "qwen2_5":
        blocks = {
            "n1_w": jnp.ones((d, e), cfg.dtype),
            "qkv_w": dense(ks[1], (d, e, 3 * e), e),
            "qkv_b": jnp.zeros((d, 3 * e), cfg.dtype),
            "proj_w": dense(ks[2], (d, e, e), e),
            "proj_b": jnp.zeros((d, e), cfg.dtype),
            "n2_w": jnp.ones((d, e), cfg.dtype),
            "gate_w": dense(ks[3], (d, e, md), e),
            "gate_b": jnp.zeros((d, md), cfg.dtype),
            "up_w": dense(ks[4], (d, e, md), e),
            "up_b": jnp.zeros((d, md), cfg.dtype),
            "down_w": dense(ks[7], (d, md, e), md),
            "down_b": jnp.zeros((d, e), cfg.dtype),
        }
        extra = {"ln_q_w": jnp.ones((e,), cfg.dtype)}
    else:
        blocks = {
            "n1_w": jnp.ones((d, e), cfg.dtype),
            "n1_b": jnp.zeros((d, e), cfg.dtype),
            "qkv_w": dense(ks[1], (d, e, 3 * e), e),
            "qkv_b": jnp.zeros((d, 3 * e), cfg.dtype),
            "proj_w": dense(ks[2], (d, e, e), e),
            "proj_b": jnp.zeros((d, e), cfg.dtype),
            "n2_w": jnp.ones((d, e), cfg.dtype),
            "n2_b": jnp.zeros((d, e), cfg.dtype),
            "fc1_w": dense(ks[3], (d, e, md), e),
            "fc1_b": jnp.zeros((d, md), cfg.dtype),
            "fc2_w": dense(ks[4], (d, md, e), md),
            "fc2_b": jnp.zeros((d, e), cfg.dtype),
        }
        extra = {
            "ln_q_w": jnp.ones((e,), cfg.dtype),
            "ln_q_b": jnp.zeros((e,), cfg.dtype),
        }
    return {
        "patch_w": dense(ks[0], (cfg.patch_dim, e), cfg.patch_dim),
        "blocks": blocks,
        **extra,
        "merge1_w": dense(ks[5], (merged, merged), merged),
        "merge1_b": jnp.zeros((merged,), cfg.dtype),
        "merge2_w": dense(ks[6], (merged, cfg.hidden_size), merged),
        "merge2_b": jnp.zeros((cfg.hidden_size,), cfg.dtype),
    }


def _ln(x, w, b, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) / jnp.sqrt(var + eps) * w + b).astype(x.dtype)


def _quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


def _rms(x, w, eps=1e-6):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * w).astype(x.dtype)


def vision_forward(
    params: dict,
    cfg: Qwen2VLVisionConfig,
    patches: jax.Array,  # [N, patch_dim] HF pixel_values layout
    grid_thw: Sequence[tuple[int, int, int]],  # static per-image grids
) -> jax.Array:
    """Encode flattened conv patches into [N / merge^2, hidden_size]
    language-model embeddings. qwen2: attention is full within each
    TEMPORAL FRAME and blocked across frames/images (HF cu_seqlens repeat
    h*w per temporal patch). qwen2_5: merge units are reordered
    window-major; most blocks attend only within their window
    (cu_window_seqlens), the fullatt_block_indexes within the frame; the
    merged outputs are restored to raster order at the end."""
    v25 = cfg.variant == "qwen2_5"
    h = patches.astype(cfg.dtype) @ params["patch_w"]  # [N, E]
    angles_np = _rot_pos_emb(cfg, grid_thw)  # [N, hd/2] raster order

    frame_lens = [gh * gw for t, gh, gw in grid_thw for _ in range(t)]
    raw_seg = np.repeat(np.arange(len(frame_lens)), frame_lens)
    unit_order = None
    if v25:
        mm = cfg.spatial_merge_size**2
        unit_order, win_seg = _window_order(cfg, grid_thw)
        patch_order = (
            unit_order[:, None] * mm + np.arange(mm)
        ).reshape(-1)
        h = h[jnp.asarray(patch_order)]
        angles_np = angles_np[patch_order]
        full_seg = raw_seg[patch_order]
        mask_full = jnp.asarray(full_seg[:, None] == full_seg[None, :])
        mask_win = jnp.asarray(win_seg[:, None] == win_seg[None, :])
    else:
        mask_full = jnp.asarray(raw_seg[:, None] == raw_seg[None, :])
        mask_win = mask_full

    cos = jnp.cos(jnp.asarray(angles_np))[:, None, :]  # [N, 1, hd/2]
    sin = jnp.sin(jnp.asarray(angles_np))[:, None, :]
    nh, hd = cfg.num_heads, cfg.head_dim
    scale = 1.0 / np.sqrt(hd)

    def block(h, lp, mask):
        if v25:
            x = _rms(h, lp["n1_w"])
        else:
            x = _ln(h, lp["n1_w"], lp["n1_b"])
        qkv = x @ lp["qkv_w"] + lp["qkv_b"]  # [N, 3E]
        n = qkv.shape[0]
        q, k, v = (
            qkv.reshape(n, 3, nh, hd).transpose(1, 0, 2, 3).astype(jnp.float32)
        )
        # 2D rope (rotate-half over the full head dim, cos/sin tiled)
        def rot(t):
            t1, t2 = jnp.split(t, 2, axis=-1)
            return jnp.concatenate(
                [t1 * cos - t2 * sin, t2 * cos + t1 * sin], axis=-1
            )

        q, k = rot(q), rot(k)
        scores = jnp.einsum("qhd,khd->hqk", q, k) * scale
        scores = jnp.where(mask[None], scores, -1e30)
        attn = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("hqk,khd->qhd", attn, v.astype(jnp.float32))
        out = out.reshape(n, nh * hd).astype(h.dtype)
        h = h + (out @ lp["proj_w"] + lp["proj_b"])
        if v25:
            x = _rms(h, lp["n2_w"])
            g = jax.nn.silu(
                (x @ lp["gate_w"] + lp["gate_b"]).astype(jnp.float32)
            )
            u = (x @ lp["up_w"] + lp["up_b"]).astype(jnp.float32)
            h = h + (
                (g * u).astype(h.dtype) @ lp["down_w"] + lp["down_b"]
            )
        else:
            x = _ln(h, lp["n2_w"], lp["n2_b"])
            m = _quick_gelu(
                (x @ lp["fc1_w"] + lp["fc1_b"]).astype(jnp.float32)
            )
            h = h + (m.astype(h.dtype) @ lp["fc2_w"] + lp["fc2_b"])
        return h

    if v25:
        # ONE traced block for all depth layers: scan with a per-layer
        # flag indexing the stacked [2, N, N] masks (unrolling would
        # compile depth copies of the O(N^2) attention per grid shape)
        masks = jnp.stack([mask_win, mask_full])
        flags = jnp.asarray(
            [int(i in cfg.fullatt_block_indexes) for i in range(cfg.depth)],
            jnp.int32,
        )
        h, _ = jax.lax.scan(
            lambda c, xs: (block(c, xs[0], masks[xs[1]]), None),
            h, (params["blocks"], flags),
        )
    else:
        h, _ = jax.lax.scan(
            lambda c, lp: (block(c, lp, mask_full), None),
            h, params["blocks"],
        )
    # PatchMerger: norm then group merge^2 CONSECUTIVE patches (raster
    # order for qwen2; window order for qwen2_5, restored after)
    if v25:
        x = _rms(h, params["ln_q_w"])
    else:
        x = _ln(h, params["ln_q_w"], params["ln_q_b"])
    x = x.reshape(-1, cfg.embed_dim * cfg.spatial_merge_size**2)
    x = jax.nn.gelu(x @ params["merge1_w"] + params["merge1_b"], approximate=False)
    out = x @ params["merge2_w"] + params["merge2_b"]
    if v25:
        out = out[jnp.asarray(np.argsort(unit_order))]
    return out


def pixels_to_patches(
    images: np.ndarray, cfg: Qwen2VLVisionConfig
) -> tuple[np.ndarray, list[tuple[int, int, int]]]:
    """[B, H, W, 3] float pixels -> (patches [B*n, patch_dim], grids).

    The HF Qwen2VLImageProcessor layout exactly: patch order is
    merge-group-major ((gh/m, gw/m, m, m)) and each patch flattens in
    (C, temporal, ps, ps) order with the still image repeated across the
    temporal patch. H and W must be multiples of patch_size *
    spatial_merge_size (the processor's resize step guarantees this for
    real inputs; callers here pre-size)."""
    b, h, w, c = images.shape
    ps, m, tps = cfg.patch_size, cfg.spatial_merge_size, cfg.temporal_patch_size
    if h % (ps * m) or w % (ps * m):
        raise ValueError(
            f"image {h}x{w} not a multiple of patch*merge {ps * m}"
        )
    gh, gw = h // ps, w // ps
    x = images.transpose(0, 3, 1, 2)  # [B, C, H, W]
    x = x.reshape(b, c, gh // m, m, ps, gw // m, m, ps)
    x = x.transpose(0, 2, 5, 3, 6, 1, 4, 7)  # [B, gh/m, gw/m, m, m, C, ps, ps]
    x = x.reshape(b, gh * gw, c, ps, ps)
    x = np.repeat(x[:, :, :, None], tps, axis=3)  # temporal duplicate
    patches = x.reshape(b * gh * gw, c * tps * ps * ps)
    return patches.astype(np.float32), [(1, gh, gw)] * b


# --- HF weight conversion ---------------------------------------------------


def vision_params_from_torch_state_dict(
    sd, cfg: Qwen2VLVisionConfig, prefix: Optional[str] = None
) -> dict:
    """Convert HF Qwen2VisionTransformerPretrainedModel weights.
    State-dict keys are `model.visual.*` in current transformers;
    original checkpoint dumps (and older versions) use bare `visual.*` —
    both are accepted, like remap_language_state_dict's tolerance."""
    if prefix is None:
        prefix = (
            "model.visual."
            if any(k.startswith("model.visual.") for k in sd)
            else "visual."
        )

    def t(name, transpose=False):
        w = np.asarray(sd[prefix + name].to("cpu").float().numpy())
        return jnp.asarray(w.T if transpose else w, cfg.dtype)

    def stack(fmt, transpose=False):
        return jnp.stack(
            [t(fmt.format(i), transpose) for i in range(cfg.depth)]
        )

    patch = np.asarray(
        sd[prefix + "patch_embed.proj.weight"].to("cpu").float().numpy()
    )  # [E, C, tps, ps, ps] conv kernel == linear on the flattened patch
    if cfg.variant == "qwen2_5":
        blocks = {
            "n1_w": stack("blocks.{}.norm1.weight"),
            "qkv_w": stack("blocks.{}.attn.qkv.weight", transpose=True),
            "qkv_b": stack("blocks.{}.attn.qkv.bias"),
            "proj_w": stack("blocks.{}.attn.proj.weight", transpose=True),
            "proj_b": stack("blocks.{}.attn.proj.bias"),
            "n2_w": stack("blocks.{}.norm2.weight"),
            "gate_w": stack("blocks.{}.mlp.gate_proj.weight", transpose=True),
            "gate_b": stack("blocks.{}.mlp.gate_proj.bias"),
            "up_w": stack("blocks.{}.mlp.up_proj.weight", transpose=True),
            "up_b": stack("blocks.{}.mlp.up_proj.bias"),
            "down_w": stack("blocks.{}.mlp.down_proj.weight", transpose=True),
            "down_b": stack("blocks.{}.mlp.down_proj.bias"),
        }
        extra = {"ln_q_w": t("merger.ln_q.weight")}
    else:
        blocks = {
            "n1_w": stack("blocks.{}.norm1.weight"),
            "n1_b": stack("blocks.{}.norm1.bias"),
            "qkv_w": stack("blocks.{}.attn.qkv.weight", transpose=True),
            "qkv_b": stack("blocks.{}.attn.qkv.bias"),
            "proj_w": stack("blocks.{}.attn.proj.weight", transpose=True),
            "proj_b": stack("blocks.{}.attn.proj.bias"),
            "n2_w": stack("blocks.{}.norm2.weight"),
            "n2_b": stack("blocks.{}.norm2.bias"),
            "fc1_w": stack("blocks.{}.mlp.fc1.weight", transpose=True),
            "fc1_b": stack("blocks.{}.mlp.fc1.bias"),
            "fc2_w": stack("blocks.{}.mlp.fc2.weight", transpose=True),
            "fc2_b": stack("blocks.{}.mlp.fc2.bias"),
        }
        extra = {
            "ln_q_w": t("merger.ln_q.weight"),
            "ln_q_b": t("merger.ln_q.bias"),
        }
    return {
        "patch_w": jnp.asarray(patch.reshape(cfg.embed_dim, -1).T, cfg.dtype),
        "blocks": blocks,
        **extra,
        "merge1_w": t("merger.mlp.0.weight", transpose=True),
        "merge1_b": t("merger.mlp.0.bias"),
        "merge2_w": t("merger.mlp.2.weight", transpose=True),
        "merge2_b": t("merger.mlp.2.bias"),
    }


def remap_language_state_dict(sd) -> dict:
    """Map Qwen2-VL language-model keys (`model.language_model.*`, plus
    the legacy `model.model.*` layout) onto the plain `model.*` names
    llama.params_from_torch_state_dict expects."""
    out = {}
    for k, v in sd.items():
        if k.startswith("model.visual.") or k.startswith("visual."):
            continue
        for old in ("model.language_model.", "language_model.model.",
                    "model.model."):
            if k.startswith(old):
                k = "model." + k[len(old):]
                break
        out[k] = v
    return out

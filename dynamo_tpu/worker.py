"""Engine worker process: engine + ingress + registration + publishers.

One worker = one JaxEngine serving one model over the fabric. It:
1. starts the engine thread (AsyncEngineRunner),
2. serves `generate` (and `flush`) on its ingress,
3. registers its endpoint instance under the process lease,
4. publishes the model card + entry (register_llm),
5. publishes KV events (subject kv_events.{instance_id}) and worker load
   metrics (subject metrics.{component}) for routers/planner.

Equivalent of the reference's engine-subprocess workers joining the
runtime (launch/dynamo-run/src/subprocess/vllm_inc.py + endpoint.rs).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

import msgpack

from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.engine.async_engine import (
    AsyncEngineRunner,
    EchoEngine,
    SpmdEngineRunner,
)
from dynamo_tpu.engine.engine import JaxEngine
from dynamo_tpu.engine.page_table import KvEvent
from dynamo_tpu.model_card import ModelDeploymentCard, register_llm
from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime import DistributedRuntime, IngressServer
from dynamo_tpu.subjects import (
    KV_EVENT_SUBJECT,
    KVBM_TIER_SUBJECT,
    METRICS_SUBJECT,
)
from dynamo_tpu import telemetry

logger = logging.getLogger(__name__)


class Worker:
    def __init__(
        self,
        runtime: DistributedRuntime,
        card: ModelDeploymentCard,
        engine_config: Optional[EngineConfig] = None,
        engine_kind: str = "jax",
        namespace: str = "dynamo",
        component: str = "backend",
        endpoint: str = "generate",
        checkpoint_path: Optional[str] = None,
        metrics_interval: float = 1.0,
        router_mode: str = "round_robin",
        enable_disagg: bool = False,
        disagg_config=None,
        prefill_queue_name: str = "prefill_queue",
        advertise_host: str = "127.0.0.1",
        kv_remote: bool = False,
        kv_remote_min_blocks: int = 2,
        kv_remote_timeout_s: float = 5.0,
        echo_delay: float = 0.0,
        mock_args=None,
        engine=None,
        drain_budget_s: float = 30.0,
        kv_sequencing: bool = True,
        kv_economy: bool = False,
    ):
        self.runtime = runtime
        self.card = card
        self.engine_config = engine_config
        self.engine_kind = engine_kind
        self.namespace = namespace
        self.component = component
        self.endpoint_name = endpoint
        self.checkpoint_path = checkpoint_path
        self.metrics_interval = metrics_interval
        self.router_mode = router_mode
        self.mock = None
        self.enable_disagg = enable_disagg
        self.disagg_config = disagg_config
        self.prefill_queue_name = prefill_queue_name
        #: host other processes (frontends, prefill workers) reach us at —
        #: must be a routable address in multi-host deployments
        self.advertise_host = advertise_host
        self.transfer_server = None
        self.disagg_router = None
        self.prefill_queue = None
        self.remote_prefills = 0
        #: G4 remote tier (cross-worker onboarding over the transfer plane)
        self.kv_remote = kv_remote
        self.kv_remote_min_blocks = kv_remote_min_blocks
        self.kv_remote_timeout_s = kv_remote_timeout_s
        self.kv_directory = None
        self.remote_onboards = 0
        self._fetch_client = None
        self._peer_source = None
        self._tier_event_buffer: list[tuple[int, Optional[int], str]] = []
        self.ingress = IngressServer()
        self.runner: Optional[AsyncEngineRunner] = None
        self.echo: Optional[EchoEngine] = None
        self.registration = None
        self.instance_id: str = ""
        self.echo_delay = echo_delay
        self.mock_args = mock_args
        #: engine_kind="external": a caller-supplied AsyncEngine — any
        #: object with `generate(context, PreprocessedRequest) -> async
        #: iterator of {token_ids, finish_reason}` joins as a first-class
        #: worker (the reference's engine-subprocess shims,
        #: launch/dynamo-run/src/subprocess/vllm_v1_inc.py). See
        #: docs/external_engines.md.
        if engine is not None and engine_kind != "external":
            # silently routing generate() to `engine` while start() builds
            # the native one would serve tokens from one engine and
            # metrics from another
            raise ValueError(
                f"engine= requires engine_kind='external' (got "
                f"{engine_kind!r})"
            )
        self.external = engine
        self._kv_event_buffer: list[KvEvent] = []
        #: KV event sequencing + rolling block-set digest (docs/
        #: operations.md "KV index consistency"): every published event
        #: carries a per-worker monotonic `seq`, and the metrics frames
        #: carry (seq, xxh3-fold, count) of the registered block set —
        #: indexers detect lost events (sequence gaps) and silent drift
        #: (digest mismatch) and resync from the `kv.snapshot` ingress
        #: op. Off = the exact pre-sequencing wire (no seq keys, no
        #: digest frame, no snapshot state), pinned by tests.
        self.kv_sequencing = kv_sequencing
        self._kv_seq = 0
        from dynamo_tpu.kv_router.digest import SetDigest

        self._kv_digest = SetDigest()
        #: designed degraded mode (docs/operations.md "Control-plane
        #: HA"): while no broker answers, KV events buffer UNSTAMPED in
        #: this bounded queue — a short outage loses nothing; overflow
        #: is stamped-and-dropped so the burned seqs surface as a
        #: detectable gap (indexers resync on reconnect) instead of
        #: silent divergence or unbounded memory
        self._kv_pending: list[dict] = []
        self.kv_pending_cap = int(
            os.environ.get("DYNTPU_KV_EVENT_BUFFER", "4096")
        )
        self.kv_events_dropped = 0
        self._tasks: list[asyncio.Task] = []
        #: graceful drain (docs/operations.md "Overload & draining"):
        #: SIGTERM or the `drain` ingress op flips this — the worker
        #: deregisters, refuses new ingress (router retries a survivor),
        #: finishes in-flight work within drain_budget_s, then `drained`
        #: fires so the CLI process can exit 0
        self.draining = False
        self.drain_budget_s = drain_budget_s
        self.drained = asyncio.Event()
        #: live role (closed-loop planner flips this between decode and
        #: prefill via the `flip` ingress op — docs/operations.md
        #: "Closed-loop autoscaling & role flips"). The engine, its KV
        #: pool, and the instance id survive a flip: hot pages stay
        #: registered (and G4-serveable), so prefix routing stays warm.
        self.role = "prefill" if "prefill" in component else "decode"
        #: where a flip to decode registers (a worker STARTED in the
        #: prefill role has component="prefill", which is not a decode
        #: pool — flips land it in the default decode pool)
        self.decode_component = (
            component if "prefill" not in component else "backend"
        )
        self.decode_endpoint = (
            endpoint if "prefill" not in component else "generate"
        )
        self.flips = 0
        self._prefill_embedded = None
        self._flip_lock = asyncio.Lock()
        #: worker handover (docs/operations.md "Rolling upgrades & worker
        #: handover"): live KV migration to a successor before this
        #: process exits — the planner's zero-downtime alternative to
        #: kill+spawn, and the drain path's warm-KV upgrade
        self.handing_over = False
        self._handover_phase: Optional[str] = None
        self.handovers = 0          # completed as the retiring side
        self.handover_fallbacks = 0  # degraded to plain drain
        self.handover_bytes = 0      # KV bytes shipped to successors
        self.handover_blocks = 0     # blocks accepted by successors
        self.handovers_adopted = 0   # blocks adopted as a successor
        self._handover_tasks: set[asyncio.Task] = set()
        #: KV economy (docs/operations.md "The KV economy"): per-prefix
        #: migration — a KV-economy router asks THIS worker (the holder
        #: of a hot prefix) to push just that chain to the worker it
        #: chose, through the same offer/transfer plane handover uses.
        #: The flag additionally drives the TierPolicy demotion loop on
        #: the publish cadence when the engine's allocator is tiered.
        self.kv_economy = kv_economy
        self._tier_policy = None
        self.migrations = 0           # completed as the source side
        self.migration_fallbacks = 0  # failed/degraded to cold prefill
        self.migration_bytes = 0      # KV bytes pushed to destinations
        self.migration_blocks = 0     # blocks accepted by destinations

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self.engine_kind == "external":
            if self.external is None:
                raise ValueError(
                    "engine_kind='external' needs an `engine` object "
                    "implementing AsyncEngine.generate"
                )
            # foreign engines publish KV events (prefix routing) by
            # calling this sink — duck-typed so a shim can opt out
            if hasattr(self.external, "on_kv_event"):
                self.external.on_kv_event = self._kv_event_buffer.append
        elif self.engine_kind == "echo":
            self.echo = EchoEngine(delay=self.echo_delay)
        elif self.engine_kind == "mock":
            from dynamo_tpu.mocker import MockEngine, MockEngineArgs

            args = self.mock_args or MockEngineArgs(
                page_size=self.card.kv_page_size, salt=self.card.name
            )
            if (
                args.page_size != self.card.kv_page_size
                or args.salt != self.card.name
            ):
                # Routers hash blocks with (card page size, card name) —
                # a mismatched mock would emit events no router can match.
                raise ValueError(
                    f"mock_args page_size/salt ({args.page_size}, "
                    f"{args.salt!r}) must match the card "
                    f"({self.card.kv_page_size}, {self.card.name!r})"
                )
            self.mock = MockEngine(
                args,
                on_kv_event=lambda e: self._kv_event_buffer.append(e),
            )
        else:
            # Engine construction (param init, first compiles) blocks for
            # seconds — run it off-loop or the fabric lease keepalives
            # starve and the registration lease expires before it exists.
            engine = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: JaxEngine(
                    self.engine_config,
                    on_kv_event=lambda e: self._kv_event_buffer.append(e),
                    checkpoint_path=self.checkpoint_path,
                    on_tier_event=(
                        (lambda h, p, t: self._tier_event_buffer.append(
                            (h, p, t)
                        ))
                        if self.kv_remote or self.kv_economy
                        else None
                    ),
                ),
            )
            if engine._multiproc:
                # One replica of a cross-host lockstep group: this host
                # (the leader) owns the fabric endpoint; admissions ride
                # the SpmdDriver broadcast to the follower replicas
                # (engine/spmd.py). Disagg/G4 mutate engine state through
                # runner.submit and would desync the replicas.
                if self.enable_disagg or self.kv_remote:
                    raise ValueError(
                        "disagg / kv-remote are not supported on a "
                        "cross-host SPMD group yet"
                    )
                from dynamo_tpu.engine.spmd import SpmdDriver

                self.runner = SpmdEngineRunner(engine, SpmdDriver(engine))
            else:
                self.runner = AsyncEngineRunner(engine)
            self.runner.start()

        self.ingress.add_handler("generate", self._generate)
        self.ingress.add_handler("embed", self._embed)
        self.ingress.add_handler("flush", self._flush)
        self.ingress.add_handler("kv.snapshot", self._kv_snapshot_handler)
        self.ingress.add_handler("drain", self._drain_handler)
        self.ingress.add_handler("flip", self._flip_handler)
        self.ingress.add_handler("handover", self._handover_handler)
        self.ingress.add_handler("handover_offer", self._handover_offer_handler)
        self.ingress.add_handler("migrate_prefix", self._migrate_prefix_handler)
        await self.ingress.start()

        metadata = {"model": self.card.name}
        if self.runner is not None or self.mock is not None:
            # role-flip capable: has an ingress the planner can reach and
            # an engine whose KV pool survives the flip (external/echo
            # engines have no paged KV to keep warm — they stay put)
            metadata["flippable"] = True
        # The KV transfer plane serves every single-host engine worker,
        # not just disagg/kv-remote ones: worker handover ships the
        # retiring worker's registered pages through it, so any jax
        # worker must be able to RECEIVE pages (docs/operations.md
        # "Rolling upgrades & worker handover"). SPMD groups refuse —
        # extraction holds only the process-local Hkv slice.
        if self.runner is not None and not isinstance(
            self.runner, SpmdEngineRunner
        ):
            from dynamo_tpu.disagg import KvTransferServer, device_transfer

            # decode also serves G4 fetches / could stage in future
            # reversals; advertise a routable pull address in multi-host
            device_transfer.configure(self.advertise_host)

            runner = self.runner

            async def write_fn(page_ids, k, v):
                await runner.submit(
                    lambda eng: eng.inject_pages(page_ids, k, v)
                )

            async def device_write_fn(page_ids, k, v):
                await runner.submit(
                    lambda eng: eng.inject_pages_device(page_ids, k, v)
                )

            fetch_fn = None
            if self.kv_remote:
                async def fetch_fn(seq_hashes):
                    return await runner.submit(
                        lambda eng: eng.serve_blocks(seq_hashes)
                    )

            self.transfer_server = KvTransferServer(
                write_fn, device_write_fn=device_write_fn, fetch_fn=fetch_fn
            )
            await self.transfer_server.start()
            metadata["kv_transfer_port"] = self.transfer_server.port
        if self.enable_disagg and self.runner is not None:
            from dynamo_tpu.disagg import DisaggregatedRouter, PrefillQueue

            self.disagg_router = DisaggregatedRouter(
                self.runtime.fabric, self.disagg_config
            )
            await self.disagg_router.start()
            self.prefill_queue = PrefillQueue(
                self.runtime.fabric, self.prefill_queue_name
            )

        if (
            self.kv_economy
            and self.runner is not None
            and not isinstance(self.runner, SpmdEngineRunner)
        ):
            alloc = getattr(self.runner.engine, "allocator", None)
            if hasattr(alloc, "demote"):
                from dynamo_tpu.kv_economy import TierPolicy

                self._tier_policy = TierPolicy(alloc)
        ep = (
            self.runtime.namespace(self.namespace)
            .component(self.component)
            .endpoint(self.endpoint_name)
        )
        self.registration = await ep.register(
            self.advertise_host, self.ingress.port, metadata=metadata
        )
        self.instance_id = self.registration.instance.instance_id
        await register_llm(
            self.runtime.fabric, self.card, self.namespace, self.component,
            self.endpoint_name, lease_id=self.runtime.primary_lease,
            router_mode=self.router_mode,
        )
        if self.kv_remote and self.runner is not None:
            from dynamo_tpu.disagg.transfer import KvTransferClient
            from dynamo_tpu.kvbm.directory import BlockDirectory
            from dynamo_tpu.runtime.component import InstanceSource

            self.kv_directory = BlockDirectory(
                self.runtime.fabric, own_instance_id=self.instance_id
            )
            await self.kv_directory.start()
            self._fetch_client = KvTransferClient()
            self._peer_source = InstanceSource(
                self.runtime.fabric, self.namespace, self.component,
                self.endpoint_name,
            )
            await self._peer_source.start()
        # fleet trace plane: finished spans buffer for shipping on the
        # metrics-frame cadence (no-op while tracing is off); fleet
        # events (flips, handovers, drains) ride the same shipper
        from dynamo_tpu.telemetry import traceplane

        traceplane.ensure_shipping()
        loop = asyncio.get_running_loop()
        self._tasks.append(loop.create_task(self._publish_loop()))
        logger.info(
            "worker %s serving %s on :%d", self.instance_id, self.card.name,
            self.ingress.port,
        )

    def _busy(self) -> bool:
        # ingress inflight covers the whole request lifecycle —
        # runner._pending hand-off, disagg transfer waits, and the
        # final response frames — not just scheduler occupancy.
        if self.ingress.num_inflight > 0:
            return True
        return self.runner is not None and self.runner.engine.has_work

    async def _deregister(self) -> None:
        if self.registration is None:
            return
        try:
            await self.registration.deregister()
        except Exception:
            # Routers will keep sending until the lease expires — make
            # that window observable instead of silent.
            logger.warning(
                "deregister failed; relying on lease expiry", exc_info=True
            )
        self.registration = None

    async def drain(self, budget_s: Optional[float] = None) -> bool:
        """Graceful drain (docs/operations.md "Overload & draining"):
        deregister so routers stop choosing this worker, refuse new
        ingress (`_generate` raises RetryableHandlerError — the router
        retries a survivor), finish in-flight requests within the
        budget, then fire `drained` so the host process exits 0. KV
        stays serveable the whole time: --kv-remote peers can still
        onboard this worker's blocks over the transfer plane until the
        process exits (the serve/adopt hand-off path). Returns True if
        everything in flight finished inside the budget."""
        if self.draining:
            await self.drained.wait()
            return not self._busy()
        self.draining = True
        budget = self.drain_budget_s if budget_s is None else budget_s
        logger.info(
            "worker %s draining (budget %.1fs, %d in flight)",
            self.instance_id, budget, self.ingress.num_inflight,
        )
        telemetry.events.record(
            "drain", source=self.instance_id,
            inflight=self.ingress.num_inflight, budget_s=budget,
        )
        # ship NOW, not on the next publish tick — a quiet drain exits
        # before the tick and would take its own timeline entry with it
        from dynamo_tpu.telemetry import traceplane

        await traceplane.ship_once(self.runtime.fabric, self.instance_id)
        await self._deregister()
        clean = True
        deadline = asyncio.get_running_loop().time() + max(budget, 0.0)
        while self._busy() and asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(0.05)
        if self._busy():
            clean = False
            logger.warning(
                "drain budget exhausted: %d calls still in flight",
                self.ingress.num_inflight,
            )
        else:
            logger.info("worker %s drained", self.instance_id)
        self.drained.set()
        return clean

    async def _drain_handler(self, ctx, request):
        """`drain` ingress op (POST /v1/admin/drain at the frontend):
        acknowledge immediately, wind down in the background."""
        budget = None
        if isinstance(request, dict) and request.get("budget_s") is not None:
            budget = float(request["budget_s"])
        task = asyncio.get_running_loop().create_task(self.drain(budget))
        task.add_done_callback(
            lambda t: t.cancelled() or t.exception()  # observe, never raise
        )
        yield {
            "draining": True,
            "inflight": self.ingress.num_inflight,
            "budget_s": self.drain_budget_s if budget is None else budget,
        }

    # -- role flips (docs/operations.md "Closed-loop autoscaling & role
    # flips"): the planner's alternative to kill+spawn -------------------

    async def flip_role(
        self, role: str, budget_s: Optional[float] = None
    ) -> bool:
        """Flip this worker between decode and prefill roles in place.

        decode -> prefill: deregister from the decode endpoint (routers
        retry survivors), let in-flight decodes finish within the budget
        (they keep streaming even past it — the ingress stays up), start
        an embedded prefill-queue consumer on the SAME engine runner,
        and register the prefill endpoint under the SAME instance id.
        The KV pool is untouched: every page the worker computed stays
        registered, serveable to G4 peers over the transfer plane, and
        warm for the flip back.

        prefill -> decode: stop consuming the queue (in-flight prefills
        finish; borrowed runner keeps running) and re-register the
        decode endpoint, again under the same instance id — routers'
        prefix indexes for this id apply immediately, so the first
        request with a cached prefix hits warm pages."""
        if role not in ("decode", "prefill"):
            raise ValueError(f"unknown role {role!r}")
        if role == "prefill" and self.runner is None and self.mock is None:
            raise ValueError(
                f"engine kind {self.engine_kind!r} cannot serve the "
                "prefill role"
            )
        async with self._flip_lock:
            if role == self.role:
                return True
            loop = asyncio.get_running_loop()
            if role == "prefill":
                # quiesce decode: stop being chosen, finish what's here
                self.draining = True
                await self._deregister()
                budget = (
                    self.drain_budget_s if budget_s is None else budget_s
                )
                deadline = loop.time() + max(budget, 0.0)
                while self._busy() and loop.time() < deadline:
                    await asyncio.sleep(0.05)
                if self._busy():
                    logger.warning(
                        "flip budget exhausted with %d in flight; they "
                        "keep streaming while the worker serves prefill",
                        self.ingress.num_inflight,
                    )
                if self.runner is not None and self.engine_config is not None:
                    from dynamo_tpu.disagg.prefill_worker import PrefillWorker

                    self._prefill_embedded = PrefillWorker(
                        self.runtime,
                        self.engine_config,
                        namespace=self.namespace,
                        queue_name=self.prefill_queue_name,
                        runner=self.runner,
                        advertise_host=self.advertise_host,
                        register=False,
                    )
                    await self._prefill_embedded.start()
                ep = (
                    self.runtime.namespace(self.namespace)
                    .component("prefill")
                    .endpoint("prefill")
                )
                self.registration = await ep.register(
                    self.advertise_host,
                    self.ingress.port,
                    metadata={"model": self.card.name, "flippable": True},
                    instance_id=self.instance_id,
                )
                self.role = "prefill"
                self.draining = False
            else:
                await self._deregister()
                if self._prefill_embedded is not None:
                    await self._prefill_embedded.stop()
                    self._prefill_embedded = None
                metadata = {"model": self.card.name, "flippable": True}
                if self.transfer_server is not None:
                    metadata["kv_transfer_port"] = self.transfer_server.port
                ep = (
                    self.runtime.namespace(self.namespace)
                    .component(self.decode_component)
                    .endpoint(self.decode_endpoint)
                )
                self.registration = await ep.register(
                    self.advertise_host,
                    self.ingress.port,
                    metadata=metadata,
                    instance_id=self.instance_id,
                )
                self.role = "decode"
                self.draining = False
            self.flips += 1
            logger.info(
                "worker %s flipped to %s (flip #%d)",
                self.instance_id, self.role, self.flips,
            )
            telemetry.events.record(
                "role_flip", source=self.instance_id,
                dst=self.role,
                src="decode" if self.role == "prefill" else "prefill",
                flips=self.flips,
            )
            return True

    async def _flip_handler(self, ctx, request):
        """`flip` ingress op (the planner's FleetFlipper): validate,
        acknowledge immediately, flip in the background."""
        role = (request or {}).get("role") if isinstance(request, dict) else None
        if role not in ("decode", "prefill"):
            raise ValueError(f"flip needs role=decode|prefill, got {role!r}")
        if role == "prefill" and self.runner is None and self.mock is None:
            raise ValueError(
                f"engine kind {self.engine_kind!r} cannot serve the "
                "prefill role"
            )
        budget = None
        if request.get("budget_s") is not None:
            budget = float(request["budget_s"])
        task = asyncio.get_running_loop().create_task(
            self.flip_role(role, budget)
        )
        task.add_done_callback(
            lambda t: t.cancelled() or t.exception()  # observe, never raise
        )
        yield {
            "flipping": True,
            "to": role,
            "from": self.role,
            "inflight": self.ingress.num_inflight,
        }

    # -- worker handover (docs/operations.md "Rolling upgrades & worker
    # handover"): live KV migration to a successor, then exit 0 ----------

    def _handover_capable(self) -> bool:
        from dynamo_tpu.engine.async_engine import SpmdEngineRunner as _Spmd

        if self.mock is not None:
            return True
        return self.runner is not None and not isinstance(
            self.runner, _Spmd
        )

    async def handover(
        self,
        successor_id: Optional[str] = None,
        budget_s: Optional[float] = None,
    ) -> bool:
        """Retire this worker with its KV pages kept warm fleet-wide:

        1. **drain** — stop admissions (deregister; routers retry
           survivors), exactly the PR-8 drain machinery;
        2. **extract** — topo-order the device-registered block set and
           pull each batch to host in the canonical quantized wire
           format (engine.export_blocks_by_hash);
        3. **offer/transfer** — the successor reserves pages and arms a
           transfer waiter (handover_offer), then the bytes ride the
           normal `KvTransferClient.send` page write — device/shm/bulk/
           inline, checksummed end to end;
        4. **adopt** (successor side) — landed pages get registered,
           'stored' events publish, KV-aware routers score the successor
           immediately; this worker announces the bulk ownership move on
           its KV-event subject (`handed_over`);
        5. **finish** — in-flight streams get the remaining budget, then
           `drained` fires and the host process exits 0. Streams still
           open at that point continue on survivors via the PR-10 replay
           path — their prompt blocks are already warm on the successor,
           so the replayed prefill is a prefix hit, not a recompute.

        ANY failure mid-phase degrades to the plain drain+replay path:
        pages freed on both sides, zero hung streams. Returns True only
        when the migration completed."""
        if self.draining:
            await self.drained.wait()
            return False
        loop = asyncio.get_running_loop()
        self.handing_over = True
        self.draining = True
        self._handover_phase = "drain"
        logger.info(
            "worker %s handing over (%d in flight)",
            self.instance_id, self.ingress.num_inflight,
        )
        telemetry.events.record(
            "handover", source=self.instance_id, phase="start",
            successor=successor_id, inflight=self.ingress.num_inflight,
        )
        # ship immediately: the retiring process exits at the end of
        # this method — its timeline entries must not die with it
        from dynamo_tpu.telemetry import traceplane

        await traceplane.ship_once(self.runtime.fabric, self.instance_id)
        await self._deregister()
        ok = False
        try:
            ok = await self._handover_migrate(successor_id)
        except Exception:
            logger.exception(
                "handover migration failed; degrading to drain+replay"
            )
        if ok:
            self.handovers += 1
            logger.info("worker %s handover complete", self.instance_id)
            telemetry.events.record(
                "handover", source=self.instance_id, phase="complete",
                bytes=self.handover_bytes, blocks=self.handover_blocks,
            )
        else:
            self.handover_fallbacks += 1
            logger.warning(
                "worker %s handover fell back to plain drain (streams "
                "continue on survivors by replay-with-recompute)",
                self.instance_id,
            )
            telemetry.events.record(
                "handover", severity="warning", source=self.instance_id,
                phase="fallback",
            )
        self._handover_phase = "finish"
        budget = self.drain_budget_s if budget_s is None else budget_s
        deadline = loop.time() + max(budget, 0.0)
        while self._busy() and loop.time() < deadline:
            await asyncio.sleep(0.05)
        if self._busy():
            logger.info(
                "handover: %d stream(s) still in flight at exit; they "
                "continue on survivors via stream replay",
                self.ingress.num_inflight,
            )
        self._handover_phase = None
        self.handing_over = False
        self.drained.set()
        # flush the complete/fallback event (and any final spans)
        # before the host process exits
        await traceplane.ship_once(self.runtime.fabric, self.instance_id)
        return ok

    async def _pick_successor(self, successor_id: Optional[str]):
        """A live peer of this worker's CURRENT role to adopt the pages:
        the named instance when given, else every candidate sorted (the
        caller tries them in order). Returns a list of Instance."""
        from dynamo_tpu.runtime.component import InstanceSource

        if self.role == "decode":
            comp, ep = self.decode_component, self.decode_endpoint
        elif "prefill" in self.component:
            comp, ep = self.component, self.endpoint_name
        else:
            comp, ep = "prefill", "prefill"
        src = InstanceSource(self.runtime.fabric, self.namespace, comp, ep)
        await src.start()
        try:
            deadline = asyncio.get_running_loop().time() + 2.0
            while asyncio.get_running_loop().time() < deadline:
                peers = [
                    i
                    for i in src.list()
                    if i.instance_id != self.instance_id
                    and (
                        successor_id is None
                        or i.instance_id == successor_id
                    )
                ]
                if peers:
                    return peers
                await asyncio.sleep(0.05)
            return []
        finally:
            await src.stop()

    async def _handover_migrate(self, successor_id: Optional[str]) -> bool:
        from dynamo_tpu import handover as ho
        from dynamo_tpu.testing import faults

        if not self._handover_capable():
            return False
        runner, mock = self.runner, self.mock
        self._handover_phase = "extract"
        await faults.fire("handover.extract")
        if runner is not None:
            metas = await runner.submit(lambda eng: eng.handover_metas())
        else:
            metas = ho.topo_order_metas(
                list(mock.allocator._page_meta.values())
            )
        peers = await self._pick_successor(successor_id)
        if not peers:
            logger.warning("handover: no successor instance available")
            return False
        succ, last_err = None, None
        for cand in peers[:3]:
            try:
                done = await self._handover_to(cand, metas, runner, mock)
            except Exception as e:
                last_err = e
                logger.warning(
                    "handover to %s failed: %s", cand.instance_id, e
                )
                continue
            if done:
                succ = cand
                break
        if succ is None:
            if last_err is not None:
                logger.warning("handover: every candidate failed")
            return False
        # bulk ownership move: indexers reassign this worker's block
        # entries to the successor NOW instead of waiting for lease
        # expiry + stored-event propagation (kv_router/indexer.py
        # `handed_over`). Rides the SAME stamped path as store/remove
        # events — with any still-buffered events flushed ahead of it in
        # the batch — so the move keeps its place in the sequence stream
        # and this worker's advertised digest empties with it.
        pending = self._kv_event_buffer[: len(self._kv_event_buffer)]
        del self._kv_event_buffer[: len(pending)]
        held, self._kv_pending = self._kv_pending, []
        await self._publish_kv_events(
            held
            + [self._kv_event_wire(e) for e in pending]
            + [{
                "kind": "handed_over",
                "block_hashes": [],
                "successor": succ.instance_id,
            }]
        )
        return True

    async def _handover_to(self, succ, metas, runner, mock) -> bool:
        """Ship every batch to ONE candidate successor. True when all
        batches were offered (an empty want-list counts — the successor
        already holds those blocks)."""
        from dynamo_tpu import handover as ho
        from dynamo_tpu.testing import faults

        if not metas:
            # nothing registered to migrate — the handover is trivially
            # complete (the drain tail still runs)
            return True
        client = None
        try:
            for batch in ho.batches(metas):
                self._handover_phase = "offer"
                await faults.fire("handover.offer")
                if mock is not None:
                    reply = await ho.call_ingress(
                        succ.host, succ.port, "handover_offer",
                        {
                            "metas": ho.metas_to_wire(batch),
                            "source": self.instance_id,
                            "payload": False,
                        },
                    )
                    self.handover_blocks += int(reply.get("adopted") or 0)
                    continue
                exported = await runner.submit(
                    lambda eng, b=batch: eng.export_blocks_by_hash(
                        [h for h, _, _ in b]
                    )
                )
                if exported is None:
                    continue  # evicted since the listing — batch gone
                emetas, k, v = exported
                reply = await ho.call_ingress(
                    succ.host, succ.port, "handover_offer",
                    {
                        "metas": ho.metas_to_wire(emetas),
                        "source": self.instance_id,
                        "payload": True,
                    },
                )
                page_ids = reply.get("page_ids") or []
                if not page_ids:
                    continue  # successor already holds the whole batch
                want = list(reply.get("want_idx") or ())
                self._handover_phase = "transfer"
                await faults.fire("handover.transfer")
                if client is None:
                    from dynamo_tpu.disagg.transfer import KvTransferClient

                    client = KvTransferClient()
                if len(want) != k.shape[2]:
                    import numpy as np

                    k = np.ascontiguousarray(k[:, :, want])
                    v = np.ascontiguousarray(v[:, :, want])
                ok = await asyncio.wait_for(
                    client.send(
                        reply["host"], int(reply["port"]), reply["rid"],
                        page_ids, k, v, 0,
                    ),
                    timeout=ho.ADOPT_TIMEOUT_S,
                )
                if not ok:
                    return False
                self.handover_bytes += int(k.nbytes + v.nbytes)
                self.handover_blocks += len(page_ids)
                if ho.MAX_BYTES and self.handover_bytes >= ho.MAX_BYTES:
                    logger.info(
                        "handover: byte budget reached (%d); leaving the "
                        "colder tail behind", self.handover_bytes,
                    )
                    break
            return True
        finally:
            if client is not None:
                client.close()

    async def _handover_handler(self, ctx, request):
        """`handover` ingress op (POST /v1/admin/handover, planner
        FleetHandover): validate, acknowledge immediately, migrate in
        the background — mirrors the drain/flip handler shape."""
        req = request if isinstance(request, dict) else {}
        if not self._handover_capable():
            raise ValueError(
                f"engine kind {self.engine_kind!r} has no KV pool to hand "
                "over; use drain"
            )
        if self.draining:
            # refuse instead of ack: an ack here would make a planner
            # (whose instance watch hasn't seen the deregistration yet)
            # count the SAME victim as a second retirement and skip its
            # kill fallback — the caller must pick another worker
            raise ValueError(
                f"worker {self.instance_id} is already "
                f"{'handing over' if self.handing_over else 'draining'}"
            )
        successor = req.get("successor") or None
        budget = (
            float(req["budget_s"]) if req.get("budget_s") is not None else None
        )
        task = asyncio.get_running_loop().create_task(
            self.handover(successor, budget)
        )
        task.add_done_callback(
            lambda t: t.cancelled() or t.exception()  # observe, never raise
        )
        yield {
            "handing_over": True,
            "inflight": self.ingress.num_inflight,
            "successor": successor,
            "budget_s": self.drain_budget_s if budget is None else budget,
        }

    async def _handover_offer_handler(self, ctx, request):
        """Successor side: reserve pages for the offered block batch and
        arm a transfer waiter; the source then writes the bytes through
        the normal transfer plane addressed at those pages, and the
        watchdog task registers them on landing (or frees them on
        timeout/failure — a dead source can never leak our pages)."""
        import time as _time
        import uuid as _uuid

        from dynamo_tpu import handover as ho
        from dynamo_tpu.telemetry import phases
        from dynamo_tpu.testing import faults

        await faults.fire("handover.adopt")
        if self.draining:
            from dynamo_tpu.runtime.ingress import RetryableHandlerError

            raise RetryableHandlerError(
                f"worker {self.instance_id} is draining; cannot adopt"
            )
        req = request if isinstance(request, dict) else {}
        metas = ho.metas_from_wire(req.get("metas") or [])
        if not metas:
            yield {"adopted": 0, "page_ids": []}
            return
        if self.mock is not None:
            # mock fleets: metadata-only adopt — the mock's KV "content"
            # IS the hash chain, so registering the metas gives replayed
            # streams the same warm-prefix admission a real pool would
            alloc = self.mock.allocator
            n = 0
            for h, p, toks in metas:
                if alloc.match_length([h]):
                    continue
                pages = alloc.allocate(1)
                if pages is None:
                    break
                alloc.register_promoted(pages[0], h, p, tuple(toks))
                alloc.free(pages)
                n += 1
            self.handovers_adopted += n
            yield {"adopted": n, "page_ids": [], "payload": False}
            return
        if (
            self.runner is None
            or self.transfer_server is None
            or not self._handover_capable()
        ):
            raise ValueError(
                f"worker {self.instance_id} cannot adopt a handover"
            )
        if req.get("payload") is False:
            raise ValueError("metadata-only offer refused: this worker "
                             "holds real KV bytes")
        runner = self.runner
        prep = await runner.submit(
            lambda eng: eng.prepare_handover_adopt(metas)
        )
        if prep is None:
            yield {"adopted": 0, "page_ids": []}
            return
        pages, kept, want_idx = prep
        rid = f"ho-{self.instance_id}-{_uuid.uuid4().hex[:8]}"
        waiter = self.transfer_server.expect(rid)
        t0 = _time.perf_counter()

        async def _watch():
            try:
                await asyncio.wait_for(waiter, ho.ADOPT_TIMEOUT_S)
            except BaseException:
                self.transfer_server.forget(rid)
                await runner.submit(
                    lambda eng: eng.abort_handover_adopt(pages)
                )
                logger.warning(
                    "handover adopt %s never landed; %d reserved pages "
                    "freed", rid, len(pages),
                )
                return
            n = await runner.submit(
                lambda eng: eng.commit_handover_adopt(pages, kept)
            )
            self.handovers_adopted += n
            phases.observe(
                "handover_adopt_ms", (_time.perf_counter() - t0) * 1000.0
            )
            logger.info(
                "adopted %d handover block(s) from %s",
                n, req.get("source") or "?",
            )

        task = asyncio.get_running_loop().create_task(_watch())
        self._handover_tasks.add(task)
        task.add_done_callback(self._handover_tasks.discard)
        yield {
            "rid": rid,
            "page_ids": list(pages),
            "want_idx": list(want_idx),
            "host": self.advertise_host,
            "port": self.transfer_server.port,
        }

    async def _hot_prefix_hashes(self, max_blocks: int) -> list:
        """The deepest resident prefix chain, root-first, capped at
        `max_blocks` — the donor side of `migrate_prefix {auto: true}`.
        Depth is the proxy for heat: the longest registered chain is the
        prefix most requests have been extending."""

        def pick(metas):
            parent = {h: p for h, p, _t in metas}
            if not parent:
                return []
            depth: dict = {}

            def d(h):
                seen = []
                x = h
                while x is not None and x not in depth and x in parent:
                    seen.append(x)
                    x = parent.get(x)
                    if len(seen) > len(parent) + 1:
                        break  # corrupt-meta cycle guard
                base = depth.get(x, 0) if x is not None else 0
                for i, y in enumerate(reversed(seen)):
                    depth[y] = base + i + 1
                return depth.get(h, 0)

            tip = max(parent, key=lambda h: (d(h), h))
            chain = []
            x = tip
            while x is not None and x in parent:
                chain.append(x)
                x = parent.get(x)
            chain.reverse()
            return [int(h) for h in chain[:max_blocks]]

        if self.mock is not None:
            return pick(list(self.mock.allocator._page_meta.values()))
        if self.runner is None:
            return []
        return await self.runner.submit(
            lambda eng: pick(list(eng.allocator._page_meta.values()))
        )

    async def _migrate_prefix_handler(self, ctx, request):
        """`migrate_prefix` ingress op — the KV economy's unit of work
        (docs/operations.md "The KV economy"). A KV-economy router picked
        worker D for a request whose prefix THIS worker holds deeper;
        when the CostModel says the bytes are cheaper than D's cold
        prefill, the router asks us (the source) to PUSH just that chain
        to D through the unchanged handover offer/transfer plane:

        - mock fleets: metadata-only offer (the mock's KV "content" IS
          the hash chain) — D registers the metas and the request
          admits warm;
        - jax engines: export_blocks_by_hash in the canonical quantized
          wire format, offer, then the normal checksummed
          KvTransferClient page write.

        Blocks are COPIED, not moved — both workers then hold (and
        advertise) the prefix, which is exactly what a hot prefix
        wants. ANY failure degrades to D cold-prefilling: our export
        refs free in its finally, D's adopt watchdog frees reserved
        pages on transfer timeout, and the reply says migrated=False so
        the router stops waiting. Nothing leaks, nothing hangs."""
        import numpy as np

        from dynamo_tpu import handover as ho
        from dynamo_tpu.testing import faults

        req = request if isinstance(request, dict) else {}
        hashes = [int(h) for h in (req.get("hashes") or [])]
        dest = req.get("dest") or {}
        if not dest.get("host") or not dest.get("port"):
            yield {"migrated": False, "error": "bad request"}
            return
        if self.draining or not self._handover_capable():
            yield {"migrated": False, "error": "source unavailable"}
            return
        if not hashes and req.get("auto"):
            # planner pre-warm / victim-drain mode: no router in the
            # loop to name a chain, so WE pick our deepest resident
            # prefix (the hottest thing a cold newcomer can inherit)
            hashes = await self._hot_prefix_hashes(
                int(req.get("max_blocks") or 32)
            )
        if not hashes:
            yield {"migrated": False, "error": "nothing to migrate"}
            return
        try:
            await faults.fire("migrate.extract")
            if self.mock is not None:
                alloc = self.mock.allocator
                meta_by_hash = {
                    h: (h, p, toks)
                    for h, p, toks in alloc._page_meta.values()
                }
                metas = []
                for h in hashes:
                    meta = meta_by_hash.get(h)
                    if meta is None:
                        break  # evicted since the router's index view
                    metas.append(meta)
                if not metas:
                    yield {"migrated": False, "error": "prefix evicted"}
                    return
                await faults.fire("migrate.offer")
                await faults.fire("migrate.transfer")
                reply = await ho.call_ingress(
                    dest["host"], int(dest["port"]), "handover_offer",
                    {
                        "metas": ho.metas_to_wire(metas),
                        "source": self.instance_id,
                        "payload": False,
                    },
                )
                blocks = int(reply.get("adopted") or 0)
                self.migrations += 1
                self.migration_blocks += blocks
                telemetry.events.record(
                    "kv_migration", source=self.instance_id,
                    dest=dest.get("instance_id"), blocks=blocks,
                    coalesce_s=5.0,
                )
                yield {"migrated": True, "blocks": blocks, "bytes": 0}
                return
            runner = self.runner
            exported = await runner.submit(
                lambda eng: eng.export_blocks_by_hash(hashes)
            )
            if exported is None:
                yield {"migrated": False, "error": "prefix evicted"}
                return
            emetas, k, v = exported
            await faults.fire("migrate.offer")
            reply = await ho.call_ingress(
                dest["host"], int(dest["port"]), "handover_offer",
                {
                    "metas": ho.metas_to_wire(emetas),
                    "source": self.instance_id,
                    "payload": True,
                },
            )
            page_ids = reply.get("page_ids") or []
            if not page_ids:
                # destination already holds the whole chain — the
                # router's view lagged; count it migrated (the request
                # admits warm either way)
                self.migrations += 1
                yield {"migrated": True, "blocks": 0, "bytes": 0}
                return
            want = list(reply.get("want_idx") or ())
            await faults.fire("migrate.transfer")
            if len(want) != k.shape[2]:
                k = np.ascontiguousarray(k[:, :, want])
                v = np.ascontiguousarray(v[:, :, want])
            from dynamo_tpu.disagg.transfer import KvTransferClient

            client = KvTransferClient()
            try:
                ok = await asyncio.wait_for(
                    client.send(
                        reply["host"], int(reply["port"]), reply["rid"],
                        page_ids, k, v, 0,
                    ),
                    timeout=ho.ADOPT_TIMEOUT_S,
                )
            finally:
                client.close()
            if not ok:
                raise RuntimeError("transfer send failed")
            nbytes = int(k.nbytes + v.nbytes)
            self.migrations += 1
            self.migration_bytes += nbytes
            self.migration_blocks += len(page_ids)
            telemetry.events.record(
                "kv_migration", source=self.instance_id,
                dest=dest.get("instance_id"), blocks=len(page_ids),
                bytes=nbytes, coalesce_s=5.0,
            )
            yield {
                "migrated": True, "blocks": len(page_ids), "bytes": nbytes,
            }
        except Exception as e:
            self.migration_fallbacks += 1
            telemetry.events.record(
                "kv_migration", severity="warning",
                source=self.instance_id, dest=dest.get("instance_id"),
                phase="fallback",
            )
            logger.warning(
                "prefix migration to %s failed (request cold-prefills): "
                "%s", dest.get("instance_id") or "?", e,
            )
            yield {"migrated": False, "error": str(e)}

    async def stop(self, drain_timeout: float = 30.0) -> None:
        """Graceful shutdown (reference: the vLLM drain handlers,
        examples worker.py:156-170): deregister FIRST so routers stop
        sending here, let in-flight requests finish up to drain_timeout,
        then tear the planes down."""
        await self._deregister()
        if drain_timeout > 0 and not self.drained.is_set():
            deadline = asyncio.get_running_loop().time() + drain_timeout
            while self._busy() and asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.05)
            if self._busy():
                logger.warning(
                    "drain timeout: %d calls still in flight; closing",
                    self.ingress.num_inflight,
                )
        for t in self._tasks:
            t.cancel()
        for t in list(self._handover_tasks):
            # cancelling an adopt watchdog frees its page reservation
            # (the _watch except-path) before the runner goes away
            t.cancel()
        if self._handover_tasks:
            await asyncio.gather(
                *self._handover_tasks, return_exceptions=True
            )
        if self._prefill_embedded is not None:
            await self._prefill_embedded.stop()
            self._prefill_embedded = None
        await self.ingress.stop()
        if self.transfer_server is not None:
            await self.transfer_server.stop()
        if self.disagg_router is not None:
            await self.disagg_router.stop()
        if self.kv_directory is not None:
            await self.kv_directory.stop()
        if self._peer_source is not None:
            await self._peer_source.stop()
        if self._fetch_client is not None:
            self._fetch_client.close()
        if self.runner:
            self.runner.stop()

    # -- handlers ----------------------------------------------------------

    async def _generate(self, ctx, request: dict):
        if self.draining or self.role != "decode":
            # the router retries a survivor; this instance is already
            # deregistered (draining, or flipped to the prefill role —
            # a stale router list may still push here briefly) and only
            # finishing what it has
            from dynamo_tpu.runtime.ingress import RetryableHandlerError

            raise RetryableHandlerError(
                f"worker {self.instance_id} is "
                f"{'draining' if self.draining else 'serving prefill'}"
            )
        pre = PreprocessedRequest.from_dict(request)
        if self.kv_directory is not None and pre.mm_embeds is None:
            try:
                await self._maybe_remote_onboard(pre)
            except Exception:
                logger.exception("remote onboard failed; serving cold")
        if self.prefill_queue is not None and await self._want_remote(pre):
            handled = False
            async for event in self._generate_disagg(ctx, pre):
                handled = True
                yield event
            if handled:
                return
            # transfer fell through — run the normal local path below
        gen = (
            self.external or self.echo or self.mock or self.runner
        ).generate(ctx, pre)
        if pre.deadline and self.runner is None:
            # engines without the runner's built-in deadline enforcement
            # (external subprocess / echo / mock): the guard cancels the
            # context on expiry — the cancel frame reaches subprocess
            # children — and error-finishes the stream
            from dynamo_tpu.runtime.overload import deadline_guard

            gen = deadline_guard(ctx, pre.deadline, gen)
        async for event in gen:
            yield event

    async def _embed(self, ctx, request: dict):
        """Embedding RPC: {"prompts": [[token ids], ...]} -> one reply with
        the vectors (float lists; the frontend handles encoding_format)."""
        prompts = request["prompts"]
        if self.runner is not None:
            vecs = await self.runner.embed(prompts)
        elif self.external is not None and hasattr(self.external, "embed"):
            vecs = await self.external.embed(prompts)
        else:
            from dynamo_tpu.engine.async_engine import fake_embedding

            import numpy as np

            vecs = np.stack([fake_embedding(p) for p in prompts])
        yield {
            "embeddings": [[float(x) for x in v] for v in vecs],
            "prompt_tokens": sum(len(p) for p in prompts),
        }

    # -- G4 remote tier: cross-worker prefix onboarding --------------------

    def _peer_transfer_addr(self, worker_id: str):
        inst = self._peer_source.instances.get(worker_id)
        if inst is None:
            return None
        port = inst.metadata.get("kv_transfer_port")
        if not port:
            return None
        return inst.host, int(port)

    async def _maybe_remote_onboard(self, pre: PreprocessedRequest) -> None:
        """Before admission: if a live peer holds more of this prompt's
        block chain than we do, pull those blocks over the transfer plane
        and adopt them — the reference's onboard_blocks driven by
        directory knowledge (block_manager.rs:169). Failures only cost the
        fetch: the request prefills the cold blocks as usual."""
        runner = self.runner
        directory = self.kv_directory
        if not directory.has_entries():
            return  # nothing claimable anywhere — skip the engine round trip
        # Hashing needs only static config (page size / salt), so it runs
        # on the event loop, and the directory is consulted BEFORE the
        # engine runner: requests with no claimable chain anywhere must not
        # serialize with engine step dispatch just to learn that.
        from dynamo_tpu.tokens import hash_token_blocks

        cfg = self.engine_config
        hashes = hash_token_blocks(
            pre.token_ids, block_size=cfg.page_size, salt=cfg.model
        )
        if not directory.has_chain(hashes, self.kv_remote_min_blocks):
            return
        n_local = await runner.submit(
            lambda eng: eng.allocator.resident_match_length(hashes)
        )
        if n_local >= len(hashes):
            return
        best = directory.best_chain(hashes, n_local)
        if best is None or best[1] < self.kv_remote_min_blocks:
            return
        worker_id, depth = best
        want = hashes[n_local : n_local + depth]
        addr = self._peer_transfer_addr(worker_id)
        if addr is None:
            # Peer is gone, or live but serving no transfer port (not
            # --kv-remote): drop its claims so we don't re-select it for
            # this prefix forever, and prune dead workers wholesale.
            directory.drop(worker_id, want)
            directory.retain_workers(list(self._peer_source.instances))
            return
        try:
            served = await asyncio.wait_for(
                self._fetch_client.fetch(*addr, want),
                self.kv_remote_timeout_s,
            )
        except Exception:
            logger.warning("KV fetch from %s failed", worker_id, exc_info=True)
            served = None
        if not served:
            directory.drop(worker_id, want)  # self-heal the stale claim
            return
        metas, k, v = served
        n = await runner.submit(lambda eng: eng.adopt_blocks(metas, k, v))
        self.remote_onboards += n
        if n:
            logger.info(
                "onboarded %d blocks for %s from peer %s",
                n, pre.request_id, worker_id,
            )

    # -- disaggregated path ------------------------------------------------

    async def _want_remote(self, pre: PreprocessedRequest) -> bool:
        # Multimodal prompts prefill locally: the remote-prefill wire
        # carries token ids only, and placeholder ids don't identify the
        # image embeddings.
        if pre.mm_embeds is not None:
            return False
        # Logprob requests prefill locally: the transfer result carries the
        # first sampled token but not its logprob, and OpenAI logprob
        # arrays must align with the emitted tokens from the first one.
        if pre.logprobs >= 0:
            return False
        # logit_bias / min_tokens requests prefill locally: the remote
        # wire's sampling dict doesn't carry them, so the prefill worker
        # would sample the FIRST token unbiased (min_tokens could even
        # end the request on an un-banned eos).
        if getattr(pre, "logit_bias", None) or getattr(pre, "min_tokens", 0):
            return False
        # Cheap local short-circuit: uncached length can't exceed prompt
        # length, so short prompts never qualify — skip the engine-thread
        # and fabric round-trips entirely.
        if (
            len(pre.token_ids)
            <= self.disagg_router.config.max_local_prefill_length
        ):
            return False
        runner = self.runner

        def _hit(eng):
            from dynamo_tpu.tokens import hash_token_blocks

            hashes = hash_token_blocks(
                pre.token_ids, block_size=eng.config.page_size,
                salt=eng.config.model,
            )
            return eng.allocator.match_length(hashes) * eng.config.page_size

        prefix_hit = await runner.submit(_hit)
        depth = await self.prefill_queue.depth()
        return self.disagg_router.prefill_remote(
            len(pre.token_ids), prefix_hit, depth
        )

    async def _generate_disagg(self, ctx, pre: PreprocessedRequest):
        """Remote prefill: reserve pages, enqueue, wait for the KV landing,
        then decode locally. Yields nothing (falls back) on reservation
        failure or transfer timeout."""
        import time as _time

        from dynamo_tpu import telemetry
        from dynamo_tpu.disagg.protocol import RemotePrefillRequest
        from dynamo_tpu.disagg.transfer import RemotePrefillError
        from dynamo_tpu.engine.async_engine import _sampling_from
        from dynamo_tpu.telemetry import phases

        runner = self.runner
        rid = pre.request_id
        sampling = _sampling_from(pre)
        with telemetry.span(
            "disagg.remote_prefill", service="disagg",
            attrs={"request_id": rid, "isl_tokens": len(pre.token_ids)},
        ) as dspan:
            req = await runner.submit(
                lambda eng: eng.allocate_for_remote_prefill(
                    rid, pre.token_ids, sampling
                )
            )
            if req is None:
                logger.info(
                    "disagg: no pages free for %s; local fallback", rid
                )
                dspan.end(status="cancelled")
                return
            dspan.add_event("pages_reserved", pages=len(req.pages))
            # From here until add_prefilled succeeds, any failure must give
            # the page reservation and the transfer waiter back.
            waiter = self.transfer_server.expect(rid)
            t_push = _time.perf_counter()
            try:
                await self.prefill_queue.push(
                    RemotePrefillRequest(
                        request_id=rid,
                        token_ids=list(pre.token_ids),
                        page_ids=list(req.pages),
                        transfer_host=self.advertise_host,
                        transfer_port=self.transfer_server.port,
                        sampling={
                            "temperature": pre.temperature, "top_p": pre.top_p,
                            "top_k": pre.top_k, "seed": pre.seed,
                        },
                        model=self.card.name,
                        trace=telemetry.wire_context() or {},
                        deadline=pre.deadline,
                    )
                )
                timeout = self.disagg_router.config.transfer_timeout_s
                result = await asyncio.wait_for(waiter, timeout)
            except RemotePrefillError as e:
                # the prefill fleet dead-lettered this request: error-
                # finish (a local fallback would just poison again)
                self.transfer_server.forget(rid)
                await runner.submit(lambda eng: eng.cancel_remote_prefill(req))
                logger.error(
                    "disagg: remote prefill for %s dead-lettered: %s", rid, e
                )
                dspan.end(status="error")
                yield {"token_ids": [], "finish_reason": "error"}
                return
            except Exception:
                self.transfer_server.forget(rid)
                await runner.submit(lambda eng: eng.cancel_remote_prefill(req))
                logger.warning(
                    "disagg: remote prefill for %s failed/timed out; "
                    "local fallback",
                    rid,
                )
                dspan.end(status="error")
                return
            transfer_ms = (_time.perf_counter() - t_push) * 1000.0
            phases.observe("disagg_transfer_ms", transfer_ms)
            dspan.add_event("kv_landed", transfer_ms=round(transfer_ms, 3))
            self.remote_prefills += 1
        from dynamo_tpu.engine.async_engine import output_to_dict

        out_q = runner.watch_request(rid)
        try:
            if pre.deadline and _time.time() > pre.deadline:
                # the deadline lapsed while the transfer was in flight:
                # never admit (the reservation frees, no decode flops) —
                # tracking it BEFORE admission would let the runner
                # expire-and-forget it, then add_prefilled would admit a
                # request nothing ever aborts
                await runner.submit(lambda eng: eng.cancel_remote_prefill(req))
                yield {"token_ids": [], "finish_reason": "error"}
                return
            try:
                outputs = await runner.submit(
                    lambda eng: eng.add_prefilled(req, result.first_token)
                )
            except Exception:
                await runner.submit(lambda eng: eng.cancel_remote_prefill(req))
                raise
            if pre.deadline:
                # decode-side deadline enforcement for the out-of-band
                # admission path, armed only once the request is ADMITTED
                # (an expiry now aborts a live request and frees pages)
                runner.track_deadline(rid, pre.deadline)
            for out in outputs:
                yield output_to_dict(out)
                if out.finish_reason is not None:
                    return
            async for item in runner.drain(ctx, rid, out_q):
                yield item
        finally:
            runner.unwatch_request(rid)

    async def _flush(self, ctx, request):
        n = 0
        if isinstance(self.runner, SpmdEngineRunner):
            # replicated clear: every host's allocator must stay identical
            n = await self.runner.clear_kv()
        elif self.runner is not None:
            # The engine thread is the only thread allowed to touch the
            # allocator — route through it.
            n = await self.runner.submit(
                lambda eng: eng.allocator.clear_cache()
            )
        elif self.mock is not None:
            n = self.mock.allocator.clear_cache()
        yield {"cleared_pages": n}

    # -- KV event sequencing + snapshot (docs/operations.md "KV index
    # consistency"): the worker side of the convergent index protocol ---

    @staticmethod
    def _kv_event_wire(e: KvEvent) -> dict:
        return {
            "kind": e.kind,
            "block_hashes": list(e.block_hashes),
            "parent_hash": e.parent_hash,
            "token_blocks": [list(t) for t in e.token_blocks],
        }

    def _stamp_kv_events(self, wire_events: list[dict]) -> None:
        """Stamp each outgoing event with the next per-worker sequence
        number and fold it into the rolling digest. Runs ONLY on the
        event-loop publish path, so seq/digest state is loop-confined
        and the advertised digest is exactly the set as-of the last
        stamped seq."""
        dg = self._kv_digest
        for ev in wire_events:
            self._kv_seq += 1
            ev["seq"] = self._kv_seq
            kind = ev.get("kind")
            if kind == "stored":
                parent = ev.get("parent_hash")
                for h in ev.get("block_hashes", ()):
                    dg.store(h, parent)
            elif kind == "removed":
                for h in ev.get("block_hashes", ()):
                    dg.remove(h)
            elif kind == "handed_over":
                # ownership moved wholesale to the successor: this
                # worker's advertised set empties, matching the index's
                # post-move view of it
                dg.clear()

    async def _publish_kv_events(self, wire_events: list[dict]) -> None:
        """Stamp (when sequencing) and publish one event batch. A failed
        publish DROPS the batch — the stamped seqs are burned, so the
        indexer sees a sequence gap and repairs by resync; re-sending
        later would reorder the stream, which is worse than honest
        loss."""
        if self.kv_sequencing:
            self._stamp_kv_events(wire_events)
        try:
            await self.runtime.fabric.publish(
                f"{KV_EVENT_SUBJECT}.{self.instance_id}",
                {"instance_id": self.instance_id, "count": len(wire_events)},
                msgpack.packb(wire_events, use_bin_type=True),
            )
        except Exception:
            logger.warning(
                "KV event publish failed; %d event(s) dropped (indexers "
                "detect the sequence gap and resync)", len(wire_events),
                exc_info=True,
            )

    async def _kv_snapshot_handler(self, ctx, request):
        """`kv.snapshot` ingress op: the full registered hash forest +
        the digest, as of the last PUBLISHED event — indexers use it for
        cold-start bootstrap and targeted resync (events with seq >
        this snapshot's seq apply cleanly on top)."""
        if not self.kv_sequencing:
            yield {"sequencing": False}
            return
        dg = self._kv_digest
        yield {
            "sequencing": True,
            "seq": self._kv_seq,
            "fold": dg.fold,
            "count": dg.count,
            "blocks": [[h, p] for h, p in dg.blocks.items()],
        }

    # -- publishers --------------------------------------------------------

    async def _publish_loop(self) -> None:
        """Ship buffered KV events + a load-metrics snapshot periodically
        (reference: KvEventPublisher publisher.rs:99 + WorkerMetricsPublisher
        :463; events ride the bus, scrape-free)."""
        fabric = self.runtime.fabric
        while True:
            await asyncio.sleep(self.metrics_interval)
            try:
                await self._publish_once(fabric)
            except asyncio.CancelledError:
                raise
            except Exception:
                # a fabric outage (or any publish failure) must not kill
                # the loop: frames resume when the fabric does, and any
                # KV events lost in between surface as sequence gaps the
                # indexer repairs by resync
                logger.warning("publish tick failed", exc_info=True)

    def _broker_reachable(self, fabric) -> bool:
        # LocalFabric (and anything without connection state) is always
        # reachable; RemoteFabric reports its live connection
        return getattr(fabric, "connected", True) is not False

    async def _publish_once(self, fabric) -> None:
        # Drain WITHOUT rebinding: the engine thread appends through a
        # late-binding callback, but any captured reference must stay
        # valid — rebinding here once silently severed the event plane
        # (appends landed in the dead list forever after).
        events = self._kv_event_buffer[: len(self._kv_event_buffer)]
        del self._kv_event_buffer[: len(events)]
        wire = self._kv_pending + [self._kv_event_wire(e) for e in events]
        self._kv_pending = []
        if wire:
            if not self._broker_reachable(fabric):
                # degraded mode: hold UNSTAMPED events for the broker's
                # return (a short outage loses nothing); past the cap,
                # stamp-and-drop the oldest — their burned seqs are the
                # detectable gap that triggers resync on reconnect
                overflow = wire[: max(0, len(wire) - self.kv_pending_cap)]
                self._kv_pending = wire[len(overflow):]
                if overflow:
                    if self.kv_sequencing:
                        self._stamp_kv_events(overflow)
                    self.kv_events_dropped += len(overflow)
                    logger.warning(
                        "degraded: KV event buffer overflowed; %d "
                        "event(s) dropped with seqs burned (indexers "
                        "resync on reconnect)", len(overflow),
                    )
            else:
                await self._publish_kv_events(wire)
        tiered = self._tier_event_buffer[: len(self._tier_event_buffer)]
        del self._tier_event_buffer[: len(tiered)]
        if tiered and not self._broker_reachable(fabric):
            # lower-tier hints are advisory (peers re-learn them from
            # later events): bound the outage backlog instead of growing
            tiered = tiered[-self.kv_pending_cap:]
            self._tier_event_buffer[:0] = tiered
            tiered = []
        if tiered:
            # the `tier` field is additive: BlockDirectory ignores it
            # (servable is servable), the router's TierMap prices it
            payload = msgpack.packb(
                [
                    {
                        "kind": "stored",
                        "block_hashes": [h],
                        "parent_hash": p,
                        "tier": t,
                    }
                    for h, p, t in tiered
                ],
                use_bin_type=True,
            )
            await fabric.publish(
                f"{KVBM_TIER_SUBJECT}.{self.instance_id}",
                {"instance_id": self.instance_id, "count": len(tiered)},
                payload,
            )
        if self._tier_policy is not None and self.runner is not None:
            # watermark-driven demotion rides the publish cadence: one
            # bounded engine-thread tick per interval, and the demoted
            # blocks' tier hints ship on the NEXT tick's publish above
            policy = self._tier_policy
            try:
                n = await self.runner.submit(lambda eng: policy.run_once())
            except Exception:
                n = 0
                logger.warning("tier policy tick failed", exc_info=True)
            if n:
                telemetry.events.record(
                    "kv_demotion", source=self.instance_id, blocks=n,
                    coalesce_s=5.0,
                )
        m = None
        if self.runner is not None:
            m = self.runner.metrics.to_dict()
        elif self.external is not None and hasattr(
            self.external, "metrics_dict"
        ):
            m = dict(self.external.metrics_dict())
        elif self.mock is not None:
            alloc = self.mock.allocator
            m = {
                "num_waiting": self.mock.num_waiting,
                "num_running": self.mock.num_running,
                "kv_active_pages": alloc.num_active,
                "kv_total_pages": alloc.num_pages - 1,
                "kv_usage": alloc.usage(),
                "prefix_hit_rate": alloc.stats.hit_rate,
                "requests_received": self.mock.requests_received,
                "generated_tokens": self.mock.generated_tokens,
                "preemptions": self.mock.preemptions,
            }
            try:
                # mock fleets ride the real SLO plane (fleet sim)
                m["slo"] = self.mock.slo.to_wire()
            except Exception:
                logger.warning(
                    "mock SLO frame failed", exc_info=True
                )
        if m is not None:
            # fleet telemetry plane (docs/observability.md "Fleet
            # view & SLO accounting"): role for the per-role fleet
            # rollup, SLO sketches + per-kind compile counters when
            # the engine carries them. Defensive: a telemetry
            # serialization bug must not sever the load-metrics
            # plane routers/planner depend on.
            # a flipped worker reports (and routes its frames) under
            # its LIVE role so /v1/fleet and the planner see the
            # pool move the moment the flip lands
            if self.role == "prefill":
                # a worker CONFIGURED as prefill keeps its own
                # component subject; only a flipped decode worker
                # moves its frames into the default prefill space
                pub_component = (
                    self.component
                    if "prefill" in self.component
                    else "prefill"
                )
            else:
                pub_component = self.decode_component
            m["component"] = pub_component
            m["role"] = self.role
            m["flips_total"] = self.flips
            # drain visibility: /v1/fleet shows state=draining while
            # the worker winds down (doctor's draining-worker rule
            # keys off this instead of tripping dead/stalled rules);
            # state=handover while a live KV migration runs (doctor's
            # handover-stuck rule watches its age + phase)
            m["state"] = (
                "handover"
                if self.handing_over
                else "draining" if self.draining else "serving"
            )
            if self._handover_phase is not None:
                m["handover_phase"] = self._handover_phase
            m["handovers_total"] = self.handovers
            m["handover_fallbacks_total"] = self.handover_fallbacks
            m["handover_bytes_total"] = self.handover_bytes
            m["handover_blocks_total"] = self.handover_blocks
            m["handovers_adopted_total"] = self.handovers_adopted
            # KV economy: source-side migration counters + tier residency
            # (the Grafana "KV economy" row and the doctor's
            # migration-storm / tier-pressure rules read these)
            m["kv_migrations_total"] = self.migrations
            m["kv_migration_fallbacks_total"] = self.migration_fallbacks
            m["kv_migration_bytes_total"] = self.migration_bytes
            m["kv_migration_blocks_total"] = self.migration_blocks
            alloc = getattr(
                getattr(self.runner, "engine", None), "allocator", None
            )
            if alloc is None and self.mock is not None:
                alloc = self.mock.allocator
            if alloc is not None and hasattr(alloc, "tier_hits"):
                occ = alloc.tier_occupancy()
                m["kvbm_host_blocks"] = occ["host"]
                m["kvbm_disk_blocks"] = occ["disk"]
                m["kvbm_demotions_total"] = alloc.stats.offloaded_blocks
                m["kvbm_promotions_total"] = alloc.stats.onboarded_blocks
                m["kvbm_host_hits_total"] = alloc.tier_hits["host"]
                m["kvbm_disk_hits_total"] = alloc.tier_hits["disk"]
            eng = getattr(self.runner, "engine", None)
            if eng is not None and getattr(eng, "slo", None) is not None:
                try:
                    m["slo"] = eng.slo.to_wire()
                    m["compiles_by_kind"] = dict(eng.compiles_by_kind)
                except Exception:
                    logger.warning(
                        "fleet telemetry frame failed", exc_info=True
                    )
            # debug plane (docs/observability.md "Debugging a slow
            # or stuck worker"): the flight-recorder window + the
            # per-kind program cost rollup ride the frame so the
            # metrics service can serve GET /v1/debug/{flight,
            # programs} for the whole fleet; same defensive wrap.
            if eng is not None:
                try:
                    fl = getattr(eng, "flight", None)
                    if fl is not None:
                        m["flight"] = fl.to_wire()
                    if getattr(eng, "programs", None):
                        m["programs_by_kind"] = eng.programs_wire()
                except Exception:
                    logger.warning(
                        "debug-plane frame failed", exc_info=True
                    )
                # HBM accounting + mesh seat (docs/observability.md
                # "Reading the perf plane"): refresh the hbm_* / host /
                # dispatch gauges (m snapshotted metrics BEFORE the
                # refresh, so fold the fresh values in), and ship the
                # full per-device table + mesh doc so the metrics
                # service serves GET /v1/debug/{memory,mesh} fleet-wide.
                try:
                    if hasattr(eng, "refresh_memory_metrics"):
                        m["memory"] = eng.refresh_memory_metrics()
                        md = eng.metrics
                        for f in (
                            "hbm_weights_bytes", "hbm_kv_pool_bytes",
                            "hbm_scratch_bytes", "hbm_free_bytes",
                            "hbm_peak_bytes", "host", "dispatch_p95_ms",
                        ):
                            m[f] = getattr(md, f)
                        m["mesh"] = eng.mesh_report()
                except Exception:
                    logger.warning(
                        "memory/mesh frame failed", exc_info=True
                    )
            wd = getattr(self.runner, "watchdog", None)
            if wd is not None:
                m["stalls_by_cause"] = wd.counters.snapshot()
                m["stalls_total"] = wd.counters.total
            if self.transfer_server is not None:
                # which KV plane transfers actually rode (device /
                # shm / bulk / inline host) — the ops signal for a
                # misconfigured fast path silently falling back
                for plane, n in self.transfer_server.transfers.items():
                    m[f"kv_transfer_{plane}_total"] = n
                m["remote_prefills_total"] = self.remote_prefills
                # frames the codec's checksum rejected (wire bit-rot
                # / chaos corrupt rules): corrupt pages never land
                m["kv_transfer_corrupt_total"] = (
                    self.transfer_server.corrupt_rejects
                )
            if self.kv_sequencing:
                # rolling block-set digest as of the last published KV
                # event: indexers run their anti-entropy sweep against
                # this (docs/operations.md "KV index consistency")
                m["kv_digest"] = {
                    "seq": self._kv_seq,
                    "fold": self._kv_digest.fold,
                    "count": self._kv_digest.count,
                }
            # control-plane health from THIS worker's seat (docs/
            # operations.md "Control-plane HA"): the live degraded flag
            # plus outage counters — during a full outage these frames
            # cannot ship, so what the fleet view mostly sees is the
            # post-recovery accounting (how long, how many drops)
            m["degraded"] = 1 if getattr(fabric, "degraded", False) else 0
            m["degraded_entries_total"] = int(
                getattr(fabric, "degraded_total", 0)
            )
            m["kv_events_dropped_total"] = self.kv_events_dropped
            m["kv_events_pending"] = len(self._kv_pending)
            m["instance_id"] = self.instance_id
            m["model"] = self.card.name
            if self._broker_reachable(fabric):
                await fabric.publish(
                    f"{METRICS_SUBJECT}.{pub_component}.{self.instance_id}",
                    m,
                )
        # fleet trace plane: ship buffered spans + fleet events on the
        # same cadence as the metrics frames (empty -> no publish)
        from dynamo_tpu.telemetry import traceplane

        await traceplane.ship_once(fabric, self.instance_id)

"""Engine worker process: engine + ingress + registration + publishers.

One worker = one JaxEngine serving one model over the fabric. It:
1. starts the engine thread (AsyncEngineRunner),
2. serves `generate` (and `flush`) on its ingress,
3. registers its endpoint instance under the process lease,
4. publishes the model card + entry (register_llm),
5. publishes KV events (subject kv_events.{instance_id}) and worker load
   metrics (subject metrics.{component}) for routers/planner.

Equivalent of the reference's engine-subprocess workers joining the
runtime (launch/dynamo-run/src/subprocess/vllm_inc.py + endpoint.rs).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import msgpack

from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.engine.async_engine import AsyncEngineRunner, EchoEngine
from dynamo_tpu.engine.engine import JaxEngine
from dynamo_tpu.engine.page_table import KvEvent
from dynamo_tpu.model_card import ModelDeploymentCard, register_llm
from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime import DistributedRuntime, IngressServer

logger = logging.getLogger(__name__)

KV_EVENT_SUBJECT = "kv_events"
METRICS_SUBJECT = "metrics"


class Worker:
    def __init__(
        self,
        runtime: DistributedRuntime,
        card: ModelDeploymentCard,
        engine_config: Optional[EngineConfig] = None,
        engine_kind: str = "jax",
        namespace: str = "dynamo",
        component: str = "backend",
        endpoint: str = "generate",
        checkpoint_path: Optional[str] = None,
        metrics_interval: float = 1.0,
    ):
        self.runtime = runtime
        self.card = card
        self.engine_config = engine_config
        self.engine_kind = engine_kind
        self.namespace = namespace
        self.component = component
        self.endpoint_name = endpoint
        self.checkpoint_path = checkpoint_path
        self.metrics_interval = metrics_interval
        self.ingress = IngressServer()
        self.runner: Optional[AsyncEngineRunner] = None
        self.echo: Optional[EchoEngine] = None
        self.registration = None
        self.instance_id: str = ""
        self._kv_event_buffer: list[KvEvent] = []
        self._tasks: list[asyncio.Task] = []

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self.engine_kind == "echo":
            self.echo = EchoEngine()
        else:
            engine = JaxEngine(
                self.engine_config,
                on_kv_event=self._kv_event_buffer.append,
                checkpoint_path=self.checkpoint_path,
            )
            self.runner = AsyncEngineRunner(engine)
            self.runner.start()

        self.ingress.add_handler("generate", self._generate)
        self.ingress.add_handler("flush", self._flush)
        await self.ingress.start()

        ep = (
            self.runtime.namespace(self.namespace)
            .component(self.component)
            .endpoint(self.endpoint_name)
        )
        self.registration = await ep.register(
            "127.0.0.1", self.ingress.port, metadata={"model": self.card.name}
        )
        self.instance_id = self.registration.instance.instance_id
        await register_llm(
            self.runtime.fabric, self.card, self.namespace, self.component,
            self.endpoint_name, lease_id=self.runtime.primary_lease,
        )
        loop = asyncio.get_running_loop()
        self._tasks.append(loop.create_task(self._publish_loop()))
        logger.info(
            "worker %s serving %s on :%d", self.instance_id, self.card.name,
            self.ingress.port,
        )

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await self.ingress.stop()
        if self.runner:
            self.runner.stop()

    # -- handlers ----------------------------------------------------------

    async def _generate(self, ctx, request: dict):
        pre = PreprocessedRequest.from_dict(request)
        gen = (self.echo or self.runner).generate(ctx, pre)
        async for event in gen:
            yield event

    async def _flush(self, ctx, request):
        n = 0
        if self.runner is not None:
            n = self.runner.engine.allocator.clear_cache()
        yield {"cleared_pages": n}

    # -- publishers --------------------------------------------------------

    async def _publish_loop(self) -> None:
        """Ship buffered KV events + a load-metrics snapshot periodically
        (reference: KvEventPublisher publisher.rs:99 + WorkerMetricsPublisher
        :463; events ride the bus, scrape-free)."""
        fabric = self.runtime.fabric
        while True:
            await asyncio.sleep(self.metrics_interval)
            events, self._kv_event_buffer = self._kv_event_buffer, []
            if events:
                payload = msgpack.packb(
                    [
                        {
                            "kind": e.kind,
                            "block_hashes": list(e.block_hashes),
                            "parent_hash": e.parent_hash,
                            "token_blocks": [list(t) for t in e.token_blocks],
                        }
                        for e in events
                    ],
                    use_bin_type=True,
                )
                await fabric.publish(
                    f"{KV_EVENT_SUBJECT}.{self.instance_id}",
                    {"instance_id": self.instance_id, "count": len(events)},
                    payload,
                )
            if self.runner is not None:
                m = self.runner.metrics.to_dict()
                m["instance_id"] = self.instance_id
                m["model"] = self.card.name
                await fabric.publish(
                    f"{METRICS_SUBJECT}.{self.component}.{self.instance_id}",
                    m,
                )

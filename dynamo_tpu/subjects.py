"""Shared fabric subject names.

Publishers (worker processes) and subscribers (routers, aggregators,
planner) must agree on these; defining them once keeps a rename from
silently severing a plane (reference: subject constants in
lib/llm/src/kv_router.rs:48-49).
"""

#: per-worker KV cache events: kv_events.{instance_id}
KV_EVENT_SUBJECT = "kv_events"

#: per-worker KVBM lower-tier events (blocks offloaded to host/disk —
#: still servable to peers over the transfer plane): kvbm_tier.{instance_id}
KVBM_TIER_SUBJECT = "kvbm_tier"

#: per-worker load metrics: metrics.{component}.{instance_id}
METRICS_SUBJECT = "metrics"

#: router-emitted per-decision prefix-cache hit rates
KV_HIT_RATE_SUBJECT = "kv-hit-rate"

#: admin broadcast: every worker (decode AND prefill) flushes reusable KV
#: pages on receipt — reaches fleet members the frontend has no route to
FLUSH_SUBJECT = "admin.flush"

#: KV index health frames (KvRouter publishes its indexer's consistency
#: stats — gaps detected, resyncs run, drift blocks corrected, stale
#: workers): the metrics service folds these into
#: dynamo_tpu_router_kv_index_*{component,router} and the `kv_index`
#: section of /v1/fleet (doctor's kv-index-drift rule)
KV_INDEX_SUBJECT = "kv_index.status"

#: finished-span batches (fleet trace plane): every traced process
#: ships its spans here on the metrics-frame cadence; the metrics
#: service assembles cross-process traces keyed by trace_id behind a
#: tail-based sampler and serves them at GET /v1/traces
#: (docs/observability.md "Fleet traces & event timeline")
TRACE_SPANS_SUBJECT = "trace.spans"

#: structured fleet events (planner decisions, role flips, handovers,
#: drains, shed episodes, stream replays, KV resyncs): the metrics
#: service stores them in a bounded ring served at GET /v1/fleet/events
#: and exposes dynamo_tpu_fleet_events_total{type,severity} for the
#: Grafana annotation layer
FLEET_EVENTS_SUBJECT = "fleet.events"

#: closed-loop planner status frames (ControlRunner.status): targets vs
#: observed pool sizes, SLO signals, decision counters, recent-decision
#: ring — the metrics service folds these into dynamo_tpu_planner_* and
#: the `planner` section of /v1/fleet (doctor's planner rules read it)
PLANNER_SUBJECT = "planner.status"

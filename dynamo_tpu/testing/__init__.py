"""Test-support planes shipped with the package (fault injection)."""

from dynamo_tpu.testing.faults import (  # noqa: F401
    FaultError,
    FaultInjector,
    FaultRule,
    HOOK_POINTS,
    fire,
    fire_sync,
    get_injector,
    install,
    install_from_env,
    uninstall,
)

"""Deterministic, seedable fault injection for the distributed stack.

The chaos companion to the subprocess kill harness (tests/fault_tolerance):
instead of killing whole processes, named HOOK POINTS inside the fabric
client, the disagg transfer planes, the worker ingress and the engine step
loop consult a process-global injector and — per an installed rule table —
drop (connection loss), delay, or error the operation. Everything is
driven by one `random.Random(seed)`, so a chaos scenario replays
identically under a pinned seed.

Default state is OFF: no injector installed means every hook site is a
single global-load + `is None` check on the host path (the token path is
bit-identical — pinned by tests/test_overload.py). Installation is either
programmatic (tests) or via `DYNTPU_FAULTS` for subprocess workers:

    DYNTPU_FAULTS="transfer.land:error:1.0:times=2;engine.step:delay:0.5:delay_ms=200"
    DYNTPU_FAULTS_SEED=7

Spec grammar, `;`-separated rules of `point:kind:prob[:k=v...]` with
k=v in {times, delay_ms}. Unknown points are rejected at install time —
a typo must not silently inject nothing.

Hook points (each named after the operation it brackets):

    fabric.call     RemoteFabric._call — every control-plane op (kv,
                    lease, queue, bus). `op=` carries the fabric op name
                    so rules can target e.g. only `queue.pop`.
    ingress.call    IngressServer._serve_call — a pushed request arriving
                    at a worker, before its handler runs.
    transfer.send   KvTransferClient.send — the prefill→decode KV push,
                    client side (before any bytes move).
    transfer.land   KvTransferServer._land — the decode-side landing of a
                    KV write (an injected error nacks the sender, exactly
                    like a real landing failure).
    engine.step     the engine thread, immediately before `eng.step()` —
                    an injected delay stalls the loop (watchdog fodder),
                    an injected error is swallowed by the step-loop guard
                    like any real step failure.

Kinds:

    drop       raise ConnectionError (the wire died mid-operation)
    error      raise FaultError (an application-level failure)
    delay      sleep `delay_ms` (async at async sites, blocking at sync
               sites), then proceed
    partition  alias of drop with prob=1.0 and no `times` cap — a peer
               that stays unreachable until the rule is removed
    corrupt    bit-rot on the wire: flip one byte of the ENCODED frame
               AFTER its checksum was computed, at byte-moving sites
               (transfer.send payload frames, fabric.call frames — the
               queue plane). `fire()` ignores corrupt rules; sites that
               ship bytes call `corrupt_bytes(point, buf, ...)` instead,
               which returns the (possibly flipped) buffer. The receiver
               must reject the frame via the codec's xxh3 check — this is
               how tests prove corruption becomes a connection-level
               failure, never landed data.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

logger = logging.getLogger(__name__)

HOOK_POINTS = (
    "fabric.call",
    "ingress.call",
    "transfer.send",
    "transfer.land",
    "engine.step",
    # worker handover phases (docs/operations.md "Rolling upgrades &
    # worker handover"): a fault at any of them must degrade the
    # handover to the plain drain + replay-by-recompute path
    "handover.extract",
    "handover.offer",
    "handover.transfer",
    "handover.adopt",
    # per-prefix KV migration phases (docs/operations.md "The KV
    # economy"): a fault at any of them must degrade the request to a
    # cold prefill with both sides' pages freed
    "migrate.extract",
    "migrate.offer",
    "migrate.transfer",
)


class FaultError(RuntimeError):
    """An injected application-level failure."""


@dataclass
class FaultRule:
    point: str
    kind: str  # drop | error | delay | partition
    prob: float = 1.0
    #: max times this rule fires (None = unbounded)
    times: Optional[int] = None
    delay_ms: float = 100.0
    #: ctx key=value filters — every listed key must match the hook's
    #: keyword context exactly (e.g. op="queue.pop")
    match: dict[str, Any] = field(default_factory=dict)
    fired: int = 0

    def __post_init__(self):
        if self.point not in HOOK_POINTS:
            raise ValueError(
                f"unknown hook point {self.point!r}; valid: {HOOK_POINTS}"
            )
        if self.kind not in ("drop", "error", "delay", "partition", "corrupt"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.kind == "partition":
            # a partition IS a persistent drop: normalize so firing logic
            # has three behaviors, not four
            self.kind = "drop"
            self.prob = 1.0
            self.times = None

    def matches(self, ctx: dict) -> bool:
        return all(ctx.get(k) == v for k, v in self.match.items())


class FaultInjector:
    """Rule table + seeded RNG + fire log. Thread-safe: hook sites live
    on the event loop AND the engine thread."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        self._lock = threading.Lock()
        #: (point, kind) -> fire count
        self.fired: dict[tuple[str, str], int] = {}
        #: chronological fire log [(point, kind, ctx)] for assertions
        self.log: list[tuple[str, str, dict]] = []

    def add_rule(self, point: str, kind: str, prob: float = 1.0,
                 times: Optional[int] = None, delay_ms: float = 100.0,
                 **match) -> FaultRule:
        rule = FaultRule(
            point=point, kind=kind, prob=prob, times=times,
            delay_ms=delay_ms, match=match,
        )
        with self._lock:
            self.rules.append(rule)
        return rule

    def remove_rule(self, rule: FaultRule) -> None:
        with self._lock:
            if rule in self.rules:
                self.rules.remove(rule)

    def clear(self) -> None:
        with self._lock:
            self.rules.clear()

    def _decide(
        self, point: str, ctx: dict, corrupting: bool = False
    ) -> Optional[FaultRule]:
        """First matching rule that wins its coin flip (under the lock:
        the RNG and the `fired` budgets are shared state). `corrupting`
        selects between the two disjoint rule populations: fire()/
        fire_sync() consider everything EXCEPT corrupt rules (those are
        payload transforms, not control-flow faults), corrupt_bytes()
        considers ONLY corrupt rules."""
        with self._lock:
            for rule in self.rules:
                if (rule.kind == "corrupt") != corrupting:
                    continue
                if rule.point != point or not rule.matches(ctx):
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.prob < 1.0 and self.rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                key = (point, rule.kind)
                self.fired[key] = self.fired.get(key, 0) + 1
                self.log.append((point, rule.kind, dict(ctx)))
                return rule
        return None

    @staticmethod
    def _raise(point: str, rule: FaultRule) -> None:
        if rule.kind == "drop":
            raise ConnectionError(f"fault-injected drop at {point}")
        raise FaultError(f"fault-injected error at {point}")

    async def fire(self, point: str, **ctx) -> None:
        rule = self._decide(point, ctx)
        if rule is None:
            return
        logger.warning("fault injected: %s %s %s", rule.kind, point, ctx)
        if rule.kind == "delay":
            await asyncio.sleep(rule.delay_ms / 1000.0)
            return
        self._raise(point, rule)

    def fire_sync(self, point: str, **ctx) -> None:
        """Blocking variant for sync sites (the engine thread)."""
        rule = self._decide(point, ctx)
        if rule is None:
            return
        logger.warning("fault injected: %s %s %s", rule.kind, point, ctx)
        if rule.kind == "delay":
            time.sleep(rule.delay_ms / 1000.0)
            return
        self._raise(point, rule)

    def corrupt(self, point: str, buf: bytes, **ctx) -> bytes:
        """Flip one byte of `buf` when a matching corrupt rule fires —
        the position is drawn from the seeded RNG so scenarios replay.
        The flip lands in the BACK half of the buffer, which for an
        encoded frame is payload territory (either checksum tripping is
        a rejection; payload bytes are the interesting victim for KV
        pages)."""
        rule = self._decide(point, ctx, corrupting=True)
        if rule is None or not buf:
            return buf
        with self._lock:
            pos = self.rng.randrange(len(buf) // 2, len(buf))
        logger.warning(
            "fault injected: corrupt %s byte %d/%d %s",
            point, pos, len(buf), ctx,
        )
        out = bytearray(buf)
        out[pos] ^= 0xFF
        return bytes(out)

    def wants_corrupt(self, point: str) -> bool:
        """True when an armed (budget-remaining) corrupt rule targets
        `point` — lets vectored-write fast paths pre-flatten only when a
        corruption could actually fire."""
        with self._lock:
            return any(
                r.kind == "corrupt"
                and r.point == point
                and (r.times is None or r.fired < r.times)
                for r in self.rules
            )


#: the process-global injector; None = fault injection entirely off
_injector: Optional[FaultInjector] = None


def get_injector() -> Optional[FaultInjector]:
    return _injector


def install(injector: Optional[FaultInjector] = None,
            seed: int = 0) -> FaultInjector:
    global _injector
    _injector = injector or FaultInjector(seed=seed)
    return _injector


def uninstall() -> None:
    global _injector
    _injector = None


async def fire(point: str, **ctx) -> None:
    """Hook entry for async sites; a no-op (one global load) when no
    injector is installed."""
    inj = _injector
    if inj is not None:
        await inj.fire(point, **ctx)


def fire_sync(point: str, **ctx) -> None:
    """Hook entry for sync sites (engine thread)."""
    inj = _injector
    if inj is not None:
        inj.fire_sync(point, **ctx)


def corrupt_bytes(point: str, buf: bytes, **ctx) -> bytes:
    """Hook entry for byte-moving sites: returns `buf`, possibly with one
    byte flipped per an installed corrupt rule. No-op (one global load)
    when no injector is installed."""
    inj = _injector
    if inj is None:
        return buf
    return inj.corrupt(point, buf, **ctx)


def wants_corrupt(point: str) -> bool:
    inj = _injector
    return inj is not None and inj.wants_corrupt(point)


def parse_spec(spec: str) -> list[FaultRule]:
    """`point:kind:prob[:k=v...]` rules, `;`-separated (see module doc)."""
    rules: list[FaultRule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) < 2:
            raise ValueError(f"bad fault rule {part!r}")
        point, kind = bits[0], bits[1]
        prob = float(bits[2]) if len(bits) > 2 else 1.0
        kw: dict[str, Any] = {}
        for extra in bits[3:]:
            k, _, v = extra.partition("=")
            if k == "times":
                kw["times"] = int(v)
            elif k == "delay_ms":
                kw["delay_ms"] = float(v)
            else:
                raise ValueError(f"bad fault rule option {extra!r}")
        rules.append(FaultRule(point=point, kind=kind, prob=prob, **kw))
    return rules


def install_from_env() -> Optional[FaultInjector]:
    """Install from DYNTPU_FAULTS / DYNTPU_FAULTS_SEED (subprocess chaos
    workers); returns the injector or None when the env is unset."""
    spec = os.environ.get("DYNTPU_FAULTS")
    if not spec:
        return None
    inj = FaultInjector(seed=int(os.environ.get("DYNTPU_FAULTS_SEED", "0")))
    inj.rules.extend(parse_spec(spec))
    install(inj)
    logger.warning("fault injection active: %s", spec)
    return inj

"""GGUF model-file reader.

Parses GGUF v2/v3 containers: header, typed metadata KV pairs, the tensor
index, and tensor data as numpy arrays (raw or dequantized). Extracts
the embedded tokenizer vocabulary and maps `llama.*` metadata onto
LlamaConfig so a .gguf file can be served directly.

Parity: the reference's GGUF support (lib/llm/src/gguf/{content,
gguf_metadata,gguf_tokenizer}.rs — metadata + tokenizer for model cards
and the mistralrs engine, which serves the quantized tensors). This
implementation loads tensor data for the JAX engine directly: F32/F16/
BF16 raw, plus vectorized dequantizers for the common ggml quant blocks
(Q4_0/Q4_1/Q5_0/Q5_1/Q8_0 and k-quants Q4_K/Q5_K/Q6_K) so quantized
checkpoints — the main reason .gguf files exist — are servable.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Optional

import numpy as np

GGUF_MAGIC = 0x46554747  # "GGUF" little-endian

# metadata value types
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, _F64 = range(13)

_SCALAR_FMT = {
    _U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I", _I32: "<i",
    _F32: "<f", _U64: "<Q", _I64: "<q", _F64: "<d",
}

#: ggml tensor types we can materialize (id -> (numpy dtype, bytes/elt))
_TENSOR_DTYPES = {
    0: ("float32", 4),  # F32
    1: ("float16", 2),  # F16
    30: ("bfloat16", 2),  # BF16
}

# -- ggml quantized blocks ---------------------------------------------------
# Byte layouts follow the public ggml spec (block structs in ggml-common.h);
# dequantized here with vectorized numpy so quantized .gguf checkpoints are
# servable in-process. Reference parity: the reference serves quantized
# GGUF via mistralrs (lib/engines/mistralrs; lib/llm/src/gguf/content.rs).

#: quantized ggml type id -> (elements per block, bytes per block)
_QUANT_BLOCKS = {
    2: (32, 18),    # Q4_0: f16 d + 16B nibbles
    3: (32, 20),    # Q4_1: f16 d + f16 m + 16B nibbles
    6: (32, 22),    # Q5_0: f16 d + 4B high bits + 16B nibbles
    7: (32, 24),    # Q5_1: f16 d + f16 m + 4B high bits + 16B nibbles
    8: (32, 34),    # Q8_0: f16 d + 32 x i8
    12: (256, 144),  # Q4_K: f16 d + f16 dmin + 12B 6-bit scales + 128B
    13: (256, 176),  # Q5_K: Q4_K + 32B high bits
    14: (256, 210),  # Q6_K: 128B low + 64B high + 16 x i8 scales + f16 d
}


def _f16(raw: np.ndarray) -> np.ndarray:
    return raw.view("<f2").astype(np.float32)


def _dequant_q8_0(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2])  # [N, 1]
    q = b[:, 2:34].view(np.int8).astype(np.float32)
    return d * q


def _dequant_q4_0(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2])
    qs = b[:, 2:18]
    q = np.concatenate([qs & 0xF, qs >> 4], axis=1).astype(np.float32) - 8.0
    return d * q


def _dequant_q4_1(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2])
    m = _f16(b[:, 2:4])
    qs = b[:, 4:20]
    q = np.concatenate([qs & 0xF, qs >> 4], axis=1).astype(np.float32)
    return d * q + m


def _dequant_q5_0(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2])
    qh = b[:, 2:6].copy().view("<u4")  # [N, 1] — 32 high bits
    qs = b[:, 6:22]
    bits = (qh >> np.arange(32, dtype=np.uint32)[None, :]) & 1  # [N, 32]
    q = np.concatenate([qs & 0xF, qs >> 4], axis=1).astype(np.int32)
    q = (q | (bits.astype(np.int32) << 4)).astype(np.float32) - 16.0
    return d * q


def _dequant_q5_1(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2])
    m = _f16(b[:, 2:4])
    qh = b[:, 4:8].copy().view("<u4")
    qs = b[:, 8:24]
    bits = (qh >> np.arange(32, dtype=np.uint32)[None, :]) & 1
    q = np.concatenate([qs & 0xF, qs >> 4], axis=1).astype(np.int32)
    q = (q | (bits.astype(np.int32) << 4)).astype(np.float32)
    return d * q + m


def _k_scale_min(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Unpack Q4_K/Q5_K 12-byte 6-bit scale/min pairs -> ([N,8], [N,8])
    (get_scale_min_k4 in ggml)."""
    s = scales.astype(np.uint16)
    sc = np.empty((s.shape[0], 8), np.float32)
    mn = np.empty((s.shape[0], 8), np.float32)
    for j in range(4):
        sc[:, j] = (s[:, j] & 63).astype(np.float32)
        mn[:, j] = (s[:, j + 4] & 63).astype(np.float32)
    for j in range(4, 8):
        sc[:, j] = ((s[:, j + 4] & 0xF) | ((s[:, j - 4] >> 6) << 4)).astype(
            np.float32
        )
        mn[:, j] = ((s[:, j + 4] >> 4) | ((s[:, j] >> 6) << 4)).astype(
            np.float32
        )
    return sc, mn


def _dequant_q4_k(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2])  # [N, 1]
    dmin = _f16(b[:, 2:4])
    sc, mn = _k_scale_min(b[:, 4:16])  # [N, 8]
    qs = b[:, 16:144]  # [N, 128] — 4 chunks of 32B, low/high nibbles
    out = np.empty((b.shape[0], 256), np.float32)
    for c in range(4):
        chunk = qs[:, c * 32 : (c + 1) * 32]
        g_lo, g_hi = 2 * c, 2 * c + 1
        out[:, g_lo * 32 : g_lo * 32 + 32] = (
            d * sc[:, g_lo : g_lo + 1] * (chunk & 0xF).astype(np.float32)
            - dmin * mn[:, g_lo : g_lo + 1]
        )
        out[:, g_hi * 32 : g_hi * 32 + 32] = (
            d * sc[:, g_hi : g_hi + 1] * (chunk >> 4).astype(np.float32)
            - dmin * mn[:, g_hi : g_hi + 1]
        )
    return out


def _dequant_q5_k(b: np.ndarray) -> np.ndarray:
    d = _f16(b[:, 0:2])
    dmin = _f16(b[:, 2:4])
    sc, mn = _k_scale_min(b[:, 4:16])
    qh = b[:, 16:48]  # [N, 32]
    qs = b[:, 48:176]  # [N, 128]
    out = np.empty((b.shape[0], 256), np.float32)
    for c in range(4):
        chunk = qs[:, c * 32 : (c + 1) * 32]
        hi_lo = ((qh >> (2 * c)) & 1).astype(np.float32) * 16.0
        hi_hi = ((qh >> (2 * c + 1)) & 1).astype(np.float32) * 16.0
        g_lo, g_hi = 2 * c, 2 * c + 1
        out[:, g_lo * 32 : g_lo * 32 + 32] = (
            d * sc[:, g_lo : g_lo + 1]
            * ((chunk & 0xF).astype(np.float32) + hi_lo)
            - dmin * mn[:, g_lo : g_lo + 1]
        )
        out[:, g_hi * 32 : g_hi * 32 + 32] = (
            d * sc[:, g_hi : g_hi + 1]
            * ((chunk >> 4).astype(np.float32) + hi_hi)
            - dmin * mn[:, g_hi : g_hi + 1]
        )
    return out


def _dequant_q6_k(b: np.ndarray) -> np.ndarray:
    ql = b[:, 0:128]
    qh = b[:, 128:192]  # [N, 64]
    scales = b[:, 192:208].view(np.int8).astype(np.float32)  # [N, 16]
    d = _f16(b[:, 208:210])
    out = np.empty((b.shape[0], 256), np.float32)
    sidx = np.arange(32) // 16  # 16-element sub-blocks: scale l//16 + 2k
    for half in range(2):  # dequantize_row_q6_K: 128 elements per pass
        qlh = ql[:, half * 64 : half * 64 + 64]
        qhh = qh[:, half * 32 : half * 32 + 32].astype(np.int32)
        sch = scales[:, half * 8 : half * 8 + 8]
        base = half * 128
        for k, (qlow, shift) in enumerate((
            ((qlh[:, 0:32] & 0xF).astype(np.int32), 0),
            ((qlh[:, 32:64] & 0xF).astype(np.int32), 2),
            ((qlh[:, 0:32] >> 4).astype(np.int32), 4),
            ((qlh[:, 32:64] >> 4).astype(np.int32), 6),
        )):
            q = (qlow | (((qhh >> shift) & 3) << 4)).astype(
                np.float32
            ) - 32.0
            s = sch[:, sidx + 2 * k]  # [N, 32]
            out[:, base + 32 * k : base + 32 * k + 32] = d * s * q
    return out


_DEQUANT_FNS = {
    2: _dequant_q4_0, 3: _dequant_q4_1, 6: _dequant_q5_0,
    7: _dequant_q5_1, 8: _dequant_q8_0, 12: _dequant_q4_k,
    13: _dequant_q5_k, 14: _dequant_q6_k,
}


def dequantize(raw: bytes, ggml_type: int, count: int) -> np.ndarray:
    """Dequantize a ggml-quantized tensor payload to float32 [count]."""
    if ggml_type not in _QUANT_BLOCKS:
        raise ValueError(
            f"ggml type {GGML_TYPE_NAMES.get(ggml_type, ggml_type)} has no "
            "dequantizer"
        )
    elts, nbytes = _QUANT_BLOCKS[ggml_type]
    if count % elts:
        raise ValueError(
            f"quantized tensor length {count} not a multiple of the "
            f"{elts}-element block"
        )
    blocks = count // elts
    if len(raw) < blocks * nbytes:
        raise ValueError("quantized tensor data truncated")
    b = np.frombuffer(raw, np.uint8, blocks * nbytes).reshape(blocks, nbytes)
    return _DEQUANT_FNS[ggml_type](b).reshape(-1)


def quantize_q8_0(arr: np.ndarray) -> bytes:
    """Pack float data into Q8_0 blocks (export tooling + test fixtures).
    Layout: per 32 elements, f16 scale d = absmax/127 then 32 x int8."""
    flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
    if flat.size % 32:
        raise ValueError("Q8_0 needs a multiple of 32 elements")
    blocks = flat.reshape(-1, 32)
    d = np.abs(blocks).max(axis=1, keepdims=True) / 127.0
    d = np.maximum(d, 1e-12)
    q = np.clip(np.round(blocks / d), -127, 127).astype(np.int8)
    out = np.empty((blocks.shape[0], 34), np.uint8)
    out[:, 0:2] = d.astype("<f2").view(np.uint8)
    out[:, 2:34] = q.view(np.uint8)
    return out.tobytes()

#: ggml type id -> name, for error messages / inventories
GGML_TYPE_NAMES = {
    0: "F32", 1: "F16", 2: "Q4_0", 3: "Q4_1", 6: "Q5_0", 7: "Q5_1",
    8: "Q8_0", 9: "Q8_1", 10: "Q2_K", 11: "Q3_K", 12: "Q4_K", 13: "Q5_K",
    14: "Q6_K", 15: "Q8_K", 16: "IQ2_XXS", 24: "I8", 25: "I16", 26: "I32",
    27: "I64", 28: "F64", 30: "BF16",
}


@dataclass
class GgufTensorInfo:
    name: str
    shape: tuple[int, ...]  # row-major (numpy) order
    ggml_type: int
    offset: int  # relative to the data section

    @property
    def type_name(self) -> str:
        return GGML_TYPE_NAMES.get(self.ggml_type, f"type{self.ggml_type}")


@dataclass
class GgufFile:
    path: str
    version: int
    metadata: dict[str, Any]
    tensors: dict[str, GgufTensorInfo]
    data_start: int = 0
    alignment: int = 32

    # -- tensor data -------------------------------------------------------

    def load_tensor(self, name: str) -> np.ndarray:
        info = self.tensors.get(name)
        if info is None:
            raise KeyError(f"no tensor {name!r} in {self.path}")
        count = int(np.prod(info.shape)) if info.shape else 1
        if info.ggml_type in _QUANT_BLOCKS:
            elts, nbytes = _QUANT_BLOCKS[info.ggml_type]
            size = (count // elts) * nbytes
            with open(self.path, "rb") as f:
                f.seek(self.data_start + info.offset)
                raw = f.read(size)
            return dequantize(raw, info.ggml_type, count).reshape(
                info.shape
            )
        if info.ggml_type not in _TENSOR_DTYPES:
            raise ValueError(
                f"tensor {name!r} has unsupported ggml type "
                f"{info.type_name}; F32/F16/BF16 and "
                "Q4_0/Q4_1/Q5_0/Q5_1/Q8_0/Q4_K/Q5_K/Q6_K load as arrays"
            )
        dtype_name, elt = _TENSOR_DTYPES[info.ggml_type]
        with open(self.path, "rb") as f:
            f.seek(self.data_start + info.offset)
            raw = f.read(count * elt)
        if len(raw) != count * elt:
            raise ValueError(f"tensor {name!r} data truncated")
        if dtype_name == "bfloat16":
            # numpy has no bf16: widen via the upper half of f32 bits
            u16 = np.frombuffer(raw, np.uint16)
            arr = (u16.astype(np.uint32) << 16).view(np.float32)
        else:
            arr = np.frombuffer(raw, dtype_name)
        return arr.reshape(info.shape)

    # -- tokenizer ---------------------------------------------------------

    def tokenizer_vocab(self) -> Optional[dict]:
        """Embedded tokenizer: model kind, token strings, scores, merge
        rules, special ids (tokenizer.ggml.* keys — gguf_tokenizer.rs)."""
        tokens = self.metadata.get("tokenizer.ggml.tokens")
        if tokens is None:
            return None
        return {
            "model": self.metadata.get("tokenizer.ggml.model", "llama"),
            "tokens": tokens,
            "scores": self.metadata.get("tokenizer.ggml.scores"),
            "token_types": self.metadata.get("tokenizer.ggml.token_type"),
            "merges": self.metadata.get("tokenizer.ggml.merges"),
            "bos_token_id": self.metadata.get("tokenizer.ggml.bos_token_id"),
            "eos_token_id": self.metadata.get("tokenizer.ggml.eos_token_id"),
            "chat_template": self.metadata.get("tokenizer.chat_template"),
        }

    # -- model config ------------------------------------------------------

    def architecture(self) -> str:
        return self.metadata.get("general.architecture", "llama")

    def to_llama_config(self):
        """Map llama.* metadata onto LlamaConfig (serving config parity
        with content.rs::to_llama_config)."""
        from dynamo_tpu.models.llama import LlamaConfig

        arch = self.architecture()
        md = self.metadata

        def key(suffix, default=None):
            return md.get(f"{arch}.{suffix}", default)

        n_heads = int(key("attention.head_count", 32))
        embed = int(key("embedding_length", 4096))
        head_dim = int(key("attention.key_length", embed // n_heads))
        vocab = md.get("tokenizer.ggml.tokens")
        vocab_size = int(
            key("vocab_size", len(vocab) if vocab else 32000)
        )
        rope_scale = key("rope.scaling.factor")
        gemma = arch in ("gemma", "gemma2", "gemma3")
        n_layers = int(key("block_count", 32))
        gemma_kw = {}
        if gemma:
            # llama.cpp's converter folds Gemma's (1+w) norm offset INTO
            # the stored norm tensors, so the config must NOT add the
            # unit offset again; embeddings scale at runtime as usual.
            gemma_kw = dict(
                hidden_act="gelu_tanh",
                rms_norm_unit_offset=False,
                scale_embeddings=True,
                tie_word_embeddings=True,
                post_block_norms=(arch in ("gemma2", "gemma3")),
                sliding_window=int(key("attention.sliding_window", 0) or 0),
            )
            # GGUF metadata carries no query_pre_attn_scalar key; the
            # 27B-class checkpoints are the only ones where it differs
            # from head_dim (gemma2-27B: 4608/32=144 at 46 layers;
            # gemma3-27B: 5376/32=168 at 62 layers). llama.cpp
            # special-cases them by model type the same way.
            if (arch == "gemma2" and n_layers == 46) or (
                arch == "gemma3" and n_layers == 62
            ):
                gemma_kw["query_pre_attn_scalar"] = float(embed / n_heads)
            if arch == "gemma2":
                sc = key("attn_logit_softcapping")
                fc = key("final_logit_softcapping")
                gemma_kw.update(
                    attn_logit_softcap=float(sc) if sc else None,
                    final_logit_softcap=float(fc) if fc else None,
                    sliding_window_every=2,
                )
            if arch == "gemma3":
                local = key("rope.local.freq_base", 10_000.0)
                gemma_kw.update(
                    sliding_global_every=6,  # llama.cpp hardcodes 5:1 too
                    rope_local_theta=float(local),
                    rope_linear_factor=(
                        float(rope_scale) if rope_scale else None
                    ),
                )
                rope_scale = None  # consumed as the linear factor
        return LlamaConfig(
            attention_bias=(arch == "qwen2"),
            qk_norm=arch in ("qwen3", "gemma3"),
            vocab_size=vocab_size,
            hidden_size=embed,
            intermediate_size=int(key("feed_forward_length", 4 * embed)),
            num_layers=n_layers,
            num_heads=n_heads,
            num_kv_heads=int(key("attention.head_count_kv", n_heads)),
            head_dim=head_dim,
            rope_theta=float(key("rope.freq_base", 10000.0)),
            rms_norm_eps=float(
                key("attention.layer_norm_rms_epsilon", 1e-5)
            ),
            rope_scaling_factor=(
                float(rope_scale) if rope_scale is not None else None
            ),
            **gemma_kw,
        )

    def context_length(self) -> int:
        return int(
            self.metadata.get(f"{self.architecture()}.context_length", 4096)
        )


# -- parsing ----------------------------------------------------------------


def _read(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    data = f.read(size)
    if len(data) != size:
        raise ValueError("unexpected end of GGUF file")
    return struct.unpack(fmt, data)[0]


def _read_string(f: BinaryIO) -> str:
    n = _read(f, "<Q")
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int):
    if vtype in _SCALAR_FMT:
        return _read(f, _SCALAR_FMT[vtype])
    if vtype == _BOOL:
        return bool(_read(f, "<B"))
    if vtype == _STR:
        return _read_string(f)
    if vtype == _ARR:
        elem_type = _read(f, "<I")
        count = _read(f, "<Q")
        return [_read_value(f, elem_type) for _ in range(count)]
    raise ValueError(f"unknown GGUF metadata value type {vtype}")


#: (abspath, mtime_ns, size) -> parsed file; serving one model touches the
#: metadata three times (config, tokenizer, weights) — parse once.
_PARSE_CACHE: dict[tuple, "GgufFile"] = {}


def read_gguf(path: str, use_cache: bool = True) -> GgufFile:
    """Parse header, metadata, and the tensor index (no tensor data)."""
    import os

    if use_cache:
        st = os.stat(path)
        key = (os.path.abspath(path), st.st_mtime_ns, st.st_size)
        hit = _PARSE_CACHE.get(key)
        if hit is not None:
            return hit
    parsed = _read_gguf_impl(path)
    if use_cache:
        _PARSE_CACHE.clear()  # hold at most one file — they can be large
        _PARSE_CACHE[key] = parsed
    return parsed


def _read_gguf_impl(path: str) -> GgufFile:
    with open(path, "rb") as f:
        magic = _read(f, "<I")
        if magic != GGUF_MAGIC:
            raise ValueError(f"{path}: not a GGUF file (magic {magic:#x})")
        version = _read(f, "<I")
        if version not in (2, 3):
            raise ValueError(f"{path}: unsupported GGUF version {version}")
        n_tensors = _read(f, "<Q")
        n_kv = _read(f, "<Q")
        metadata: dict[str, Any] = {}
        for _ in range(n_kv):
            key = _read_string(f)
            vtype = _read(f, "<I")
            metadata[key] = _read_value(f, vtype)
        tensors: dict[str, GgufTensorInfo] = {}
        for _ in range(n_tensors):
            name = _read_string(f)
            n_dims = _read(f, "<I")
            # GGUF stores dims innermost-first; reverse for numpy order.
            dims = tuple(_read(f, "<Q") for _ in range(n_dims))[::-1]
            ggml_type = _read(f, "<I")
            offset = _read(f, "<Q")
            tensors[name] = GgufTensorInfo(
                name=name, shape=dims, ggml_type=ggml_type, offset=offset
            )
        alignment = int(metadata.get("general.alignment", 32))
        pos = f.tell()
        data_start = (pos + alignment - 1) // alignment * alignment
    return GgufFile(
        path=path,
        version=version,
        metadata=metadata,
        tensors=tensors,
        data_start=data_start,
        alignment=alignment,
    )


# -- writing (tests / tooling) ----------------------------------------------


def write_gguf(
    path: str,
    metadata: dict[str, Any],
    tensors: dict[str, np.ndarray],
    alignment: int = 32,
) -> None:
    """Minimal GGUF v3 writer for fixtures and export tooling. Tensor
    values are F32/F16 numpy arrays, or `(ggml_type, shape, raw_bytes)`
    tuples carrying a pre-quantized payload (e.g. from quantize_q8_0)."""

    def w_string(f, s: str):
        b = s.encode()
        f.write(struct.pack("<Q", len(b)))
        f.write(b)

    def value_type(v) -> int:
        if isinstance(v, bool):
            return _BOOL
        if isinstance(v, int):
            return _I64 if v < 0 else _U64
        if isinstance(v, float):
            return _F64
        if isinstance(v, str):
            return _STR
        if isinstance(v, (list, tuple)):
            return _ARR
        raise TypeError(f"unsupported metadata value {type(v)}")

    def w_value(f, v, vtype: int):
        if vtype == _BOOL:
            f.write(struct.pack("<B", int(v)))
        elif vtype in _SCALAR_FMT:
            f.write(struct.pack(_SCALAR_FMT[vtype], v))
        elif vtype == _STR:
            w_string(f, v)
        elif vtype == _ARR:
            et = value_type(v[0]) if v else _U64
            f.write(struct.pack("<IQ", et, len(v)))
            for item in v:
                w_value(f, item, et)

    with open(path, "wb") as f:
        f.write(struct.pack("<IIQQ", GGUF_MAGIC, 3, len(tensors), len(metadata)))
        for k, v in metadata.items():
            w_string(f, k)
            vt = value_type(v)
            f.write(struct.pack("<I", vt))
            w_value(f, v, vt)
        offset = 0
        blobs = []
        for name, arr in tensors.items():
            if isinstance(arr, tuple):
                # (ggml_type, shape, raw_bytes) — pre-quantized payload
                gt, shape, blob = arr
            elif arr.dtype == np.float32:
                gt, shape = 0, arr.shape
                blob = np.ascontiguousarray(arr).tobytes()
            elif arr.dtype == np.float16:
                gt, shape = 1, arr.shape
                blob = np.ascontiguousarray(arr).tobytes()
            else:
                raise TypeError(
                    "write_gguf supports f32/f16 arrays or (ggml_type, "
                    f"shape, raw_bytes) tuples, got {arr.dtype}"
                )
            w_string(f, name)
            dims = shape[::-1]  # innermost-first on disk
            f.write(struct.pack("<I", len(dims)))
            for d in dims:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<IQ", gt, offset))
            blobs.append((offset, blob))
            offset += (len(blob) + alignment - 1) // alignment * alignment
        pos = f.tell()
        pad = (pos + alignment - 1) // alignment * alignment - pos
        f.write(b"\x00" * pad)
        data_start = f.tell()
        for off, blob in blobs:
            f.seek(data_start + off)
            f.write(blob)

"""Incremental detokenization.

Token-at-a-time decode with a sliding window so multi-token glyphs (BPE
continuation bytes, SentencePiece pieces) render correctly: we keep the
last `read_offset` decoded text and emit only the stable suffix delta
(vLLM-style prefix-offset detokenization; reference contract:
DecodeStream::step — tokenizers.rs:212).
"""

from __future__ import annotations

from typing import Optional, Sequence

from dynamo_tpu.preprocessor.tokenizer import Tokenizer


class DecodeStream:
    def __init__(self, tokenizer: Tokenizer, window: int = 8):
        self.tokenizer = tokenizer
        self.window = window
        self.ids: list[int] = []
        self._emitted = ""

    def step(self, token_id: int) -> str:
        """Feed one token id; returns the newly-stable text delta ('' if the
        glyph is still incomplete)."""
        self.ids.append(token_id)
        tail = self.ids[-self.window :]
        prev_tail_text = self.tokenizer.decode(tail[:-1])
        tail_text = self.tokenizer.decode(tail)
        if tail_text.endswith("�"):
            return ""  # incomplete multi-byte glyph; hold
        if prev_tail_text.endswith("�"):
            # previous call held text back; recompute delta from full decode
            full = self.tokenizer.decode(self.ids)
            delta = full[len(self._emitted) :]
        else:
            delta = tail_text[len(prev_tail_text) :]
        self._emitted += delta
        return delta

    @property
    def text(self) -> str:
        return self._emitted

from dynamo_tpu.preprocessor.tokenizer import (
    ByteTokenizer,
    HfTokenizer,
    Tokenizer,
    load_tokenizer,
)
from dynamo_tpu.preprocessor.detokenize import DecodeStream
from dynamo_tpu.preprocessor.stop import StopChecker
from dynamo_tpu.preprocessor.preprocessor import (
    OpenAIPreprocessor,
    PreprocessedRequest,
)

__all__ = [
    "ByteTokenizer",
    "HfTokenizer",
    "Tokenizer",
    "load_tokenizer",
    "DecodeStream",
    "StopChecker",
    "OpenAIPreprocessor",
    "PreprocessedRequest",
]

"""Tokenizer abstraction: HF tokenizers for real models, a reversible
byte-level tokenizer for tests/echo (no downloads, vocab 256).

Parity with the reference's tokenizer layer (/root/reference lib/llm/src/
tokenizers.rs — Tokenizer :84, DecodeStream :212) with chat-template
rendering folded in (the reference renders via minijinja in its
preprocessor; HF tokenizers carry their template, and the byte tokenizer
uses a simple role-prefix format).
"""

from __future__ import annotations

import os
from typing import Optional, Protocol, Sequence


class Tokenizer(Protocol):
    name: str
    vocab_size: int
    eos_token_ids: tuple[int, ...]

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...

    def apply_chat_template(
        self, messages: list[dict], tools: Optional[list[dict]] = None
    ) -> str: ...

    def token_bytes(self, tok: int) -> bytes:
        """The exact bytes one token contributes to the output stream —
        the OpenAI logprobs `bytes` field. Unlike decode([tok]), partial
        UTF-8 sequences come back verbatim, not as replacement chars."""
        ...


_FALLBACK_TEMPLATE_SUFFIX = "assistant:"


def fallback_role_prefix(message: dict) -> str:
    """One message's role prefix in the structured fallback format — the
    multimodal prompt assembler builds the same format piecewise, so both
    paths share these constants."""
    return f"{message.get('role', 'user')}: "


FALLBACK_MESSAGE_SEP = "\n"


def render_fallback_template(messages: list[dict]) -> str:
    parts = []
    for m in messages:
        content = m.get("content") or ""
        if isinstance(content, list):  # multimodal-style content parts
            content = " ".join(
                p.get("text", "") for p in content if isinstance(p, dict)
            )
        parts.append(fallback_role_prefix(m) + content)
    parts.append(_FALLBACK_TEMPLATE_SUFFIX)
    return FALLBACK_MESSAGE_SEP.join(parts)


class ByteTokenizer:
    """UTF-8 bytes as token ids (0..255). Reversible, dependency-free."""

    def __init__(self, eos_token_ids: tuple[int, ...] = (0,)):
        self.name = "byte"
        self.vocab_size = 256
        self.eos_token_ids = eos_token_ids

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")

    def token_bytes(self, tok: int) -> bytes:
        return bytes([tok]) if 0 <= tok < 256 else b""

    def apply_chat_template(
        self, messages: list[dict], tools: Optional[list[dict]] = None
    ) -> str:
        return render_fallback_template(messages)


class HfTokenizer:
    """transformers AutoTokenizer wrapper (local files; zero-egress env)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.name = os.path.basename(path.rstrip("/"))
        self.vocab_size = len(self._tok)
        eos = self._tok.eos_token_id
        self.eos_token_ids = tuple(eos if isinstance(eos, list) else [eos]) if eos is not None else ()

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def token_bytes(self, tok: int) -> bytes:
        piece = self._tok.convert_ids_to_tokens(int(tok))
        if piece is None:
            return b""
        # sentencepiece byte token <0xNN>
        if len(piece) == 6 and piece.startswith("<0x") and piece.endswith(">"):
            try:
                return bytes([int(piece[3:5], 16)])
            except ValueError:
                pass
        # sentencepiece word-boundary marker
        if "▁" in piece:
            return piece.replace("▁", " ").encode()
        # byte-level BPE alphabet (GPT-2/llama3 style)
        u2b = _gpt2_unicode_to_byte()
        try:
            return bytes(u2b[c] for c in piece)
        except KeyError:
            return piece.encode()

    def apply_chat_template(
        self, messages: list[dict], tools: Optional[list[dict]] = None
    ) -> str:
        try:
            kwargs = {"tokenize": False, "add_generation_prompt": True}
            if tools:
                kwargs["tools"] = tools  # HF templates render these natively
            return self._tok.apply_chat_template(messages, **kwargs)
        except Exception:
            return render_fallback_template(messages)


def _gpt2_byte_table() -> dict[int, str]:
    """GPT-2's printable byte<->unicode map (byte-level BPE vocabs store
    pieces in this alphabet)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = list(bs)
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


_U2B_CACHE: Optional[dict[str, int]] = None


def _gpt2_unicode_to_byte() -> dict[str, int]:
    global _U2B_CACHE
    if _U2B_CACHE is None:
        _U2B_CACHE = {u: b for b, u in _gpt2_byte_table().items()}
    return _U2B_CACHE


class GgufTokenizer:
    """Tokenizer from a GGUF file's embedded vocabulary.

    Handles both vocab styles llama.cpp embeds: sentencepiece ("llama":
    ▁-prefixed pieces + <0xNN> byte tokens) and byte-level BPE ("gpt2":
    GPT-2 byte-alphabet pieces, e.g. Ġ for space). Decode joins pieces
    exactly (one sentencepiece dummy-prefix space stripped). Encode is
    greedy longest-match — a serviceable approximation of the true
    unigram/BPE merge search; unmatched input falls back to byte tokens
    or the unk id, never silently dropped. (The reference parses the same
    vocab for its model cards / mistralrs — gguf_tokenizer.rs.)"""

    def __init__(self, path: str):
        from dynamo_tpu.gguf import read_gguf

        vocab = read_gguf(path).tokenizer_vocab()
        if vocab is None:
            raise ValueError(f"{path}: GGUF file has no embedded tokenizer")
        self.name = os.path.basename(path)
        self.kind = vocab.get("model") or "llama"  # "llama" | "gpt2"
        self._tokens: list[str] = list(vocab["tokens"])
        self.vocab_size = len(self._tokens)
        eos = vocab.get("eos_token_id")
        self.eos_token_ids = (int(eos),) if eos is not None else ()
        self._bos = vocab.get("bos_token_id")
        self._chat_template = vocab.get("chat_template")
        self._index = {t: i for i, t in enumerate(self._tokens)}
        self._max_len = max((len(t) for t in self._tokens), default=1)
        self._unk = self._index.get("<unk>", 0)
        if self.kind == "gpt2":
            self._b2u = _gpt2_byte_table()
            self._u2b = {u: b for b, u in self._b2u.items()}
        else:
            self._byte_ids = {}
            for i, t in enumerate(self._tokens):
                if len(t) == 6 and t.startswith("<0x") and t.endswith(">"):
                    self._byte_ids[i] = int(t[3:5], 16)

    def _greedy(self, text: str, byte_fallback) -> list[int]:
        out: list[int] = []
        i = 0
        while i < len(text):
            for ln in range(min(self._max_len, len(text) - i), 0, -1):
                tid = self._index.get(text[i : i + ln])
                if tid is not None:
                    out.append(tid)
                    i += ln
                    break
            else:
                out.extend(byte_fallback(text[i]))
                i += 1
        return out

    def encode(self, text: str) -> list[int]:
        if self.kind == "gpt2":
            mapped = "".join(self._b2u[b] for b in text.encode("utf-8"))
            return self._greedy(mapped, lambda ch: [self._unk])
        spm = "▁" + text.replace(" ", "▁")

        def bytes_or_unk(ch: str) -> list[int]:
            ids = [
                self._index[f"<0x{byte:02X}>"]
                for byte in ch.encode("utf-8")
                if f"<0x{byte:02X}>" in self._index
            ]
            return ids or [self._unk]

        return self._greedy(spm, bytes_or_unk)

    def token_bytes(self, tok: int) -> bytes:
        if not 0 <= tok < len(self._tokens):
            return b""
        if self.kind == "gpt2":
            piece = self._tokens[tok]
            try:
                return bytes(self._u2b[c] for c in piece)
            except KeyError:
                # special/added token outside the byte alphabet: its piece
                # string IS its surface form — keep the bytes exact rather
                # than substituting
                return piece.encode()
        if tok in self._byte_ids:
            return bytes([self._byte_ids[tok]])
        return self._tokens[tok].replace("▁", " ").encode()

    def decode(self, ids: Sequence[int]) -> str:
        if self.kind == "gpt2":
            chars = "".join(
                self._tokens[i] for i in ids if 0 <= i < len(self._tokens)
            )
            data = bytes(self._u2b.get(c, ord(" ") & 0xFF) for c in chars)
            return data.decode("utf-8", errors="replace")
        parts: list[bytes] = []
        for i in ids:
            if i in self._byte_ids:
                parts.append(bytes([self._byte_ids[i]]))
            elif 0 <= i < len(self._tokens):
                parts.append(self._tokens[i].replace("▁", " ").encode())
        text = b"".join(parts).decode("utf-8", errors="replace")
        # sentencepiece dummy prefix: strip exactly one leading space, and
        # only when the first piece carries the ▁ marker (other leading
        # whitespace the model generated must survive).
        first = next(iter(ids), None)
        if (
            text.startswith(" ")
            and first is not None
            and 0 <= first < len(self._tokens)
            and self._tokens[first].startswith("▁")
        ):
            text = text[1:]
        return text

    def apply_chat_template(
        self, messages: list[dict], tools: Optional[list[dict]] = None
    ) -> str:
        # GGUF carries a jinja template string; rendering it would need a
        # jinja engine — use the structured fallback format instead
        # (tools accepted for interface parity; the fallback format has
        # no tool section).
        return render_fallback_template(messages)


def load_tokenizer(spec: dict | str) -> Tokenizer:
    """spec: "byte" | {"kind": "byte"} | {"kind": "hf", "path": dir}
    | {"kind": "gguf", "path": file.gguf}"""
    if isinstance(spec, str):
        spec = {"kind": spec}
    kind = spec.get("kind", "byte")
    if kind == "byte":
        return ByteTokenizer(tuple(spec.get("eos_token_ids", (0,))))
    if kind == "hf":
        return HfTokenizer(spec["path"])
    if kind == "gguf":
        return GgufTokenizer(spec["path"])
    raise ValueError(f"unknown tokenizer kind {kind!r}")

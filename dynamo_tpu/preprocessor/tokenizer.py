"""Tokenizer abstraction: HF tokenizers for real models, a reversible
byte-level tokenizer for tests/echo (no downloads, vocab 256).

Parity with the reference's tokenizer layer (/root/reference lib/llm/src/
tokenizers.rs — Tokenizer :84, DecodeStream :212) with chat-template
rendering folded in (the reference renders via minijinja in its
preprocessor; HF tokenizers carry their template, and the byte tokenizer
uses a simple role-prefix format).
"""

from __future__ import annotations

import os
from typing import Optional, Protocol, Sequence


class Tokenizer(Protocol):
    name: str
    vocab_size: int
    eos_token_ids: tuple[int, ...]

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...

    def apply_chat_template(self, messages: list[dict]) -> str: ...


_FALLBACK_TEMPLATE_SUFFIX = "assistant:"


def render_fallback_template(messages: list[dict]) -> str:
    parts = []
    for m in messages:
        content = m.get("content") or ""
        if isinstance(content, list):  # multimodal-style content parts
            content = " ".join(
                p.get("text", "") for p in content if isinstance(p, dict)
            )
        parts.append(f"{m.get('role', 'user')}: {content}")
    parts.append(_FALLBACK_TEMPLATE_SUFFIX)
    return "\n".join(parts)


class ByteTokenizer:
    """UTF-8 bytes as token ids (0..255). Reversible, dependency-free."""

    def __init__(self, eos_token_ids: tuple[int, ...] = (0,)):
        self.name = "byte"
        self.vocab_size = 256
        self.eos_token_ids = eos_token_ids

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: list[dict]) -> str:
        return render_fallback_template(messages)


class HfTokenizer:
    """transformers AutoTokenizer wrapper (local files; zero-egress env)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.name = os.path.basename(path.rstrip("/"))
        self.vocab_size = len(self._tok)
        eos = self._tok.eos_token_id
        self.eos_token_ids = tuple(eos if isinstance(eos, list) else [eos]) if eos is not None else ()

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict]) -> str:
        try:
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
        except Exception:
            return render_fallback_template(messages)


def load_tokenizer(spec: dict | str) -> Tokenizer:
    """spec: "byte" | {"kind": "byte"} | {"kind": "hf", "path": dir}"""
    if isinstance(spec, str):
        spec = {"kind": spec}
    kind = spec.get("kind", "byte")
    if kind == "byte":
        return ByteTokenizer(tuple(spec.get("eos_token_ids", (0,))))
    if kind == "hf":
        return HfTokenizer(spec["path"])
    raise ValueError(f"unknown tokenizer kind {kind!r}")

"""Stop-sequence detection over streaming text.

Stop *token ids* are handled on-engine; stop *strings* need text and can
straddle token boundaries, so the checker holds back the longest suffix of
emitted text that could still be a stop-string prefix (reference contract:
backend.rs StopTrigger/SeqResult :309-347).
"""

from __future__ import annotations

from typing import Optional


class StopChecker:
    def __init__(self, stop_strings: list[str]):
        self.stop_strings = [s for s in stop_strings if s]
        self._held = ""
        self.stopped = False

    def feed(self, delta: str) -> str:
        """Feed a text delta; returns text safe to emit. Sets .stopped when
        a stop string is seen (emitting only the text before it)."""
        if self.stopped:
            return ""
        if not self.stop_strings:
            return delta
        buf = self._held + delta
        # full match?
        first_hit = None
        for s in self.stop_strings:
            idx = buf.find(s)
            if idx != -1 and (first_hit is None or idx < first_hit[0]):
                first_hit = (idx, s)
        if first_hit is not None:
            self.stopped = True
            self._held = ""
            return buf[: first_hit[0]]
        # hold back longest tail that is a proper prefix of any stop string
        hold = 0
        for s in self.stop_strings:
            for k in range(min(len(s) - 1, len(buf)), 0, -1):
                if buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            self._held = buf[-hold:]
            return buf[:-hold]
        self._held = ""
        return buf

    def flush(self) -> str:
        """End of stream: release any held text (no stop matched)."""
        out, self._held = self._held, ""
        return out

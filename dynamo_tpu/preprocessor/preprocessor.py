"""OpenAI request ⇄ engine tokens: the preprocessor + stream postprocessor.

Forward: template render → tokenize → sampling/stop defaults →
PreprocessedRequest (the engine-facing contract; reference:
OpenAIPreprocessor::preprocess_request — preprocessor.rs:156).
Backward: token stream → incremental detokenize → stop strings → OpenAI
chunks (transform_postprocessor_stream :335 + backend.rs Decoder).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.preprocessor.detokenize import DecodeStream
from dynamo_tpu.preprocessor.stop import StopChecker
from dynamo_tpu.preprocessor.tokenizer import Tokenizer
from dynamo_tpu.protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatStreamChoice,
    ChatChoiceDelta,
    CompletionRequest,
    Usage,
    new_request_id,
    now,
)

DEFAULT_MAX_TOKENS = 512


@dataclass
class PreprocessedRequest:
    """Engine-facing request (msgpack-able via to_dict)."""

    request_id: str
    token_ids: list[int]
    max_tokens: int = DEFAULT_MAX_TOKENS
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    seed: Optional[int] = None
    stop_token_ids: list[int] = field(default_factory=list)
    stop_strings: list[str] = field(default_factory=list)
    ignore_eos: bool = False
    annotations: dict[str, Any] = field(default_factory=dict)
    #: multimodal: projected image embeddings [n, H] f32 (numpy) spliced at
    #: mm_positions (absolute prompt indices of the placeholder tokens)
    mm_embeds: Optional[Any] = None
    mm_positions: list[int] = field(default_factory=list)

    def to_dict(self) -> dict:
        d = {
            "request_id": self.request_id,
            "token_ids": self.token_ids,
            "max_tokens": self.max_tokens,
            "temperature": self.temperature,
            "top_p": self.top_p,
            "top_k": self.top_k,
            "seed": self.seed,
            "stop_token_ids": self.stop_token_ids,
            "stop_strings": self.stop_strings,
            "ignore_eos": self.ignore_eos,
            "annotations": self.annotations,
        }
        if self.mm_embeds is not None:
            import numpy as np

            arr = np.asarray(self.mm_embeds, np.float32)
            d["mm_embeds"] = arr.tobytes()
            d["mm_shape"] = list(arr.shape)
            d["mm_positions"] = list(self.mm_positions)
        return d

    @staticmethod
    def from_dict(d: dict) -> "PreprocessedRequest":
        d = dict(d)
        raw = d.pop("mm_embeds", None)
        shape = d.pop("mm_shape", None)
        pre = PreprocessedRequest(**d)
        if raw is not None:
            import numpy as np

            pre.mm_embeds = np.frombuffer(raw, np.float32).reshape(shape)
        return pre


def _stop_list(stop) -> list[str]:
    if stop is None:
        return []
    if isinstance(stop, str):
        return [stop]
    return list(stop)


class OpenAIPreprocessor:
    def __init__(self, tokenizer: Tokenizer, model_name: str = ""):
        self.tokenizer = tokenizer
        self.model_name = model_name

    # -- forward -----------------------------------------------------------

    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        messages = [m.model_dump(exclude_none=True) for m in request.messages]
        return self.preprocess_chat_messages(messages, request)

    def preprocess_chat_messages(
        self, messages: list[dict], request: ChatCompletionRequest
    ) -> PreprocessedRequest:
        """Chat preprocessing over already-dumped message dicts (the
        multimodal path transforms image parts into embeddings first)."""
        if any(
            isinstance(m.get("content"), list)
            and any(
                isinstance(p, dict) and p.get("type") == "image_embed"
                for p in m["content"]
            )
            for m in messages
        ):
            ids, mm_embeds, mm_positions = self._multimodal_prompt(messages)
        else:
            prompt = self.tokenizer.apply_chat_template(messages)
            ids, mm_embeds, mm_positions = (
                self.tokenizer.encode(prompt), None, []
            )
        pre = self._common(
            prompt_ids=ids,
            max_tokens=request.effective_max_tokens,
            temperature=request.temperature,
            top_p=request.top_p,
            top_k=request.top_k,
            seed=request.seed,
            stop=request.stop,
            ext=request.extension,
        )
        pre.mm_embeds = mm_embeds
        pre.mm_positions = mm_positions
        return pre

    def _multimodal_prompt(self, messages: list[dict]):
        """llava-style prompt assembly: text parts tokenize normally; each
        image_embed part contributes one placeholder token per embedding
        row, recorded in (mm_embeds, mm_positions). Uses the structured
        fallback chat format (templates are text-only)."""
        import base64 as b64mod

        import numpy as np

        from dynamo_tpu.preprocessor.tokenizer import (
            _FALLBACK_TEMPLATE_SUFFIX,
            FALLBACK_MESSAGE_SEP,
            fallback_role_prefix,
        )

        ids: list[int] = []
        vecs: list[np.ndarray] = []
        positions: list[int] = []
        for m in messages:
            ids += self.tokenizer.encode(fallback_role_prefix(m))
            content = m.get("content") or ""
            if isinstance(content, str):
                ids += self.tokenizer.encode(content)
            else:
                for part in content:
                    ptype = part.get("type")
                    if ptype == "text":
                        ids += self.tokenizer.encode(part.get("text", ""))
                    elif ptype == "image_embed":
                        emb = part.get("embedding")
                        if isinstance(emb, (bytes, str)):
                            raw = (
                                b64mod.b64decode(emb)
                                if isinstance(emb, str)
                                else emb
                            )
                            arr = np.frombuffer(raw, np.float32).reshape(
                                part["shape"]
                            )
                        else:
                            arr = np.asarray(emb, np.float32)
                        if arr.ndim == 1:
                            arr = arr[None]
                        for row in arr:
                            positions.append(len(ids))
                            ids.append(0)  # placeholder; masked by mm_mask
                            vecs.append(row)
                    else:
                        raise ValueError(
                            f"unsupported content part type {ptype!r} "
                            "(no image encoder attached?)"
                        )
            ids += self.tokenizer.encode(FALLBACK_MESSAGE_SEP)
        ids += self.tokenizer.encode(_FALLBACK_TEMPLATE_SUFFIX)
        embeds = np.stack(vecs) if vecs else None
        return ids, embeds, positions

    def preprocess_completion(self, request: CompletionRequest) -> PreprocessedRequest:
        prompt = request.prompt
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            ids = list(prompt)
        elif isinstance(prompt, list):
            ids = self.tokenizer.encode("".join(prompt))
        else:
            ids = self.tokenizer.encode(prompt)
        return self._common(
            prompt_ids=ids,
            max_tokens=request.max_tokens,
            temperature=request.temperature,
            top_p=request.top_p,
            top_k=request.top_k,
            seed=request.seed,
            stop=request.stop,
            ext=request.extension,
        )

    def _common(
        self, prompt_ids, max_tokens, temperature, top_p, top_k, seed, stop, ext
    ) -> PreprocessedRequest:
        return PreprocessedRequest(
            request_id=new_request_id(),
            token_ids=prompt_ids,
            max_tokens=max_tokens or DEFAULT_MAX_TOKENS,
            temperature=temperature if temperature is not None else 0.0,
            top_p=top_p if top_p is not None else 1.0,
            top_k=top_k if top_k is not None else 0,
            seed=seed,
            stop_token_ids=list(self.tokenizer.eos_token_ids),
            stop_strings=_stop_list(stop),
            ignore_eos=bool(ext.ignore_eos) if ext else False,
            annotations=(ext.annotations or {}) if ext else {},
        )

    # -- backward ----------------------------------------------------------

    async def postprocess_chat_stream(
        self,
        engine_stream: AsyncIterator[dict],
        request_id: str,
        preprocessed: PreprocessedRequest,
        include_usage: bool = False,
    ) -> AsyncIterator[ChatCompletionChunk]:
        """Engine events {token_ids, finish_reason} → OpenAI chunks."""
        decode = DecodeStream(self.tokenizer)
        stop = StopChecker(preprocessed.stop_strings)
        created = now()
        completion_tokens = 0
        first = True
        finish: Optional[str] = None

        def chunk(content=None, role=None, finish_reason=None):
            return ChatCompletionChunk(
                id=request_id,
                created=created,
                model=self.model_name,
                choices=[
                    ChatStreamChoice(
                        delta=ChatChoiceDelta(role=role, content=content),
                        finish_reason=finish_reason,
                    )
                ],
            )

        stop_ids = set(preprocessed.stop_token_ids)
        async for event in engine_stream:
            for tok in event.get("token_ids", ()):
                completion_tokens += 1
                if tok in stop_ids and not preprocessed.ignore_eos:
                    finish = "stop"
                    break  # never render the stop/eos token itself
                delta = decode.step(tok)
                text = stop.feed(delta)
                if text:
                    if first:
                        yield chunk(role="assistant", content=text)
                        first = False
                    else:
                        yield chunk(content=text)
                if stop.stopped:
                    finish = "stop"
                    break
            if stop.stopped or finish == "stop":
                break
            if event.get("finish_reason"):
                finish = event["finish_reason"]
        if not stop.stopped:
            tail = stop.flush()
            if tail:
                yield chunk(content=tail, role="assistant" if first else None)
                first = False
        final = chunk(finish_reason=finish or "stop")
        if include_usage:
            final.usage = Usage(
                prompt_tokens=len(preprocessed.token_ids),
                completion_tokens=completion_tokens,
                total_tokens=len(preprocessed.token_ids) + completion_tokens,
            )
        yield final

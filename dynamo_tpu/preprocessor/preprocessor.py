"""OpenAI request ⇄ engine tokens: the preprocessor + stream postprocessor.

Forward: template render → tokenize → sampling/stop defaults →
PreprocessedRequest (the engine-facing contract; reference:
OpenAIPreprocessor::preprocess_request — preprocessor.rs:156).
Backward: token stream → incremental detokenize → stop strings → OpenAI
chunks (transform_postprocessor_stream :335 + backend.rs Decoder).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field, fields
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.preprocessor.detokenize import DecodeStream
from dynamo_tpu.preprocessor.stop import StopChecker
from dynamo_tpu.preprocessor.tokenizer import Tokenizer
from dynamo_tpu.protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatStreamChoice,
    ChatChoiceDelta,
    CompletionRequest,
    Usage,
    new_request_id,
    now,
)

logger = logging.getLogger(__name__)

DEFAULT_MAX_TOKENS = 512


@dataclass
class PreprocessedRequest:
    """Engine-facing request (msgpack-able via to_dict)."""

    request_id: str
    token_ids: list[int]
    max_tokens: int = DEFAULT_MAX_TOKENS
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    seed: Optional[int] = None
    stop_token_ids: list[int] = field(default_factory=list)
    stop_strings: list[str] = field(default_factory=list)
    ignore_eos: bool = False
    #: -1 off; 0 chosen-token logprob; N>0 chosen + top-N alternatives
    logprobs: int = -1
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    #: multiplicative repetition penalty over generated tokens (1 = off;
    #: ext or top-level — the reference carries it in nvext; prompt
    #: tokens are deliberately not penalized)
    repetition_penalty: float = 1.0
    #: OpenAI logit_bias as [[token_id, bias], ...] (validated/clamped)
    logit_bias: list = field(default_factory=list)
    #: eos/stop suppression floor (ext.min_tokens)
    min_tokens: int = 0
    #: absolute end-to-end deadline, epoch seconds (None = none). Minted
    #: at the HTTP frontend from `x-request-timeout` (or the server
    #: default) and carried through every hop — router wire, disagg
    #: queue, external-engine frames (docs/operations.md "Overload &
    #: draining"). Clocks across hosts are assumed loosely NTP-synced.
    deadline: Optional[float] = None
    annotations: dict[str, Any] = field(default_factory=dict)
    #: multimodal: projected image embeddings [n, H] f32 (numpy) spliced at
    #: mm_positions (absolute prompt indices of the placeholder tokens)
    mm_embeds: Optional[Any] = None
    mm_positions: list[int] = field(default_factory=list)

    def to_dict(self) -> dict:
        d = {
            "request_id": self.request_id,
            "token_ids": self.token_ids,
            "max_tokens": self.max_tokens,
            "temperature": self.temperature,
            "top_p": self.top_p,
            "top_k": self.top_k,
            "seed": self.seed,
            "stop_token_ids": self.stop_token_ids,
            "stop_strings": self.stop_strings,
            "ignore_eos": self.ignore_eos,
            "logprobs": self.logprobs,
            "frequency_penalty": self.frequency_penalty,
            "presence_penalty": self.presence_penalty,
            "logit_bias": self.logit_bias,
            "min_tokens": self.min_tokens,
            "annotations": self.annotations,
        }
        if self.repetition_penalty != 1.0:
            # omit the no-op default so older external-engine shims
            # (docs/external_engines.md) keep parsing the dict
            d["repetition_penalty"] = self.repetition_penalty
        if self.deadline is not None:
            # same back-compat shape: only deadline-carrying requests
            # put the key on the wire
            d["deadline"] = self.deadline
        if self.mm_embeds is not None:
            import numpy as np

            arr = np.asarray(self.mm_embeds, np.float32)
            d["mm_embeds"] = arr.tobytes()
            d["mm_shape"] = list(arr.shape)
            d["mm_positions"] = list(self.mm_positions)
        return d

    @staticmethod
    def from_dict(d: dict) -> "PreprocessedRequest":
        d = dict(d)
        raw = d.pop("mm_embeds", None)
        shape = d.pop("mm_shape", None)
        # the wire contract (docs/external_engines.md) says unknown fields
        # may be ignored — honor it here too, so a newer frontend can add
        # optional fields without breaking older workers
        unknown = [k for k in d if k not in _REQUEST_FIELDS]
        if unknown:
            logger.debug("ignoring unknown request fields: %s", unknown)
            d = {k: v for k, v in d.items() if k in _REQUEST_FIELDS}
        pre = PreprocessedRequest(**d)
        if raw is not None:
            import numpy as np

            pre.mm_embeds = np.frombuffer(raw, np.float32).reshape(shape)
        return pre


_REQUEST_FIELDS = frozenset(f.name for f in fields(PreprocessedRequest))


def _logit_bias_list(raw) -> list:
    """OpenAI logit_bias dict (JSON string or int keys) -> validated
    [[token_id, bias], ...]. Values clamp to [-100, 100] (OpenAI's
    documented range); non-integer keys are a 400, like the reference's
    validate_logit_bias (protocols/openai/validate.rs)."""
    if not raw:
        return []
    from dynamo_tpu.engine.sampling import BIAS_SLOTS

    if len(raw) > BIAS_SLOTS:
        raise ValueError(
            f"logit_bias supports at most {BIAS_SLOTS} entries; got {len(raw)}"
        )
    out = []
    for k, v in raw.items():
        try:
            tid = int(k)
        except (TypeError, ValueError):
            raise ValueError(f"logit_bias keys must be token ids; got {k!r}")
        if tid < 0:
            raise ValueError(f"logit_bias token id must be >= 0; got {tid}")
        out.append([tid, max(-100.0, min(100.0, float(v)))])
    return out


def _stop_list(stop) -> list[str]:
    if stop is None:
        return []
    if isinstance(stop, str):
        return [stop]
    return list(stop)


def _chat_logprobs(request) -> int:
    """Chat logprobs knobs → engine value, with OpenAI's validation
    (rejected with 400, not silently clamped)."""
    n = request.top_logprobs
    if n is not None and not 0 <= int(n) <= 20:
        raise ValueError(
            f"top_logprobs must be between 0 and 20; got {n}"
        )
    if not request.logprobs:
        if n is not None:
            raise ValueError(
                "top_logprobs requires logprobs to be true"
            )
        return -1
    return int(n or 0)


def _completion_logprobs(request) -> int:
    """Legacy completions logprobs=N → engine value, validated.

    The legacy OpenAI completions API caps logprobs at 5 (unlike chat's
    top_logprobs<=20); match it so clients get the same 400 they'd get
    upstream."""
    n = request.logprobs
    if n is None:
        return -1
    if not 0 <= int(n) <= 5:
        raise ValueError(f"logprobs must be between 0 and 5; got {n}")
    return int(n)


class OpenAIPreprocessor:
    def __init__(self, tokenizer: Tokenizer, model_name: str = ""):
        self.tokenizer = tokenizer
        self.model_name = model_name

    # -- forward -----------------------------------------------------------

    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        messages = [m.model_dump(exclude_none=True) for m in request.messages]
        return self.preprocess_chat_messages(messages, request)

    def preprocess_chat_messages(
        self, messages: list[dict], request: ChatCompletionRequest
    ) -> PreprocessedRequest:
        """Chat preprocessing over already-dumped message dicts (the
        multimodal path transforms image parts into embeddings first)."""
        if any(
            isinstance(m.get("content"), list)
            and any(
                isinstance(p, dict) and p.get("type") == "image_embed"
                for p in m["content"]
            )
            for m in messages
        ):
            if getattr(request, "tools", None):
                # the multimodal prompt is assembled piecewise in the
                # fallback format, which has no tool section — surface the
                # drop instead of silently hiding the definitions
                logger.warning(
                    "tools ignored for multimodal request (no template "
                    "rendering on the multimodal path)"
                )
            ids, mm_embeds, mm_positions = self._multimodal_prompt(messages)
        elif request.extension and request.extension.use_raw_prompt:
            # nvext use_raw_prompt (reference nvext.rs:56): skip the chat
            # template and tokenize the message contents verbatim — for
            # clients that pre-render their own prompt. Messages join
            # with a newline (the reference's raw-prompt fallback
            # semantics; a bare ''.join would fuse tokens across message
            # boundaries). Structured content contributes its text parts.
            texts: list[str] = []
            for m in messages:
                c = m.get("content")
                if isinstance(c, str):
                    texts.append(c)
                elif isinstance(c, list):
                    # a message's own text parts stay contiguous
                    texts.append(
                        "".join(
                            p.get("text", "")
                            for p in c
                            if isinstance(p, dict)
                            and p.get("type") == "text"
                        )
                    )
            ids, mm_embeds, mm_positions = (
                self.tokenizer.encode("\n".join(texts)), None, []
            )
        else:
            prompt = self.tokenizer.apply_chat_template(
                messages, tools=getattr(request, "tools", None)
            )
            ids, mm_embeds, mm_positions = (
                self.tokenizer.encode(prompt), None, []
            )
        pre = self._common(
            prompt_ids=ids,
            max_tokens=request.effective_max_tokens,
            temperature=request.temperature,
            top_p=request.top_p,
            top_k=request.top_k,
            seed=request.seed,
            stop=request.stop,
            ext=request.extension,
            # chat API: logprobs=true turns reporting on; top_logprobs asks
            # for N alternatives per token (OpenAI caps at 20)
            logprobs=_chat_logprobs(request),
            frequency_penalty=request.frequency_penalty or 0.0,
            presence_penalty=request.presence_penalty or 0.0,
            repetition_penalty=(
                request.repetition_penalty
                if request.repetition_penalty is not None
                else 1.0
            ),
            logit_bias=_logit_bias_list(request.logit_bias),
        )
        pre.mm_embeds = mm_embeds
        pre.mm_positions = mm_positions
        return pre

    def _multimodal_prompt(self, messages: list[dict]):
        """llava-style prompt assembly: text parts tokenize normally; each
        image_embed part contributes one placeholder token per embedding
        row, recorded in (mm_embeds, mm_positions). Uses the structured
        fallback chat format (templates are text-only)."""
        import base64 as b64mod

        import numpy as np

        from dynamo_tpu.preprocessor.tokenizer import (
            _FALLBACK_TEMPLATE_SUFFIX,
            FALLBACK_MESSAGE_SEP,
            fallback_role_prefix,
        )

        ids: list[int] = []
        vecs: list[np.ndarray] = []
        positions: list[int] = []
        for m in messages:
            ids += self.tokenizer.encode(fallback_role_prefix(m))
            content = m.get("content") or ""
            if isinstance(content, str):
                ids += self.tokenizer.encode(content)
            else:
                for part in content:
                    ptype = part.get("type")
                    if ptype == "text":
                        ids += self.tokenizer.encode(part.get("text", ""))
                    elif ptype == "image_embed":
                        emb = part.get("embedding")
                        if isinstance(emb, (bytes, str)):
                            raw = (
                                b64mod.b64decode(emb)
                                if isinstance(emb, str)
                                else emb
                            )
                            arr = np.frombuffer(raw, np.float32).reshape(
                                part["shape"]
                            )
                        else:
                            arr = np.asarray(emb, np.float32)
                        if arr.ndim == 1:
                            arr = arr[None]
                        for row in arr:
                            positions.append(len(ids))
                            ids.append(0)  # placeholder; masked by mm_mask
                            vecs.append(row)
                    else:
                        raise ValueError(
                            f"unsupported content part type {ptype!r} "
                            "(no image encoder attached?)"
                        )
            ids += self.tokenizer.encode(FALLBACK_MESSAGE_SEP)
        ids += self.tokenizer.encode(_FALLBACK_TEMPLATE_SUFFIX)
        embeds = np.stack(vecs) if vecs else None
        return ids, embeds, positions

    def preprocess_completion(self, request: CompletionRequest) -> PreprocessedRequest:
        prompt = request.prompt
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            ids = list(prompt)
        elif isinstance(prompt, list):
            ids = self.tokenizer.encode("".join(prompt))
        else:
            ids = self.tokenizer.encode(prompt)
        return self._common(
            prompt_ids=ids,
            max_tokens=request.max_tokens,
            temperature=request.temperature,
            top_p=request.top_p,
            top_k=request.top_k,
            seed=request.seed,
            stop=request.stop,
            ext=request.extension,
            # completions API (legacy): logprobs=N means chosen + top-N
            logprobs=_completion_logprobs(request),
            frequency_penalty=request.frequency_penalty or 0.0,
            presence_penalty=request.presence_penalty or 0.0,
            repetition_penalty=(
                request.repetition_penalty
                if request.repetition_penalty is not None
                else 1.0
            ),
            logit_bias=_logit_bias_list(request.logit_bias),
        )

    def _common(
        self, prompt_ids, max_tokens, temperature, top_p, top_k, seed, stop,
        ext, logprobs: int = -1, frequency_penalty: float = 0.0,
        presence_penalty: float = 0.0, logit_bias=None,
        repetition_penalty: float = 1.0,
    ) -> PreprocessedRequest:
        min_tokens = int(ext.min_tokens or 0) if ext else 0
        if min_tokens < 0:
            raise ValueError(f"min_tokens must be >= 0; got {min_tokens}")
        rep = repetition_penalty
        if ext and ext.repetition_penalty is not None:
            # nvext-sourced values mirror the reference's validation
            # range (nvext.rs:42) for drop-in parity; the top-level
            # field stays an any->0 extension (docs/migrating.md).
            rep = ext.repetition_penalty
            if not 0 < rep <= 2.0:
                raise ValueError(
                    f"nvext repetition_penalty must be in (0, 2.0]; got "
                    f"{rep} (the top-level field accepts any value > 0 "
                    "as an extension)"
                )
        if rep <= 0:
            raise ValueError(f"repetition_penalty must be > 0; got {rep}")
        if ext and ext.greed_sampling:
            # nvext greed_sampling: force argmax decoding regardless of
            # the request's temperature (reference nvext.rs:50)
            temperature = 0.0
        return PreprocessedRequest(
            request_id=new_request_id(),
            token_ids=prompt_ids,
            max_tokens=max_tokens or DEFAULT_MAX_TOKENS,
            temperature=temperature if temperature is not None else 0.0,
            top_p=top_p if top_p is not None else 1.0,
            top_k=top_k if top_k is not None else 0,
            seed=seed,
            stop_token_ids=list(self.tokenizer.eos_token_ids),
            stop_strings=_stop_list(stop),
            ignore_eos=bool(ext.ignore_eos) if ext else False,
            logprobs=logprobs,
            frequency_penalty=frequency_penalty or 0.0,
            presence_penalty=presence_penalty or 0.0,
            repetition_penalty=rep,
            logit_bias=logit_bias or [],
            min_tokens=min_tokens,
            annotations=(ext.annotations or {}) if ext else {},
        )

    # -- backward ----------------------------------------------------------

    async def postprocess_chat_stream(
        self,
        engine_stream: AsyncIterator[dict],
        request_id: str,
        preprocessed: PreprocessedRequest,
        include_usage: bool = False,
    ) -> AsyncIterator[ChatCompletionChunk]:
        """Engine events {token_ids, finish_reason} → OpenAI chunks."""
        decode = DecodeStream(self.tokenizer)
        stop = StopChecker(preprocessed.stop_strings)
        created = now()
        completion_tokens = 0
        cached_tokens = 0
        first = True
        finish: Optional[str] = None
        #: logprob entries for tokens whose text is still buffered by the
        #: stop-checker — attached to the next emitted chunk so the entry
        #: sequence stays complete and ordered
        pending_lp: list = []

        def chunk(content=None, role=None, finish_reason=None, logprobs=None):
            return ChatCompletionChunk(
                id=request_id,
                created=created,
                model=self.model_name,
                choices=[
                    ChatStreamChoice(
                        delta=ChatChoiceDelta(role=role, content=content),
                        logprobs=logprobs,
                        finish_reason=finish_reason,
                    )
                ],
            )

        def tok_repr(t: int) -> tuple[str, list[int]]:
            """(display text, exact bytes) for one token. token_bytes keeps
            partial-UTF-8 tokens exact — the whole point of the OpenAI
            `bytes` field; the display string may show replacement chars."""
            if hasattr(self.tokenizer, "token_bytes"):
                raw = self.tokenizer.token_bytes(t)
            else:
                raw = self.tokenizer.decode([t]).encode()
            return raw.decode("utf-8", errors="replace"), list(raw)

        def lp_entry(tok: int, i: int, event: dict):
            from dynamo_tpu.protocols.openai import TokenLogprob, TopLogprob

            lps = event.get("logprobs")
            if lps is None or i >= len(lps):
                return None
            tok_text, tok_raw = tok_repr(tok)
            alts = []
            for pair in (event.get("top_logprobs") or [[]] * len(lps))[i]:
                alt_text, alt_raw = tok_repr(int(pair[0]))
                alts.append(
                    TopLogprob(
                        token=alt_text,
                        logprob=float(pair[1]),
                        bytes=alt_raw,
                    )
                )
            return TokenLogprob(
                token=tok_text,
                logprob=float(lps[i]),
                bytes=tok_raw,
                top_logprobs=alts,
            )

        def take_lp():
            if not pending_lp:
                return None
            from dynamo_tpu.protocols.openai import ChoiceLogprobs

            out = ChoiceLogprobs(content=list(pending_lp))
            pending_lp.clear()
            return out

        stop_ids = set(preprocessed.stop_token_ids)
        async for event in engine_stream:
            if event.get("cached_tokens"):
                cached_tokens = int(event["cached_tokens"])
            for i, tok in enumerate(event.get("token_ids", ())):
                completion_tokens += 1
                if tok in stop_ids and not preprocessed.ignore_eos:
                    finish = "stop"
                    break  # never render the stop/eos token itself
                e = lp_entry(tok, i, event)
                if e is not None:
                    pending_lp.append(e)
                delta = decode.step(tok)
                text = stop.feed(delta)
                if text:
                    if first:
                        yield chunk(
                            role="assistant", content=text, logprobs=take_lp()
                        )
                        first = False
                    else:
                        yield chunk(content=text, logprobs=take_lp())
                if stop.stopped:
                    finish = "stop"
                    break
            if stop.stopped or finish == "stop":
                break
            if event.get("finish_reason"):
                finish = event["finish_reason"]
        if not stop.stopped:
            tail = stop.flush()
            if tail:
                yield chunk(
                    content=tail, role="assistant" if first else None,
                    logprobs=take_lp(),
                )
                first = False
        # Any logprob entries still pending (tokens whose text never
        # rendered — partial UTF-8 at stream end, or buffered by a stop
        # string) ride the final chunk; dropping them would desync the
        # entry list from the sampled tokens.
        final = chunk(finish_reason=finish or "stop", logprobs=take_lp())
        yield final
        if include_usage:
            # OpenAI contract: usage rides its own trailing chunk with an
            # empty choices list, after the finish_reason chunk.
            yield ChatCompletionChunk(
                id=request_id,
                created=created,
                model=self.model_name,
                choices=[],
                usage=Usage(
                    prompt_tokens=len(preprocessed.token_ids),
                    completion_tokens=completion_tokens,
                    total_tokens=(
                        len(preprocessed.token_ids) + completion_tokens
                    ),
                    prompt_tokens_details=(
                        {"cached_tokens": cached_tokens}
                        if cached_tokens
                        else None
                    ),
                ),
            )

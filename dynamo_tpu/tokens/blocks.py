"""Content-addressed token blocks.

The canonical contract that makes KV-cache-aware routing work across engines:
a token stream is chunked into fixed-size blocks; each full block gets a
*sequence hash* chained from its parent so that an identical prefix always
produces an identical chain of hashes, regardless of which worker produced it.
The engine's paged KV cache, the KV router's radix indexer, the block manager,
and the mock engine all speak in these hashes.

Capability parity with the reference's token primitives crate
(/root/reference lib/tokens/src/lib.rs: `TokenBlock` :221, chained hash :231,
`PartialTokenBlock::push_token` :152, xxh3 with salt :44), re-implemented
independently: we chain xxh3_64 over little-endian u32 tokens with the parent
sequence hash folded in as the seed.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

import xxhash

Token = int
SequenceHash = int  # u64
SaltHash = int  # u64

#: Seed used when hashing the salt string and the root block.
BLOCK_HASH_SEED = 1337

#: Default block size. The reference deploys 64/128-token blocks; 64 hits a
#: good balance between routing granularity and page-table overhead on TPU
#: (one block == one KV page in the engine).
DEFAULT_BLOCK_SIZE = 64

_U64_MASK = (1 << 64) - 1


def _native_bulk_hashes(tokens: Sequence[Token], block_size: int, salt_hash: int):
    """All full-block (block_hash, seq_hash) pairs in one C call, or None.

    The C++ path (native/dynamo_native.cpp dyn_hash_token_blocks) is
    byte-identical to the Python chain below — asserted by
    tests/test_native.py on random streams.
    """
    n_full = len(tokens) - len(tokens) % block_size
    if n_full == 0:
        return None
    from dynamo_tpu.native import lib

    l = lib()
    if l is None:
        return None
    import numpy as np

    try:
        arr = np.ascontiguousarray(
            (np.asarray(tokens, dtype=np.int64) & 0xFFFFFFFF).astype(np.uint32)
        )
    except (OverflowError, ValueError, TypeError):
        # Token outside int64 range — mask in Python like the scalar path.
        arr = np.asarray([t & 0xFFFFFFFF for t in tokens], np.uint32)
    nb = n_full // block_size
    bh = np.empty(nb, np.uint64)
    sh = np.empty(nb, np.uint64)
    l.dyn_hash_token_blocks(
        arr.ctypes.data, len(arr), block_size, salt_hash & _U64_MASK,
        BLOCK_HASH_SEED, bh.ctypes.data, sh.ctypes.data,
    )
    return bh.tolist(), sh.tolist()


def compute_salt_hash(salt: str = "") -> SaltHash:
    """Hash a namespace salt (e.g. model id) so hash chains from different
    models never collide in a shared index."""
    return xxhash.xxh3_64_intdigest(salt.encode("utf-8"), seed=BLOCK_HASH_SEED)


def _pack_tokens(tokens: Sequence[Token]) -> bytes:
    return struct.pack(f"<{len(tokens)}I", *[t & 0xFFFFFFFF for t in tokens])


def compute_block_hash(tokens: Sequence[Token], seed: int) -> int:
    """Hash one block's tokens under a chaining seed (parent hash or salt)."""
    return xxhash.xxh3_64_intdigest(_pack_tokens(tokens), seed=seed & _U64_MASK)


def compute_seq_hash(parent: Optional[SequenceHash], block_hash: int) -> SequenceHash:
    """Chain a block hash onto its parent to get the block's sequence hash."""
    if parent is None:
        return block_hash & _U64_MASK
    return xxhash.xxh3_64_intdigest(
        struct.pack("<QQ", parent & _U64_MASK, block_hash & _U64_MASK),
        seed=BLOCK_HASH_SEED,
    )


@dataclass(frozen=True)
class TokenBlock:
    """An immutable, full block of tokens with its chained identity."""

    tokens: tuple[Token, ...]
    block_hash: int
    sequence_hash: SequenceHash
    parent_sequence_hash: Optional[SequenceHash]
    block_index: int

    def __len__(self) -> int:
        return len(self.tokens)


@dataclass
class PartialTokenBlock:
    """The mutable tail of a sequence: accumulates tokens until full."""

    block_size: int
    salt_hash: SaltHash
    parent_sequence_hash: Optional[SequenceHash]
    block_index: int
    tokens: list[Token] = field(default_factory=list)

    @property
    def remaining(self) -> int:
        return self.block_size - len(self.tokens)

    def push_token(self, token: Token) -> Optional[TokenBlock]:
        """Append one token; returns the committed TokenBlock when it fills."""
        self.tokens.append(token)
        if len(self.tokens) == self.block_size:
            return self._commit()
        return None

    def _commit(self) -> TokenBlock:
        seed = (
            self.parent_sequence_hash
            if self.parent_sequence_hash is not None
            else self.salt_hash
        )
        block_hash = compute_block_hash(self.tokens, seed)
        seq_hash = compute_seq_hash(self.parent_sequence_hash, block_hash)
        return TokenBlock(
            tokens=tuple(self.tokens),
            block_hash=block_hash,
            sequence_hash=seq_hash,
            parent_sequence_hash=self.parent_sequence_hash,
            block_index=self.block_index,
        )


class TokenBlockSequence:
    """A token stream chunked into content-addressed blocks.

    Appending tokens commits full blocks eagerly; `blocks` holds the immutable
    prefix and `partial` the in-progress tail. Truncation (for stop-sequence
    rollback) is supported via `truncate`.
    """

    def __init__(
        self,
        tokens: Iterable[Token] = (),
        block_size: int = DEFAULT_BLOCK_SIZE,
        salt: str = "",
    ):
        if block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        self.salt_hash = compute_salt_hash(salt)
        self.blocks: list[TokenBlock] = []
        self.partial = PartialTokenBlock(
            block_size=block_size,
            salt_hash=self.salt_hash,
            parent_sequence_hash=None,
            block_index=0,
        )
        toks = list(tokens)
        bulk = _native_bulk_hashes(toks, block_size, self.salt_hash)
        if bulk is None:
            self.extend(toks)
            return
        # Bulk ingest (prompt admission hot path): hashes computed in one
        # native call; Python only materializes the block objects.
        block_hashes, seq_hashes = bulk
        parent: Optional[SequenceHash] = None
        for i, (bh, sh) in enumerate(zip(block_hashes, seq_hashes)):
            self.blocks.append(
                TokenBlock(
                    tokens=tuple(toks[i * block_size : (i + 1) * block_size]),
                    block_hash=bh,
                    sequence_hash=sh,
                    parent_sequence_hash=parent,
                    block_index=i,
                )
            )
            parent = sh
        nb = len(block_hashes)
        self.partial = PartialTokenBlock(
            block_size=block_size,
            salt_hash=self.salt_hash,
            parent_sequence_hash=parent,
            block_index=nb,
            tokens=list(toks[nb * block_size :]),
        )

    # -- mutation ----------------------------------------------------------

    def append(self, token: Token) -> Optional[TokenBlock]:
        committed = self.partial.push_token(token)
        if committed is not None:
            self.blocks.append(committed)
            self.partial = PartialTokenBlock(
                block_size=self.block_size,
                salt_hash=self.salt_hash,
                parent_sequence_hash=committed.sequence_hash,
                block_index=committed.block_index + 1,
            )
        return committed

    def extend(self, tokens: Iterable[Token]) -> list[TokenBlock]:
        out = []
        for t in tokens:
            b = self.append(t)
            if b is not None:
                out.append(b)
        return out

    def truncate(self, num_tokens: int) -> None:
        """Keep only the first `num_tokens` tokens.

        Full blocks before the cut are immutable and keep their hashes; only
        the new partial tail is rebuilt — O(block_size), not O(n).
        """
        if num_tokens > len(self):
            raise ValueError(f"cannot truncate to {num_tokens}, have {len(self)}")
        keep_blocks = num_tokens // self.block_size
        tail = self.tokens[keep_blocks * self.block_size : num_tokens]
        self.blocks = self.blocks[:keep_blocks]
        parent = self.blocks[-1].sequence_hash if self.blocks else None
        self.partial = PartialTokenBlock(
            block_size=self.block_size,
            salt_hash=self.salt_hash,
            parent_sequence_hash=parent,
            block_index=keep_blocks,
            tokens=list(tail),
        )

    # -- views -------------------------------------------------------------

    @property
    def tokens(self) -> list[Token]:
        out: list[Token] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.partial.tokens)
        return out

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self.partial.tokens)

    def sequence_hashes(self) -> list[SequenceHash]:
        """The chained hash per full block — the routing/caching identity."""
        return [b.sequence_hash for b in self.blocks]

    def block_hashes(self) -> list[int]:
        return [b.block_hash for b in self.blocks]


def hash_token_blocks(
    tokens: Sequence[Token],
    block_size: int = DEFAULT_BLOCK_SIZE,
    salt: str = "",
) -> list[SequenceHash]:
    """One-shot helper: sequence hashes of all *full* blocks of `tokens`."""
    seq = TokenBlockSequence(tokens, block_size=block_size, salt=salt)
    return seq.sequence_hashes()

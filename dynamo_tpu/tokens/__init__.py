from dynamo_tpu.tokens.blocks import (
    BLOCK_HASH_SEED,
    DEFAULT_BLOCK_SIZE,
    PartialTokenBlock,
    SaltHash,
    SequenceHash,
    TokenBlock,
    TokenBlockSequence,
    compute_block_hash,
    compute_seq_hash,
    hash_token_blocks,
)

__all__ = [
    "BLOCK_HASH_SEED",
    "DEFAULT_BLOCK_SIZE",
    "PartialTokenBlock",
    "SaltHash",
    "SequenceHash",
    "TokenBlock",
    "TokenBlockSequence",
    "compute_block_hash",
    "compute_seq_hash",
    "hash_token_blocks",
]

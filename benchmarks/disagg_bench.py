"""Agg vs disagg A/B: boot both topologies, drive identical traffic,
compare TTFT/ITL/throughput.

The reference's headline disagg claim (+30% throughput/GPU single node,
2x two nodes — architecture.md:75) comes from exactly this A/B: same
model, same traffic, aggregated vs disaggregated prefill/decode. This
harness launches the real serving stack via the CLI for each topology:

  agg:    fabric + 1 decode worker                + frontend
  disagg: fabric + 1 decode worker (remote prefill) + N prefill + frontend

and drives a long-ISL streaming workload over HTTP (benchmarks/perf.py's
bench_http), emitting one JSON document with both sweeps and the ratios.

CPU smoke: --model tiny --isl 24 --max-context 64. TPU: the decode and
prefill engines need their own chips (or timeshare one chip — expect
contention; the honest single-host run is dp mesh halves or two hosts).

Usage: python -m benchmarks.disagg_bench --model llama3-8b --isl 3000 ...
"""

from __future__ import annotations

import argparse
import asyncio
import json

from benchmarks._procs import ManagedProc as Proc
from benchmarks._procs import cli as _cli
from benchmarks._procs import free_port as _free_port


def run_topology(args, disagg: bool) -> dict:
    fport, hport = _free_port(), _free_port()
    engine = [
        "--model", args.model, "--page-size", str(args.page_size),
        "--num-pages", str(args.num_pages), "--dtype", args.dtype,
        "--max-context", str(args.max_context),
    ]
    if args.quantize:
        engine += ["--quantize", args.quantize]
    if args.decode_steps is not None:
        engine += ["--decode-steps", str(args.decode_steps)]
    procs = []
    try:
        fb = Proc("fabric", _cli("fabric", "--port", str(fport)))
        procs.append(fb)
        fb.wait_for("listening|fabric server on")
        decode_flags = list(engine)
        if disagg:
            decode_flags += [
                "--disagg", "--max-local-prefill", str(args.max_local_prefill),
            ]
        d = Proc(
            "decode",
            _cli("run", "in=dyn", "out=jax", *decode_flags,
                 "--fabric", f"127.0.0.1:{fport}"),
        )
        procs.append(d)
        # two-stage wait: "booting" appears pre-engine-construction, so a
        # wedged device tunnel fails in 180s instead of burning the full
        # engine-bringup budget; compiles after that get the long wait.
        d.wait_for(r"worker booting", timeout=180)
        d.wait_for(r"worker \w+ up", timeout=900)
        if disagg:
            for i in range(args.prefill_workers):
                p = Proc(
                    f"prefill{i}",
                    _cli("run", "in=dyn", "out=jax", *engine,
                         "--role", "prefill",
                         "--fabric", f"127.0.0.1:{fport}"),
                )
                procs.append(p)
                p.wait_for(r"worker booting", timeout=180)
                p.wait_for(r"prefill worker \w+ up", timeout=900)
        fe = Proc(
            "frontend",
            _cli("run", "in=http", "out=dyn",
                 "--fabric", f"127.0.0.1:{fport}", "--port", str(hport)),
        )
        procs.append(fe)
        fe.wait_for("listening on")
        fe.wait_for("model attached", timeout=120)

        from benchmarks.perf import bench_http
        from benchmarks.synthesizer import SynthConfig, synthesize

        reqs = synthesize(
            SynthConfig(
                num_requests=args.requests, depth=0,
                mean_suffix_len=args.isl, mean_output_len=args.osl, seed=3,
            )
        )
        # byte tokenizer serving: ship text whose TOKEN length ~= isl
        # (ascii chars map 1:1); clamp under the context budget
        limit = max(4, args.max_context - args.osl - 20)
        texts = [
            ("".join(chr(97 + (t % 26)) for t in r.prompt_tokens)[:limit],
             args.osl)
            for r in reqs
        ]
        from benchmarks.perf import warmup_and_flush

        warmup_and_flush(
            f"http://127.0.0.1:{hport}", args.model, texts, args.warmup,
            args.concurrency, request_timeout_s=args.request_timeout,
        )

        out = asyncio.run(
            bench_http(
                f"http://127.0.0.1:{hport}", args.model, texts,
                args.concurrency,
                request_timeout_s=args.request_timeout,
            )
        )
        out["topology"] = "disagg" if disagg else "agg"
        return out
    except BaseException:
        import sys

        for p in procs:
            rc = p.proc.poll()
            print(
                f"--- {p.name}: {'alive' if rc is None else f'EXITED {rc}'}"
                f" ({p.log_path})", file=sys.stderr,
            )
            try:
                with open(p.log_path) as f:
                    print("\n".join(f.read().splitlines()[-30:]),
                          file=sys.stderr)
            except OSError:
                pass
        raise
    finally:
        for p in reversed(procs):
            p.stop()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="agg vs disagg A/B")
    p.add_argument("--model", default="tiny")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--quantize", default=None, choices=[None, "int8"])
    p.add_argument("--page-size", type=int, default=4, dest="page_size")
    p.add_argument("--num-pages", type=int, default=256, dest="num_pages")
    p.add_argument("--max-context", type=int, default=64, dest="max_context")
    p.add_argument("--max-local-prefill", type=int, default=8,
                   dest="max_local_prefill")
    p.add_argument("--prefill-workers", type=int, default=1,
                   dest="prefill_workers")
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--warmup", type=int, default=0)
    p.add_argument("--isl", type=int, default=24)
    p.add_argument("--osl", type=int, default=8)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--decode-steps", type=int, default=None,
                   dest="decode_steps",
                   help="worker decode fusion (~64 on a tunneled TPU)")
    p.add_argument("--request-timeout", type=float, default=None,
                   dest="request_timeout",
                   help="per-request total-stream bound in seconds; timed-out"
                   " requests are counted, not fatal (flaky-tunnel mode)")
    p.add_argument("--out", default=None,
                   help="also write the JSON here incrementally after each"
                   " topology, so a wedge mid-phase keeps the finished phase")
    args = p.parse_args(argv)

    def _flush(results: dict) -> None:
        if args.out:
            with open(args.out, "w") as f:
                json.dump(results, f, indent=1)

    results: dict = {}
    # provenance: the A/B's workers run on this platform (bench.py only
    # carries the artifact forward as chip evidence when it says "tpu")
    import subprocess
    import sys as _sys

    try:
        results["platform"] = subprocess.run(
            [_sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=180,
        ).stdout.strip() or "unknown"
    except (subprocess.SubprocessError, OSError):
        # provenance is best-effort: a wedged tunnel hanging the probe
        # must not kill the A/B (bench.py simply won't carry "unknown")
        results["platform"] = "unknown"
    results["agg"] = run_topology(args, disagg=False)
    _flush(results)
    results["disagg"] = run_topology(args, disagg=True)
    agg, dis = results["agg"], results["disagg"]
    if agg.get("output_tok_s") and dis.get("output_tok_s"):
        results["disagg_throughput_ratio"] = round(
            dis["output_tok_s"] / agg["output_tok_s"], 3
        )
        if agg["ttft_ms"]["p50"] and dis["ttft_ms"]["p50"]:
            results["disagg_ttft_ratio"] = round(
                agg["ttft_ms"]["p50"] / dis["ttft_ms"]["p50"], 3
            )
    _flush(results)
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()

"""Benchmark harness (reference: benchmarks/ — SURVEY.md #43).

- synthesizer: prefix-tree structured synthetic workloads
- perf: concurrency-sweep serving benchmark (tok/s, TTFT, ITL)
- profile_sla: per-worker perf tables for the SLA planner
"""

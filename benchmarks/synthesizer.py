"""Synthetic workload generator: prefix-tree structured request streams.

Real serving traffic shares prompt prefixes (system prompts, multi-turn
context, templated tasks). The reference synthesizes this with a prefix
tree (benchmarks/data_generator/synthesizer.py:34): requests are paths
root→leaf through a shared token tree plus a unique suffix. KV-routing and
prefix-cache behavior under such workloads is what the KV-aware router's
3× TTFT claim is measured on (SURVEY.md §6).

Everything is deterministic under `seed`.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class SynthConfig:
    num_requests: int = 100
    #: tokens per shared-tree node (one router block per node is natural)
    node_len: int = 64
    #: children per tree node
    branching: int = 3
    #: tree depth (max shared-prefix length = depth * node_len)
    depth: int = 3
    #: unique per-request suffix token count (mean of a geometric)
    mean_suffix_len: int = 128
    #: output tokens per request (mean of a geometric)
    mean_output_len: int = 64
    #: mean request inter-arrival seconds (poisson process); 0 = all at t=0
    mean_interarrival_s: float = 0.0
    vocab_size: int = 32000
    seed: int = 0
    #: suffix/output length law: "geometric" (mean_*_len as mean) or
    #: "sharegpt" — lognormal ISL/OSL shaped like the public ShareGPT
    #: serving-benchmarks mixture (median ~130/~160 tokens, heavy tail,
    #: clipped to [4, 2048]); mean_*_len scales the medians.
    distribution: str = "geometric"


@dataclass(frozen=True)
class SynthRequest:
    prompt_tokens: tuple[int, ...]
    output_len: int
    arrival_s: float
    #: depth of the shared-tree path this prompt rides (0 = no shared prefix)
    shared_depth: int


class PrefixTree:
    """Lazy random token tree: node (path) -> its node_len tokens."""

    def __init__(self, cfg: SynthConfig, rng: random.Random):
        self.cfg = cfg
        self.rng = rng
        self._nodes: dict[tuple[int, ...], tuple[int, ...]] = {}

    def tokens_for_path(self, path: tuple[int, ...]) -> list[int]:
        out: list[int] = []
        for i in range(len(path)):
            key = path[: i + 1]
            node = self._nodes.get(key)
            if node is None:
                node = tuple(
                    self.rng.randrange(1, self.cfg.vocab_size)
                    for _ in range(self.cfg.node_len)
                )
                self._nodes[key] = node
            out.extend(node)
        return out


def _geometric(rng: random.Random, mean: float) -> int:
    """>=1 geometric sample with the given mean."""
    if mean <= 1:
        return 1
    p = 1.0 / mean
    u = rng.random()
    return max(1, int(math.log(u) / math.log(1.0 - p)) + 1)


def _sharegpt_len(rng: random.Random, median: float, sigma: float = 1.0) -> int:
    """Lognormal token count with the ShareGPT mixture's shape: most
    requests short, a heavy conversational tail; clipped to [4, 2048]."""
    return int(min(2048, max(4, rng.lognormvariate(math.log(max(4, median)), sigma))))


def _draw_len(cfg: SynthConfig, rng: random.Random, mean: float) -> int:
    if cfg.distribution == "sharegpt":
        return _sharegpt_len(rng, mean)
    return _geometric(rng, mean)


def synthesize(cfg: SynthConfig) -> list[SynthRequest]:
    rng = random.Random(cfg.seed)
    tree = PrefixTree(cfg, rng)
    out: list[SynthRequest] = []
    t = 0.0
    for _ in range(cfg.num_requests):
        depth = rng.randint(0, cfg.depth)
        path = tuple(rng.randrange(cfg.branching) for _ in range(depth))
        prompt = tree.tokens_for_path(path)
        suffix_len = _draw_len(cfg, rng, cfg.mean_suffix_len)
        prompt.extend(
            rng.randrange(1, cfg.vocab_size) for _ in range(suffix_len)
        )
        if cfg.mean_interarrival_s > 0:
            t += rng.expovariate(1.0 / cfg.mean_interarrival_s)
        out.append(
            SynthRequest(
                prompt_tokens=tuple(prompt),
                output_len=_draw_len(cfg, rng, cfg.mean_output_len),
                arrival_s=t,
                shared_depth=depth,
            )
        )
    return out


def sharing_stats(requests: list[SynthRequest], block_size: int = 64) -> dict:
    """How much block-level prefix sharing the workload actually contains
    (sanity signal when calibrating cache-hit benchmarks)."""
    from dynamo_tpu.tokens import hash_token_blocks

    seen: set[int] = set()
    total_blocks = 0
    shared_blocks = 0
    for r in requests:
        hashes = hash_token_blocks(list(r.prompt_tokens), block_size=block_size)
        total_blocks += len(hashes)
        for h in hashes:
            if h in seen:
                shared_blocks += 1
            else:
                seen.add(h)
    return {
        "total_blocks": total_blocks,
        "reused_blocks": shared_blocks,
        "reuse_fraction": shared_blocks / total_blocks if total_blocks else 0.0,
    }

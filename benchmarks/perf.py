"""Concurrency-sweep serving benchmark.

The reference's genai-perf harness shape (benchmarks/llm/perf.sh: ISL 3000 /
OSL 150, concurrency sweep 1-256, streaming) pointed at either:
- the in-process engine (`--mode engine`, default — what the driver's
  bench.py wraps), or
- a live OpenAI frontend (`--mode http --url http://host:port`), measuring
  the full network path.

Per concurrency level: output tok/s, request/s, TTFT p50/p95, ITL p50/p95.
Prints one JSON document; `--csv` emits a sweep table.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import time
from dataclasses import dataclass
from typing import Optional


@dataclass
class RequestResult:
    ttft_s: Optional[float]
    latency_s: float
    output_tokens: int
    itls_s: list[float]


def _percentiles(values, ps=(50, 95)):
    from benchmarks._procs import pct

    return {f"p{p}": pct(values, p / 100) for p in ps}


def summarize(results: list[RequestResult], wall_s: float) -> dict:
    ttfts = [r.ttft_s for r in results if r.ttft_s is not None]
    itls = [v for r in results for v in r.itls_s]
    out_tokens = sum(r.output_tokens for r in results)
    return {
        "requests": len(results),
        "wall_s": round(wall_s, 3),
        "output_tok_s": round(out_tokens / wall_s, 2) if wall_s else 0.0,
        "req_s": round(len(results) / wall_s, 3) if wall_s else 0.0,
        "ttft_ms": {
            k: round(v * 1e3, 2) if v is not None else None
            for k, v in _percentiles(ttfts).items()
        },
        "itl_ms": {
            k: round(v * 1e3, 3) if v is not None else None
            for k, v in _percentiles(itls).items()
        },
    }


# -- engine mode ------------------------------------------------------------


def tpu_bf16_peak_flops() -> Optional[float]:
    """Per-chip bf16 peak for the attached TPU generation (public specs);
    None when not on TPU or the generation is unrecognized."""
    import jax

    if jax.default_backend() != "tpu":
        return None
    # normalize "TPU v5 lite" -> "tpuv5lite" so spaced kinds match
    kind = jax.devices()[0].device_kind.lower().replace(" ", "")
    for tag, peak in (
        ("v6e", 918e12), ("v6", 918e12), ("v5p", 459e12),
        ("v5e", 197e12), ("v5lite", 197e12), ("v4", 275e12),
    ):
        if tag in kind:
            return peak
    return None


def engine_mfu(engine, prompt_tokens: int, output_tokens: int, wall_s: float) -> Optional[float]:
    """Approximate model-FLOPs utilization: ~2*params FLOPs per token
    (prefill and decode both; attention is second-order at these lengths)
    against the chip's bf16 peak. None off-TPU or unknown generation."""
    import jax

    peak = tpu_bf16_peak_flops()
    if peak is None:
        return None
    n_params = sum(int(x.size) for x in jax.tree.leaves(engine.params))
    return (2.0 * n_params * (prompt_tokens + output_tokens) / wall_s) / peak


def bench_engine(
    engine, prompts: list[tuple[list[int], int]], concurrency: int
) -> dict:
    """Closed-loop: keep `concurrency` requests in flight inside the
    engine's step loop; measure per-request TTFT/ITL from step timestamps."""
    from dynamo_tpu.engine.request import SamplingParams

    pending = list(enumerate(prompts))
    timing0 = {
        k: getattr(engine.metrics, k)
        for k in type(engine.metrics).TIMING_FIELDS
    }
    starts: dict[str, float] = {}
    first: dict[str, float] = {}
    last: dict[str, float] = {}
    itls: dict[str, list[float]] = {}
    counts: dict[str, int] = {}
    done: list[str] = []

    def submit_next() -> bool:
        if not pending:
            return False
        i, (toks, osl) = pending.pop(0)
        rid = f"r{i}"
        engine.add_request(
            rid, toks, SamplingParams(max_tokens=osl, ignore_eos=True)
        )
        starts[rid] = time.perf_counter()
        itls[rid] = []
        counts[rid] = 0
        return True

    for _ in range(concurrency):
        submit_next()
    t0 = time.perf_counter()
    while engine.has_work:
        outs = engine.step()
        now = time.perf_counter()
        for o in outs:
            rid = o.request_id
            if o.new_token_ids:
                n = len(o.new_token_ids)
                counts[rid] += n
                if rid not in first:
                    first[rid] = now  # whole first output counts as TTFT
                if rid in last and n > 0:
                    # Fused multi-step decode and speculative acceptance
                    # emit several tokens per step: spread the step interval
                    # so ITL stays per-token, not per-dispatch.
                    gap = (now - last[rid]) / n
                    itls[rid].extend([gap] * n)
                last[rid] = now
            if o.finish_reason is not None:
                done.append(rid)
                submit_next()
    wall = time.perf_counter() - t0
    results = [
        RequestResult(
            ttft_s=(first[rid] - starts[rid]) if rid in first else None,
            latency_s=(last.get(rid, starts[rid]) - starts[rid]),
            output_tokens=counts[rid],
            itls_s=itls[rid],
        )
        for rid in done
    ]
    out = summarize(results, wall)
    mfu = engine_mfu(
        engine,
        prompt_tokens=sum(len(p) for p, _ in prompts[: len(done)]),
        output_tokens=sum(counts[rid] for rid in done),
        wall_s=wall,
    )
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
    # the engine's step-phase timing plane, as a DELTA over this call —
    # per-level numbers that exclude warmup/compile from earlier calls
    m = engine.metrics
    out["engine_timing"] = {
        k: round(getattr(m, k) - timing0[k], 1) for k in timing0
    }
    return out


# -- http mode --------------------------------------------------------------


async def _one_http(session, url: str, model: str, prompt_text: str, osl: int):
    payload = {
        "model": model,
        "messages": [{"role": "user", "content": prompt_text}],
        "stream": True,
        "max_tokens": osl,
    }
    t0 = time.perf_counter()
    ttft = None
    prev = None
    itls: list[float] = []
    n = 0
    async with session.post(url + "/v1/chat/completions", json=payload) as resp:
        resp.raise_for_status()
        async for raw in resp.content:
            line = raw.decode().strip()
            if not line.startswith("data:") or line == "data: [DONE]":
                continue
            now = time.perf_counter()
            n += 1
            if ttft is None:
                ttft = now - t0
            else:
                itls.append(now - prev)
            prev = now
    return RequestResult(
        ttft_s=ttft, latency_s=time.perf_counter() - t0, output_tokens=n,
        itls_s=itls,
    )


async def bench_http(
    url: str, model: str, prompts: list[tuple[str, int]], concurrency: int,
    request_timeout_s: float | None = None,
) -> dict:
    """`request_timeout_s` bounds each request's total stream time; timed-out
    or errored requests are counted (summary key `failed`) instead of killing
    the whole run — on a flaky device tunnel the surviving requests still
    yield an honest partial measurement."""
    import aiohttp

    queue: asyncio.Queue = asyncio.Queue()
    for p in prompts:
        queue.put_nowait(p)
    results: list[RequestResult] = []
    failures = 0
    # None keeps aiohttp's default (total=300 s); ClientTimeout(total=None)
    # would instead disable the bound and let a wedged stream hang forever
    kw = (
        {"timeout": aiohttp.ClientTimeout(total=request_timeout_s)}
        if request_timeout_s is not None else {}
    )

    async with aiohttp.ClientSession(**kw) as session:

        async def worker():
            nonlocal failures
            while True:
                try:
                    text, osl = queue.get_nowait()
                except asyncio.QueueEmpty:
                    return
                try:
                    results.append(
                        await _one_http(session, url, model, text, osl)
                    )
                except (asyncio.TimeoutError, aiohttp.ClientError):
                    failures += 1

        t0 = time.perf_counter()
        await asyncio.gather(*(worker() for _ in range(concurrency)))
        wall = time.perf_counter() - t0
    out = summarize(results, wall)
    if failures:
        out["failed"] = failures
    return out


def warmup_and_flush(
    url: str, model: str, texts: list[tuple[str, int]], warmup: int,
    concurrency: int, request_timeout_s: float | None = None,
) -> None:
    """Compile-then-flush prelude for HTTP A/B harnesses: drive `warmup`
    uncached random prompts whose lengths span the timed sweep's length
    spread (prefill shapes are bucketed — warming one length leaves other
    buckets to cold-compile inside the timed window), then POST
    /clear_kv_blocks so the timed run starts cold on prefixes but warm on
    XLA. Random prompts share no prefix, so a kv router balances them by
    load across ALL workers."""
    if not warmup:
        return
    import random
    import urllib.request

    r = random.Random(13)
    lens = sorted({len(t) for t, _ in texts})
    picks = [
        lens[i * (len(lens) - 1) // max(1, warmup - 1)]
        for i in range(warmup)
    ]
    osl = texts[0][1]
    warm = [
        ("".join(chr(97 + r.randrange(26)) for _ in range(n)), osl)
        for n in picks
    ]
    asyncio.run(
        bench_http(url, model, warm, concurrency,
                   request_timeout_s=request_timeout_s)
    )
    req = urllib.request.Request(
        f"{url}/clear_kv_blocks", data=b"{}",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200


# -- CLI --------------------------------------------------------------------


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="concurrency-sweep benchmark")
    p.add_argument("--mode", choices=["engine", "http"], default="engine")
    p.add_argument("--url", default="http://127.0.0.1:8080")
    p.add_argument("--model", default="llama3-1b")
    p.add_argument("--num-requests", type=int, default=32, dest="num_requests")
    p.add_argument("--isl", type=int, default=128)
    p.add_argument("--osl", type=int, default=64)
    p.add_argument(
        "--concurrency", default="1,4,16",
        help="comma-separated sweep levels",
    )
    p.add_argument("--num-pages", type=int, default=2048, dest="num_pages")
    p.add_argument("--page-size", type=int, default=64, dest="page_size")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument(
        "--spec-ngram", type=int, default=0, dest="spec_ngram",
        help="engine mode: speculative decoding draft length (0 = off)",
    )
    p.add_argument(
        "--spec-draft", default=None, dest="spec_draft",
        help="engine mode: draft-model speculation (same-vocab small "
        "model, e.g. llama3-draft; composes with overlap + mixed steps)",
    )
    p.add_argument(
        "--spec-draft-tokens", type=int, default=4,
        dest="spec_draft_tokens",
        help="engine mode: drafts proposed per spec step (with "
        "--spec-draft)",
    )
    p.add_argument(
        "--quantize", default=None, choices=["int8"],
        help="engine mode: weight-only quantization",
    )
    p.add_argument(
        "--prefill-budget", type=int, default=None, dest="prefill_budget",
        help="engine mode: prefill tokens per step across sequences "
        "(EngineConfig.prefill_token_budget; default 4x prefill_chunk). "
        "The saturation-TTFT knob: a bigger budget batches more prompts "
        "into one prefill dispatch, draining an arrival burst in fewer, "
        "larger steps at the cost of longer decode stalls while it runs.",
    )
    p.add_argument(
        "--prefill-policy", default="fixed", dest="prefill_policy",
        choices=["fixed", "adaptive"],
        help="engine mode: adaptive grows the step budget with the "
        "un-prefilled backlog (to 4x the budget), draining saturation "
        "bursts in O(1) dispatches without raising the idle-time budget",
    )
    p.add_argument(
        "--prefill-budget-max", type=int, default=None,
        dest="prefill_budget_max",
        help="engine mode: adaptive-policy ceiling (default 4x the "
        "budget); bounds the worst-case single prefill dispatch and so "
        "the ITL spike it can inflict",
    )
    p.add_argument(
        "--prefill-chunk", type=int, default=None, dest="prefill_chunk",
        help="engine mode: per-sequence prefill chunk length",
    )
    p.add_argument(
        "--decode-steps", type=int, default=None, dest="decode_steps",
        help="engine mode: decode steps fused per dispatch (one host sync "
        "per K tokens/seq; ~64 on a remote/tunneled TPU where the sync "
        "RTT dominates a step). Default: engine default (8)",
    )
    p.add_argument(
        "--distribution", default="geometric",
        choices=["geometric", "sharegpt"],
        help="ISL/OSL law; sharegpt = lognormal heavy-tail mixture",
    )
    p.add_argument("--csv", action="store_true")
    args = p.parse_args(argv)

    from dynamo_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from benchmarks.synthesizer import SynthConfig, synthesize

    reqs = synthesize(
        SynthConfig(
            num_requests=args.num_requests,
            depth=0,
            mean_suffix_len=args.isl,
            mean_output_len=args.osl,
            distribution=args.distribution,
        )
    )
    levels = [int(x) for x in args.concurrency.split(",")]
    sweep = []
    if args.mode == "engine":
        from dynamo_tpu.engine import EngineConfig
        from dynamo_tpu.engine.engine import JaxEngine

        prompts = [(list(r.prompt_tokens), r.output_len) for r in reqs]
        # Budget pages for the ACTUAL longest sequence — the geometric
        # suffix has a heavy tail and a mean-sized budget trips the
        # scheduler's max-context guard mid-run.
        longest = max(len(p) + osl for p, osl in prompts)
        engine = JaxEngine(
            EngineConfig(
                model=args.model,
                num_pages=args.num_pages,
                page_size=args.page_size,
                max_pages_per_seq=max(8, -(-(longest + 1) // args.page_size)),
                dtype=args.dtype,
                enable_prefix_caching=False,
                spec_ngram=args.spec_ngram,
                spec_draft_model=args.spec_draft,
                spec_draft_tokens=args.spec_draft_tokens,
                quantize=args.quantize,
                prefill_token_budget=args.prefill_budget,
                prefill_budget_policy=args.prefill_policy,
                prefill_budget_max=args.prefill_budget_max,
                **(
                    {"prefill_chunk": args.prefill_chunk}
                    if args.prefill_chunk is not None
                    else {}
                ),
                **(
                    {"decode_steps": args.decode_steps}
                    if args.decode_steps is not None
                    else {}
                ),
            )
        )
        # warmup compiles every program shape the sweep will touch
        bench_engine(engine, prompts[: max(levels)], max(levels))
        for c in levels:
            sweep.append({"concurrency": c, **bench_engine(engine, prompts, c)})
    else:
        texts = [
            (" ".join(str(t) for t in r.prompt_tokens[: args.isl // 4]),
             r.output_len)
            for r in reqs
        ]
        for c in levels:
            sweep.append(
                {
                    "concurrency": c,
                    **asyncio.run(bench_http(args.url, args.model, texts, c)),
                }
            )

    if args.csv:
        cols = ["concurrency", "output_tok_s", "req_s"]
        print(",".join(cols + ["ttft_p50_ms", "itl_p50_ms"]))
        for row in sweep:
            print(
                ",".join(
                    str(x)
                    for x in (
                        row["concurrency"], row["output_tok_s"], row["req_s"],
                        row["ttft_ms"]["p50"], row["itl_ms"]["p50"],
                    )
                )
            )
    else:
        print(json.dumps({"mode": args.mode, "sweep": sweep}, indent=2))


if __name__ == "__main__":
    main()

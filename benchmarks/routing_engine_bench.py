"""KV-aware routing vs round-robin against REAL engines over HTTP.

routing_bench.py measures the routing win at fleet scale on mock workers;
this harness is the hardware complement (VERDICT r2: "routing_bench
against real engines"): N real JaxEngine workers behind the real frontend
in each router mode, driven with the same prefix-tree workload over
/v1/chat/completions. The win comes from the same mechanism the reference
claims 3x TTFT for (architecture.md:91): routing a request to the worker
whose paged cache already holds its prefix skips recomputing it.

On one TPU chip the N worker processes timeshare the device — identical
contention in both modes, so the A/B stays fair; absolute numbers are
lower than a one-process-per-chip fleet.

CPU smoke:  python -m benchmarks.routing_engine_bench
TPU:        python -m benchmarks.routing_engine_bench --model llama3-1b \
                --dtype bfloat16 --page 16 --pages 512 --max-context 2048 \
                --depth 4 --suffix 64 --requests 64 --concurrency 8
"""

from __future__ import annotations

import argparse
import asyncio
import json

from benchmarks._procs import ManagedProc as Proc
from benchmarks._procs import cli as _cli
from benchmarks._procs import free_port as _free_port


def _texts(args) -> tuple[list[tuple[str, int]], float]:
    from benchmarks.synthesizer import SynthConfig, sharing_stats, synthesize

    reqs = synthesize(
        SynthConfig(
            num_requests=args.requests,
            node_len=args.page,
            branching=args.branching,
            depth=args.depth,
            mean_suffix_len=args.suffix,
            mean_output_len=args.osl,
            seed=7,
        )
    )
    share = sharing_stats(reqs, block_size=args.page)
    limit = max(4, args.max_context - args.osl - 20)
    # byte tokenizer: one ascii char per token, so shared token prefixes
    # become shared TEXT prefixes and survive the chat template verbatim
    texts = [
        ("".join(chr(97 + (t % 26)) for t in r.prompt_tokens)[:limit],
         args.osl)
        for r in reqs
    ]
    return texts, share["reuse_fraction"]


def run_mode(args, mode: str, texts) -> dict:
    fport, hport = _free_port(), _free_port()
    engine = [
        "--model", args.model, "--dtype", args.dtype,
        "--page-size", str(args.page), "--num-pages", str(args.pages),
        "--max-context", str(args.max_context),
        "--router-mode", mode,
    ]
    if args.decode_steps is not None:
        engine += ["--decode-steps", str(args.decode_steps)]
    procs: list[Proc] = []
    try:
        fb = Proc("fabric", _cli("fabric", "--port", str(fport)))
        procs.append(fb)
        fb.wait_for("listening|fabric server on")
        for i in range(args.workers):
            w = Proc(
                f"worker{i}",
                _cli("run", "in=dyn", "out=jax", *engine,
                     "--fabric", f"127.0.0.1:{fport}"),
            )
            procs.append(w)
            w.wait_for(r"worker \w+ up", timeout=900)
        fe = Proc(
            "frontend",
            _cli("run", "in=http", "out=dyn",
                 "--fabric", f"127.0.0.1:{fport}", "--port", str(hport)),
        )
        procs.append(fe)
        fe.wait_for("listening on")
        fe.wait_for("model attached", timeout=120)

        from benchmarks.perf import bench_http, warmup_and_flush

        warmup_and_flush(
            f"http://127.0.0.1:{hport}", args.model, texts, args.warmup,
            args.concurrency,
        )

        out = asyncio.run(
            bench_http(
                f"http://127.0.0.1:{hport}", args.model, texts,
                args.concurrency,
            )
        )
        out["mode"] = mode
        return out
    except BaseException:
        import sys

        for p in procs:
            rc = p.proc.poll()
            print(f"--- {p.name}: {'alive' if rc is None else rc} "
                  f"({p.log_path})", file=sys.stderr)
            try:
                with open(p.log_path) as f:
                    print("\n".join(f.read().splitlines()[-20:]),
                          file=sys.stderr)
            except OSError:
                pass
        raise
    finally:
        for p in reversed(procs):
            p.stop()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="real-engine routing A/B")
    p.add_argument("--model", default="tiny")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--page", type=int, default=4)
    p.add_argument("--pages", type=int, default=128)
    p.add_argument("--max-context", type=int, default=96, dest="max_context")
    p.add_argument("--depth", type=int, default=3)
    p.add_argument("--branching", type=int, default=4)
    p.add_argument("--suffix", type=int, default=8)
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--warmup", type=int, default=8)
    p.add_argument("--osl", type=int, default=8)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--decode-steps", type=int, default=None,
                   dest="decode_steps",
                   help="worker decode fusion (~64 on a tunneled TPU)")
    args = p.parse_args(argv)

    texts, reuse = _texts(args)
    results = {
        "workload": {
            "requests": args.requests, "workers": args.workers,
            "block_reuse_fraction": round(reuse, 3),
            "model": args.model,
        },
        "modes": {},
    }
    # round_robin first: neither mode inherits a warm cache from the other
    # (each mode boots a fresh fleet), so order only affects XLA's on-disk
    # compile cache, which warms identically for both.
    for mode in ("round_robin", "kv"):
        results["modes"][mode] = run_mode(args, mode, texts)
    rr, kv = results["modes"]["round_robin"], results["modes"]["kv"]
    if rr.get("ttft_ms") and kv.get("ttft_ms"):
        results["kv_ttft_speedup_p50"] = round(
            rr["ttft_ms"]["p50"] / max(kv["ttft_ms"]["p50"], 1e-9), 2
        )
        results["kv_ttft_speedup_p95"] = round(
            rr["ttft_ms"]["p95"] / max(kv["ttft_ms"]["p95"], 1e-9), 2
        )
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()

"""SLA profiler: build the per-worker perf tables the SLA planner consumes.

Reference parity: benchmarks/profiler/profile_sla.py sweeps parallel
configs and interpolates TTFT/ITL against load to pre-compute planner
tables (docs sla_planner.md). Here: sweep closed-loop concurrency against
ONE engine worker, record (achieved req/s -> TTFT ms, ITL ms), and emit
exactly the JSON `dynamo-tpu planner --mode sla --perf-table` loads:

    {"ttft_vs_rate": [[req_s, ttft_p50_ms], ...],
     "itl_vs_rate":  [[req_s, itl_p50_ms], ...],
     "meta": {...}}
"""

from __future__ import annotations

import argparse
import json


def profile(
    model: str = "tiny",
    num_requests: int = 32,
    isl: int = 64,
    osl: int = 32,
    concurrency_levels=(1, 2, 4, 8),
    engine_config=None,
) -> dict:
    from benchmarks.perf import bench_engine
    from benchmarks.synthesizer import SynthConfig, synthesize
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine

    reqs = synthesize(
        SynthConfig(
            num_requests=num_requests, depth=0,
            mean_suffix_len=isl, mean_output_len=osl,
        )
    )
    prompts = [(list(r.prompt_tokens), r.output_len) for r in reqs]
    # Budget pages for the actual longest sequence (geometric tail).
    longest = max(len(p) + o for p, o in prompts)
    cfg = engine_config or EngineConfig(
        model=model,
        num_pages=2048,
        page_size=64,
        max_pages_per_seq=max(8, -(-(longest + 1) // 64)),
        dtype="bfloat16",
        enable_prefix_caching=False,
    )
    # A caller-supplied config has a fixed context budget: clamp prompts to
    # it (the synthesizer's geometric tail would trip the admission guard).
    prompts = [
        (p[: max(1, cfg.max_context - o - 1)], o) for p, o in prompts
    ]
    engine = JaxEngine(cfg)
    # compile every shape before the timed sweeps
    bench_engine(engine, prompts[: max(concurrency_levels)],
                 max(concurrency_levels))

    ttft_rows, itl_rows, sweep = [], [], []
    for c in concurrency_levels:
        s = bench_engine(engine, prompts, c)
        sweep.append({"concurrency": c, **s})
        if s["req_s"] and s["ttft_ms"]["p50"] is not None:
            ttft_rows.append([s["req_s"], s["ttft_ms"]["p50"]])
        if s["req_s"] and s["itl_ms"]["p50"] is not None:
            itl_rows.append([s["req_s"], s["itl_ms"]["p50"]])
    return {
        "ttft_vs_rate": sorted(ttft_rows),
        "itl_vs_rate": sorted(itl_rows),
        "meta": {
            "model": model, "isl": isl, "osl": osl,
            "concurrency_levels": list(concurrency_levels),
            "sweep": sweep,
        },
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="profile one worker for the SLA planner")
    p.add_argument("--model", default="llama3-1b")
    p.add_argument("--num-requests", type=int, default=32, dest="num_requests")
    p.add_argument("--isl", type=int, default=128)
    p.add_argument("--osl", type=int, default=64)
    p.add_argument("--concurrency", default="1,2,4,8,16")
    p.add_argument("-o", "--output", default=None, help="write JSON here")
    args = p.parse_args(argv)

    from dynamo_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    table = profile(
        model=args.model,
        num_requests=args.num_requests,
        isl=args.isl,
        osl=args.osl,
        concurrency_levels=[int(x) for x in args.concurrency.split(",")],
    )
    text = json.dumps(table, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
        print(f"wrote {args.output}")
    else:
        print(text)


if __name__ == "__main__":
    main()

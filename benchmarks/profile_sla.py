"""SLA profiler: build the per-worker perf tables the SLA planner consumes.

Reference parity: benchmarks/profiler/profile_sla.py sweeps PARALLEL
CONFIGS (TP) and picks the one meeting the TTFT/ITL targets
(profile_sla.py:81-84), interpolating metric-vs-load to pre-compute
planner tables (docs sla_planner.md). Here:

- `profile(...)` sweeps closed-loop concurrency against ONE engine config,
  recording (achieved req/s -> TTFT ms, ITL ms);
- `sweep_parallel_configs(...)` runs that per (tp, dp) mesh config and
  SELECTS the config with the highest SLA-feasible rate PER CHIP — the
  quantity that decides deployment cost.

Emits the JSON `dynamo-tpu planner --mode sla --perf-table` loads: the
top-level `ttft_vs_rate`/`itl_vs_rate` are the SELECTED config's rows
(back-compatible), with every swept config under `configs` so the planner
can re-select against ITS OWN targets at load time:

    {"ttft_vs_rate": [[req_s, ttft_p50_ms], ...],
     "itl_vs_rate":  [[req_s, itl_p50_ms], ...],
     "selected": {"tp": T, "dp": D},
     "sla": {"ttft_ms": ..., "itl_ms": ...},
     "configs": [{"tp": ..., "dp": ..., "ttft_vs_rate": ...,
                  "itl_vs_rate": ..., "sla_rate": ...,
                  "sla_rate_per_chip": ...}, ...],
     "meta": {...}}
"""

from __future__ import annotations

import argparse
import json


# selection policy shared with the planner's load-time re-selection
from dynamo_tpu.planner.perf_model import (  # noqa: E402
    select_parallel_config,
    sla_feasible_rate,
)


def sweep_parallel_configs(
    parallel: list[tuple[int, int]],
    ttft_target_ms: float = 200.0,
    itl_target_ms: float = 20.0,
    model: str = "tiny",
    num_requests: int = 32,
    isl: int = 64,
    osl: int = 32,
    concurrency_levels=(1, 2, 4, 8),
    base_engine_config=None,
    quantize: str | None = None,
    num_pages: int = 2048,
    page_size: int = 64,
) -> dict:
    """Profile each (tp, dp) config and select the SLA-best per chip.

    Reference: profiler sweeps TP and picks the config meeting TTFT/ITL
    (profile_sla.py:81-84); per-chip normalization is what makes a tp=4
    config that's 1.5x faster still LOSE to tp=1 on cost."""
    from dataclasses import replace

    from dynamo_tpu.engine import EngineConfig

    configs = []
    for tp, dp in parallel:
        if base_engine_config is not None:
            # a supplied config owns its page geometry, but an explicit
            # quantize request must not be silently dropped — profiling
            # bf16 when the caller asked for int8 would poison the
            # planner's tables
            cfg = replace(base_engine_config, tp=tp, dp=dp)
            if quantize is not None:
                cfg = replace(cfg, quantize=quantize)
        else:
            cfg = None
        t = profile(
            model=model, num_requests=num_requests, isl=isl, osl=osl,
            concurrency_levels=concurrency_levels, engine_config=cfg,
            tp=tp, dp=dp, quantize=quantize,
            num_pages=num_pages, page_size=page_size,
        )
        rate = sla_feasible_rate(t, ttft_target_ms, itl_target_ms)
        configs.append(
            {
                "tp": tp, "dp": dp,
                "ttft_vs_rate": t["ttft_vs_rate"],
                "itl_vs_rate": t["itl_vs_rate"],
                "sla_rate": round(rate, 4),
                "sla_rate_per_chip": round(rate / (tp * dp), 4),
                "meta": t["meta"],
            }
        )
    best = select_parallel_config(configs, ttft_target_ms, itl_target_ms)
    feasible = [c for c in configs if c["sla_rate"] > 0]
    return {
        "ttft_vs_rate": best["ttft_vs_rate"],
        "itl_vs_rate": best["itl_vs_rate"],
        "selected": {"tp": best["tp"], "dp": best["dp"]},
        "sla": {"ttft_ms": ttft_target_ms, "itl_ms": itl_target_ms},
        "configs": configs,
        "meta": {
            "model": model, "isl": isl, "osl": osl,
            "sla_feasible": bool(feasible),
        },
    }


def profile(
    model: str = "tiny",
    num_requests: int = 32,
    isl: int = 64,
    osl: int = 32,
    concurrency_levels=(1, 2, 4, 8),
    engine_config=None,
    tp: int = 1,
    dp: int = 1,
    quantize: str | None = None,
    num_pages: int = 2048,
    page_size: int = 64,
    decode_steps: int | None = None,
) -> dict:
    from benchmarks.perf import bench_engine
    from benchmarks.synthesizer import SynthConfig, synthesize
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine

    reqs = synthesize(
        SynthConfig(
            num_requests=num_requests, depth=0,
            mean_suffix_len=isl, mean_output_len=osl,
        )
    )
    prompts = [(list(r.prompt_tokens), r.output_len) for r in reqs]
    # Budget pages for the actual longest sequence (geometric tail).
    longest = max(len(p) + o for p, o in prompts)
    cfg = engine_config or EngineConfig(
        model=model,
        num_pages=num_pages,
        page_size=page_size,
        max_pages_per_seq=max(8, -(-(longest + 1) // page_size)),
        dtype="bfloat16",
        enable_prefix_caching=False,
        tp=tp,
        dp=dp,
        quantize=quantize,
        **({"decode_steps": decode_steps} if decode_steps is not None
           else {}),
    )
    # A caller-supplied config has a fixed context budget: clamp prompts to
    # it (the synthesizer's geometric tail would trip the admission guard).
    prompts = [
        (p[: max(1, cfg.max_context - o - 1)], o) for p, o in prompts
    ]
    engine = JaxEngine(cfg)
    # compile every shape before the timed sweeps
    bench_engine(engine, prompts[: max(concurrency_levels)],
                 max(concurrency_levels))

    ttft_rows, itl_rows, sweep = [], [], []
    for c in concurrency_levels:
        s = bench_engine(engine, prompts, c)
        sweep.append({"concurrency": c, **s})
        if s["req_s"] and s["ttft_ms"]["p50"] is not None:
            ttft_rows.append([s["req_s"], s["ttft_ms"]["p50"]])
        if s["req_s"] and s["itl_ms"]["p50"] is not None:
            itl_rows.append([s["req_s"], s["itl_ms"]["p50"]])
    return {
        "ttft_vs_rate": sorted(ttft_rows),
        "itl_vs_rate": sorted(itl_rows),
        "meta": {
            "model": model, "isl": isl, "osl": osl,
            "concurrency_levels": list(concurrency_levels),
            "sweep": sweep,
        },
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="profile one worker for the SLA planner")
    p.add_argument("--model", default="llama3-1b")
    p.add_argument("--num-requests", type=int, default=32, dest="num_requests")
    p.add_argument("--isl", type=int, default=128)
    p.add_argument("--osl", type=int, default=64)
    p.add_argument("--concurrency", default="1,2,4,8,16")
    p.add_argument(
        "--parallel", default=None,
        help='comma-separated TPxDP mesh configs to sweep, e.g. "1x1,2x1,4x1"'
             " — selects the SLA-best per chip (omit = single default config)",
    )
    p.add_argument("--quantize", default=None, choices=[None, "int8"],
                   help="weight-only quantization (8B-class models on one "
                        "16 GB chip need int8)")
    p.add_argument("--num-pages", type=int, default=2048, dest="num_pages")
    p.add_argument("--page-size", type=int, default=64, dest="page_size")
    p.add_argument("--ttft-target", type=float, default=200.0, dest="ttft_target")
    p.add_argument("--itl-target", type=float, default=20.0, dest="itl_target")
    p.add_argument("-o", "--output", default=None, help="write JSON here")
    p.add_argument(
        "--decode-steps", type=int, default=None, dest="decode_steps",
        help="decode steps fused per dispatch (~64 on a tunneled TPU)",
    )
    args = p.parse_args(argv)

    from dynamo_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    levels = [int(x) for x in args.concurrency.split(",")]
    if args.parallel:
        parallel = [
            (int(t), int(d))
            for t, d in (s.split("x") for s in args.parallel.split(","))
        ]
        table = sweep_parallel_configs(
            parallel,
            ttft_target_ms=args.ttft_target,
            itl_target_ms=args.itl_target,
            model=args.model,
            num_requests=args.num_requests,
            isl=args.isl,
            osl=args.osl,
            concurrency_levels=levels,
            quantize=args.quantize,
            num_pages=args.num_pages,
            page_size=args.page_size,
        )
    else:
        table = profile(
            model=args.model,
            num_requests=args.num_requests,
            isl=args.isl,
            osl=args.osl,
            concurrency_levels=levels,
            quantize=args.quantize,
            num_pages=args.num_pages,
            page_size=args.page_size,
            decode_steps=args.decode_steps,
        )
    text = json.dumps(table, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
        print(f"wrote {args.output}")
    else:
        print(text)


if __name__ == "__main__":
    main()

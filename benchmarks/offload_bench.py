"""KV offload A/B: multi-turn conversations with the host-DRAM tier on
vs off, same traffic, same (deliberately small) HBM page pool.

The reference's headline offload claim — TTFT +40% with system-memory KV
offload on a multi-turn workload (architecture.md:95, "10 multi-turn
convs x 80 users, prefix caching on") — comes from exactly this shape:
conversations cycle faster than the device pool can hold them, so each
turn's prefix has been evicted by the time the user returns. Without a
host tier the prefix recomputes; with one it onboards back from DRAM.

This harness boots ONE single-process HTTP server per mode (the tier is
an engine feature — no fleet needed), drives U users x T turns
round-robin (each turn appends the assistant reply and re-sends the
grown conversation, so consecutive turns share a true chat-template
prefix), and reports per-turn TTFT percentiles for turns >= 2 (turn 1 is
cold in both modes).

CPU smoke: defaults — validates MECHANICS only. On a tiny CPU model the
economics invert (recomputing a few dozen tokens costs ~nothing, while
each eviction pays a device->host extraction), so expect speedup < 1
there; the claim under test needs real prefill costs, i.e. the TPU run:
--model llama3-1b --dtype bfloat16 --page-size 16 --num-pages 192
--max-context 2048 --users 8 --turns 4 --turn-chars 400
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

from benchmarks._procs import ManagedProc as Proc
from benchmarks._procs import cli as _cli
from benchmarks._procs import free_port as _free_port
from benchmarks._procs import pct as _shared_pct


def _pct(values, q):
    v = _shared_pct(values, q)
    return None if v is None else round(v, 2)


async def _one_turn(session, url, model, messages, osl):
    """POST the conversation, stream the reply; returns (ttft_ms, text)."""
    t0 = time.perf_counter()
    ttft = None
    text = []
    async with session.post(
        f"{url}/v1/chat/completions",
        json={"model": model, "messages": messages, "stream": True,
              "max_tokens": osl},
    ) as resp:
        resp.raise_for_status()
        async for raw in resp.content:
            line = raw.decode().strip()
            if not line.startswith("data:") or line == "data: [DONE]":
                continue
            if ttft is None:
                ttft = (time.perf_counter() - t0) * 1000
            try:
                delta = json.loads(line[5:])["choices"][0]["delta"]
                if delta.get("content"):
                    text.append(delta["content"])
            except Exception:  # noqa: BLE001 — error frames end the turn
                break
    return ttft, "".join(text)


async def _drive(url, model, args) -> dict:
    import aiohttp

    import random

    r = random.Random(11)
    convs = [
        [{"role": "user",
          "content": "".join(chr(97 + r.randrange(26))
                             for _ in range(args.turn_chars))}]
        for _ in range(args.users)
    ]
    ttfts_by_turn: dict[int, list[float]] = {}
    async with aiohttp.ClientSession() as session:
        for turn in range(args.turns):
            # round-robin: every user takes their turn before anyone's
            # next turn — by the time user u returns, the other users'
            # prefills have churned the small HBM pool past u's pages
            for conv in convs:
                ttft, reply = await _one_turn(
                    session, url, model, conv, args.osl
                )
                if ttft is not None:
                    ttfts_by_turn.setdefault(turn + 1, []).append(ttft)
                conv.append({"role": "assistant", "content": reply or "."})
                conv.append({
                    "role": "user",
                    "content": "".join(chr(97 + r.randrange(26))
                                       for _ in range(args.turn_chars)),
                })
    warm = [t for turn, ts in ttfts_by_turn.items() if turn >= 2 for t in ts]
    return {
        "ttft_ms_by_turn": {
            str(k): {"p50": _pct(v, 0.5), "p95": _pct(v, 0.95)}
            for k, v in sorted(ttfts_by_turn.items())
        },
        "warm_turns_ttft_ms": {
            "p50": _pct(warm, 0.5), "p95": _pct(warm, 0.95),
            "n": len(warm),
        },
    }


def run_mode(args, host_tier: bool) -> dict:
    hport = _free_port()
    argv = _cli(
        "run", "in=http", "out=jax", "--model", args.model,
        "--dtype", args.dtype, "--page-size", str(args.page_size),
        "--num-pages", str(args.num_pages),
        "--max-context", str(args.max_context), "--port", str(hport),
    )
    if host_tier:
        argv += ["--host-kv-bytes", str(args.host_kv_bytes)]
    if args.decode_steps is not None:
        argv += ["--decode-steps", str(args.decode_steps)]
    server = Proc("server", argv)
    try:
        server.wait_for("listening on", timeout=900)
        out = asyncio.run(
            _drive(f"http://127.0.0.1:{hport}", args.model, args)
        )
        out["host_tier"] = host_tier
        return out
    except BaseException:
        import sys

        print(f"--- server log ({server.log_path}):", file=sys.stderr)
        try:
            with open(server.log_path) as f:
                print("\n".join(f.read().splitlines()[-30:]),
                      file=sys.stderr)
        except OSError:
            pass
        raise
    finally:
        server.stop()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="KV offload A/B (host tier)")
    p.add_argument("--model", default="tiny")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--page-size", type=int, default=4, dest="page_size")
    p.add_argument("--num-pages", type=int, default=48, dest="num_pages")
    p.add_argument("--max-context", type=int, default=192,
                   dest="max_context")
    p.add_argument("--host-kv-bytes", type=int, default=1 << 30,
                   dest="host_kv_bytes")
    p.add_argument("--users", type=int, default=6)
    p.add_argument("--turns", type=int, default=3)
    p.add_argument("--turn-chars", type=int, default=24, dest="turn_chars")
    p.add_argument("--osl", type=int, default=8)
    p.add_argument("--decode-steps", type=int, default=None,
                   dest="decode_steps",
                   help="worker decode fusion (~64 on a tunneled TPU)")
    args = p.parse_args(argv)

    results = {
        "workload": {
            "users": args.users, "turns": args.turns,
            "turn_chars": args.turn_chars, "model": args.model,
            "num_pages": args.num_pages, "page_size": args.page_size,
        },
        "modes": {
            "no_tier": run_mode(args, host_tier=False),
            "host_tier": run_mode(args, host_tier=True),
        },
    }
    off = results["modes"]["no_tier"]["warm_turns_ttft_ms"]
    on = results["modes"]["host_tier"]["warm_turns_ttft_ms"]
    if off.get("p50") and on.get("p50"):
        results["offload_ttft_speedup_p50"] = round(
            off["p50"] / max(on["p50"], 1e-9), 3
        )
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()

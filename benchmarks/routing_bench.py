"""KV-aware routing vs round-robin: the reference's headline routing claim
reproduced in simulation at fleet scale.

The reference reports 3x TTFT / 2x mean latency from KV-aware routing on
prefix-heavy real traffic (architecture.md:91). This harness stands up N
batched mock workers (real PageAllocators, real KV events, watermark
scheduler — mocker/engine.py) over a real fabric server, drives the SAME
prefix-tree workload (synthesizer.py, the reference's
data_generator/synthesizer.py shape) through a round-robin router and a
KV router, and reports per-mode TTFT/latency percentiles plus the fleet
prefix-hit rate.

Prefill cost in the mocker is proportional to UNCACHED tokens, so the win
measured here is the same mechanism as on hardware: routing to the worker
that already holds the prefix skips recomputing it.

Usage:  python -m benchmarks.routing_bench [--workers 4] [--requests 200]
Prints one JSON document; --markdown appends a row table to stdout.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time


from benchmarks._procs import pct as _pct


async def _drive_mode(
    mode: str,
    num_workers: int,
    reqs,
    page: int,
    decode_tick_s: float,
    prefill_budget: int,
    concurrency: int,
    num_pages: int,
) -> dict:
    from dynamo_tpu.kv_router import KvRouter, KvRouterConfig
    from dynamo_tpu.mocker import MockEngineArgs
    from dynamo_tpu.model_card import ModelDeploymentCard
    from dynamo_tpu.runtime import DistributedRuntime, RouterMode
    from dynamo_tpu.runtime.fabric import FabricServer
    from dynamo_tpu.runtime.push_router import PushRouter
    from dynamo_tpu.worker import Worker

    card = ModelDeploymentCard(name="mock-model", kv_page_size=page)
    server = FabricServer(port=0)
    await server.start()
    runtimes, workers = [], []
    try:
        for _ in range(num_workers):
            rt = await DistributedRuntime.create(server.address)
            w = Worker(
                rt, card, engine_kind="mock", namespace="bench",
                metrics_interval=0.05, router_mode=mode,
                # decode-realistic ticks; small prefill budget makes the
                # workload prefill-bound like long-ISL serving
                mock_args=MockEngineArgs(
                    page_size=page, salt=card.name,
                    num_pages=num_pages,
                    decode_s_per_step=decode_tick_s,
                    prefill_tokens_per_step=prefill_budget,
                ),
            )
            await w.start()
            runtimes.append(rt)
            workers.append(w)

        rt_c = await DistributedRuntime.create(server.address)
        runtimes.append(rt_c)
        ep = rt_c.namespace("bench").component("backend").endpoint("generate")
        src = await ep.instance_source()
        if mode == "kv":
            kv = KvRouter(
                rt_c.fabric, "backend", src, block_size=page,
                salt=card.name, config=KvRouterConfig(temperature=0.0),
            )
            await kv.start()
            router = PushRouter(
                src, "generate", mode=RouterMode.KV, kv_chooser=kv.choose
            )
        else:
            kv = None
            router = PushRouter(src, "generate", mode=RouterMode.ROUND_ROBIN)
        await src.wait_for_instances()

        sem = asyncio.Semaphore(concurrency)
        ttfts, latencies = [], []

        async def one(i, r):
            async with sem:
                t0 = time.perf_counter()
                first = None
                req = {
                    "request_id": f"{mode}-{i}",
                    "token_ids": list(r.prompt_tokens),
                    "max_tokens": max(4, min(r.output_len, 32)),
                    "temperature": 0.0, "top_p": 1.0, "top_k": 0,
                    "seed": None, "stop_token_ids": [], "stop_strings": [],
                    "ignore_eos": True, "annotations": {},
                }
                async for item in router.generate(req):
                    if first is None and item.get("token_ids"):
                        first = time.perf_counter() - t0
                ttfts.append(first)
                latencies.append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        await asyncio.gather(*(one(i, r) for i, r in enumerate(reqs)))
        wall = time.perf_counter() - t0

        hit_tokens = sum(
            w.mock.allocator.stats.hit_tokens for w in workers
        )
        query_tokens = sum(
            w.mock.allocator.stats.query_tokens for w in workers
        )
        out = {
            "mode": mode,
            "ttft_ms": {
                "p50": round(_pct(ttfts, 0.5) * 1e3, 1),
                "p95": round(_pct(ttfts, 0.95) * 1e3, 1),
            },
            "latency_ms": {
                "p50": round(_pct(latencies, 0.5) * 1e3, 1),
                "p95": round(_pct(latencies, 0.95) * 1e3, 1),
            },
            "wall_s": round(wall, 2),
            "prefix_hit_rate": round(hit_tokens / max(query_tokens, 1), 3),
        }
        if kv is not None:
            await kv.stop()
        return out
    finally:
        for w in workers:
            await w.stop(drain_timeout=1)
        for rt in runtimes:
            await rt.close()
        await server.stop()


async def bench(args) -> dict:
    from benchmarks.synthesizer import SynthConfig, sharing_stats, synthesize

    reqs = synthesize(
        SynthConfig(
            num_requests=args.requests,
            node_len=args.page,          # one tree node = one KV page
            branching=args.branching,
            depth=args.depth,
            mean_suffix_len=args.suffix,
            mean_output_len=16,
            seed=7,
        )
    )
    share = sharing_stats(reqs, block_size=args.page)
    out = {
        "workload": {
            "requests": args.requests, "workers": args.workers,
            "shared_tree": f"depth {args.depth} x node {args.page}",
            "block_reuse_fraction": round(share["reuse_fraction"], 3),
        },
        "modes": {},
    }
    for mode in ("round_robin", "kv"):
        out["modes"][mode] = await _drive_mode(
            mode, args.workers, reqs, args.page,
            decode_tick_s=args.tick, prefill_budget=args.prefill_budget,
            concurrency=args.concurrency, num_pages=args.pages,
        )
    rr, kvm = out["modes"]["round_robin"], out["modes"]["kv"]
    out["kv_ttft_speedup_p50"] = round(
        rr["ttft_ms"]["p50"] / max(kvm["ttft_ms"]["p50"], 1e-9), 2
    )
    out["kv_latency_speedup_p50"] = round(
        rr["latency_ms"]["p50"] / max(kvm["latency_ms"]["p50"], 1e-9), 2
    )
    return out


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="KV routing vs round robin")
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--page", type=int, default=16)
    p.add_argument("--pages", type=int, default=128,
                   help="per-worker KV pool pages (bounded: duplicated "
                        "caching under round-robin thrashes, as on HW)")
    p.add_argument("--depth", type=int, default=6)
    p.add_argument("--branching", type=int, default=4)
    p.add_argument("--suffix", type=int, default=32)
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument(
        "--tick", type=float, default=0.004,
        help="mock decode seconds per step",
    )
    p.add_argument(
        "--prefill-budget", type=int, default=16, dest="prefill_budget",
        help="mock prefill tokens per tick (lower = prefill-bound, like "
             "long-ISL serving)",
    )
    args = p.parse_args(argv)

    from dynamo_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    print(json.dumps(asyncio.run(bench(args)), indent=1))


if __name__ == "__main__":
    main()

"""KV transfer plane microbenchmark: host TCP path vs device pull path.

Measures end-to-end GB/s of shipping KV pages between a sender and a
receiver in one process (loopback worst case for the device plane — on a
real pod the pull rides ICI/DCN). Mirrors the reference's motivation for
NIXL over host staging (block/transfer.rs strategies): the host path pays
device→host, TCP, host→device; the device path pays none of them.

Usage:  python -m benchmarks.transfer_bench [--mb 64] [--iters 5]
Prints one JSON document with GB/s for both strategies.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

import numpy as np


async def _bench(mb: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.disagg.device_transfer import DevicePlane
    from dynamo_tpu.disagg.transfer import KvTransferClient, KvTransferServer

    # KV-page-shaped payload: [L, Hkv, n, ps, D] bf16, ~mb MB per k/v pair
    elems = mb * (1 << 20) // 2 // 2  # /2 dtype bytes, /2 for k+v
    n_pages = max(1, elems // (8 * 64 * 128))
    shape = (1, 8, n_pages, 64, 128)
    k_dev = jnp.ones(shape, jnp.bfloat16)
    v_dev = jnp.zeros(shape, jnp.bfloat16)
    k_host = np.asarray(k_dev)
    v_host = np.asarray(v_dev)
    nbytes = 2 * k_host.nbytes
    page_ids = list(range(n_pages))

    landed: dict = {}

    async def write_fn(ids, kk, vv):
        landed["np"] = (kk.shape, vv.shape)

    async def device_write_fn(ids, kk, vv):
        kk.block_until_ready()
        landed["dev"] = (kk.shape, vv.shape)

    server = KvTransferServer(write_fn, device_write_fn=device_write_fn)
    await server.start()
    client = KvTransferClient()
    out = {"payload_mb": round(nbytes / (1 << 20), 1), "pages": n_pages}
    try:
        # host strategies, most- to least-preferred: shm is the same-host
        # fast path; bulk is THE remote path (side blocking socket,
        # threads both ends); inline is the legacy single-connection
        # asyncio framing. Each is isolated by suppressing the faster
        # ones on the shared client.
        import dynamo_tpu.disagg.transfer as _tr

        shm_ok = client._shm_pool is not None
        # below the bulk threshold the "bulk" row would silently measure
        # the inline path — skip it instead of lying
        bulk_ok = nbytes >= _tr._BULK_MIN
        host_strategies = [("host_shm", shm_ok), ("host_bulk", bulk_ok),
                           ("host_inline", True), ("device", True)]
        for strategy, available in host_strategies:
            if not available:
                out[strategy] = None
                continue
            # plane isolation for the host variants
            client._shm_bad.clear()
            client._bulk_bad.clear()
            if strategy in ("host_bulk", "host_inline"):
                client._shm_bad[server.address] = 1 << 30
            if strategy == "host_inline":
                client._bulk_bad[server.address] = 1 << 30
            times = []
            for i in range(iters + 1):
                rid = f"{strategy}-{i}"
                server.expect(rid)
                t0 = time.perf_counter()
                if strategy.startswith("host"):
                    ok = await client.write(
                        *server.address, rid, page_ids,
                        np.asarray(k_dev), np.asarray(v_dev), 0,
                    )
                else:
                    plane = DevicePlane.get()
                    if plane is None:
                        out["device"] = None
                        break
                    ok = await client.send(
                        *server.address, rid, page_ids, k_dev, v_dev, 0
                    )
                dt = time.perf_counter() - t0
                assert ok
                if i > 0:  # first iter warms connections/compiles
                    times.append(dt)
            if times:
                best = min(times)
                out[strategy] = {
                    "gb_s": round(nbytes / best / (1 << 30), 3),
                    "ms": round(best * 1e3, 2),
                }
        out["planes_landed"] = dict(server.transfers)
    finally:
        client.close()
        await server.stop()
    host_best = next(
        (
            out[s]["gb_s"]
            for s in ("host_shm", "host_bulk", "host_inline")
            if isinstance(out.get(s), dict)
        ),
        None,
    )
    if host_best and isinstance(out.get("device"), dict):
        out["device_speedup"] = round(out["device"]["gb_s"] / host_best, 2)
    return out


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="KV transfer plane microbench")
    p.add_argument("--mb", type=int, default=64, help="payload size, MB")
    p.add_argument("--iters", type=int, default=5)
    args = p.parse_args(argv)

    from dynamo_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    import os

    # Sender and receiver share this process, so the device plane is safe
    # on every backend (the CPU cross-PROCESS abort doesn't apply).
    os.environ.setdefault("DYN_KV_TRANSFER", "device")
    import jax

    out = asyncio.run(_bench(args.mb, args.iters))
    out["platform"] = jax.default_backend()
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()

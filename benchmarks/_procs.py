"""Shared subprocess machinery for benches and the FT harness: managed
CLI processes with log capture + wait-for-pattern readiness (the
reference's ManagedProcess, tests/utils/managed_process.py:69)."""

from __future__ import annotations

import os
import re
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: child env: repo on PYTHONPATH (prepended, not defaulted) + CPU platform
#: unless the caller wants the TPU
ENV = dict(
    os.environ,
    PYTHONPATH=REPO + (
        os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else ""
    ),
)


class ManagedProc:
    """Subprocess with a log file and wait-for-pattern readiness."""

    def __init__(self, name: str, argv: list[str], env: dict | None = None):
        self.name = name
        self.log_path = tempfile.NamedTemporaryFile(
            mode="w", suffix=f"-{name}.log", delete=False
        ).name
        self._log = open(self.log_path, "w")
        self.proc = subprocess.Popen(
            argv, cwd=REPO, env=env or ENV,
            stdout=self._log, stderr=subprocess.STDOUT,
        )

    def wait_for(self, pattern: str, timeout: float = 30.0,
                 peers: "list[ManagedProc] | None" = None) -> None:
        """Wait until the log matches. Fails fast if this process — or any
        of `peers` (e.g. the rest of a cluster this one depends on) —
        exits first, dumping the dead process's log."""
        rx = re.compile(pattern)
        deadline = time.time() + timeout
        while time.time() < deadline:
            with open(self.log_path) as f:
                if rx.search(f.read()):
                    return
            for p in (self, *(peers or ())):
                if p.proc.poll() is not None:
                    raise AssertionError(
                        f"{p.name} exited {p.proc.returncode} while "
                        f"waiting for {pattern!r} from {self.name}:\n"
                        + open(p.log_path).read()
                    )
            time.sleep(0.2)
        raise AssertionError(
            f"{self.name}: {pattern!r} not seen in {timeout}s:\n"
            + open(self.log_path).read()
        )

    def kill(self, sig=signal.SIGKILL) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(sig)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                # escalate instead of raising: a raise here would skip
                # the caller's remaining stop() calls and leak processes
                if sig != signal.SIGKILL:
                    self.proc.kill()
                try:
                    self.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    # D-state zombie (wedged TPU tunnel RPC): nothing more
                    # a signal can do — report it rather than abort the
                    # caller's remaining cleanup
                    print(f"[{self.name}] survived SIGKILL "
                          f"(pid {self.proc.pid})", file=sys.stderr)

    def stop(self) -> None:
        self.kill(signal.SIGTERM)
        self._log.close()


def cli(*args: str) -> list[str]:
    return [sys.executable, "-m", "dynamo_tpu.cli.run", *args]


def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def pct(values, q: float):
    """Nearest-rank percentile (q in [0,1]); None on empty input. The one
    shared implementation for every bench's TTFT/latency tables."""
    if not values:
        return None
    v = sorted(values)
    return v[min(len(v) - 1, int(round(q * (len(v) - 1))))]

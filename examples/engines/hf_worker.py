"""Foreign-engine worker shim: HuggingFace transformers (torch CPU).

This is the framework's external-engine integration path — the role of
the reference's engine-subprocess shims
(launch/dynamo-run/src/subprocess/vllm_v1_inc.py:1-375, sglang_inc.py,
trtllm_inc.py): a process whose ENGINE is not ours joins the runtime as
a first-class worker. The shim side of the contract
(docs/external_engines.md) is tiny:

1. implement `generate(context, PreprocessedRequest) -> async iterator
   of {"token_ids": [...], "finish_reason": None|"stop"|"length"}`,
2. hand the object to `Worker(engine_kind="external", engine=...)`,
3. (optional) expose `on_kv_event` so prefix-cache stored/removed events
   reach the KV router, and `metrics_dict()` for the load plane.

Everything else — fabric registration under a lease, model-card publish,
ingress framing, router targeting, metrics/KV-event publishing — is the
Worker's job, exactly as it is for the native JAX engine.

Run (CPU, random-weight tiny model unless --checkpoint is a real HF dir):

  python examples/engines/hf_worker.py --fabric 127.0.0.1:4499 \
      --model hf-tiny [--checkpoint /path/to/hf_dir]

then serve through any frontend: `run in=http out=dyn --fabric ...`.

Level-2 alternative (`--shim`): speak the subprocess harness wire
protocol on stdio instead of joining the fabric directly — a supervised
Worker owns lifecycle/restarts (docs/external_engines.md "Level 2"):

  dynamo-tpu run in=http \
      'out=ext:python examples/engines/hf_worker.py --shim --model hf-tiny'
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from dynamo_tpu.engine.page_table import KvEvent
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.tokens.blocks import TokenBlockSequence
from dynamo_tpu.worker import Worker

logger = logging.getLogger("hf_worker")


class HFTransformersEngine:
    """AsyncEngine over a torch-CPU transformers CausalLM.

    Incremental decode with past_key_values, one token per stream item;
    honors temperature/top-p, stop ids, ignore_eos and max_tokens, and
    checks `context.cancelled` between steps (client-disconnect → stop).
    Emits "stored" KV events for each full prompt block so KV-aware
    routers can prefix-route to this worker too.
    """

    def __init__(self, model, eos_token_ids=(), block_size: int = 16,
                 salt: str = ""):
        self.model = model
        self.eos_token_ids = tuple(eos_token_ids)
        self.block_size = block_size
        self.salt = salt
        self.on_kv_event = None  # set by Worker(engine_kind="external")
        self.requests_received = 0
        self.active = 0

    def metrics_dict(self) -> dict:
        return {
            "num_waiting": 0,
            "num_running": self.active,
            "requests_received": self.requests_received,
        }

    def _emit_stored(self, token_ids) -> None:
        if self.on_kv_event is None:
            return
        seq = TokenBlockSequence(
            tuple(int(t) for t in token_ids),
            block_size=self.block_size, salt=self.salt,
        )
        blocks = seq.blocks
        if not blocks:
            return
        self.on_kv_event(
            KvEvent(
                kind="stored",
                block_hashes=tuple(b.sequence_hash for b in blocks),
                parent_hash=None,
                token_blocks=tuple(tuple(b.tokens) for b in blocks),
            )
        )

    @staticmethod
    def _sample(logits, temperature: float, top_p: float, generator):
        import torch

        if temperature <= 0.0:
            return int(torch.argmax(logits, dim=-1))
        probs = torch.softmax(logits / temperature, dim=-1)
        if 0.0 < top_p < 1.0:
            sorted_probs, idx = torch.sort(probs, descending=True)
            keep = torch.cumsum(sorted_probs, -1) - sorted_probs < top_p
            keep[..., 0] = True
            probs = torch.zeros_like(probs).scatter(
                -1, idx, sorted_probs * keep
            )
            probs = probs / probs.sum(-1, keepdim=True)
        return int(torch.multinomial(probs, 1, generator=generator))

    async def generate(self, context, request: PreprocessedRequest):
        import torch

        self.requests_received += 1
        self.active += 1
        try:
            generator = None
            if request.seed is not None:
                generator = torch.Generator().manual_seed(int(request.seed))
            # ignore_eos suppresses ALL eos-derived stops (the
            # preprocessor seeds stop_token_ids with the tokenizer's eos
            # ids) — matching the native engine's semantics, so
            # fixed-length benchmarking behaves identically here
            stop_ids = (
                set()
                if request.ignore_eos
                else set(request.stop_token_ids) | set(self.eos_token_ids)
            )
            input_ids = torch.tensor([list(request.token_ids)], dtype=torch.long)
            past = None
            produced = 0
            # optional wire field (omitted at the 1.0 no-op): HF-style
            # multiplicative repetition penalty over generated tokens
            rep = float(getattr(request, "repetition_penalty", 1.0) or 1.0)
            generated: list[int] = []
            loop = asyncio.get_running_loop()
            while produced < request.max_tokens:
                if context.cancelled:
                    return

                def step(ids=input_ids, past_kv=past):
                    with torch.no_grad():
                        out = self.model(
                            input_ids=ids, past_key_values=past_kv,
                            use_cache=True,
                        )
                    return out

                # the forward blocks for ~ms–s: keep the worker's event
                # loop (lease keepalives, other requests) responsive
                out = await loop.run_in_executor(None, step)
                past = out.past_key_values
                logits = out.logits[0, -1]
                if rep != 1.0 and generated:
                    idx = torch.tensor(sorted(set(generated)), dtype=torch.long)
                    vals = logits[idx]
                    logits = logits.clone()
                    logits[idx] = torch.where(vals > 0, vals / rep, vals * rep)
                tok = self._sample(
                    logits, request.temperature, request.top_p,
                    generator,
                )
                generated.append(tok)
                produced += 1
                input_ids = torch.tensor([[tok]], dtype=torch.long)
                if tok in stop_ids:
                    yield {"token_ids": [tok], "finish_reason": "stop"}
                    return
                yield {
                    "token_ids": [tok],
                    "finish_reason": (
                        "length" if produced >= request.max_tokens else None
                    ),
                }
            return
        finally:
            self.active -= 1
            self._emit_stored(request.token_ids)


def build_model(checkpoint: str | None, vocab_size: int):
    """A real HF checkpoint directory, or a tiny random-weight Llama (the
    protocol demo needs a causal LM, not a good one)."""
    import torch

    torch.manual_seed(0)
    if checkpoint:
        from transformers import AutoModelForCausalLM

        return AutoModelForCausalLM.from_pretrained(
            checkpoint, torch_dtype=torch.float32
        ).eval()
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=2048,
    )
    return LlamaForCausalLM(cfg).eval()


def _build_engine(args):
    model = build_model(args.checkpoint, vocab_size=512)
    eos = ()
    if args.checkpoint:
        eos_id = getattr(model.config, "eos_token_id", None)
        if eos_id is not None:
            eos = tuple(eos_id) if isinstance(eos_id, list) else (int(eos_id),)
    return HFTransformersEngine(
        model, eos_token_ids=eos, block_size=args.page_size,
        salt=args.model,
    )


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--fabric", default=None, help="host:port")
    p.add_argument(
        "--shim", action="store_true",
        help="speak the external-engine wire protocol on stdio (run under "
             "a Worker's subprocess supervisor) instead of joining the "
             "fabric as a self-registered worker",
    )
    p.add_argument("--model", default="hf-tiny", help="served model name")
    p.add_argument("--checkpoint", default=None, help="HF model directory")
    p.add_argument("--tokenizer", default=None,
                   help="HF tokenizer dir (default: byte tokenizer)")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--page-size", type=int, default=16, dest="page_size")
    p.add_argument("--max-context", type=int, default=2048,
                   dest="max_context")
    p.add_argument("--router-mode", default="round_robin",
                   dest="router_mode", choices=["round_robin", "random", "kv"])
    return p


async def _serve_fabric(args) -> None:
    logging.basicConfig(level=logging.INFO)
    tokenizer = (
        {"kind": "hf", "path": args.tokenizer}
        if args.tokenizer
        else {"kind": "byte"}
    )
    card = ModelDeploymentCard(
        name=args.model, tokenizer=tokenizer,
        context_length=args.max_context, kv_page_size=args.page_size,
    )
    engine = _build_engine(args)

    rt = await DistributedRuntime.create(args.fabric)
    print(f"worker booting (model={args.model}, role=external-hf)",
          flush=True)
    worker = Worker(
        rt, card, engine_kind="external", engine=engine,
        namespace=args.namespace, router_mode=args.router_mode,
    )
    await worker.start()
    print(f"worker {worker.instance_id} up (model={args.model})", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await worker.stop()


def main() -> None:
    p = _build_parser()
    args = p.parse_args()
    if args.shim:
        # torch/transformers still gate this path (build_model imports
        # them); the shim owns the event loop, so dispatch pre-asyncio
        from dynamo_tpu.external.shim import run_engine

        run_engine(_build_engine(args), model=args.model)
        return
    if not args.fabric:
        p.error("--fabric is required (or use --shim)")
    asyncio.run(_serve_fabric(args))


if __name__ == "__main__":
    main()

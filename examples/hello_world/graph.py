"""Hello-world service graph: Frontend (HTTP) -> Middle -> Backend.

The SDK's canonical smoke graph (reference: examples/hello_world) — three
services chained with depends(); run it with:

    PYTHONPATH=. python -m dynamo_tpu.cli.run serve examples.hello_world.graph:Frontend

then: curl 'http://127.0.0.1:8017/generate?text=hello world'
"""

from __future__ import annotations

from aiohttp import web

from dynamo_tpu.sdk import depends, endpoint, service


@service
class Backend:
    @endpoint
    async def generate(self, ctx, request):
        for word in request["text"].split():
            yield {"word": word.upper()}


@service
class Middle:
    backend = depends(Backend)

    @endpoint
    async def generate(self, ctx, request):
        async for item in self.backend.generate(
            {"text": request["text"]}
        ):
            yield {"word": f"mid-{item['word']}"}


@service
class Frontend:
    middle = depends(Middle)

    def __init__(self):
        self._runner = None
        self.port = None

    async def setup(self):
        app = web.Application()
        app.router.add_get("/generate", self._generate)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(
            self._runner, "127.0.0.1", int(self.config.get("port", 8017))
        )
        await site.start()
        self.port = self._runner.addresses[0][1]
        print(f"hello-world frontend on 127.0.0.1:{self.port}", flush=True)

    async def teardown(self):
        if self._runner is not None:
            await self._runner.cleanup()

    async def _generate(self, request: web.Request) -> web.Response:
        text = request.query.get("text", "hello world")
        words = [
            item["word"]
            async for item in self.middle.generate({"text": text})
        ]
        return web.json_response({"words": words})

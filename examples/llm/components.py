"""Canonical LLM serving components for the SDK graphs.

Reference parity: examples/llm/components/{frontend,worker,prefill_worker}
— the deployment everyone starts from (examples/llm/graphs/agg.py etc.),
rebuilt on this framework's runtime: the Frontend serves OpenAI HTTP and
watches MODEL_ROOT so workers attach dynamically; Worker wraps the JAX
engine worker (aggregated or disaggregated decode); PrefillWorkerService
drains the shared prefill queue.

Config keys (YAML per service, see configs/):
  Frontend:   port
  Worker:     model, engine (jax|echo|mock), router-mode, page-size,
              num-pages, max-context, dtype, disagg, max-local-prefill,
              prefill-chunk, prefill-budget, prefill-policy (fixed|adaptive),
              prefill-budget-max, max-seqs, decode-steps, decode-kstep,
              spec-ngram,
              spec-draft, spec-draft-tokens, spec-draft-checkpoint,
              quantize, host-kv-bytes, disk-kv-bytes, disk-kv-dir,
              dp, tp, sp, ep
  PrefillWorkerService: model + the same engine keys as Worker
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.sdk import depends, service


def _engine_config(cfg: dict):
    from dynamo_tpu.engine import EngineConfig

    page_size = int(cfg.get("page-size", 64))
    max_context = int(cfg.get("max-context", 4096))
    return EngineConfig(
        model=cfg.get("model", "tiny"),
        num_pages=int(cfg.get("num-pages", 2048)),
        page_size=page_size,
        max_pages_per_seq=max(1, max_context // page_size),
        prefill_chunk=int(cfg.get("prefill-chunk", 512)),
        max_seqs=int(cfg.get("max-seqs", 64)),
        dtype=cfg.get("dtype", "bfloat16"),
        decode_steps=int(cfg.get("decode-steps", 8)),
        decode_kstep=int(cfg.get("decode-kstep", 1)),
        spec_ngram=int(cfg.get("spec-ngram", 0)),
        spec_draft_model=cfg.get("spec-draft"),
        spec_draft_tokens=int(cfg.get("spec-draft-tokens", 4)),
        spec_draft_checkpoint=cfg.get("spec-draft-checkpoint"),
        quantize=cfg.get("quantize"),
        prefill_token_budget=(
            int(cfg["prefill-budget"])
            if cfg.get("prefill-budget") is not None
            else None
        ),
        prefill_budget_policy=cfg.get("prefill-policy", "fixed"),
        prefill_budget_max=(
            int(cfg["prefill-budget-max"])
            if cfg.get("prefill-budget-max") is not None
            else None
        ),
        host_kv_cache_bytes=int(cfg.get("host-kv-bytes", 0)),
        disk_kv_cache_bytes=int(cfg.get("disk-kv-bytes", 0)),
        disk_kv_cache_dir=cfg.get("disk-kv-dir"),
        dp=int(cfg.get("dp", 1)),
        tp=int(cfg.get("tp", 1)),
        sp=int(cfg.get("sp", 1)),
        ep=int(cfg.get("ep", 1)),
    )


def _card(cfg: dict):
    from dynamo_tpu.model_card import ModelDeploymentCard

    tokenizer = {"kind": "byte"}
    if cfg.get("tokenizer"):
        tokenizer = {"kind": "hf", "path": cfg["tokenizer"]}
    return ModelDeploymentCard(
        name=cfg.get("model", "tiny"),
        tokenizer=tokenizer,
        context_length=int(cfg.get("max-context", 4096)),
        kv_page_size=int(cfg.get("page-size", 64)),
    )


@service
class Worker:
    """Engine worker: serves `generate`/`embed`/`flush`, publishes KV
    events + load metrics, optionally decodes with remote prefill."""

    def __init__(self):
        self._worker = None

    async def setup(self):
        from dynamo_tpu.worker import Worker as EngineWorker

        cfg = self.config
        disagg_config = None
        if cfg.get("disagg"):
            from dynamo_tpu.disagg import DisaggConfig

            disagg_config = DisaggConfig(
                max_local_prefill_length=int(
                    cfg.get("max-local-prefill", 512)
                )
            )
        self._worker = EngineWorker(
            self.runtime,
            _card(cfg),
            engine_config=(
                _engine_config(cfg)
                if cfg.get("engine", "jax") == "jax"
                else None
            ),
            engine_kind=cfg.get("engine", "jax"),
            router_mode=cfg.get("router-mode", "round_robin"),
            enable_disagg=bool(cfg.get("disagg")),
            disagg_config=disagg_config,
            checkpoint_path=cfg.get("checkpoint"),
        )
        await self._worker.start()

    async def teardown(self):
        if self._worker is not None:
            await self._worker.stop()


@service
class PrefillWorkerService:
    """Stateless prefill worker: pulls RemotePrefillRequests off the shared
    queue, runs the prefill pass, pushes KV pages to the decode worker."""

    def __init__(self):
        self._worker = None

    async def setup(self):
        from dynamo_tpu.disagg.prefill_worker import PrefillWorker

        self._worker = PrefillWorker(
            self.runtime,
            _engine_config(self.config),
            checkpoint_path=self.config.get("checkpoint"),
        )
        await self._worker.start()

    async def teardown(self):
        if self._worker is not None:
            await self._worker.stop()


class _FrontendBase:
    def __init__(self):
        self.http: Optional[object] = None
        self._watcher = None
        self.port = None

    def _make_manager(self):
        """Hook for deployments that wrap the manager (e.g. the multimodal
        frontend attaches an image encoder to every pipeline)."""
        from dynamo_tpu.frontend import ModelManager

        return ModelManager()

    async def setup(self):
        from dynamo_tpu.frontend import HttpService
        from dynamo_tpu.frontend.service import ModelWatcher

        manager = self._make_manager()
        self.http = HttpService(
            manager,
            host=self.config.get("host", "0.0.0.0"),
            port=int(self.config.get("port", 8080)),
        )
        await self.http.start()
        self.port = self.http.port
        self._watcher = ModelWatcher(self.runtime, manager)
        await self._watcher.start()

    async def teardown(self):
        if self._watcher is not None:
            await self._watcher.stop()
        if self.http is not None:
            await self.http.stop()


@service
class Frontend(_FrontendBase):
    """OpenAI-compatible HTTP frontend; models attach via MODEL_ROOT watch."""

    worker = depends(Worker)


@service
class DisaggFrontend(_FrontendBase):
    """Frontend for the disaggregated graphs (decode + prefill workers)."""

    worker = depends(Worker)
    prefill = depends(PrefillWorkerService)

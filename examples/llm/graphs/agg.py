"""Aggregated serving: Frontend -> Worker (prefill+decode in one engine).

Reference parity: examples/llm/graphs/agg.py (Frontend.link(Processor)
.link(VllmWorker)) — the Processor's tokenize/detokenize role lives in
this framework's frontend pipeline, so the graph is two services.

    python -m dynamo_tpu.cli.run serve examples.llm.graphs.agg:Frontend \
        -f examples/llm/configs/agg.yaml
"""

from examples.llm.components import Frontend, Worker

__all__ = ["Frontend", "Worker"]

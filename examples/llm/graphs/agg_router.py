"""Aggregated serving with KV-aware routing: workers publish KV events;
frontends route each request to the worker with the deepest prefix
overlap (reference: examples/llm/graphs/agg_router.py).

    python -m dynamo_tpu.cli.run serve examples.llm.graphs.agg_router:Frontend \
        -f examples/llm/configs/agg_router.yaml
"""

from examples.llm.components import Frontend, Worker

__all__ = ["Frontend", "Worker"]

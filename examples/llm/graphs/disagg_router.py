"""Disaggregated prefill/decode + KV-aware routing (reference:
examples/llm/graphs/disagg_router.py).

    python -m dynamo_tpu.cli.run serve \
        examples.llm.graphs.disagg_router:DisaggFrontend \
        -f examples/llm/configs/disagg_router.yaml
"""

from examples.llm.components import DisaggFrontend, PrefillWorkerService, Worker

__all__ = ["DisaggFrontend", "Worker", "PrefillWorkerService"]

"""Disaggregated prefill/decode: long prompts are prefilled by dedicated
prefill workers and their KV pages pushed to the decode worker
(reference: examples/llm/graphs/disagg.py).

    python -m dynamo_tpu.cli.run serve examples.llm.graphs.disagg:DisaggFrontend \
        -f examples/llm/configs/disagg.yaml
"""

from examples.llm.components import DisaggFrontend, PrefillWorkerService, Worker

__all__ = ["DisaggFrontend", "Worker", "PrefillWorkerService"]

"""Multimodal serving: encode / prefill / decode split.

Reference parity: examples/multimodal — a vision encode worker produces
image embeddings that are handed to the LLM worker and spliced into the
prompt (llava-style). The reference ships embeddings over its NIXL RDMA
`connect` library (examples/multimodal/connect/__init__.py); here they
ride the fabric data plane as framed tensors: the EncodeWorker serves an
`encode` endpoint, and the frontend attaches it to every model pipeline
as the image encoder.

Config keys:
  EncodeWorker:       vision-model (tiny | clip-vit-l-14 | path to an HF
                      CLIP/CLIP-vision checkpoint DIRECTORY — real
                      weights, golden-tested vs transformers), proj-dim
  Worker / Frontend:  as in examples/llm
"""

from __future__ import annotations

import numpy as np

from dynamo_tpu.frontend.service import ModelManager
from dynamo_tpu.sdk import depends, endpoint, service
from examples.llm.components import Worker, _FrontendBase


@service
class EncodeWorker:
    """Vision encoder: pixels in, projected patch embeddings out."""

    def __init__(self):
        self._forward = None
        self._params = None
        self._cfg = None

    async def setup(self):
        import asyncio

        def build():
            import jax

            from dynamo_tpu.models import vision

            import os

            name = self.config.get("vision-model", "clip-vit-l-14")
            proj_dim = int(self.config.get("proj-dim", 4096))
            if ("qwen2-vl" in name or "qwen2.5-vl" in name
                    or self._is_qwen2vl_dir(name)):
                return self._build_qwen2vl(name, proj_dim)
            if os.path.isdir(name):
                # real weights: an HF CLIP(-vision) checkpoint directory
                cfg, params = vision.load_vision_checkpoint(
                    name, proj_dim=proj_dim
                )
            elif name == "tiny":
                cfg = vision.VisionConfig.tiny(proj_dim=proj_dim)
                params = vision.init_params(jax.random.key(0), cfg)
            else:
                cfg = vision.VisionConfig.clip_vit_l_14(proj_dim=proj_dim)
                params = vision.init_params(jax.random.key(0), cfg)
            fwd = jax.jit(
                lambda params, images: vision.forward(params, cfg, images)
            )
            return cfg, params, fwd

        # Model init + first compiles block for seconds — off-loop, or the
        # fabric lease keepalives starve and registration fails (same
        # discipline as Worker's engine construction, worker.py).
        self._cfg, self._params, self._forward = (
            await asyncio.get_running_loop().run_in_executor(None, build)
        )

    @staticmethod
    def _is_qwen2vl_dir(name: str) -> bool:
        import json
        import os

        cfg_path = os.path.join(name, "config.json")
        if not (os.path.isdir(name) and os.path.exists(cfg_path)):
            return False
        with open(cfg_path) as f:
            hf = json.load(f)
        return hf.get("model_type") in ("qwen2_vl", "qwen2_5_vl")

    def _build_qwen2vl(self, name: str, proj_dim: int):
        """Qwen2-VL tower: pixels are patched in the HF processor layout
        and encoded through the native ViT (models/qwen2vl.py); the
        merger projects straight into the LM hidden size, so proj-dim
        names that size here. Checkpoint dirs load ONLY the `visual.*`
        tensors (safetensors shard scan) — the 2B/7B language weights
        belong to the LM worker, not this process."""
        import functools
        import glob
        import json
        import os

        import jax
        import jax.numpy as jnp

        from dynamo_tpu.models import qwen2vl

        if os.path.isdir(name):
            with open(os.path.join(name, "config.json")) as f:
                full = json.load(f)
            hfv = full["vision_config"]
            v25 = full.get("model_type") == "qwen2_5_vl"
            cfg = qwen2vl.Qwen2VLVisionConfig(
                depth=hfv.get("depth", 32),
                # 2.5 renames embed_dim -> hidden_size and the merger
                # output -> out_hidden_size
                embed_dim=hfv.get("embed_dim")
                or hfv.get("hidden_size", 1280),
                num_heads=hfv.get("num_heads", 16),
                in_channels=hfv.get("in_channels", 3),
                patch_size=hfv.get("patch_size", 14),
                temporal_patch_size=hfv.get("temporal_patch_size", 2),
                spatial_merge_size=hfv.get("spatial_merge_size", 2),
                mlp_ratio=hfv.get("mlp_ratio", 4.0),
                hidden_size=(
                    hfv.get("out_hidden_size", proj_dim)
                    if v25
                    else hfv.get("hidden_size", proj_dim)
                ),
                variant="qwen2_5" if v25 else "qwen2",
                window_size=hfv.get("window_size", 112),
                fullatt_block_indexes=tuple(
                    hfv.get("fullatt_block_indexes")
                    # HF's default when the config omits it
                    or ((7, 15, 23, 31) if v25 else ())
                ),
                intermediate_size=hfv.get("intermediate_size")
                if v25
                else None,
            )
            from safetensors import torch as st

            sd = {}
            for shard in sorted(glob.glob(os.path.join(name, "*.safetensors"))):
                for k, v in st.load_file(shard).items():
                    if "visual." in k:
                        sd[k] = v
            params = qwen2vl.vision_params_from_torch_state_dict(sd, cfg)
        elif name == "qwen2-vl-tiny":
            cfg = qwen2vl.Qwen2VLVisionConfig.tiny(hidden_size=proj_dim)
            params = qwen2vl.init_vision_params(jax.random.key(0), cfg)
        elif name == "qwen2.5-vl-tiny":
            cfg = qwen2vl.Qwen2VLVisionConfig.tiny_25(hidden_size=proj_dim)
            params = qwen2vl.init_vision_params(jax.random.key(0), cfg)
        elif "2.5" in name or "2_5" in name:
            cfg = qwen2vl.Qwen2VLVisionConfig.qwen2_5_vl(
                hidden_size=proj_dim
            )
            params = qwen2vl.init_vision_params(jax.random.key(0), cfg)
        else:
            # production geometry (depth 32, patch 14 — images must be
            # multiples of 28), random weights until a dir is given
            cfg = qwen2vl.Qwen2VLVisionConfig.qwen2_vl(hidden_size=proj_dim)
            params = qwen2vl.init_vision_params(jax.random.key(0), cfg)

        @functools.lru_cache(maxsize=8)
        def compiled(grids):  # grids are static per pixel shape
            return jax.jit(
                lambda p, x: qwen2vl.vision_forward(p, cfg, x, list(grids))
            )

        def fwd(params, images):
            b = images.shape[0]
            patches, grids = qwen2vl.pixels_to_patches(
                np.asarray(images, np.float32), cfg
            )
            out = compiled(tuple(grids))(params, jnp.asarray(patches))
            return np.asarray(out, np.float32).reshape(b, -1, out.shape[-1])

        return cfg, params, fwd

    @endpoint
    async def encode(self, ctx, request):
        """{"pixels": bytes f32, "shape": [B, H, W, 3]} ->
        {"embeddings": bytes f32, "shape": [B, N, proj_dim]}"""
        import asyncio

        pixels = np.frombuffer(request["pixels"], np.float32).reshape(
            request["shape"]
        )
        out = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: np.asarray(self._forward(self._params, pixels), np.float32),
        )
        yield {"embeddings": out.tobytes(), "shape": list(out.shape)}


class _EncoderAttachingManager(ModelManager):
    """Every attached model pipeline gets the encode worker as its image
    encoder, enabling image_pixels content parts."""

    def __init__(self, encode_fn):
        super().__init__()
        self._encode_fn = encode_fn

    def add(self, name, pipeline):
        pipeline.image_encode_fn = self._encode_fn
        super().add(name, pipeline)


@service
class MultimodalFrontend(_FrontendBase):
    worker = depends(Worker)
    encoder = depends(EncodeWorker)

    def _make_manager(self):
        async def encode_fn(pixels: np.ndarray) -> np.ndarray:
            reply = await self.encoder.encode.unary(
                {
                    "pixels": np.asarray(pixels, np.float32).tobytes(),
                    "shape": list(pixels.shape),
                }
            )
            return np.frombuffer(reply["embeddings"], np.float32).reshape(
                reply["shape"]
            )

        return _EncoderAttachingManager(encode_fn)

"""Multimodal serving: encode / prefill / decode split.

Reference parity: examples/multimodal — a vision encode worker produces
image embeddings that are handed to the LLM worker and spliced into the
prompt (llava-style). The reference ships embeddings over its NIXL RDMA
`connect` library (examples/multimodal/connect/__init__.py); here they
ride the fabric data plane as framed tensors: the EncodeWorker serves an
`encode` endpoint, and the frontend attaches it to every model pipeline
as the image encoder.

Config keys:
  EncodeWorker:       vision-model (tiny | clip-vit-l-14 | path to an HF
                      CLIP/CLIP-vision checkpoint DIRECTORY — real
                      weights, golden-tested vs transformers), proj-dim
  Worker / Frontend:  as in examples/llm
"""

from __future__ import annotations

import numpy as np

from dynamo_tpu.frontend.service import ModelManager
from dynamo_tpu.sdk import depends, endpoint, service
from examples.llm.components import Worker, _FrontendBase


@service
class EncodeWorker:
    """Vision encoder: pixels in, projected patch embeddings out."""

    def __init__(self):
        self._forward = None
        self._params = None
        self._cfg = None

    async def setup(self):
        import asyncio

        def build():
            import jax

            from dynamo_tpu.models import vision

            import os

            name = self.config.get("vision-model", "clip-vit-l-14")
            proj_dim = int(self.config.get("proj-dim", 4096))
            if os.path.isdir(name):
                # real weights: an HF CLIP(-vision) checkpoint directory
                cfg, params = vision.load_vision_checkpoint(
                    name, proj_dim=proj_dim
                )
            elif name == "tiny":
                cfg = vision.VisionConfig.tiny(proj_dim=proj_dim)
                params = vision.init_params(jax.random.key(0), cfg)
            else:
                cfg = vision.VisionConfig.clip_vit_l_14(proj_dim=proj_dim)
                params = vision.init_params(jax.random.key(0), cfg)
            fwd = jax.jit(
                lambda params, images: vision.forward(params, cfg, images)
            )
            return cfg, params, fwd

        # Model init + first compiles block for seconds — off-loop, or the
        # fabric lease keepalives starve and registration fails (same
        # discipline as Worker's engine construction, worker.py).
        self._cfg, self._params, self._forward = (
            await asyncio.get_running_loop().run_in_executor(None, build)
        )

    @endpoint
    async def encode(self, ctx, request):
        """{"pixels": bytes f32, "shape": [B, H, W, 3]} ->
        {"embeddings": bytes f32, "shape": [B, N, proj_dim]}"""
        import asyncio

        pixels = np.frombuffer(request["pixels"], np.float32).reshape(
            request["shape"]
        )
        out = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: np.asarray(self._forward(self._params, pixels), np.float32),
        )
        yield {"embeddings": out.tobytes(), "shape": list(out.shape)}


class _EncoderAttachingManager(ModelManager):
    """Every attached model pipeline gets the encode worker as its image
    encoder, enabling image_pixels content parts."""

    def __init__(self, encode_fn):
        super().__init__()
        self._encode_fn = encode_fn

    def add(self, name, pipeline):
        pipeline.image_encode_fn = self._encode_fn
        super().add(name, pipeline)


@service
class MultimodalFrontend(_FrontendBase):
    worker = depends(Worker)
    encoder = depends(EncodeWorker)

    def _make_manager(self):
        async def encode_fn(pixels: np.ndarray) -> np.ndarray:
            reply = await self.encoder.encode.unary(
                {
                    "pixels": np.asarray(pixels, np.float32).tobytes(),
                    "shape": list(pixels.shape),
                }
            )
            return np.frombuffer(reply["embeddings"], np.float32).reshape(
                reply["shape"]
            )

        return _EncoderAttachingManager(encode_fn)

"""Multimodal graph: MultimodalFrontend -> (Worker, EncodeWorker).

    python -m dynamo_tpu.cli.run serve \
        examples.multimodal.graph:MultimodalFrontend \
        -f examples/multimodal/config.yaml
"""

from examples.llm.components import Worker
from examples.multimodal.components import EncodeWorker, MultimodalFrontend

__all__ = ["MultimodalFrontend", "Worker", "EncodeWorker"]

"""Property tests for the streaming quantile sketch + SLO tracker
(dynamo_tpu/telemetry/slo.py): <=1% rank error against exact
numpy.percentile on adversarial distributions, exact merge
associativity, wire round-trips, and SLA/burn-rate accounting."""

import numpy as np
import pytest

from dynamo_tpu.telemetry.slo import (
    MergedSlo,
    QuantileSketch,
    SlaTargets,
    SloTracker,
    merge_trackers,
)

QS = (0.01, 0.05, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99)


def rank_error(data: np.ndarray, estimate: float, q: float) -> float:
    """Distance from the target rank q to the cdf interval the estimate
    occupies: [P(x < est), P(x <= est)]. 0 for any estimate lying on the
    exact quantile's tie range."""
    n = len(data)
    lo = np.sum(data < estimate) / n
    hi = np.sum(data <= estimate) / n
    if lo <= q <= hi:
        return 0.0
    return min(abs(q - lo), abs(q - hi))


def sketch_of(values) -> QuantileSketch:
    sk = QuantileSketch()
    for v in values:
        sk.observe(float(v))
    return sk


def _distributions(rng):
    """Adversarial latency-shaped distributions (ms scale)."""
    n = 20_000
    return {
        "bimodal": np.concatenate(
            [
                rng.normal(12.0, 0.8, n // 2).clip(0.5),
                rng.normal(900.0, 45.0, n - n // 2).clip(500),
            ]
        ),
        "heavy_tail": rng.lognormal(mean=3.0, sigma=1.6, size=n).clip(
            0.01, 1e7
        ),
        "pareto_tail": (rng.pareto(1.3, n) + 1.0) * 7.0,
        "constant": np.full(n, 42.5),
        "uniform_wide": rng.uniform(0.05, 5_000.0, n),
    }


def test_rank_error_within_one_percent():
    rng = np.random.default_rng(7)
    for name, data in _distributions(rng).items():
        sk = sketch_of(data)
        for q in QS:
            est = sk.quantile(q)
            err = rank_error(data, est, q)
            assert err <= 0.01, (
                f"{name} q={q}: estimate {est} rank error {err:.4f}"
            )


def test_constant_distribution_is_exact():
    sk = sketch_of([42.5] * 5000)
    for q in QS:
        assert sk.quantile(q) == 42.5


def test_merge_associative_and_equals_concat():
    rng = np.random.default_rng(11)
    a = rng.lognormal(2.0, 1.2, 7000)
    b = rng.normal(300.0, 20.0, 5000).clip(1)
    c = rng.uniform(0.1, 50.0, 3000)
    concat = np.concatenate([a, b, c])

    ab_c = sketch_of(a)
    ab_c.merge(sketch_of(b))
    ab_c.merge(sketch_of(c))
    c_ba = sketch_of(c)
    bc = sketch_of(b)
    bc.merge(sketch_of(a))
    c_ba.merge(bc)
    direct = sketch_of(concat)

    # merging is bucket-wise addition: both orders and the direct sketch
    # agree exactly on structure (buckets, counts, extrema); bucket sums
    # only differ in float addition order
    for other in (c_ba, direct):
        assert sorted(ab_c.buckets) == sorted(other.buckets)
        for idx, (cnt, s, mn, mx) in ab_c.buckets.items():
            ocnt, os_, omn, omx = other.buckets[idx]
            assert (cnt, mn, mx) == (ocnt, omn, omx)
            assert s == pytest.approx(os_, rel=1e-12)
    assert ab_c.count == len(concat)
    for q in QS:
        assert ab_c.quantile(q) == c_ba.quantile(q) == direct.quantile(q)
        assert rank_error(concat, ab_c.quantile(q), q) <= 0.01


def test_wire_round_trip_preserves_quantiles():
    rng = np.random.default_rng(3)
    data = rng.lognormal(1.0, 2.0, 4000)
    sk = sketch_of(data)
    back = QuantileSketch.from_wire(sk.to_wire())
    assert back.count == sk.count
    for q in QS:
        assert back.quantile(q) == sk.quantile(q)
    # wire is msgpack/json-safe (lists + scalars only)
    import json

    json.dumps(sk.to_wire())


def test_merge_rejects_alpha_mismatch():
    a = QuantileSketch(alpha=0.005)
    b = QuantileSketch(alpha=0.01)
    with pytest.raises(ValueError):
        a.merge(b)


def test_empty_and_single_value():
    sk = QuantileSketch()
    assert sk.quantile(0.5) is None
    sk.observe(17.0)
    assert sk.quantile(0.0) == sk.quantile(1.0) == 17.0


def test_tracker_sla_judgement_and_goodput():
    clock = [1000.0]
    tr = SloTracker(
        sla=SlaTargets(ttft_ms=100.0, itl_ms=20.0, objective=0.9),
        windows=(60.0,),
        clock=lambda: clock[0],
    )
    assert tr.finish_request(ttft_ms=50.0, itl_ms=10.0, tokens=32)
    assert not tr.finish_request(ttft_ms=500.0, itl_ms=10.0, tokens=32)
    assert not tr.finish_request(ttft_ms=50.0, itl_ms=90.0, tokens=8)
    # None latencies aren't judged against their target
    assert tr.finish_request(ttft_ms=None, itl_ms=None, tokens=4)
    assert tr.requests_total == 4
    assert tr.within_sla_total == 2
    assert tr.goodput_tokens_total == 36
    assert tr.tokens_total == 76
    assert tr.attainment() == 0.5
    assert tr.attainment(60.0) == 0.5
    # burn rate: (1 - 0.5) / (1 - 0.9) = 5x the error budget
    assert abs(tr.burn_rate(60.0) - 5.0) < 1e-9
    # the window slides: 10 minutes later the failures age out
    clock[0] += 600.0
    assert tr.attainment(60.0) == 1.0
    assert tr.burn_rate(60.0) == 0.0
    # cumulative accounting never forgets
    assert tr.attainment() == 0.5


def test_merge_trackers_skips_garbage_wires():
    tr = SloTracker()
    tr.observe("ttft_ms", 120.0)
    tr.finish_request(ttft_ms=120.0, tokens=10)
    merged = merge_trackers(
        [
            tr.to_wire(),
            {"sketches": "nonsense"},
            ["not", "a", "dict"],
            {"sketches": {"ttft_ms": {"b": "garbage"}}},
            tr.to_wire(),
        ]
    )
    assert isinstance(merged, MergedSlo)
    assert merged.sources == 2
    assert merged.requests_total == 2
    assert merged.sketches["ttft_ms"].count == 2
    snap = merged.to_snapshot()
    assert snap["ttft_ms"]["p50"] == pytest.approx(120.0, rel=0.02)

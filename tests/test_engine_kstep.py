"""On-device K-step decode windows (EngineConfig.decode_kstep): K decode
iterations fused into ONE XLA program with on-device sampling, stop
checks, and paged-KV writes. The headline contract is bit-exactness —
every per-request token stream at K>1 must be identical to K=1
sequential stepping (which itself is pinned bit-identical to a
decode_kstep-free engine), across greedy, sampled, penalty, bias,
min_tokens, mid-window stops, overlap chaining/rollback, mixed-step
carry, and preemption."""

import numpy as np
import pytest

from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.engine.engine import JaxEngine
from dynamo_tpu.engine.request import SamplingParams


@pytest.fixture(scope="module")
def engine_factory():
    def make(**overrides):
        return JaxEngine(EngineConfig.for_tests(**overrides))

    return make


def _run(eng, reqs):
    for rid, prompt, s in reqs:
        eng.add_request(rid, prompt, s)
    return eng.run_to_completion()


def _workload(styles=("greedy", "sampled")):
    """Mixed per-row sampling configurations with staggered max_tokens so
    finishes land mid-window at K=8."""
    rng = np.random.default_rng(11)
    mk = {
        "greedy": lambda i: SamplingParams(
            temperature=0.0, max_tokens=5 + 4 * (i % 3), ignore_eos=True
        ),
        "sampled": lambda i: SamplingParams(
            temperature=0.8, top_p=0.9, top_k=20, seed=300 + i,
            max_tokens=5 + 4 * (i % 3), ignore_eos=True,
        ),
        "penalty": lambda i: SamplingParams(
            temperature=0.7, seed=400 + i, repetition_penalty=1.3,
            frequency_penalty=0.2, max_tokens=6 + 3 * (i % 2),
            ignore_eos=True,
        ),
        "bias": lambda i: SamplingParams(
            temperature=0.0, logit_bias=((3, 4.0), (7, -2.0)),
            max_tokens=6 + 3 * (i % 2), ignore_eos=True,
        ),
        "min_tokens": lambda i: SamplingParams(
            temperature=0.0, min_tokens=6, max_tokens=9,
        ),
    }
    reqs = []
    for i in range(6):
        style = styles[i % len(styles)]
        prompt = [int(x) for x in rng.integers(1, 200, 3 + (i % 4))]
        reqs.append((f"{style}{i}", prompt, mk[style](i)))
    return reqs


# -- K=1 default: the engine must be bit-identical to a kstep-free build --


def test_default_is_off_and_pinned(engine_factory):
    """decode_kstep defaults to 1: the window policy never engages, no
    kstep program compiles, and streams equal an explicit K=1 build (the
    pin that the default path is untouched)."""
    reqs = _workload()
    base = engine_factory()
    assert base.config.decode_kstep == 1 and not base._kstep_enabled
    ref = _run(base, reqs)
    assert base.metrics.kstep_windows == 0
    assert _run(engine_factory(decode_kstep=1), reqs) == ref


# -- bit-exactness vs K=1 across the sampling feature matrix --------------


@pytest.mark.parametrize(
    "styles",
    [("greedy",), ("sampled",), ("penalty",), ("bias", "min_tokens"),
     ("greedy", "sampled", "penalty", "bias")],
    ids=["greedy", "sampled", "penalty", "bias_min_tokens", "mixed_rows"],
)
def test_kstep_bitexact_vs_k1(engine_factory, styles):
    reqs = _workload(styles)
    ref = _run(engine_factory(decode_kstep=1, overlap_decode=False), reqs)
    eng = engine_factory(decode_kstep=8, overlap_decode=False)
    got = _run(eng, reqs)
    assert got == ref
    m = eng.metrics
    assert m.kstep_windows > 0, "window path never engaged"
    assert m.kstep_steps >= m.kstep_windows
    assert m.time_kstep_ms > 0
    assert m.kstep_window_size in (2, 4, 8)


def test_kstep_k16_long_wave(engine_factory):
    """A K=16 window over a long greedy wave: one host visit per 16
    tokens, stream still byte-identical."""
    reqs = [("w", [5, 17, 42], SamplingParams(max_tokens=48, ignore_eos=True))]
    geom = dict(num_pages=128, max_pages_per_seq=16)  # room for 51 tokens
    ref = _run(
        engine_factory(decode_kstep=1, overlap_decode=False, **geom), reqs
    )
    eng = engine_factory(decode_kstep=16, overlap_decode=False, **geom)
    got = _run(eng, reqs)
    assert got == ref
    assert eng.metrics.kstep_window_size == 16
    # 48 tokens in far fewer host visits than per-token stepping
    assert eng.metrics.kstep_steps >= 32


# -- on-device finish evaluation: stops and budgets mid-window ------------


def test_stop_token_freezes_mid_window(engine_factory):
    """Pick a token the greedy stream actually emits mid-stream, then
    re-run with it as a stop token: the device must emit it and freeze
    the row for the rest of the window — same stream as K=1, nothing
    past the stop."""
    probe = _run(
        engine_factory(decode_kstep=1, overlap_decode=False),
        [("p", [9, 9, 9], SamplingParams(max_tokens=24, ignore_eos=True))],
    )["p"]
    stop_tok = probe[10]  # fires mid-stream, mid-window at K=8

    def reqs():
        return [
            ("s", [9, 9, 9],
             SamplingParams(max_tokens=24, stop_token_ids=(stop_tok,))),
            ("other", [4, 4, 2],
             SamplingParams(max_tokens=24, ignore_eos=True)),
        ]

    ref = _run(engine_factory(decode_kstep=1, overlap_decode=False), reqs())
    eng = engine_factory(decode_kstep=8, overlap_decode=False)
    got = _run(eng, reqs())
    assert got == ref
    assert got["s"][-1] == stop_tok or len(got["s"]) < len(probe)
    assert len(got["other"]) == 24  # survivor unaffected by the freeze
    assert eng.metrics.kstep_windows > 0


def test_max_tokens_budget_mid_window(engine_factory):
    """max_tokens that isn't a multiple of K: the on-device budget must
    cut the row at exactly the host's count — never K-rounded."""
    reqs = [
        ("a", [1, 2, 3], SamplingParams(max_tokens=5, ignore_eos=True)),
        ("b", [4, 5, 6], SamplingParams(max_tokens=13, ignore_eos=True)),
    ]
    eng = engine_factory(decode_kstep=8, overlap_decode=False)
    got = _run(eng, reqs)
    assert len(got["a"]) == 5 and len(got["b"]) == 13
    assert got == _run(
        engine_factory(decode_kstep=1, overlap_decode=False), reqs
    )


def test_oversized_stop_set_falls_back(engine_factory):
    """More stop ids than the device's STOP_SLOTS packing: the window
    must fall back to per-token stepping (counted), streams unchanged."""
    from dynamo_tpu.engine.sampling import STOP_SLOTS

    stops = tuple(range(1000, 1000 + STOP_SLOTS + 3))
    reqs = [("f", [1, 2, 3],
             SamplingParams(max_tokens=6, stop_token_ids=stops))]
    eng = engine_factory(decode_kstep=8, overlap_decode=False)
    got = _run(eng, reqs)
    assert eng.metrics.kstep_windows == 0
    assert eng.metrics.kstep_fallbacks > 0
    assert got == _run(
        engine_factory(decode_kstep=1, overlap_decode=False), reqs
    )


def test_logprobs_rows_fall_back(engine_factory):
    """No logprobs variant of the window program: a logprobs row drops
    the batch to the classic path, values identical."""

    def run(k):
        eng = engine_factory(decode_kstep=k, overlap_decode=False)
        eng.add_request(
            "lp", [5, 6, 7],
            SamplingParams(max_tokens=8, ignore_eos=True, logprobs=2),
        )
        toks, lps = [], []
        while eng.has_work:
            for o in eng.step():
                toks.extend(o.new_token_ids)
                if o.logprobs:
                    lps.extend(o.logprobs)
        return toks, lps, eng.metrics.kstep_windows

    ref_t, ref_l, _ = run(1)
    got_t, got_l, windows = run(8)
    assert (got_t, got_l) == (ref_t, ref_l)
    assert windows == 0


# -- composition: overlap chaining, rollback, mixed steps, preemption -----


def test_overlap_chains_kstep_windows(engine_factory):
    """With overlap on, the next K-window dispatches speculatively while
    the host postprocesses the current one — streams bit-exact vs both
    (overlap off, K=8) and (overlap off, K=1)."""
    reqs = _workload(("greedy", "sampled"))
    ref = _run(engine_factory(decode_kstep=1, overlap_decode=False), reqs)
    eng = engine_factory(decode_kstep=8, overlap_decode=True)
    got = _run(eng, reqs)
    assert got == ref
    assert eng.metrics.kstep_windows > 0
    assert _run(
        engine_factory(decode_kstep=8, overlap_decode=False), reqs
    ) == ref


def test_overlap_rollback_on_midwave_admission(engine_factory):
    """A prefill admitted while a speculative K-window is in flight must
    roll it back (overshoot discarded) and still match the synchronous
    K=1 engine fed the same arrival order."""

    def run(k, overlap):
        eng = engine_factory(decode_kstep=k, overlap_decode=overlap)
        eng.add_request("a", [1, 2, 3, 4],
                        SamplingParams(max_tokens=24, ignore_eos=True))
        eng.add_request("b", [9, 8, 7],
                        SamplingParams(max_tokens=24, ignore_eos=True))
        out = {}
        steps = 0
        late = False
        while eng.has_work:
            for o in eng.step():
                out.setdefault(o.request_id, []).extend(o.new_token_ids)
            steps += 1
            if steps == 2 and not late:
                eng.add_request(
                    "late", [3, 1, 4, 1, 5],
                    SamplingParams(max_tokens=8, ignore_eos=True),
                )
                late = True
        return out, eng.metrics

    ref, _ = run(1, False)
    got, m = run(8, True)
    assert got == ref
    assert m.kstep_windows > 0


def test_kstep_under_preemption(engine_factory):
    """Page pressure preempts a row mid-wave; the window path (including
    its pre-reserved page runway) must recover to the exact K=1 stream."""

    def run(k):
        eng = engine_factory(
            decode_kstep=k, overlap_decode=False,
            num_pages=12, max_pages_per_seq=8,
        )
        eng.add_request("p1", [1, 2, 3, 4, 5, 6, 7, 8],
                        SamplingParams(max_tokens=16, ignore_eos=True))
        eng.add_request("p2", [9, 10, 11, 12, 13, 14, 15, 16],
                        SamplingParams(max_tokens=16, ignore_eos=True))
        return eng.run_to_completion()

    assert run(8) == run(1)


def test_mixed_step_kstep_decode_leg(engine_factory):
    """Under mixed_steps a K-window serves as the decode leg beside the
    prefill chunk (two dispatches instead of one fused program) — the
    staggered-arrival streams still match K=1 exactly."""

    def run(k):
        eng = engine_factory(
            decode_kstep=k, overlap_decode=False, mixed_steps=True
        )
        eng.add_request("d1", [1, 2, 3],
                        SamplingParams(max_tokens=20, ignore_eos=True))
        eng.add_request("d2", [4, 5, 6],
                        SamplingParams(max_tokens=20, ignore_eos=True))
        out = {}
        steps = 0
        late = False
        while eng.has_work:
            for o in eng.step():
                out.setdefault(o.request_id, []).extend(o.new_token_ids)
            steps += 1
            if steps == 2 and not late:
                eng.add_request(
                    "late", list(range(1, 20)),
                    SamplingParams(max_tokens=8, ignore_eos=True),
                )
                late = True
        return out, eng.metrics.kstep_windows

    ref, _ = run(1)
    got, windows = run(8)
    assert got == ref
    assert windows > 0


def test_spec_ngram_disables_kstep(engine_factory):
    """Prompt-lookup speculation owns the decode batch: decode_kstep
    must auto-disable (logged at construction) with streams unchanged."""
    eng = engine_factory(decode_kstep=8, spec_ngram=4, overlap_decode=False)
    assert not eng._kstep_enabled
    reqs = [("g", [7, 8, 9, 7, 8], SamplingParams(max_tokens=8,
                                                  ignore_eos=True))]
    got = _run(eng, reqs)
    assert eng.metrics.kstep_windows == 0
    assert got == _run(
        engine_factory(decode_kstep=1, spec_ngram=4, overlap_decode=False),
        reqs,
    )


# -- scheduler page runway ------------------------------------------------


def test_clamp_kstep_window_runway(engine_factory):
    """The scheduler halves K until the allocator can cover the whole
    window's page growth: any K it returns must actually fit, and a
    starved pool clamps to 1."""
    eng = engine_factory(decode_kstep=8, overlap_decode=False,
                         num_pages=16, max_pages_per_seq=8)
    eng.add_request("c1", [1, 2, 3, 4, 5, 6],
                    SamplingParams(max_tokens=32, ignore_eos=True))
    eng.add_request("c2", [9, 8, 7, 6, 5, 4],
                    SamplingParams(max_tokens=32, ignore_eos=True))
    while eng.has_work and not eng.scheduler.running:
        eng.step()
    reqs = list(eng.scheduler.running)
    sched = eng.scheduler
    ps = eng.config.page_size
    for ask in (16, 8, 4):
        k = sched.clamp_kstep_window(reqs, ask)
        assert 1 <= k <= ask
        if k > 1:  # returned window's growth must fit the free pool
            need = sum(
                max(0, -(-(r.num_tokens + k - 1) // ps) - len(r.pages))
                for r in reqs
            )
            assert need <= sched.allocator.num_free
    # a starved pool may only return a k whose page growth is ZERO (the
    # rows' current page slack covers the whole window)
    taken = sched.allocator.allocate(sched.allocator.num_free)
    k0 = sched.clamp_kstep_window(reqs, 8)
    need0 = sum(
        max(0, -(-(r.num_tokens + k0 - 1) // ps) - len(r.pages))
        for r in reqs
    )
    assert k0 < 8 and need0 == 0
    sched.allocator.free(taken)
    eng.run_to_completion()


# -- telemetry: watchdog floor, stall spread, flight deltas ---------------


def test_watchdog_floor_at_k16():
    """Regression for the false-stall bug: a healthy K=16 window emits
    once per 16×ITL. With stall_factor=8 the naive threshold (8×ITL)
    sits INSIDE the healthy gap — the watchdog must floor the factor at
    2K so the threshold clears it."""
    from dynamo_tpu.telemetry.watchdog import StallWatchdog

    itl_ms = 100.0
    naive = StallWatchdog(
        itl_estimate_ms=lambda: itl_ms, stall_factor=8.0, stall_min_s=0.1
    )
    assert naive.stall_threshold_s() == pytest.approx(0.8)

    wd = StallWatchdog(
        itl_estimate_ms=lambda: itl_ms, stall_factor=8.0, stall_min_s=0.1,
        window_steps=lambda: 16,
    )
    healthy_gap_s = 16 * itl_ms / 1000.0
    assert wd.stall_threshold_s() > healthy_gap_s  # 2*16*0.1 = 3.2 > 1.6
    # per-token engines (window 1) keep the configured factor exactly
    wd1 = StallWatchdog(
        itl_estimate_ms=lambda: itl_ms, stall_factor=8.0, stall_min_s=0.1,
        window_steps=lambda: 1,
    )
    assert wd1.stall_threshold_s() == naive.stall_threshold_s()
    # a broken callable degrades to the configured factor, not a crash
    def boom():
        raise RuntimeError("nope")

    wdx = StallWatchdog(
        itl_estimate_ms=lambda: itl_ms, stall_factor=8.0, stall_min_s=0.1,
        window_steps=boom,
    )
    assert wdx.stall_threshold_s() == naive.stall_threshold_s()


def test_observe_emission_spreads_window(engine_factory):
    """A K-token window emission observed after a prefill dispatch must
    discount the device-measured healthy window time (K × per-step ms)
    so only true prefill-induced excess lands in the stall histogram."""
    import time as _time

    from dynamo_tpu.telemetry import phases

    eng = engine_factory(decode_kstep=8)
    eng.add_request("o", [1, 2, 3], SamplingParams(max_tokens=4,
                                                   ignore_eos=True))
    req = eng.scheduler.waiting[0]
    eng._kstep_step_ms = 1e6  # huge healthy-window time: spread clamps to 0
    eng._observe_emission(req, finished=False)  # arm prev mark
    eng.metrics.prefill_dispatches += 1  # a prefill ran in between
    hist = phases.phase_histograms
    before = list(hist._counts.get("decode_stall_ms", []))
    n_before = sum(before)
    zero_before = before[0] if before else 0
    _time.sleep(0.002)
    eng._observe_emission(req, finished=True, n_tokens=8, kstep=True)
    after = hist._counts["decode_stall_ms"]
    # exactly one new observation, clamped into the lowest bucket (0 ms)
    assert sum(after) == n_before + 1
    assert after[0] == zero_before + 1


def test_flight_recorder_kstep_deltas(engine_factory):
    """The flight recorder's per-window frame deltas include the window
    counters, so a post-mortem shows K-step cadence around an incident."""
    from dynamo_tpu.telemetry.flight import _DELTA_FIELDS

    tracked = {src for src, _ in _DELTA_FIELDS}
    assert {"kstep_windows", "kstep_steps"} <= tracked


def test_debug_programs_reports_kstep_family(engine_factory):
    """/v1/debug/programs joins decode_kstep dispatches with the
    time_kstep_ms column for live attainment."""
    assert JaxEngine._MEASURED_BY_KIND.get("decode_kstep") == (
        "time_kstep_ms", "kstep_windows",
    )
    eng = engine_factory(decode_kstep=8, overlap_decode=False)
    _run(eng, [("d", [1, 2, 3], SamplingParams(max_tokens=16,
                                               ignore_eos=True))])
    kinds = eng.programs_report()["kinds"]
    assert "decode_kstep" in kinds
    assert kinds["decode_kstep"]["measured_ms_per_dispatch"] is not None

"""Operator vs a REAL (fake) API server — the test tier beyond the
in-memory double (round-4 verdict item 9; reference operator: envtest,
deploy/cloud/operator/internal/controller/suite_test.go).

The reconciler/controller drive `InClusterKube` (the production REST
client, stdlib urllib + Bearer auth) against a kwok-style HTTP apiserver
with real semantics: resourceVersions, 409 Conflicts, Status error
bodies, label selectors, /status merge-patch. Covers create / heal /
GC / conflict-retry / 401 token-refresh."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent / "helpers"))

import pytest

from fake_kube_apiserver import FakeKubeApiServer  # noqa: E402

from dynamo_tpu.operator.controller import Controller  # noqa: E402
from dynamo_tpu.operator.kube import InClusterKube  # noqa: E402


def _cr(name="demo", ns="default", replicas=1):
    return {
        "apiVersion": "dynamo.tpu/v1alpha1",
        "kind": "DynamoGraphDeployment",
        "metadata": {"name": name, "namespace": ns, "generation": 1},
        "spec": {
            "image": "dynamo-tpu:test",
            "services": [
                {"name": "Frontend", "class": "frontend",
                 "replicas": replicas, "endpoints": [], "depends": [],
                 "config": {}, "k8s": {}},
            ],
        },
    }


@pytest.fixture()
def stack(tmp_path, monkeypatch):
    server = FakeKubeApiServer(token="sekret").start()
    sa = tmp_path / "sa"
    sa.mkdir()
    (sa / "token").write_text("sekret")
    monkeypatch.setattr(InClusterKube, "SA_DIR", str(sa))
    kube = InClusterKube(base_url=server.base_url)
    yield server, kube
    server.stop()


def test_create_heal_gc_against_http_apiserver(stack):
    server, kube = stack
    server.seed("DynamoGraphDeployment", "default", _cr())
    ctl = Controller(kube, namespace="default")

    # CREATE: children appear on the server with ownership labels + RVs
    statuses = ctl.reconcile_once()
    assert statuses["demo"]["lastAction"]["created"] >= 2  # deploys + svcs
    deploys = server.objects("Deployment", "default")
    assert deploys and all(
        d["metadata"]["labels"]["dynamo.tpu/deployment"] == "demo"
        for d in deploys
    )
    assert all(d["metadata"]["resourceVersion"] for d in deploys)
    cr = server.get("DynamoGraphDeployment", "default", "demo")
    assert cr["status"]["conditions"][0]["status"] == "True"

    # steady state: second pass is a no-op
    statuses = ctl.reconcile_once()
    assert statuses["demo"]["lastAction"] == {
        "created": 0, "replaced": 0, "deleted": 0,
    }

    # HEAL: hand-break a child's spec server-side; reconcile replaces it
    victim = deploys[0]["metadata"]["name"]
    broken = server.get("Deployment", "default", victim)
    broken["spec"]["replicas"] = 99
    server.seed("Deployment", "default", broken)
    statuses = ctl.reconcile_once()
    assert statuses["demo"]["lastAction"]["replaced"] == 1
    healed = server.get("Deployment", "default", victim)
    assert healed["spec"]["replicas"] != 99

    # GC: CR vanishes -> every owned child is swept
    server.delete("DynamoGraphDeployment", "default", "demo")
    ctl.reconcile_once()
    assert server.objects("Deployment", "default") == []
    assert server.objects("Service", "default") == []


def test_orphan_child_sweep(stack):
    """A child whose name is no longer desired (service renamed/removed)
    is deleted by the ownership sweep."""
    server, kube = stack
    server.seed("DynamoGraphDeployment", "default", _cr())
    ctl = Controller(kube, namespace="default")
    ctl.reconcile_once()
    server.seed(
        "Deployment", "default",
        {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {
                "name": "demo-stale-worker",
                "labels": {
                    "app.kubernetes.io/managed-by": "dynamo-tpu-operator",
                    "dynamo.tpu/deployment": "demo",
                },
            },
            "spec": {"replicas": 1},
        },
    )
    statuses = ctl.reconcile_once()
    assert statuses["demo"]["lastAction"]["deleted"] == 1
    assert server.get("Deployment", "default", "demo-stale-worker") is None


def test_conflict_on_update_retries_to_convergence(stack):
    """A 409 Conflict mid-reconcile errors THAT pass (Ready=False) but
    must not wedge the loop: the next pass re-reads fresh
    resourceVersions and converges."""
    server, kube = stack
    server.seed("DynamoGraphDeployment", "default", _cr())
    ctl = Controller(kube, namespace="default")
    ctl.reconcile_once()

    victim = server.objects("Deployment", "default")[0]["metadata"]["name"]
    broken = server.get("Deployment", "default", victim)
    broken["spec"]["replicas"] = 99
    server.seed("Deployment", "default", broken)

    server.fail_next(409)  # the healing PUT hits a conflict
    statuses = ctl.reconcile_once()
    assert statuses["demo"]["conditions"][0]["status"] == "False"
    assert server.get(
        "Deployment", "default", victim
    )["spec"]["replicas"] == 99  # still broken after the failed pass

    statuses = ctl.reconcile_once()  # retry pass converges
    assert statuses["demo"]["conditions"][0]["status"] == "True"
    assert server.get(
        "Deployment", "default", victim
    )["spec"]["replicas"] != 99


def test_401_refreshes_token_and_retries(stack, tmp_path):
    """A 401 (rotated service-account token) is absorbed by the client's
    refresh+retry — the reconcile pass succeeds transparently."""
    server, kube = stack
    server.seed("DynamoGraphDeployment", "default", _cr())
    ctl = Controller(kube, namespace="default")
    server.fail_next(401)
    statuses = ctl.reconcile_once()
    assert statuses["demo"]["conditions"][0]["status"] == "True"
    assert server.objects("Deployment", "default")


def test_scale_subresource_end_to_end(stack):
    """The planner's /scale PATCH against real HTTP semantics: only
    spec.replicas changes on the component CR, the graph CR is never
    written, and the next controller pass converges the Deployment."""
    import asyncio

    from dynamo_tpu.planner.kube_connector import KubeConnector

    server, kube = stack
    server.seed("DynamoGraphDeployment", "default", _cr())
    ctl = Controller(kube, namespace="default")
    ctl.reconcile_once()
    dcd = kube.get("DynamoComponentDeployment", "default", "demo-frontend")
    assert dcd is not None and dcd["spec"]["replicas"] == 1
    graph_rv = server.get("DynamoGraphDeployment", "default", "demo")[
        "metadata"]["resourceVersion"]

    conn = KubeConnector(
        kube, cr_name="demo", role_services={"decode": "Frontend"}
    )
    asyncio.run(conn.scale("decode", target=4, observed=1))
    dcd = kube.get("DynamoComponentDeployment", "default", "demo-frontend")
    assert dcd["spec"]["replicas"] == 4
    # the graph CR was not rewritten by the scale
    assert server.get("DynamoGraphDeployment", "default", "demo")[
        "metadata"]["resourceVersion"] == graph_rv

    ctl.reconcile_once()
    dep = server.get("Deployment", "default", "frontend")
    assert dep["spec"]["replicas"] == 4
    # and a later no-op graph pass preserves the scaled value
    ctl.reconcile_once()
    assert server.get("Deployment", "default", "frontend")[
        "spec"]["replicas"] == 4

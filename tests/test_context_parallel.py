"""Ring attention + Ulysses all-to-all vs dense attention on a CPU mesh.

Sequence parallelism is TPU-first-class here (the reference has none —
SURVEY.md §5.7); these tests run the real shard_map programs (ppermute /
all_to_all collectives) on the 8-virtual-device CPU platform.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.parallel import MeshConfig, make_mesh
from dynamo_tpu.parallel.context import (
    dense_gqa_attention,
    ring_attention,
    ulysses_attention,
)


def _qkv(rng, b, t, hq, hkv, d):
    q = jnp.asarray(rng.normal(size=(b, t, hq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
    return q, k, v


def test_dense_gqa_matches_naive():
    """Pin the test oracle itself against a naive per-head softmax."""
    rng = np.random.default_rng(0)
    b, t, hq, hkv, d = 1, 8, 4, 2, 16
    q, k, v = _qkv(rng, b, t, hq, hkv, d)
    out = dense_gqa_attention(q, k, v, causal=True)

    g = hq // hkv
    expected = np.zeros((b, t, hq, d), np.float32)
    for h in range(hq):
        kk = np.asarray(k[:, :, h // g])
        vv = np.asarray(v[:, :, h // g])
        s = np.asarray(q)[:, :, h] @ kk.transpose(0, 2, 1) / np.sqrt(d)
        mask = np.tril(np.ones((t, t), bool))
        s = np.where(mask[None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        expected[:, :, h] = p @ vv
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_attention_matches_dense(cpu_mesh_devices, sp, causal):
    mesh = make_mesh(
        MeshConfig(dp=1, sp=sp, tp=1), devices=cpu_mesh_devices[:sp]
    )
    rng = np.random.default_rng(sp)
    b, t, hq, hkv, d = 2, 8 * sp, 4, 2, 16
    q, k, v = _qkv(rng, b, t, hq, hkv, d)
    ref = dense_gqa_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(cpu_mesh_devices, causal):
    sp = 4
    mesh = make_mesh(
        MeshConfig(dp=1, sp=sp, tp=1), devices=cpu_mesh_devices[:sp]
    )
    rng = np.random.default_rng(9)
    b, t, hq, hkv, d = 2, 32, 8, 4, 16
    q, k, v = _qkv(rng, b, t, hq, hkv, d)
    ref = dense_gqa_attention(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_under_jit_with_dp(cpu_mesh_devices):
    """ring attention composes with a dp axis under jit (the serving shape)."""
    mesh = make_mesh(
        MeshConfig(dp=2, sp=4, tp=1), devices=cpu_mesh_devices[:8]
    )
    rng = np.random.default_rng(3)
    b, t, hq, hkv, d = 4, 32, 4, 2, 16
    q, k, v = _qkv(rng, b, t, hq, hkv, d)
    ref = dense_gqa_attention(q, k, v, causal=True)
    out = jax.jit(lambda *a: ring_attention(*a, mesh=mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_shape_validation(cpu_mesh_devices):
    mesh = make_mesh(MeshConfig(dp=1, sp=4, tp=1), devices=cpu_mesh_devices[:4])
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, 1, 30, 4, 2, 16)  # 30 % 4 != 0
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh)
    q, k, v = _qkv(rng, 1, 32, 4, 2, 16)  # Hkv=2 % 4 != 0
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh)

"""OpenAI logit_bias + min_tokens: sampler-level sparse biases and
min-token-gated eos/stop bans (reference validates logit_bias in
protocols/openai/validate.rs and carries min_tokens in common.rs)."""

import numpy as np
import pytest

from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.engine.engine import JaxEngine
from dynamo_tpu.engine.request import SamplingParams


def _cfg(**kw):
    base = dict(
        model="tiny", num_pages=64, page_size=4, max_pages_per_seq=8,
        decode_buckets=(1, 2, 4), prefill_chunk=16, max_seqs=4,
        dtype="float32",
    )
    base.update(kw)
    return EngineConfig(**base)


PROMPT = [5, 17, 42, 9, 3, 8]


@pytest.mark.parametrize("decode_steps", [1, 8])
def test_logit_bias_forces_token(decode_steps):
    """A +1000 bias on one token makes greedy emit it every step, on both
    the single-step and fused decode paths (and the prefill first
    token)."""
    eng = JaxEngine(_cfg(decode_steps=decode_steps))
    eng.add_request(
        "b", PROMPT,
        SamplingParams(
            temperature=0.0, max_tokens=6, ignore_eos=True,
            logit_bias=((77, 1000.0),),
        ),
    )
    out = eng.run_to_completion()["b"]
    assert out == [77] * 6


def test_logit_bias_ban_changes_output():
    """Banning greedy's natural first choice (-1000) must change it."""
    eng = JaxEngine(_cfg())
    eng.add_request(
        "ref", PROMPT, SamplingParams(temperature=0.0, max_tokens=1,
                                      ignore_eos=True)
    )
    first = eng.run_to_completion()["ref"][0]

    eng2 = JaxEngine(_cfg())
    eng2.add_request(
        "ban", PROMPT,
        SamplingParams(
            temperature=0.0, max_tokens=1, ignore_eos=True,
            logit_bias=((first, -1000.0),),
        ),
    )
    banned = eng2.run_to_completion()["ban"][0]
    assert banned != first

    # and a bias-free request sharing no state is unaffected
    eng3 = JaxEngine(_cfg())
    eng3.add_request(
        "plain", PROMPT, SamplingParams(temperature=0.0, max_tokens=1,
                                        ignore_eos=True)
    )
    assert eng3.run_to_completion()["plain"][0] == first


@pytest.mark.parametrize("decode_steps", [1, 8])
def test_min_tokens_suppresses_stop(decode_steps):
    """A stop token that would fire immediately is banned until
    min_tokens output tokens exist — then allowed again."""
    eng = JaxEngine(_cfg(decode_steps=decode_steps))
    eng.add_request(
        "ref", PROMPT, SamplingParams(temperature=0.0, max_tokens=8,
                                      ignore_eos=True)
    )
    ref = eng.run_to_completion()["ref"]
    stop = ref[0]  # greedy's first choice, used as the stop token

    eng2 = JaxEngine(_cfg(decode_steps=decode_steps))
    eng2.add_request(
        "short", PROMPT,
        SamplingParams(temperature=0.0, max_tokens=8,
                       stop_token_ids=(stop,)),
    )
    short = eng2.run_to_completion()["short"]
    assert len(short) == 1 and short[0] == stop  # stops immediately

    eng3 = JaxEngine(_cfg(decode_steps=decode_steps))
    eng3.add_request(
        "min", PROMPT,
        SamplingParams(temperature=0.0, max_tokens=8,
                       stop_token_ids=(stop,), min_tokens=4),
    )
    got = eng3.run_to_completion()["min"]
    assert len(got) >= 4
    assert stop not in got[:4]  # banned while under the minimum


def test_bias_slot_overflow_rejected():
    from dynamo_tpu.engine.sampling import BIAS_SLOTS

    eng = JaxEngine(_cfg())
    with pytest.raises(ValueError, match="slots"):
        eng.add_request(
            "x", PROMPT,
            SamplingParams(
                logit_bias=tuple((i, 1.0) for i in range(BIAS_SLOTS + 1)),
            ),
        )
    with pytest.raises(ValueError, match="vocab"):
        eng.add_request(
            "y", PROMPT, SamplingParams(logit_bias=((99999, 1.0),)),
        )


# -- HTTP API surface --------------------------------------------------------


def test_http_logit_bias_and_min_tokens():
    """OpenAI logit_bias (string keys, clamped) + ext.min_tokens through
    the real HTTP frontend into the jitted sampler."""
    import asyncio

    import aiohttp

    from dynamo_tpu.engine.async_engine import AsyncEngineRunner
    from dynamo_tpu.frontend import HttpService, ModelManager
    from dynamo_tpu.frontend.service import local_pipeline
    from dynamo_tpu.model_card import ModelDeploymentCard

    async def main():
        engine = JaxEngine(EngineConfig.for_tests())
        runner = AsyncEngineRunner(engine)
        runner.start()
        card = ModelDeploymentCard(
            name="tiny", tokenizer={"kind": "byte"}, context_length=32
        )
        manager = ModelManager()
        manager.add("tiny", local_pipeline(card, runner))
        svc = HttpService(manager, host="127.0.0.1", port=0)
        await svc.start()
        base = f"http://127.0.0.1:{svc.port}"
        try:
            async with aiohttp.ClientSession() as s:
                # +100 bias on byte 'Z' (90) forces greedy onto it
                async with s.post(
                    f"{base}/v1/chat/completions",
                    json={
                        "model": "tiny",
                        "messages": [{"role": "user", "content": "ab"}],
                        "max_tokens": 4,
                        "temperature": 0,
                        "logit_bias": {"90": 100},
                        "ext": {"ignore_eos": True},
                    },
                ) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                assert data["choices"][0]["message"]["content"] == "ZZZZ"

                # non-integer key is a 400, like the reference's
                # validate_logit_bias
                async with s.post(
                    f"{base}/v1/chat/completions",
                    json={
                        "model": "tiny",
                        "messages": [{"role": "user", "content": "ab"}],
                        "logit_bias": {"not-a-token": 1},
                    },
                ) as r:
                    assert r.status == 400

                # min_tokens floors the output even when the model would
                # stop (bias eos-ish behavior indirectly: just assert the
                # completion reaches the floor)
                async with s.post(
                    f"{base}/v1/completions",
                    json={
                        "model": "tiny",
                        "prompt": "ab",
                        "max_tokens": 6,
                        "temperature": 0,
                        "ext": {"min_tokens": 6, "ignore_eos": False},
                    },
                ) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                assert data["usage"]["completion_tokens"] == 6
        finally:
            runner.stop()
            await svc.stop()

    asyncio.run(main())

"""Qwen2-VL (vision tower + m-RoPE LM) vs HF Qwen2VLForConditionalGeneration.

BASELINE config 5's model family; the reference reaches it only through
vLLM (/root/reference examples/multimodal/), here it is golden-tested
like the other families.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.models import qwen2vl
from dynamo_tpu.models.llama import (
    forward,
    init_kv_pages,
    params_from_torch_state_dict,
)

PAGE_SIZE = 4
IMG_TOK = 251
VIDEO_TOK = 252
VSTART = 250

pytestmark = pytest.mark.filterwarnings("ignore")


def _hf_model():
    torch = pytest.importorskip("torch")
    from transformers import Qwen2VLConfig, Qwen2VLForConditionalGeneration

    cfg = Qwen2VLConfig(
        vision_config=dict(
            depth=2, embed_dim=32, num_heads=4, in_channels=3,
            patch_size=4, temporal_patch_size=2, spatial_merge_size=2,
            mlp_ratio=2.0, hidden_size=64,
        ),
        text_config=dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, rope_theta=10000.0, rms_norm_eps=1e-6,
            tie_word_embeddings=False,
            rope_scaling={"type": "mrope", "mrope_section": [2, 3, 3]},
            max_position_embeddings=512,
        ),
        image_token_id=IMG_TOK, video_token_id=VIDEO_TOK,
        vision_start_token_id=VSTART, vision_end_token_id=253,
    )
    torch.manual_seed(7)
    model = Qwen2VLForConditionalGeneration(cfg).eval()
    with torch.no_grad():  # qkv biases are zero-init; make them matter
        for layer in model.model.language_model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(0.0, 0.3)
    return model


def _ours_from_hf(model):
    sd = model.state_dict()
    vcfg = qwen2vl.Qwen2VLVisionConfig.tiny(hidden_size=64)
    tcfg = qwen2vl.text_tiny()
    vparams = qwen2vl.vision_params_from_torch_state_dict(sd, vcfg)
    tparams = params_from_torch_state_dict(
        qwen2vl.remap_language_state_dict(sd), tcfg
    )
    return vcfg, vparams, tcfg, tparams


def _grid_patches(rng, vcfg, grid):
    """Random pixel patches in the HF pixel_values layout [N, patch_dim]."""
    t, h, w = grid
    n = t * h * w
    return rng.normal(size=(n, vcfg.patch_dim)).astype(np.float32)


def test_vision_tower_golden():
    torch = pytest.importorskip("torch")
    model = _hf_model()
    vcfg, vparams, _, _ = _ours_from_hf(model)
    rng = np.random.default_rng(0)
    grid = (1, 4, 4)  # 16 patches -> 4 merged embeds
    patches = _grid_patches(rng, vcfg, grid)
    with torch.no_grad():
        ref = model.model.visual(
            torch.from_numpy(patches),
            grid_thw=torch.tensor([list(grid)]),
        ).numpy()
    ours = np.asarray(
        qwen2vl.vision_forward(vparams, vcfg, jnp.asarray(patches), [grid])
    )
    assert ours.shape == ref.shape == (4, 64)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_vision_tower_two_images_block_diagonal():
    """Two images must not attend to each other (cu_seqlens semantics):
    encoding [A, B] jointly equals encoding A and B separately."""
    model = _hf_model()
    vcfg, vparams, _, _ = _ours_from_hf(model)
    rng = np.random.default_rng(1)
    ga, gb = (1, 4, 4), (1, 2, 4)
    pa, pb = _grid_patches(rng, vcfg, ga), _grid_patches(rng, vcfg, gb)
    joint = np.asarray(
        qwen2vl.vision_forward(
            vparams, vcfg, jnp.asarray(np.concatenate([pa, pb])), [ga, gb]
        )
    )
    solo_a = np.asarray(
        qwen2vl.vision_forward(vparams, vcfg, jnp.asarray(pa), [ga])
    )
    solo_b = np.asarray(
        qwen2vl.vision_forward(vparams, vcfg, jnp.asarray(pb), [gb])
    )
    np.testing.assert_allclose(
        joint, np.concatenate([solo_a, solo_b]), rtol=1e-5, atol=1e-5
    )


def test_get_rope_index_golden():
    torch = pytest.importorskip("torch")
    model = _hf_model()
    grid = (1, 4, 4)  # 4 merged image tokens
    toks = [5, 9, VSTART, IMG_TOK, IMG_TOK, IMG_TOK, IMG_TOK, 253, 17, 3]
    ref_pos, ref_delta = model.model.get_rope_index(
        torch.tensor([toks]), image_grid_thw=torch.tensor([list(grid)])
    )
    pos, delta = qwen2vl.get_rope_index(
        toks, [grid], image_token_id=IMG_TOK
    )
    np.testing.assert_array_equal(pos, ref_pos[:, 0].numpy())
    assert delta == int(ref_delta[0, 0])

    # text-only: all three streams equal arange
    pos2, delta2 = qwen2vl.get_rope_index(
        [1, 2, 3, 4], [], image_token_id=IMG_TOK
    )
    np.testing.assert_array_equal(pos2, np.tile(np.arange(4), (3, 1)))
    assert delta2 == 0


def _run_ours(tcfg, tparams, toks, pos3=None, mm_embeds=None, mm_mask=None):
    b, t = toks.shape
    kv = init_kv_pages(tcfg, 64, PAGE_SIZE)
    n_pages = -(-t // PAGE_SIZE)
    pts = np.zeros((b, n_pages), np.int32)
    for i in range(b):
        pts[i] = np.arange(1 + i * n_pages, 1 + (i + 1) * n_pages)
    positions = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    kw = {}
    if pos3 is not None:
        kw["rope_positions"] = jnp.asarray(pos3)
    if mm_embeds is not None:
        kw["mm_embeds"] = mm_embeds
        kw["mm_mask"] = jnp.asarray(mm_mask)
    logits, _ = forward(
        tparams, tcfg, jnp.asarray(toks), jnp.asarray(positions),
        jnp.ones((b, t), bool), kv, jnp.asarray(pts), **kw,
    )
    return np.asarray(logits)


def test_full_model_golden_with_image():
    """End to end: vision encode -> splice -> m-RoPE LM forward equals
    HF Qwen2VLForConditionalGeneration logits."""
    torch = pytest.importorskip("torch")
    model = _hf_model()
    vcfg, vparams, tcfg, tparams = _ours_from_hf(model)
    rng = np.random.default_rng(2)
    grid = (1, 4, 4)
    patches = _grid_patches(rng, vcfg, grid)
    toks = [5, 9, VSTART, IMG_TOK, IMG_TOK, IMG_TOK, IMG_TOK, 253, 17, 3]

    with torch.no_grad():
        ref = model(
            input_ids=torch.tensor([toks]),
            pixel_values=torch.from_numpy(patches),
            image_grid_thw=torch.tensor([list(grid)]),
        ).logits.numpy()

    embeds = qwen2vl.vision_forward(
        vparams, vcfg, jnp.asarray(patches), [grid]
    )  # [4, H]
    toks_np = np.asarray([toks], np.int32)
    mm_mask = toks_np == IMG_TOK
    mm_embeds = jnp.zeros((1, len(toks), tcfg.hidden_size), jnp.float32)
    mm_embeds = mm_embeds.at[0, np.nonzero(mm_mask[0])[0]].set(embeds)
    pos3, _ = qwen2vl.get_rope_index(toks, [grid], image_token_id=IMG_TOK)
    ours = _run_ours(
        tcfg, tparams, toks_np, pos3=pos3[:, None, :],
        mm_embeds=mm_embeds, mm_mask=mm_mask,
    )
    np.testing.assert_allclose(ours, ref, rtol=5e-2, atol=5e-2)
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.95


def test_text_only_scalar_positions_exact():
    """Text-only m-RoPE with equal streams IS standard rope: the serving
    engine's [B, T] scalar positions are exact, not approximate."""
    model = _hf_model()
    _, _, tcfg, tparams = _ours_from_hf(model)
    rng = np.random.default_rng(3)
    toks = rng.integers(0, 250, size=(2, 8)).astype(np.int32)
    pos3 = np.tile(np.arange(8, dtype=np.int32), (3, 2, 1))
    a = _run_ours(tcfg, tparams, toks)  # scalar positions
    b = _run_ours(tcfg, tparams, toks, pos3=pos3)
    np.testing.assert_array_equal(a, b)


def test_text_golden_vs_hf():
    """Text-only logits vs HF (the serving path)."""
    torch = pytest.importorskip("torch")
    model = _hf_model()
    _, _, tcfg, tparams = _ours_from_hf(model)
    rng = np.random.default_rng(4)
    toks = rng.integers(0, 250, size=(2, 11)).astype(np.int32)
    with torch.no_grad():
        ref = model(
            input_ids=torch.from_numpy(toks.astype(np.int64))
        ).logits.numpy()
    ours = _run_ours(tcfg, tparams, toks)
    np.testing.assert_allclose(ours, ref, rtol=5e-2, atol=5e-2)
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.95


def test_preset_and_engine_serving():
    """The registry preset serves text through the real engine."""
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.models.registry import get_model

    adapter = get_model("qwen2-vl-tiny", dtype="float32")
    assert adapter.config.mrope_section == (2, 3, 3)
    assert adapter.config.attention_bias

    eng = JaxEngine(
        EngineConfig(
            model="qwen2-vl-tiny", num_pages=64, page_size=4,
            max_pages_per_seq=8, decode_buckets=(1, 2, 4),
            prefill_chunk=16, max_seqs=4, dtype="float32",
        )
    )
    eng.add_request(
        "q", [5, 17, 42, 9], SamplingParams(temperature=0.0, max_tokens=4)
    )
    out = eng.run_to_completion()["q"]
    assert len(out) == 4


def test_mrope_sharding_specs(cpu_mesh_devices):
    from dynamo_tpu.models.registry import get_model
    from dynamo_tpu.parallel import MeshConfig, make_mesh, shardings_for

    adapter = get_model("qwen2-vl-tiny", dtype="float32")
    mesh = make_mesh(
        MeshConfig(dp=1, tp=2, sp=1), devices=cpu_mesh_devices[:2]
    )
    params = adapter.init_params(jax.random.key(0))
    sh = shardings_for(mesh, adapter.param_specs())
    jax.device_put(params, sh)  # must not throw


def test_pixels_to_patches_matches_hf_processor():
    """Our patch layout equals Qwen2VLImageProcessor's (merge-group-major
    patch order, (C, temporal, ps, ps) flattening)."""
    pytest.importorskip("torch")
    from transformers.models.qwen2_vl.image_processing_qwen2_vl import (
        Qwen2VLImageProcessor,
    )

    vcfg = qwen2vl.Qwen2VLVisionConfig.tiny()
    proc = Qwen2VLImageProcessor(
        patch_size=vcfg.patch_size, merge_size=vcfg.spatial_merge_size,
        temporal_patch_size=vcfg.temporal_patch_size,
        do_resize=False, do_rescale=False, do_normalize=False,
        do_convert_rgb=False,
    )
    rng = np.random.default_rng(6)
    img = rng.normal(size=(16, 8, 3)).astype(np.float32)
    out = proc(images=[img], return_tensors="np")
    ref = out["pixel_values"]
    ref_grid = out["image_grid_thw"][0]
    patches, grids = qwen2vl.pixels_to_patches(img[None], vcfg)
    assert tuple(ref_grid) == grids[0]
    np.testing.assert_allclose(patches, ref, rtol=1e-6, atol=1e-6)


def test_video_temporal_grid_golden():
    """t>1 grids (video): the vision tower tiles positions across
    temporal patches and get_rope_index advances the temporal stream —
    both must match HF exactly."""
    torch = pytest.importorskip("torch")
    model = _hf_model()
    vcfg, vparams, _, _ = _ours_from_hf(model)
    rng = np.random.default_rng(8)
    grid = (2, 2, 4)  # 2 temporal patches of a 2x4 spatial grid
    patches = _grid_patches(rng, vcfg, grid)
    with torch.no_grad():
        ref = model.model.visual(
            torch.from_numpy(patches), grid_thw=torch.tensor([list(grid)])
        ).numpy()
    ours = np.asarray(
        qwen2vl.vision_forward(vparams, vcfg, jnp.asarray(patches), [grid])
    )
    assert ours.shape == ref.shape == (4, 64)  # 16 patches -> 4 merged
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    # m-RoPE position streams for the video placeholder run
    toks = [5, VSTART, *([VIDEO_TOK] * 4), 253, 9]
    ref_pos, ref_delta = model.model.get_rope_index(
        torch.tensor([toks]), video_grid_thw=torch.tensor([list(grid)])
    )
    pos, delta = qwen2vl.get_rope_index(
        toks, [grid], image_token_id=VIDEO_TOK
    )
    np.testing.assert_array_equal(pos, ref_pos[:, 0].numpy())
    assert delta == int(ref_delta[0, 0])


# -- Qwen2.5-VL tower --------------------------------------------------------


def _hf_25_vision(vcfg):
    torch = pytest.importorskip("torch")
    from transformers.models.qwen2_5_vl import modeling_qwen2_5_vl as m25
    from transformers.models.qwen2_5_vl.configuration_qwen2_5_vl import (
        Qwen2_5_VLVisionConfig,
    )

    hf_cfg = Qwen2_5_VLVisionConfig(
        depth=vcfg.depth, hidden_size=vcfg.embed_dim,
        num_heads=vcfg.num_heads, in_channels=vcfg.in_channels,
        patch_size=vcfg.patch_size,
        temporal_patch_size=vcfg.temporal_patch_size,
        spatial_merge_size=vcfg.spatial_merge_size,
        window_size=vcfg.window_size,
        fullatt_block_indexes=list(vcfg.fullatt_block_indexes),
        intermediate_size=vcfg.intermediate_size,
        out_hidden_size=vcfg.hidden_size,
        hidden_act="silu",
        attn_implementation="eager",
    )
    torch.manual_seed(31)
    return m25.Qwen2_5_VisionTransformerPretrainedModel(hf_cfg).eval()


def test_qwen2_5_vision_tower_golden():
    """The 2.5 tower: RMSNorm blocks, biased SwiGLU MLP, window-major
    reordering with per-block window/full attention, raster-order
    restore — vs HF Qwen2_5_VisionTransformer. Grid (1, 8, 12) gives
    2x3 windows of 2x2 merge units, so the window mask and the reorder
    both bite."""
    torch = pytest.importorskip("torch")
    vcfg = qwen2vl.Qwen2VLVisionConfig.tiny_25(hidden_size=64)
    model = _hf_25_vision(vcfg)
    vparams = qwen2vl.vision_params_from_torch_state_dict(
        model.state_dict(), vcfg, prefix=""
    )
    assert "gate_w" in vparams["blocks"] and "n1_b" not in vparams["blocks"]

    rng = np.random.default_rng(21)
    grid = (1, 8, 12)
    patches = rng.normal(size=(96, vcfg.patch_dim)).astype(np.float32)
    with torch.no_grad():
        ref = model(
            torch.from_numpy(patches), grid_thw=torch.tensor([list(grid)])
        ).numpy()
    ours = np.asarray(
        qwen2vl.vision_forward(vparams, vcfg, jnp.asarray(patches), [grid])
    )
    assert ours.shape == ref.shape == (24, 64)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_qwen2_5_windowing_matters():
    """The window mask and full-attention block selection must actually
    flow: making every block full-attention changes the output."""
    from dataclasses import replace

    vcfg = qwen2vl.Qwen2VLVisionConfig.tiny_25()
    import jax as _jax

    vparams = qwen2vl.init_vision_params(_jax.random.key(8), vcfg)
    rng = np.random.default_rng(22)
    grid = (1, 8, 12)
    patches = rng.normal(size=(96, vcfg.patch_dim)).astype(np.float32)
    base = np.asarray(
        qwen2vl.vision_forward(vparams, vcfg, jnp.asarray(patches), [grid])
    )
    all_full = replace(vcfg, fullatt_block_indexes=(0, 1, 2, 3))
    assert not np.allclose(
        base,
        np.asarray(
            qwen2vl.vision_forward(
                vparams, all_full, jnp.asarray(patches), [grid]
            )
        ),
    )

"""/v1/responses: OpenAI Responses API over the chat pipeline.

Reference surface: the responses route of the HTTP service
(lib/llm/src/http/service/openai.rs; protocols/openai/responses types).
"""

from __future__ import annotations

import asyncio
import json

import pytest

from dynamo_tpu.engine.async_engine import EchoEngine
from dynamo_tpu.frontend.http import HttpService
from dynamo_tpu.frontend.service import ModelManager, local_pipeline
from dynamo_tpu.model_card import ModelDeploymentCard


@pytest.fixture()
def service():
    card = ModelDeploymentCard(name="tiny", context_length=128, kv_page_size=4)
    manager = ModelManager()
    manager.add("tiny", local_pipeline(card, EchoEngine()))
    return HttpService(manager, host="127.0.0.1", port=0)


def test_responses_unary(service):
    import aiohttp

    async def run():
        await service.start()
        try:
            async with aiohttp.ClientSession() as sess:
                url = f"http://127.0.0.1:{service.port}/v1/responses"
                r = await sess.post(
                    url,
                    json={
                        "model": "tiny",
                        "input": "Hello there",
                        "instructions": "Be brief.",
                        "max_output_tokens": 5,
                    },
                )
                assert r.status == 200, await r.text()
                body = await r.json()
                assert body["object"] == "response"
                assert body["status"] == "completed"
                assert body["output"][0]["type"] == "message"
                assert body["output"][0]["content"][0]["type"] == "output_text"
                assert len(body["output"][0]["content"][0]["text"]) > 0
                assert body["usage"]["output_tokens"] > 0

                # structured input messages
                r2 = await sess.post(
                    url,
                    json={
                        "model": "tiny",
                        "input": [
                            {"role": "user", "content": "hi"},
                        ],
                        "max_output_tokens": 3,
                    },
                )
                assert r2.status == 200

                r3 = await sess.post(
                    url, json={"model": "nope", "input": "x"}
                )
                assert r3.status == 404
        finally:
            await service.stop()

    asyncio.run(run())


def test_responses_streaming(service):
    import aiohttp

    async def run():
        await service.start()
        try:
            async with aiohttp.ClientSession() as sess:
                url = f"http://127.0.0.1:{service.port}/v1/responses"
                r = await sess.post(
                    url,
                    json={
                        "model": "tiny",
                        "input": "Hello",
                        "max_output_tokens": 4,
                        "stream": True,
                    },
                )
                assert r.status == 200
                raw = (await r.read()).decode()
        finally:
            await service.stop()

        events = []
        for block in raw.strip().split("\n\n"):
            lines = dict(
                l.split(": ", 1) for l in block.splitlines() if ": " in l
            )
            if "data" in lines:
                events.append(json.loads(lines["data"]))
        kinds = [e["type"] for e in events]
        assert kinds[0] == "response.created"
        assert "response.output_text.delta" in kinds
        assert kinds[-1] == "response.completed"
        final = events[-1]["response"]
        deltas = "".join(
            e["delta"] for e in events
            if e["type"] == "response.output_text.delta"
        )
        assert final["output"][0]["content"][0]["text"] == deltas
        assert final["usage"]["output_tokens"] > 0

    asyncio.run(run())

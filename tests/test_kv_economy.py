"""The KV economy (ISSUE 18): one cost model, per-prefix migration,
tiered warmth.

Three layers of proof:

- **Pricing** — CostModel's formulas are the PR 12 handover accounting
  verbatim (2·P·T flops vs blocks·block_bytes wire bytes), the modeled
  TTFT ratio is pinned, and the break-even threshold suppresses every
  degenerate move. MigrationManager's admission order (single-flight →
  backoff → concurrency → byte budget) runs on an injected clock.
- **Routing** — KvRouter with economy=None is bit-identical to the
  pre-economy decision path (the migration hook is provably never
  reached); with an economy installed, a below-threshold delta never
  even consults the manager, and the credited/failed migration paths
  account into the manager exactly once each.
- **Fleet** — a multi-turn chat session over the mocker fleet sim:
  turn 1 warms one worker, the router is forced off it, and turn 2
  must arrive warm on the OTHER worker via a real migrate_prefix →
  handover_offer round trip (cross-worker prefix hit rate > 0, zero
  dropped streams, modeled TTFT strictly better than cold). A fault
  injected mid-migration must degrade the request to a cold prefill
  with every page back in both workers' free pools. The 500-worker
  variant is `slow`.
"""

import asyncio
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))

from helpers.fleet_sim import MODEL, PAGE_SIZE, FleetSim  # noqa: E402

from dynamo_tpu.kv_economy import (
    CostModel,
    EconomyPolicy,
    MigrationManager,
    block_wire_bytes,
    cost_model_from_card,
)
from dynamo_tpu.kv_router import KvRouter, KvRouterConfig
from dynamo_tpu.kv_router.indexer import OverlapScores
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.testing import faults
from dynamo_tpu.tokens import hash_token_blocks


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# CostModel: the shared pricing function
# ---------------------------------------------------------------------------


def test_modeled_ttft_ratio_pinned():
    # THE contract number: bench.py handover_ab / prefix_migration_ab
    # (2048 total, 1536 cached, 128-token prefill chunks → 4/16 chunks)
    assert CostModel.modeled_ttft_ratio(2048, 1536, 128) == 0.25
    assert CostModel.modeled_ttft_ratio(512, 384, 128) == 0.25
    # nothing cached → no speedup
    assert CostModel.modeled_ttft_ratio(96, 0, 32) == 1.0
    # even a full-prefix hit still dispatches one chunk (warm floor)
    assert CostModel.modeled_ttft_ratio(256, 256, 128) == 0.5


def test_pricing_formulas_are_the_handover_accounting():
    cm = CostModel(params=10**9, block_bytes=4096, page_size=16)
    p = cm.price(8)
    assert p.blocks == 8
    assert p.bytes_moved == 8 * 4096
    assert p.cached_tokens == 8 * 16
    assert p.flops_saved == 2 * 10**9 * 128
    assert p.flops_saved_per_byte == p.flops_saved / p.bytes_moved
    assert cm.worth_it(p)
    assert cm.should_migrate(8)
    # non-positive deltas are never a migration
    assert not cm.should_migrate(0)
    assert not cm.should_migrate(-3)
    # a single block never pays for its offer/transfer round trips
    assert not cm.should_migrate(1)


def test_threshold_suppresses_every_delta():
    """The router-facing guarantee: when bytes-moved out-prices
    flops-saved at the configured exchange rate, NO delta migrates."""
    cm = CostModel(
        params=1, block_bytes=10**15, page_size=16, min_flops_per_byte=1e30
    )
    assert not any(cm.should_migrate(d) for d in range(0, 512))


def test_tier_discount_ordering():
    cm = CostModel(params=10**9, block_bytes=262144, page_size=16)
    # HBM-resident blocks are full-price, however spelled
    for t in (None, "", "device", "hbm"):
        assert cm.tier_discount(t) == 1.0
    host, disk = cm.tier_discount("host"), cm.tier_discount("disk")
    # promotion costs strictly discount, and disk costs more than host
    assert 0.0 < disk < host < 1.0
    # unknown tiers are worthless rather than mispriced
    assert cm.tier_discount("tape") == 0.0


def test_cost_model_from_card():
    # no card at all (planner process): 1B-class defaults
    cm = cost_model_from_card(None)
    assert cm.params == 1_000_000_000
    assert cm.page_size == 16
    assert cm.block_bytes == block_wire_bytes(16, 8, 16, 64, 1)

    # a card that publishes its shape gets exact pricing
    card = ModelDeploymentCard(
        name="m", kv_page_size=32,
        extra={"params": 7_000_000_000, "layers": 32, "kv_heads": 4,
               "head_dim": 128, "kv_itemsize": 2},
    )
    cm2 = cost_model_from_card(card)
    assert cm2.params == 7_000_000_000
    assert cm2.page_size == 32
    assert cm2.block_bytes == block_wire_bytes(32, 4, 32, 128, 2)

    # junk extras fall back per-key instead of exploding
    cm3 = cost_model_from_card(
        ModelDeploymentCard(name="m", extra={"params": "lots", "layers": -1})
    )
    assert cm3.params == 1_000_000_000


def test_scored_with_tiers_discounts_and_never_mutates():
    cm = CostModel(params=10**9, block_bytes=262144, page_size=16)

    class _Tiers:
        def chain_tiers(self, iid, hashes, base):
            return ["host", "disk"] if iid == "w1" else []

        def stats(self):
            return {}

    eco = EconomyPolicy(cm, tier_map=_Tiers())
    scores = {"w1": 2}
    out = eco.scored_with_tiers(scores, ["w1", "w2"], [1, 2, 3, 4])
    assert scores == {"w1": 2}  # the indexer's dict is untouched
    assert out["w1"] == 2 + cm.tier_discount("host") + cm.tier_discount("disk")
    assert "w2" not in out
    # no tier map → a plain copy
    out2 = EconomyPolicy(cm).scored_with_tiers(scores, ["w1"], [])
    assert out2 == scores and out2 is not scores


# ---------------------------------------------------------------------------
# MigrationManager: admission control on an injected clock
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_manager_single_flight_and_backoff():
    clk = _Clock()
    m = MigrationManager(
        backoff_s=30.0, max_inflight=8, window_bytes=0, clock=clk
    )
    ok, _ = m.admit(1, "w2", 100)
    assert ok
    # same (prefix, dest) rides the in-flight pull
    ok, why = m.admit(1, "w2", 100)
    assert not ok and why == "inflight"
    # a different destination is a separate flight
    ok, _ = m.admit(1, "w3", 100)
    assert ok
    m.complete(1, "w3", ok=True, bytes_moved=100, blocks=2)
    m.complete(1, "w2", ok=True, bytes_moved=100, blocks=2)
    # the prefix just moved: re-moving it inside the window is a storm
    ok, why = m.admit(1, "w4", 100)
    assert not ok and why == "backoff"
    assert m.storm_repeats == 1
    clk.t += 31.0
    ok, _ = m.admit(1, "w4", 100)
    assert ok
    m.complete(1, "w4", ok=True)
    assert m.migrations_total == 3
    assert m.bytes_total == 200 and m.blocks_total == 4


def test_manager_failure_also_starts_backoff():
    """Retrying a broken transfer on every request IS the storm."""
    clk = _Clock()
    m = MigrationManager(backoff_s=30.0, clock=clk)
    ok, _ = m.admit(7, "w1", 10)
    assert ok
    m.complete(7, "w1", ok=False)
    assert m.migrations_failed == 1
    ok, why = m.admit(7, "w2", 10)
    assert not ok and why == "backoff"


def test_manager_concurrency_and_byte_budget():
    clk = _Clock()
    m = MigrationManager(
        backoff_s=0.0, max_inflight=1,
        window_bytes=1000, window_s=10.0, clock=clk,
    )
    ok, _ = m.admit(1, "a", 10)
    assert ok
    ok, why = m.admit(2, "b", 10)
    assert not ok and why == "concurrency"
    m.complete(1, "a", ok=True, bytes_moved=900, blocks=1)
    # 900 of the 1000-byte window is spent
    ok, why = m.admit(2, "b", 200)
    assert not ok and why == "budget"
    ok, _ = m.admit(2, "b", 50)
    assert ok
    m.complete(2, "b", ok=True, bytes_moved=50, blocks=1)
    # the window rolls off with the clock
    clk.t += 11.0
    ok, _ = m.admit(3, "c", 1000)
    assert ok
    m.complete(3, "c", ok=True, bytes_moved=1000, blocks=2)
    s = m.stats()
    assert s["migrations_total"] == 3
    assert s["migrations_suppressed"] == {"concurrency": 1, "budget": 1}
    assert s["migrations_inflight"] == 0


# ---------------------------------------------------------------------------
# KvRouter decision layer: dummy-fabric harness (constructors are
# fabric-free; subscriptions only happen on start(), which we never call)
# ---------------------------------------------------------------------------


class _Inst:
    def __init__(self, iid, host="127.0.0.1", port=0):
        self.instance_id = iid
        self.host = host
        self.port = port


class _Source:
    def __init__(self, instances):
        self._instances = instances

    def list(self):
        return self._instances


class _Fabric:
    def __init__(self):
        self.published = []

    async def publish(self, subject, payload):
        self.published.append((subject, payload))


def _router(economy=None, scores=None, snapshot=None):
    """A KvRouter over canned index/metrics views: w1 is lightly loaded
    with a shallow prefix, w2 holds a deeper prefix but is heavily
    loaded — the selector must pick w1, making w2 the migration
    source."""
    r = KvRouter(
        _Fabric(), "backend",
        _Source([_Inst("w1", port=7001), _Inst("w2", port=7002)]),
        block_size=16, salt="m",
        config=KvRouterConfig(temperature=0.0), economy=economy,
    )
    canned = dict(scores or {})
    r.indexer.find_matches = lambda hashes: OverlapScores(
        scores=dict(canned),
        matched_blocks=max(canned.values(), default=0),
    )
    r.metrics.snapshot = lambda: dict(snapshot or {})
    return r


_SNAPSHOT = {"w2": {"kv_active_pages": 500, "kv_total_pages": 1000}}
_SCORES = {"w1": 1, "w2": 4}
_TOKENS = list(range(4 * 16))


def test_router_never_migrates_below_threshold():
    """The acceptance gate: when the shared pricing fn says bytes-moved
    out-prices flops-saved, the router must not even consult the
    manager — the decision is identical to the pre-economy router."""

    class _Recorder(MigrationManager):
        def __init__(self):
            super().__init__()
            self.admit_calls = []

        def admit(self, *a, **k):
            self.admit_calls.append(a)
            return super().admit(*a, **k)

    man = _Recorder()
    eco = EconomyPolicy(
        CostModel(params=1, block_bytes=10**15, page_size=16,
                  min_flops_per_byte=1e30),
        manager=man,
    )
    r = _router(economy=eco, scores=_SCORES, snapshot=_SNAPSHOT)
    choice, overlap = run(r.find_best_match(_TOKENS))
    assert (choice, overlap) == ("w1", 1)
    assert man.admit_calls == []
    assert man.stats()["migrations_total"] == 0


def test_router_off_path_is_pre_economy_identical():
    """economy=None: the migration hook is unreachable and the decision
    matches the economy router's suppressed decision bit for bit."""
    r = _router(economy=None, scores=_SCORES, snapshot=_SNAPSHOT)

    async def boom(*a, **k):  # pragma: no cover - must never run
        raise AssertionError("economy off-path reached _maybe_migrate")

    r._maybe_migrate = boom
    choice, overlap = run(r.find_best_match(_TOKENS))
    assert (choice, overlap) == ("w1", 1)


def test_router_migration_credits_and_failure_falls_back(monkeypatch):
    from dynamo_tpu import handover

    eco = EconomyPolicy(
        CostModel(params=10**9, block_bytes=4096, page_size=16),
        manager=MigrationManager(backoff_s=0.0),
    )
    r = _router(economy=eco, scores=_SCORES, snapshot=_SNAPSHOT)
    calls = []

    async def fake_call(host, port, op, payload, **kw):
        calls.append((host, port, op, payload))
        return {"migrated": True, "blocks": 3, "bytes": 3 * 4096}

    monkeypatch.setattr(handover, "call_ingress", fake_call)
    choice, overlap = run(r.find_best_match(_TOKENS))
    # the request admits warm at the source's depth on the chosen worker
    assert (choice, overlap) == ("w1", 4)
    (host, port, op, payload), = calls
    assert (port, op) == (7002, "migrate_prefix")  # asked the deep holder
    hashes = hash_token_blocks(_TOKENS, block_size=16, salt="m")
    # only the missing chain moves: past w1's overlap, up to w2's depth
    assert payload["hashes"] == [int(h) for h in hashes[1:4]]
    assert payload["dest"]["instance_id"] == "w1"
    assert payload["dest"]["port"] == 7001
    assert eco.manager.migrations_total == 1
    assert eco.manager.blocks_total == 3
    assert eco.manager.bytes_total == 3 * 4096

    async def dead_call(host, port, op, payload, **kw):
        raise ConnectionError("transfer plane down")

    monkeypatch.setattr(handover, "call_ingress", dead_call)
    choice, overlap = run(r.find_best_match(_TOKENS))
    # failure → the unmodified overlap: the request cold-prefills
    assert (choice, overlap) == ("w1", 1)
    assert eco.manager.migrations_failed == 1


# ---------------------------------------------------------------------------
# Fleet proof: multi-turn chat over the mocker fleet sim
# ---------------------------------------------------------------------------

#: a deterministic 6-page chat session; turn 1 sends the first 4 pages,
#: turn 2 re-sends the full history (the multi-turn chat shape)
_SESSION = [((i * 37) % 199) + 1 for i in range(6 * PAGE_SIZE)]


async def _find_holder(sim, prefix, deadline=15.0):
    """Poll the router's index until some worker advertises the whole
    prefix; returns its instance_id."""
    hashes = hash_token_blocks(prefix, block_size=PAGE_SIZE, salt=MODEL)
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline:
        ov = sim.kv_router.indexer.find_matches(hashes)
        if ov.scores and max(ov.scores.values()) >= len(hashes):
            return max(ov.scores, key=lambda w: (ov.scores[w], w))
        await asyncio.sleep(0.05)
    raise AssertionError("turn-1 prefix never appeared in the KV index")


async def _settled_free(w, deadline=5.0):
    """The worker's free-page count once the engine thread has finished
    releasing stream pages (stable across a few polls)."""
    last, stable = None, 0
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline:
        cur = w.mock.allocator.num_free
        stable = stable + 1 if cur == last else 0
        last = cur
        if stable >= 3:
            return cur
        await asyncio.sleep(0.05)
    return last


async def _chat_scenario(n_workers, fault_point=None, sim_kw=None):
    """Turn 1 warms one worker; the router is pinned off it; turn 2 must
    migrate the hot prefix to the fresh worker (or, under an injected
    fault, degrade to a cold prefill with no leaked pages)."""
    sim = FleetSim(
        decode_s_per_step=0.005, prefill_tokens_per_step=32,
        **(sim_kw or {}),
    )
    eco = EconomyPolicy(
        CostModel(params=10**9, block_bytes=4096, page_size=PAGE_SIZE),
        manager=MigrationManager(backoff_s=0.0),
    )
    inj = None
    try:
        await sim.start(router="kv", economy=eco)
        for _ in range(n_workers):
            await sim.add_worker()

        turn1 = _SESSION[: 4 * PAGE_SIZE]
        tokens, finish, _ = await sim.one(prompt=turn1, osl=4)
        assert finish in ("length", "stop")
        holder = await _find_holder(sim, turn1)
        baseline_free = {
            w.instance_id: await _settled_free(w) for w in sim.workers
        }

        if fault_point is not None:
            inj = faults.install(seed=7)
            inj.add_rule(fault_point, "error")

        # force the selector off the warm worker: a fat router-local
        # footprint makes every other worker cheaper
        sim.kv_router.active.add(holder, "pin-holder", 400)
        try:
            tokens, finish, _ = await sim.one(prompt=_SESSION, osl=4)
        finally:
            sim.kv_router.active.free("pin-holder")
        assert finish in ("length", "stop")
        assert sim.stats.dropped == 0

        src = next(w for w in sim.workers if w.instance_id == holder)
        dests = [w for w in sim.workers if w.instance_id != holder]
        if fault_point is None:
            # the hot prefix moved and turn 2 admitted warm elsewhere
            assert src.migrations >= 1
            assert eco.manager.migrations_total >= 1
            assert eco.manager.blocks_total >= 2
            assert any(
                w.mock.allocator.stats.hit_tokens > 0 for w in dests
            ), "turn 2 never hit the migrated prefix cross-worker"
            # deterministic TTFT claim: the migrated continuation skips
            # prefill chunks the cold path must run
            ratio = CostModel.modeled_ttft_ratio(
                len(_SESSION),
                eco.manager.blocks_total * PAGE_SIZE,
                sim.prefill_tokens_per_step,
            )
            assert ratio < 1.0
        else:
            # mid-migration fault: the stream completed COLD, the
            # failure was counted, and nothing adopted
            assert inj.fired.get((fault_point, "error"), 0) >= 1
            assert src.migration_fallbacks >= 1
            assert eco.manager.migrations_failed >= 1
            assert all(
                w.mock.allocator.stats.hit_tokens == 0 for w in dests
            ), "a faulted migration must not leave adopted blocks"
            # both sides' pages are back in the free pool
            for w in sim.workers:
                free = await _settled_free(w)
                assert free == baseline_free[w.instance_id], (
                    f"{w.instance_id} leaked pages: "
                    f"{baseline_free[w.instance_id]} -> {free}"
                )
    finally:
        if inj is not None:
            faults.uninstall()
        await sim.stop()


def test_fleet_chat_migration_warms_cross_worker():
    run(_chat_scenario(n_workers=2))


def test_fleet_chat_migration_fault_degrades_to_cold():
    run(_chat_scenario(n_workers=2, fault_point="migrate.transfer"))


@pytest.mark.slow
def test_fleet_chat_migration_500_workers():
    run(_chat_scenario(
        n_workers=500,
        sim_kw=dict(metrics_interval=2.0, num_pages=64),
    ))

"""Mixed prefill+decode steps (EngineConfig.mixed_steps, ISSUE 5): one
fused step carries a bounded prefill chunk plus the current decode batch,
so decode rows emit a token every step while a prompt backlog drains.
Token streams must be BIT-EXACT vs the XOR (prefill-priority) scheduler —
same kernels, same per-request order — across chunked prompts, sampling,
logprobs, penalties, bias, preemption-resume, and the overlapped decode
pipeline; and the compiled-program family must stay finite."""

import numpy as np
import pytest

from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.engine.engine import JaxEngine
from dynamo_tpu.engine.request import SamplingParams
from dynamo_tpu.telemetry import phases, promlint


@pytest.fixture(scope="module")
def engine_factory():
    def make(**overrides):
        base = EngineConfig.for_tests()
        cfg = EngineConfig(**{**base.__dict__, **overrides})
        return JaxEngine(cfg)

    return make


def _drive(eng, late=(), late_at=5):
    """Run to completion, injecting `late` requests after `late_at`
    steps — the shape that forces mixed (or XOR prefill) scheduling
    against a running decode wave."""
    out = {}
    steps = 0
    added = not late
    while eng.has_work:
        for o in eng.step():
            out.setdefault(o.request_id, []).extend(o.new_token_ids)
        steps += 1
        if steps == late_at and not added:
            for rid, prompt, s in late:
                eng.add_request(rid, prompt, s)
            added = True
    return out


def _chunked_late(rng, n=2, max_tokens=6):
    """Prompts longer than prefill_chunk (16) => multi-chunk prefill."""
    return [
        (
            f"late{i}",
            [int(x) for x in rng.integers(1, 200, 24 + 2 * i)],
            SamplingParams(max_tokens=max_tokens, ignore_eos=True),
        )
        for i in range(n)
    ]


def test_mixed_greedy_bitexact_chunked_prompts(engine_factory):
    """The headline contract: greedy streams identical, mixed on vs off,
    with chunked prompts arriving against a decode wave — and the on-arm
    really scheduled mixed steps."""
    rng = np.random.default_rng(5)
    late = _chunked_late(rng)
    base = [
        ("a", [1, 2, 3], SamplingParams(max_tokens=20, ignore_eos=True)),
        ("b", [4, 5, 6, 7], SamplingParams(max_tokens=20, ignore_eos=True)),
    ]

    def run(mixed):
        eng = engine_factory(mixed_steps=mixed, decode_steps=1)
        for rid, p, s in base:
            eng.add_request(rid, p, s)
        return _drive(eng, late), eng.metrics

    ref, m_off = run(False)
    got, m_on = run(True)
    assert got == ref
    assert m_on.mixed_dispatches > 0
    assert m_off.mixed_dispatches == 0


def test_mixed_parity_sampled_logprobs_bias(engine_factory):
    """Sampled rows, logprob reporting and logit_bias ride the fused
    program's combined row space; values must match XOR exactly."""
    rng = np.random.default_rng(9)
    late = [
        (
            "late-lp",
            [int(x) for x in rng.integers(1, 200, 26)],
            SamplingParams(max_tokens=5, ignore_eos=True, logprobs=1),
        ),
        (
            "late-s",
            [int(x) for x in rng.integers(1, 200, 20)],
            SamplingParams(temperature=1.1, seed=7, max_tokens=5,
                           ignore_eos=True),
        ),
    ]

    def run(mixed):
        eng = engine_factory(mixed_steps=mixed, decode_steps=1)
        eng.add_request(
            "s", [5, 6, 7],
            SamplingParams(temperature=0.8, top_p=0.9, seed=42,
                           max_tokens=16, ignore_eos=True),
        )
        eng.add_request(
            "lp", [8, 9],
            SamplingParams(max_tokens=16, ignore_eos=True, logprobs=2,
                           logit_bias=((3, 4.0),)),
        )
        out, lps = {}, {}
        steps = 0
        added = False
        while eng.has_work:
            for o in eng.step():
                out.setdefault(o.request_id, []).extend(o.new_token_ids)
                if o.logprobs:
                    lps.setdefault(o.request_id, []).extend(o.logprobs)
            steps += 1
            if steps == 4 and not added:
                for rid, p, s in late:
                    eng.add_request(rid, p, s)
                added = True
        return out, lps, eng.metrics.mixed_dispatches

    ref_out, ref_lps, _ = run(False)
    got_out, got_lps, n_mixed = run(True)
    assert got_out == ref_out
    assert got_lps == ref_lps
    assert n_mixed > 0


def test_mixed_parity_with_penalties(engine_factory):
    """Penalty history rides the fused program's combined row space
    (build_output_counts over decode + prefill rows)."""
    rng = np.random.default_rng(13)
    late = [
        (
            "late-pen",
            [int(x) for x in rng.integers(1, 200, 22)],
            SamplingParams(max_tokens=4, ignore_eos=True,
                           presence_penalty=0.7),
        )
    ]

    def run(mixed):
        eng = engine_factory(mixed_steps=mixed, decode_steps=1)
        eng.add_request(
            "pen", [5, 6, 7],
            SamplingParams(max_tokens=14, ignore_eos=True,
                           repetition_penalty=1.5, frequency_penalty=0.4),
        )
        return _drive(eng, late, late_at=4), eng.metrics.mixed_dispatches

    ref, _ = run(False)
    got, n_mixed = run(True)
    assert got == ref
    assert n_mixed > 0


def test_mixed_parity_heterogeneous_piece_buckets(engine_factory):
    """Pieces landing in DIFFERENT T buckets (a mid-prompt tail beside a
    short whole prompt) must run under exactly the program variants the
    XOR scheduler would pick — the fused step carries one bucket group
    and dispatches the rest through the plain prefill path. The tiny
    default config can't exercise this (every piece buckets to 32), so
    this test widens the chunk to 64."""
    rng = np.random.default_rng(41)
    late = [
        (
            "two-chunk",  # 64-token chunk + 26-token tail (bucket 32)
            [int(x) for x in rng.integers(1, 200, 90)],
            SamplingParams(max_tokens=4, ignore_eos=True),
        ),
        (
            "one-piece",  # 50 tokens -> bucket 64, first_chunk
            [int(x) for x in rng.integers(1, 200, 50)],
            SamplingParams(max_tokens=4, ignore_eos=True),
        ),
    ]

    def run(mixed, overlap=True):
        eng = engine_factory(
            mixed_steps=mixed, overlap_decode=overlap, decode_steps=1,
            prefill_chunk=64, page_size=4, max_pages_per_seq=32,
            num_pages=128,
        )
        eng.add_request("w", [1, 2, 3],
                        SamplingParams(max_tokens=24, ignore_eos=True))
        return _drive(eng, late), eng.metrics

    ref, _ = run(False)
    for overlap in (False, True):
        got, m = run(True, overlap)
        assert got == ref, f"overlap={overlap}"
        assert m.mixed_dispatches > 0


def test_mixed_parity_under_preemption_resume(engine_factory):
    """Page pressure preempts mid-wave; the folded request re-prefills
    through mixed steps and the streams still match XOR bit-for-bit."""

    def run(mixed):
        eng = engine_factory(
            mixed_steps=mixed, decode_steps=1,
            num_pages=12, max_pages_per_seq=8,
        )
        eng.add_request("p1", [1, 2, 3, 4, 5, 6, 7, 8],
                        SamplingParams(max_tokens=16, ignore_eos=True))
        eng.add_request("p2", [9, 10, 11, 12, 13, 14, 15, 16],
                        SamplingParams(max_tokens=16, ignore_eos=True))
        return _drive(eng)

    assert run(True) == run(False)


def test_mixed_overlap_interaction(engine_factory):
    """Overlap + mixed: a matching in-flight speculation is consumed as
    the decode half of the mixed step (mixed steps count as decode steps
    for the pipeline), a composition change rolls it back, and the
    streams never contain stale tokens — they match the fully
    synchronous engine exactly."""
    rng = np.random.default_rng(21)
    late = _chunked_late(rng, n=2)
    base = [
        ("a", [1, 2, 3], SamplingParams(max_tokens=24, ignore_eos=True)),
        # finishes right around the arrival: composition change
        ("b", [4, 5, 6], SamplingParams(max_tokens=7, ignore_eos=True)),
    ]

    def run(overlap):
        eng = engine_factory(
            mixed_steps=True, overlap_decode=overlap, decode_steps=1
        )
        for rid, p, s in base:
            eng.add_request(rid, p, s)
        return _drive(eng, late), eng.metrics

    ref, _ = run(False)
    got, m = run(True)
    assert got == ref
    # the pipeline engaged across mixed steps...
    assert m.overlap_dispatches > 0 and m.overlap_hits > 0
    # ...and every dispatch was either consumed or rolled back
    assert m.overlap_hits + m.overlap_rollbacks == m.overlap_dispatches
    assert m.mixed_dispatches > 0


def test_mixed_speculation_rides_through_backlog(engine_factory):
    """While a long prompt drains chunk by chunk, the decode rows are
    stable — the engine must keep speculating (decode_rows_stable), so
    overlap hits accumulate DURING the mixed phase, not just after."""
    rng = np.random.default_rng(2)
    eng = engine_factory(mixed_steps=True, decode_steps=1)
    eng.add_request("w", [1, 2, 3], SamplingParams(max_tokens=30, ignore_eos=True))
    out = {}

    def pump(n=None):
        while eng.has_work if n is None else n:
            for o in eng.step():
                out.setdefault(o.request_id, []).extend(o.new_token_ids)
            if n is not None:
                n -= 1

    pump(4)
    hits_before = eng.metrics.overlap_hits
    # 28-token prompt: 2 chunks => at least one mixed step with no piece
    # completing (the mid-prompt chunk), where speculation must engage
    eng.add_request(
        "long", [int(x) for x in rng.integers(1, 200, 28)],
        SamplingParams(max_tokens=4, ignore_eos=True),
    )
    pump()
    assert eng.metrics.overlap_hits > hits_before
    assert eng.metrics.mixed_dispatches > 0
    sync = engine_factory(mixed_steps=True, overlap_decode=False,
                          decode_steps=1)
    sync.add_request("w", [1, 2, 3], SamplingParams(max_tokens=30, ignore_eos=True))
    ref = sync.run_to_completion()
    assert out["w"] == ref["w"]


def test_mixed_off_never_schedules_mixed(engine_factory):
    """--no-mixed-steps: the scheduler never emits mixed batches and the
    jit cache holds no mixed programs — the XOR path is untouched."""
    rng = np.random.default_rng(8)
    eng = engine_factory(mixed_steps=False, decode_steps=1)
    eng.add_request("a", [1, 2, 3], SamplingParams(max_tokens=12, ignore_eos=True))
    _drive(eng, _chunked_late(rng))
    assert eng.metrics.mixed_dispatches == 0
    assert not any(k[0] == "mixed" for k in eng._jit_cache)


def test_compile_cache_family_stays_finite(engine_factory):
    """Acceptance: no per-request shapes. Every _get_step_fn cache key
    stays inside the finite family — mixed keys are (b_decode_bucket,
    t_prefill_bucket, b_prefill_bucket) with bucketed members — and
    re-running the same workload shape with NEW requests adds no keys."""
    rng = np.random.default_rng(17)
    # overlap off => the fused mixed program (the overlap split path
    # dispatches the pure prefill/decode programs instead)
    eng = engine_factory(
        mixed_steps=True, decode_steps=1, overlap_decode=False
    )

    def wave(tag):
        for i in range(3):
            eng.add_request(
                f"{tag}w{i}", [int(x) for x in rng.integers(1, 200, 2 + i)],
                SamplingParams(max_tokens=14, ignore_eos=True),
            )
        late = [
            (
                f"{tag}l{i}",
                [int(x) for x in rng.integers(1, 200, 18 + 3 * i)],
                SamplingParams(max_tokens=4, ignore_eos=True),
            )
            for i in range(3)
        ]
        _drive(eng, late)

    wave("x")
    keys = set(eng._jit_cache)
    cfg = eng.config
    known_kinds = {
        "prefill", "prefill_nosample", "decode", "decode_multi", "mixed",
        "spec_verify", "embed",
    }
    pow2 = {1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024}
    for key in keys:
        if not isinstance(key[0], str) or key[0] not in known_kinds:
            continue  # extract/inject helper entries
        kind, b, t = key[0], key[1], key[2]
        if kind == "mixed":
            b_pre = key[9]
            assert b in cfg.decode_buckets, key
            assert t in pow2 and t <= max(cfg.prefill_chunk, 32), key
            assert b_pre in pow2 and b_pre <= cfg.max_seqs, key
    assert any(k[0] == "mixed" for k in keys)
    # same shapes, different requests => zero new programs
    wave("y")
    assert set(eng._jit_cache) == keys


def test_bucket_t_guard_rejects_oversized_piece(engine_factory):
    """Satellite bugfix: the T bucket used to cap by silently rounding
    DOWN (truncating the valid mask); oversized pieces must raise."""
    eng = engine_factory()
    cap = max(eng.config.prefill_chunk, 32)
    assert eng._bucket_t(cap) == cap
    with pytest.raises(ValueError, match="T-bucket cap"):
        eng._bucket_t(cap + 1)


def test_decode_stall_histogram_observed(engine_factory):
    """dynamo_tpu_phase_decode_stall_ms: gaps between a running request's
    token emissions with a prefill-carrying dispatch in between land in
    the histogram (both schedulers), and the exposition passes promlint."""
    phases.phase_histograms.reset()
    rng = np.random.default_rng(31)
    for mixed in (False, True):
        eng = engine_factory(mixed_steps=mixed, decode_steps=1)
        eng.add_request("a", [1, 2, 3], SamplingParams(max_tokens=16, ignore_eos=True))
        _drive(eng, _chunked_late(rng))
    text = "\n".join(phases.expose_lines()) + "\n"
    assert "# TYPE dynamo_tpu_phase_decode_stall_ms histogram" in text
    assert "dynamo_tpu_phase_decode_stall_ms_count" in text
    assert promlint.lint(text) == []
    phases.phase_histograms.reset()


def test_mixed_outputs_marked_for_span_attribute(engine_factory):
    """StepOutputs emitted by a mixed step carry mixed=True (the engine
    span's `mixed` attribute rides this through output_to_dict)."""
    from dynamo_tpu.engine.async_engine import output_to_dict

    rng = np.random.default_rng(23)
    eng = engine_factory(mixed_steps=True, decode_steps=1)
    eng.add_request("a", [1, 2, 3], SamplingParams(max_tokens=16, ignore_eos=True))
    flagged = []
    steps = 0
    added = False
    while eng.has_work:
        before = eng.metrics.mixed_dispatches
        outs = eng.step()
        for o in outs:
            if eng.metrics.mixed_dispatches > before:
                flagged.append(o.mixed)
            d = output_to_dict(o)
            assert d.get("mixed", False) == o.mixed
        steps += 1
        if steps == 4 and not added:
            eng.add_request(
                "late", [int(x) for x in rng.integers(1, 200, 20)],
                SamplingParams(max_tokens=4, ignore_eos=True),
            )
            added = True
    assert flagged and all(flagged)

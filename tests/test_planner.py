"""Planner: predictors, perf interpolation, and scaling policy cores."""

import asyncio
import sys

import pytest

from dynamo_tpu.planner import (
    ConstantPredictor,
    LoadPlanner,
    LocalConnector,
    MovingAveragePredictor,
    PerfInterpolator,
    PlannerConfig,
    RecordingConnector,
    SlaPlanner,
    TrendPredictor,
    make_predictor,
)
from dynamo_tpu.planner.planner import Decision, FleetState, PlannerRunner, SlaTargets


def _state(**kw):
    base = dict(
        num_decode=2, num_prefill=1, kv_usage=0.5, num_waiting=0,
        prefill_queue_depth=0, request_rate=0.0,
    )
    base.update(kw)
    return FleetState(**base)


# -- predictors -------------------------------------------------------------


def test_constant_predictor():
    p = ConstantPredictor()
    assert p.predict() == 0.0
    p.observe(5)
    p.observe(9)
    assert p.predict() == 9.0


def test_moving_average_window():
    p = MovingAveragePredictor(window=3)
    for v in (3, 6, 9, 12):
        p.observe(v)
    assert p.predict() == pytest.approx((6 + 9 + 12) / 3)


def test_trend_predictor_extrapolates_ramp():
    p = TrendPredictor(window=4)
    for v in (10, 20, 30, 40):
        p.observe(v)
    assert p.predict() == pytest.approx(50.0)  # linear ramp continues


def test_trend_predictor_never_negative():
    p = TrendPredictor(window=4)
    for v in (40, 20, 5, 0):
        p.observe(v)
    assert p.predict() >= 0.0


def test_make_predictor_rejects_unknown():
    with pytest.raises(ValueError):
        make_predictor("prophet")


def test_ar_predictor_tracks_ramp():
    from dynamo_tpu.planner.load_predictor import ArPredictor

    p = ArPredictor(window=16, p=2, d=1)
    for i in range(12):
        p.observe(10.0 * i)
    # differenced series is constant 10 -> forecast continues the ramp
    assert p.predict() == pytest.approx(120.0, rel=0.05)


def test_ar_predictor_flat_series_stays_flat():
    from dynamo_tpu.planner.load_predictor import ArPredictor

    p = ArPredictor(window=16, p=3, d=1)
    for _ in range(12):
        p.observe(7.0)
    assert p.predict() == pytest.approx(7.0, abs=0.5)


def test_ar_predictor_never_negative():
    from dynamo_tpu.planner.load_predictor import ArPredictor

    p = ArPredictor(window=16, p=2, d=1)
    for v in (50, 30, 15, 5, 1, 0, 0):
        p.observe(v)
    assert p.predict() >= 0.0


def test_ar_predictor_order_validation():
    from dynamo_tpu.planner.load_predictor import ArPredictor

    with pytest.raises(ValueError):
        ArPredictor(window=3, p=3, d=1)
    with pytest.raises(ValueError):
        ArPredictor(p=0)
    with pytest.raises(ValueError):
        ArPredictor(d=2)


def test_holt_winters_learns_seasonality():
    from dynamo_tpu.planner.load_predictor import HoltWintersPredictor

    # period-4 sawtooth: 0, 10, 20, 10 repeating
    season = [0.0, 10.0, 20.0, 10.0]
    p = HoltWintersPredictor(season_length=4)
    for cycle in range(8):
        for v in season:
            p.observe(v)
    # next slot is phase 0 -> forecast near the low point, nowhere
    # near the series mean (10): seasonality was actually learned
    assert p.predict() < 5.0


def test_holt_winters_no_season_tracks_trend():
    from dynamo_tpu.planner.load_predictor import HoltWintersPredictor

    p = HoltWintersPredictor(alpha=0.8, beta=0.5)
    for i in range(20):
        p.observe(5.0 * i)
    assert p.predict() == pytest.approx(100.0, rel=0.1)


def test_make_predictor_arima_and_hw():
    from dynamo_tpu.planner.load_predictor import (
        ArPredictor,
        HoltWintersPredictor,
    )

    assert isinstance(make_predictor("arima", window=16), ArPredictor)
    hw = make_predictor("holt_winters", season_length=6)
    assert isinstance(hw, HoltWintersPredictor)
    assert hw.m == 6


# -- perf interpolation -----------------------------------------------------


def test_interpolator_midpoints_and_clamps():
    t = PerfInterpolator([1, 2, 4], [100, 200, 400])
    assert t.at(1.5) == pytest.approx(150)
    assert t.at(3) == pytest.approx(300)
    assert t.at(0) == 100  # clamped
    assert t.at(10) == 400


def test_max_load_within_target():
    t = PerfInterpolator([1, 2, 4], [100, 200, 400])
    assert t.max_load_within(300) == pytest.approx(3.0)
    assert t.max_load_within(50) == 0.0  # unreachable
    assert t.max_load_within(1000) == 4.0  # everything qualifies


# -- load planner policy ----------------------------------------------------


def test_scale_up_on_kv_pressure():
    p = LoadPlanner(PlannerConfig(max_decode=4))
    d = p.tick(_state(kv_usage=0.9))
    assert d.target_decode == 3


def test_scale_up_on_queue_pressure():
    p = LoadPlanner(PlannerConfig(waiting_per_worker_high=4.0))
    d = p.tick(_state(num_waiting=8))  # 4 per worker
    assert d.target_decode == 3


def test_scale_down_requires_stable_calm():
    p = LoadPlanner(PlannerConfig(down_stable_ticks=3, min_decode=1))
    for _ in range(2):
        assert p.tick(_state(kv_usage=0.1)).target_decode == 2
    assert p.tick(_state(kv_usage=0.1)).target_decode == 1
    # a pressure blip resets the calm streak
    p2 = LoadPlanner(PlannerConfig(down_stable_ticks=3))
    p2.tick(_state(kv_usage=0.1))
    p2.tick(_state(kv_usage=0.9))  # blip
    assert p2.tick(_state(kv_usage=0.1)).target_decode == 2
    assert p2.tick(_state(kv_usage=0.1)).target_decode == 2


def test_bounds_respected():
    p = LoadPlanner(PlannerConfig(min_decode=2, max_decode=3))
    assert p.tick(_state(num_decode=3, kv_usage=0.99)).target_decode == 3
    p2 = LoadPlanner(PlannerConfig(min_decode=2, max_decode=3, down_stable_ticks=1))
    assert p2.tick(_state(num_decode=2, kv_usage=0.0)).target_decode == 2


def test_prefill_scales_with_queue_depth():
    p = LoadPlanner(
        PlannerConfig(
            prefill_queue_per_worker_high=2.0, max_prefill=4, down_stable_ticks=2
        )
    )
    d = p.tick(_state(num_prefill=1, prefill_queue_depth=3))
    assert d.target_prefill == 2
    # scale-down needs sustained emptiness (same hysteresis as decode)
    d = p.tick(_state(num_prefill=2, prefill_queue_depth=0))
    assert d.target_prefill == 2
    d = p.tick(_state(num_prefill=2, prefill_queue_depth=0))
    assert d.target_prefill == 1


def test_prefill_down_hysteresis_resets_on_backlog():
    p = LoadPlanner(PlannerConfig(down_stable_ticks=2, max_prefill=4))
    p.tick(_state(num_prefill=2, prefill_queue_depth=0))
    p.tick(_state(num_prefill=2, prefill_queue_depth=1))  # backlog blip
    d = p.tick(_state(num_prefill=2, prefill_queue_depth=0))
    assert d.target_prefill == 2  # streak restarted


# -- SLA planner ------------------------------------------------------------


def _sla(cfg=None, **kw):
    # one worker keeps TTFT<=200ms up to 2 req/s and ITL<=20ms up to 3 req/s
    return SlaPlanner(
        cfg or PlannerConfig(min_decode=1, max_decode=8),
        SlaTargets(ttft_ms=200, itl_ms=20),
        ttft_vs_rate=PerfInterpolator([0.5, 2, 4], [50, 200, 500]),
        itl_vs_rate=PerfInterpolator([0.5, 3, 6], [5, 20, 80]),
        **kw,
    )


def test_sla_sizes_fleet_from_predicted_rate():
    p = _sla(predictor="constant")
    # capacity = min(2, 3) = 2 req/s per worker; 5 req/s -> 3 workers
    d = p.tick(_state(request_rate=5.0))
    assert d.target_decode == 3


def test_sla_scales_ahead_of_ramp():
    p = _sla(predictor="trend", predictor_window=4)
    for rate in (1.0, 2.0, 3.0, 4.0):
        d = p.tick(_state(request_rate=rate))
    # trend predicts ~5 req/s next -> 3 workers, before the load arrives
    assert d.target_decode == 3


def test_sla_unreachable_pins_max():
    p = SlaPlanner(
        PlannerConfig(min_decode=1, max_decode=4),
        SlaTargets(ttft_ms=10, itl_ms=1),  # unreachable
        ttft_vs_rate=PerfInterpolator([1, 2], [100, 200]),
        itl_vs_rate=PerfInterpolator([1, 2], [10, 20]),
    )
    assert p.tick(_state(request_rate=0.5)).target_decode == 4


# -- runner + connectors ----------------------------------------------------


def test_runner_actuates_only_deltas():
    async def main():
        conn = RecordingConnector()
        states = iter(
            [
                _state(kv_usage=0.9, num_prefill=0),  # pressure -> decode 3
                _state(num_decode=3, kv_usage=0.5, num_prefill=0),  # steady
            ]
        )

        async def observe():
            return next(states)

        runner = PlannerRunner(
            LoadPlanner(PlannerConfig()), conn, observe, interval_s=0.01
        )
        await runner.step()
        await runner.step()
        return conn.calls

    calls = asyncio.run(main())
    assert calls == [("decode", 3, 2)]


def _sleeper(role):
    return [sys.executable, "-c", "import time; time.sleep(60)"]


def test_local_connector_spawns_and_reaps():
    async def main():
        conn = LocalConnector(_sleeper)
        try:
            await conn.scale("decode", 2, observed=0)
            assert conn.alive("decode") == 2
            # a repeat tick before registration must not double-spawn
            await conn.scale("decode", 2, observed=0)
            assert conn.alive("decode") == 2
            # both register, then load spikes: the registered children no
            # longer count as pending, so a real spawn happens immediately
            await conn.scale("decode", 3, observed=2)
            assert conn.alive("decode") == 3
            # scale back down to zero
            await conn.scale("decode", 1, observed=3)
            assert conn.alive("decode") == 1
            await conn.scale("decode", 0, observed=1)
            assert conn.alive("decode") == 0
        finally:
            conn.stop_all()

    asyncio.run(main())


def test_local_connector_counts_external_workers():
    async def main():
        conn = LocalConnector(_sleeper)
        try:
            # 2 externally started workers observed; target 3 -> spawn ONE
            await conn.scale("decode", 3, observed=2)
            assert conn.alive("decode") == 1
            # already-spawned-but-unregistered child is pending capacity:
            # the next tick still observes 2 and must not double-spawn
            await conn.scale("decode", 3, observed=2)
            assert conn.alive("decode") == 1
            # once the grace window lapses without registration, the child is
            # presumed wedged and capacity is re-spawned
            conn.startup_grace_s = 0.0
            await conn.scale("decode", 3, observed=2)
            assert conn.alive("decode") == 2
        finally:
            conn.stop_all()

    asyncio.run(main())


def test_local_connector_cannot_stop_external_workers():
    async def main():
        conn = LocalConnector(_sleeper)
        # observed 3 external workers, own none; scale down is a no-op
        await conn.scale("decode", 2, observed=3)
        assert conn.alive("decode") == 0

    asyncio.run(main())

"""Llama-4 (Scout-style text) vs HuggingFace Llama4ForCausalLM.

The 4-layer tiny config exercises every delta in one forward: interleaved
rope, the every-4th-layer NoPE pattern with temperature tuning, chunked
attention (chunk 4 < T so the mask bites), weightless L2 q/k norm, and
the sigmoid top-1 INPUT-scaled MoE routing with a shared expert.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.models.llama import init_kv_pages
from dynamo_tpu.models.moe import (
    MoeConfig,
    forward,
    init_params,
    params_from_torch_state_dict,
)

PAGE_SIZE = 4


def _hf_model(cfg: MoeConfig):
    torch = pytest.importorskip("torch")
    from transformers import Llama4ForCausalLM, Llama4TextConfig

    b = cfg.base
    hf_cfg = Llama4TextConfig(
        vocab_size=b.vocab_size,
        hidden_size=b.hidden_size,
        intermediate_size=b.intermediate_size,
        intermediate_size_mlp=2 * b.intermediate_size,  # dense layers: unused
        num_hidden_layers=b.num_layers,
        num_attention_heads=b.num_heads,
        num_key_value_heads=b.num_kv_heads,
        head_dim=b.head_dim,
        num_local_experts=cfg.num_experts,
        num_experts_per_tok=cfg.top_k,
        interleave_moe_layer_step=1,
        rope_theta=b.rope_theta,
        rope_scaling=None,
        rms_norm_eps=b.rms_norm_eps,
        attention_chunk_size=b.attention_chunk,
        floor_scale=b.attn_floor_scale,
        attn_scale=b.attn_scale_coef,
        attn_temperature_tuning=b.attn_temperature_tuning,
        use_qk_norm=b.qk_l2_norm,
        attention_bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(17)
    return Llama4ForCausalLM(hf_cfg).eval()


def _run_paged(cfg, params, toks):
    b, t = toks.shape
    kv = init_kv_pages(cfg.base, 64, PAGE_SIZE)
    n_pages = -(-t // PAGE_SIZE)
    pts = np.zeros((b, n_pages), np.int32)
    for i in range(b):
        pts[i] = np.arange(1 + i * n_pages, 1 + (i + 1) * n_pages)
    positions = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    logits, _ = forward(
        params, cfg, jnp.asarray(toks), jnp.asarray(positions),
        jnp.ones((b, t), bool), kv, jnp.asarray(pts),
    )
    return np.asarray(logits)


def test_against_hf_llama4():
    torch = pytest.importorskip("torch")
    cfg = MoeConfig.llama4_tiny()
    model = _hf_model(cfg)
    # 4 layers: the every-4th NoPE pattern must match HF's
    assert model.config.no_rope_layers == [1, 1, 1, 0]
    params = params_from_torch_state_dict(model.state_dict(), cfg)
    assert "ws_gate" in params["layers"]

    rng = np.random.default_rng(9)
    # T=12 spans 3 chunks of 4, so the chunked mask bites; positions past
    # floor_scale=4 make the NoPE temperature tuning non-trivial
    toks = rng.integers(0, cfg.base.vocab_size, size=(2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    ours = _run_paged(cfg, params, toks)
    np.testing.assert_allclose(ours, ref, rtol=5e-2, atol=5e-2)
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.95


def test_llama4_deltas_all_matter():
    """Each architectural delta must actually flow through the forward."""
    from dataclasses import replace

    cfg = MoeConfig.llama4_tiny()
    params = init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 256, size=(1, 12)).astype(np.int32)
    base_out = _run_paged(cfg, params, toks)

    def variant(**base_kw):
        return replace(cfg, base=replace(cfg.base, **base_kw))

    for name, v in (
        ("interleaved rope", variant(rope_interleaved=False)),
        ("NoPE pattern", variant(nope_every=0)),
        ("qk l2 norm", variant(qk_l2_norm=False)),
        ("temp tuning", variant(attn_temperature_tuning=False)),
        ("chunked attention", variant(attention_chunk=0)),
    ):
        assert not np.allclose(base_out, _run_paged(v, params, toks)), name
    # the shared expert too (drop it from the gate semantics side)
    no_shared = replace(cfg, shared_expert=False)
    assert not np.allclose(base_out, _run_paged(no_shared, params, toks))


def test_llama4_decode_continuation_matches_full_prefill():
    """Paged decode (T=1 continuation) under chunked attention + NoPE must
    reproduce the full-prefill logits — the chunk mask is position-driven,
    not chunk-boundary-driven."""
    cfg = MoeConfig.llama4_tiny()
    params = init_params(jax.random.key(2), cfg)
    rng = np.random.default_rng(3)
    toks = rng.integers(0, 256, size=(1, 10)).astype(np.int32)
    full = _run_paged(cfg, params, toks)

    kv = init_kv_pages(cfg.base, 64, PAGE_SIZE)
    pts = jnp.asarray(np.arange(1, 5, dtype=np.int32)[None])
    logits, kv = forward(
        params, cfg, jnp.asarray(toks[:, :6]),
        jnp.asarray(np.arange(6, dtype=np.int32)[None]),
        jnp.ones((1, 6), bool), kv, pts,
    )
    steps = [np.asarray(logits)[:, -1]]
    for t in range(6, 10):
        logits, kv = forward(
            params, cfg, jnp.asarray(toks[:, t : t + 1]),
            jnp.asarray(np.array([[t]], np.int32)),
            jnp.ones((1, 1), bool), kv, pts,
        )
        steps.append(np.asarray(logits)[:, -1])
    np.testing.assert_allclose(
        np.stack(steps, axis=1), full[:, 5:10], rtol=2e-4, atol=2e-4
    )


def test_llama4_presets_and_refusals():
    from dynamo_tpu.models.registry import get_model

    adapter = get_model("llama4-tiny", dtype="float32")
    assert adapter.config.gate == "llama4"
    assert adapter.config.base.nope_every == 4

    scout = MoeConfig.llama4_scout_text()
    assert scout.base.attention_chunk == 8192
    assert scout.base.rope_scaling_factor == 8.0  # llama3 NTK path

    # Maverick-style dense interleaving is refused, not served wrong
    with pytest.raises(ValueError, match="interleave"):
        MoeConfig.from_hf_config(
            {
                "model_type": "llama4_text",
                "architectures": ["Llama4ForCausalLM"],
                "interleave_moe_layer_step": 2,
                "vocab_size": 256, "hidden_size": 64,
                "intermediate_size": 32, "num_hidden_layers": 4,
                "num_attention_heads": 4,
            }
        )


def test_from_hf_config_empty_no_rope_list_defaults():
    """HF serializes no_rope_layers as [] meaning 'the default pattern'
    (every no_rope_layer_interval-th layer NoPE) — an empty list must NOT
    silently disable NoPE."""
    from dynamo_tpu.models.llama import LlamaConfig

    hf = {
        "model_type": "llama4_text",
        "architectures": ["Llama4ForCausalLM"],
        "no_rope_layers": [],
        "vocab_size": 256, "hidden_size": 64, "intermediate_size": 32,
        "num_hidden_layers": 8, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 16,
        "attention_chunk_size": 8192,
    }
    cfg = LlamaConfig.from_hf_config(hf)
    assert cfg.nope_every == 4
    assert cfg.rope_interleaved and cfg.qk_l2_norm
    # explicit pattern roundtrips too
    hf["no_rope_layers"] = [1, 1, 1, 0, 1, 1, 1, 0]
    assert LlamaConfig.from_hf_config(hf).nope_every == 4


def test_llama4_serves_under_tp_mesh(cpu_mesh_devices):
    """Shared-expert weights need sharding specs (missing leaves only
    explode under a mesh); tp must not change tokens."""
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.parallel.mesh import MeshConfig

    outs = {}
    for tp in (1, 2):
        eng = JaxEngine(
            EngineConfig(
                model="llama4-tiny", num_pages=64, page_size=4,
                max_pages_per_seq=8, decode_buckets=(1, 2),
                prefill_chunk=16, max_seqs=2, dtype="float32", tp=tp,
            ),
            mesh_config=MeshConfig(dp=1, tp=tp) if tp > 1 else None,
        )
        eng.add_request(
            "r", [5, 17, 42, 9, 3, 8],
            SamplingParams(temperature=0.0, max_tokens=3),
        )
        outs[tp] = eng.run_to_completion()["r"]
    assert outs[1] == outs[2]

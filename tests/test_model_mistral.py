"""Mistral family (Llama + sliding-window attention on EVERY layer) vs
HuggingFace MistralForCausalLM through the paged KV cache."""

from dataclasses import replace

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_kv_pages,
    params_from_torch_state_dict,
)

PAGE_SIZE = 4


def _tiny_mistral_cfg():
    return replace(
        LlamaConfig.tiny(),
        dtype=jnp.float32,
        sliding_window=6,  # < seq len: the window really truncates
        sliding_window_every=1,
    )


def _run_paged(cfg, params, toks, chunks=None):
    b, t = toks.shape
    kv = init_kv_pages(cfg, 64, PAGE_SIZE)
    n_pages = -(-t // PAGE_SIZE)
    pts = np.zeros((b, n_pages), np.int32)
    for i in range(b):
        pts[i] = np.arange(1 + i * n_pages, 1 + (i + 1) * n_pages)
    outs = []
    for start, end in chunks or [(0, t)]:
        positions = np.tile(np.arange(start, end, dtype=np.int32), (b, 1))
        logits, kv = forward(
            params, cfg, jnp.asarray(toks[:, start:end]),
            jnp.asarray(positions),
            jnp.ones((b, end - start), bool), kv, jnp.asarray(pts),
        )
        outs.append(np.asarray(logits))
    return np.concatenate(outs, axis=1)


def test_against_hf_mistral():
    torch = pytest.importorskip("torch")
    from transformers import MistralConfig, MistralForCausalLM

    cfg = _tiny_mistral_cfg()
    hf_cfg = MistralConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps,
        sliding_window=cfg.sliding_window,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(9)
    model = MistralForCausalLM(hf_cfg).eval()
    params = params_from_torch_state_dict(model.state_dict(), cfg)

    rng = np.random.default_rng(2)
    toks = rng.integers(0, cfg.vocab_size, size=(2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    ours = _run_paged(cfg, params, toks)
    np.testing.assert_allclose(ours, ref, rtol=5e-2, atol=5e-2)
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.95

    # window truly active: disabling it must change the tail positions
    no_window = _run_paged(replace(cfg, sliding_window=0), params, toks)
    assert not np.allclose(no_window, ours)

    # decode continuation through the paged cache
    chunked = _run_paged(cfg, params, toks, chunks=[(0, 8), (8, 12)])
    np.testing.assert_allclose(chunked, ours, rtol=1e-4, atol=1e-4)


def test_mistral_registry_resolution():
    from dynamo_tpu.models.registry import get_model

    adapter = get_model("mistral-7b", dtype="float32",
                        attention_impl="pallas")
    c = adapter.config
    assert c.sliding_window == 4096 and c.sliding_window_every == 1
    assert c.attention_impl == "xla"  # windowed attention forces xla

"""One host of the multi-process SPMD serving test.

Spawned by tests/test_spmd_serve.py (2 processes x 4 virtual CPU devices
-> one 8-device global mesh). The leader admits a fixed greedy workload
and writes the generated tokens as JSON; followers mirror every step via
SpmdDriver.serve(). Run directly only through the test.
"""

import argparse
import json
import os
import sys
from pathlib import Path

REPO = str(Path(__file__).resolve().parents[2])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host-id", type=int, required=True)
    ap.add_argument("--num-hosts", type=int, required=True)
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--devices-per-host", type=int, default=4)
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--tier", action="store_true",
                    help="host-tier config + two-phase evict/onboard "
                         "workload (per-host shard tiering)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.devices_per_host}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    sys.path.insert(0, REPO)

    from dynamo_tpu.parallel.mesh import init_multihost

    n = init_multihost(args.coordinator, args.num_hosts, args.host_id)
    assert n == args.num_hosts * args.devices_per_host, n

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.engine.spmd import SpmdDriver

    cfg = (
        spmd_tier_config(args.dp, args.tp)
        if args.tier
        else spmd_test_config(args.dp, args.tp)
    )
    eng = JaxEngine(cfg)
    drv = SpmdDriver(eng)
    if drv.is_leader:
        done = {}
        for phase in (
            spmd_tier_workload() if args.tier else [spmd_test_workload()]
        ):
            for rid, toks, mt in phase:
                drv.submit(
                    rid, toks, SamplingParams(temperature=0.0, max_tokens=mt)
                )
            done.update(drv.run_to_completion())
        drv.shutdown()
        out = dict(done)
        if args.tier:
            out = {
                "outputs": done,
                "offloaded": eng.allocator.stats.offloaded_blocks,
                "onboarded": eng.allocator.stats.onboarded_blocks,
            }
        Path(args.out).write_text(json.dumps(out))
    else:
        drv.serve()


def spmd_test_config(dp: int, tp: int):
    """Shared by the multi-process hosts and the single-process
    reference run — identical config => identical programs."""
    from dynamo_tpu.engine import EngineConfig

    return EngineConfig(
        model="tiny",
        dp=dp,
        tp=tp,
        num_pages=64,
        page_size=4,
        max_pages_per_seq=16,
        decode_buckets=(2, 4),
        prefill_chunk=32,
        prefill_token_budget=128,
        decode_steps=4,
        max_seqs=8,
        dtype="float32",
        enable_prefix_caching=True,
    )


def spmd_tier_config(dp: int, tp: int):
    """Lockstep config with a host KV tier and a pool small enough that
    the churn workload forces evictions through it."""
    from dataclasses import replace

    # pool sized so the churn phase MUST evict the pinned prompt's cached
    # blocks through the host tier (each churn request alone nearly fills
    # the free pool)
    return replace(
        spmd_test_config(dp, tp),
        num_pages=16,
        host_kv_cache_bytes=1 << 22,
    )


def spmd_tier_workload():
    """Two phases: (A) a pinned prompt + churn that evicts its cached
    blocks into the host tier, (B) the same prompt again — blocks must
    onboard from each host's tier shard, byte-identically."""
    import numpy as np

    rng = np.random.default_rng(23)
    prompt_a = [int(x) for x in rng.integers(1, 200, 16)]
    phase_a = [("a0", prompt_a, 6)] + [
        (f"churn{i}", [int(x) for x in rng.integers(200, 250, 20)], 4)
        for i in range(6)
    ]
    return [phase_a, [("a1", prompt_a, 6)]]


def spawn_two_hosts(
    devices_per_host: int = 4,
    dp: int = 4,
    tp: int = 2,
    timeout: float = 420.0,
    tier: bool = False,
):
    """Spawn the 2-process lockstep fleet and return (leader_outputs,
    logs). Shared by tests/test_spmd_serve.py and __graft_entry__'s
    dryrun; kills both hosts and surfaces their logs on timeout."""
    import socket
    import subprocess
    import tempfile

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    out = Path(tempfile.mkdtemp(prefix="spmd-fleet-")) / "leader.json"
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    procs = [
        subprocess.Popen(
            [
                sys.executable, __file__,
                "--host-id", str(i), "--num-hosts", "2",
                "--coordinator", f"127.0.0.1:{port}",
                "--devices-per-host", str(devices_per_host),
                "--dp", str(dp), "--tp", str(tp),
                *(["--tier"] if tier else []),
                *(["--out", str(out)] if i == 0 else []),
            ],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    logs = []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=timeout)[0])
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        for p in procs:
            try:
                logs.append(p.communicate(timeout=10)[0])
            except Exception:  # noqa: BLE001
                logs.append("<no output>")
        raise RuntimeError(
            "SPMD hosts timed out\n--- host0 ---\n"
            + (logs[0] if logs else "?")
            + "\n--- host1 ---\n"
            + (logs[1] if len(logs) > 1 else "?")
        ) from None
    for i, p in enumerate(procs):
        if p.returncode != 0:
            raise RuntimeError(
                f"SPMD host {i} rc={p.returncode}\n--- host0 ---\n"
                f"{logs[0]}\n--- host1 ---\n{logs[1]}"
            )
    return json.loads(out.read_text()), logs


_PLANE_OK = None


def collective_plane_available(timeout: float = 120.0) -> bool:
    """One cached probe of this host's cross-process collective plane:
    spawn a 2-process jax.distributed group and run a single broadcast.
    Containers without a working gloo rendezvous either error each
    collective after a ~30 s transport timeout or wedge inside one with
    no timeout at all — without this gate every fleet test burns its
    full spawn timeout on an environment that can never pass."""
    global _PLANE_OK
    if _PLANE_OK is not None:
        return _PLANE_OK
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    src = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['XLA_FLAGS'] = "
        "'--xla_force_host_platform_device_count=2'\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_cpu_collectives_implementation', 'gloo')\n"
        "from dynamo_tpu.parallel.mesh import init_multihost\n"
        f"init_multihost('127.0.0.1:{port}', 2, int(sys.argv[1]))\n"
        "import numpy as np\n"
        "from jax.experimental import multihost_utils\n"
        "v = multihost_utils.broadcast_one_to_all(\n"
        "    np.int32(7), is_source=(sys.argv[1] == '0'))\n"
        "assert int(v) == 7\n"
        "print('PLANE_OK')\n"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", src, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    ok = True
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            ok = ok and p.returncode == 0 and "PLANE_OK" in out
    except subprocess.TimeoutExpired:
        ok = False
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.communicate(timeout=10)
            except Exception:  # noqa: BLE001
                pass
    _PLANE_OK = ok
    return ok


def spmd_test_workload():
    """(request_id, prompt_tokens, max_tokens) — deterministic, mixed
    lengths so prefill buckets AND the decode path both run."""
    import numpy as np

    rng = np.random.default_rng(11)
    return [
        (f"req{i}", [int(x) for x in rng.integers(1, 250, ln)], mt)
        for i, (ln, mt) in enumerate([(6, 8), (13, 8), (25, 6), (9, 4)])
    ]


if __name__ == "__main__":
    main()

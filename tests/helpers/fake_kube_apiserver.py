"""A kwok-style fake Kubernetes API server for operator tests.

The reference operator's test tier runs against envtest (a real
apiserver binary, deploy/cloud/operator suite_test.go). This is the same
idea sized for this repo: a threaded stdlib HTTP server speaking the
REST subset `operator/kube.InClusterKube` uses, with REAL apiserver
semantics the in-memory double can't exercise:

- wire-level JSON over HTTP with Bearer-token auth (401 on mismatch),
- resourceVersion stamped on every object, bumped on writes,
- PUT with a stale resourceVersion -> 409 Conflict (k8s Status body),
- POST of an existing name -> 409 AlreadyExists,
- 404 Status bodies for missing objects,
- labelSelector parsing on LIST,
- merge-patch on the /status subresource.

Fault injection for retry-path tests: `fail_next(code)` makes the next
mutating request fail with that HTTP code once.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

#: path prefix -> kind (mirrors operator/kube._API)
_ROUTES = [
    (r"^/apis/apps/v1/namespaces/([^/]+)/deployments(?:/([^/]+))?(/status|/scale)?$",
     "Deployment"),
    (r"^/api/v1/namespaces/([^/]+)/services(?:/([^/]+))?(/status)?$",
     "Service"),
    (r"^/apis/dynamo\.tpu/v1alpha1/namespaces/([^/]+)/"
     r"dynamographdeployments(?:/([^/]+))?(/status|/scale)?$",
     "DynamoGraphDeployment"),
    (r"^/apis/dynamo\.tpu/v1alpha1/namespaces/([^/]+)/"
     r"dynamocomponentdeployments(?:/([^/]+))?(/status|/scale)?$",
     "DynamoComponentDeployment"),
]


class FakeKubeApiServer:
    def __init__(self, token: str = "test-token"):
        self.token = token
        self._lock = threading.Lock()
        self._objs: dict[tuple[str, str, str], dict] = {}
        self._rv = 0
        self._fail_next: list[int] = []
        self.requests: list[tuple[str, str]] = []  # (method, path)

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _status(self, code: int, reason: str, message: str):
                body = json.dumps(
                    {
                        "kind": "Status", "apiVersion": "v1",
                        "status": "Failure", "reason": reason,
                        "message": message, "code": code,
                    }
                ).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _ok(self, obj, code: int = 200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self):
                parsed = urlparse(self.path)
                for pat, kind in _ROUTES:
                    m = re.match(pat, parsed.path)
                    if m:
                        ns, name, sub = m.group(1), m.group(2), m.group(3)
                        return kind, ns, name, (sub or "").lstrip("/"), \
                            parse_qs(parsed.query)
                return None

            def _authed(self) -> bool:
                return (
                    self.headers.get("Authorization")
                    == f"Bearer {server.token}"
                )

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n)) if n else None

            def _handle(self, method: str):
                server.requests.append((method, self.path))
                if not self._authed():
                    return self._status(401, "Unauthorized", "bad token")
                route = self._route()
                if route is None:
                    return self._status(404, "NotFound", self.path)
                kind, ns, name, is_status, query = route
                if method in ("POST", "PUT", "DELETE", "PATCH"):
                    with server._lock:
                        if server._fail_next:
                            code = server._fail_next.pop(0)
                            return self._status(
                                code,
                                {409: "Conflict", 401: "Unauthorized"}.get(
                                    code, "Failure"
                                ),
                                "injected fault",
                            )
                fn = getattr(self, f"_do_{method.lower()}")
                return fn(kind, ns, name, is_status, query)

            def _do_get(self, kind, ns, name, is_status, query):
                with server._lock:
                    if name:
                        obj = server._objs.get((kind, ns, name))
                        if obj is None:
                            return self._status(
                                404, "NotFound", f"{kind} {ns}/{name}"
                            )
                        return self._ok(obj)
                    sel = {}
                    for raw in query.get("labelSelector", []):
                        for part in unquote(raw).split(","):
                            if "=" in part:
                                k, v = part.split("=", 1)
                                sel[k] = v
                    items = [
                        o
                        for (k, n_, _), o in sorted(server._objs.items())
                        if k == kind and n_ == ns and all(
                            (o.get("metadata", {}).get("labels") or {})
                            .get(sk) == sv
                            for sk, sv in sel.items()
                        )
                    ]
                    return self._ok({"kind": f"{kind}List", "items": items})

            def _do_post(self, kind, ns, name, is_status, query):
                obj = self._body()
                oname = obj["metadata"]["name"]
                with server._lock:
                    key = (kind, ns, oname)
                    if key in server._objs:
                        return self._status(
                            409, "AlreadyExists", f"{kind} {ns}/{oname}"
                        )
                    server._rv += 1
                    obj.setdefault("metadata", {})["namespace"] = ns
                    obj["metadata"]["resourceVersion"] = str(server._rv)
                    server._objs[key] = obj
                    return self._ok(obj, 201)

            def _do_put(self, kind, ns, name, is_status, query):
                obj = self._body()
                with server._lock:
                    key = (kind, ns, name)
                    cur = server._objs.get(key)
                    if cur is None:
                        return self._status(
                            404, "NotFound", f"{kind} {ns}/{name}"
                        )
                    sent_rv = obj.get("metadata", {}).get("resourceVersion")
                    if sent_rv and sent_rv != cur["metadata"][
                        "resourceVersion"
                    ]:
                        return self._status(
                            409, "Conflict",
                            f"resourceVersion {sent_rv} != "
                            f"{cur['metadata']['resourceVersion']}",
                        )
                    server._rv += 1
                    obj.setdefault("metadata", {})["namespace"] = ns
                    obj["metadata"]["resourceVersion"] = str(server._rv)
                    server._objs[key] = obj
                    return self._ok(obj)

            def _do_delete(self, kind, ns, name, is_status, query):
                with server._lock:
                    if server._objs.pop((kind, ns, name), None) is None:
                        return self._status(
                            404, "NotFound", f"{kind} {ns}/{name}"
                        )
                    return self._ok({"kind": "Status", "status": "Success"})

            def _do_patch(self, kind, ns, name, is_status, query):
                patch = self._body()
                with server._lock:
                    obj = server._objs.get((kind, ns, name))
                    if obj is None:
                        return self._status(
                            404, "NotFound", f"{kind} {ns}/{name}"
                        )
                    if is_status == "status":
                        obj["status"] = patch.get("status", {})
                    elif is_status == "scale":
                        # the /scale subresource updates ONLY
                        # spec.replicas, like a real apiserver
                        obj.setdefault("spec", {})["replicas"] = int(
                            patch.get("spec", {}).get("replicas", 0)
                        )
                    else:
                        obj.update(patch)
                    server._rv += 1
                    obj["metadata"]["resourceVersion"] = str(server._rv)
                    return self._ok(obj)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_DELETE(self):
                self._handle("DELETE")

            def do_PATCH(self):
                self._handle("PATCH")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FakeKubeApiServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- test hooks --------------------------------------------------------

    def fail_next(self, code: int) -> None:
        """Next mutating request fails once with `code`."""
        self._fail_next.append(code)

    def seed(self, kind: str, ns: str, obj: dict) -> dict:
        """Install an object server-side (like kubectl apply by hand)."""
        with self._lock:
            self._rv += 1
            obj.setdefault("metadata", {})["namespace"] = ns
            obj["metadata"]["resourceVersion"] = str(self._rv)
            self._objs[(kind, ns, obj["metadata"]["name"])] = obj
            return obj

    def get(self, kind: str, ns: str, name: str):
        with self._lock:
            return self._objs.get((kind, ns, name))

    def delete(self, kind: str, ns: str, name: str) -> None:
        with self._lock:
            self._objs.pop((kind, ns, name), None)

    def objects(self, kind: str, ns: str) -> list[dict]:
        with self._lock:
            return [
                o for (k, n, _), o in sorted(self._objs.items())
                if k == kind and n == ns
            ]

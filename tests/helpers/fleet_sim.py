"""Mocker-backed fleet simulation harness (ISSUE 10 acceptance).

Builds an N-worker fleet of `Worker(engine_kind="mock")` processes-in-one
-process over a REAL FabricServer, drives it with a REAL PushRouter (with
crash replay), observes it with the REAL FleetObserver/ControlRunner
closed loop, and actuates scaling through a SimConnector that spawns and
retires mock workers in-process. Everything between the traffic source
and the MockEngine step loop is the production code path: fabric
registration/leases/watches, ingress TCP framing, router retry/replay,
worker metrics + SLO frames, planner signal folding, flip ingress ops.

The MockEngine is the reference mocker's shape (batched step loop, real
PageAllocator, watermark admission, chunked prefill, preemption), so
fleet-level queueing and latency under load are simulated, not faked —
its SloTracker feeds MEASURED stream latencies into the planner's
burn/attainment signals.

Chaos primitives:
- kill(i): abrupt worker death — ingress torn down with live
  connections, publishing stops, registration erased (lease-expiry
  stand-in). Routers see mid-stream drops; with replay on, client
  streams continue on survivors.
- partition(i): the worker stays alive but every live connection is
  severed once (drop_connections) — the network-blip shape.
"""

from __future__ import annotations

import asyncio
import math
import random
import time
from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.mocker import MockEngine, MockEngineArgs
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.fabric import FabricServer
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu.telemetry.slo import SlaTargets
from dynamo_tpu.worker import Worker

MODEL = "sim-tiny"
PAGE_SIZE = 16


def _card() -> ModelDeploymentCard:
    return ModelDeploymentCard(
        name=MODEL, tokenizer={"kind": "byte"}, context_length=4096,
        kv_page_size=PAGE_SIZE,
    )


@dataclass
class SimStats:
    started: int = 0
    completed: int = 0
    errored: int = 0
    #: client-side TTFT/e2e per completed request: (t_submit, ttft_s, ok)
    ttfts: list = field(default_factory=list)
    finishes: dict = field(default_factory=dict)

    @property
    def dropped(self) -> int:
        return self.started - self.completed


class FleetSim:
    def __init__(
        self,
        decode_s_per_step: float = 0.01,
        max_batch: int = 4,
        num_pages: int = 256,
        metrics_interval: float = 0.4,
        sla_ttft_ms: float = 500.0,
        slo_windows: tuple = (10.0,),
        prefill_tokens_per_step: int = 256,
    ):
        self.decode_s_per_step = decode_s_per_step
        self.max_batch = max_batch
        self.num_pages = num_pages
        self.metrics_interval = metrics_interval
        self.sla = SlaTargets(ttft_ms=sla_ttft_ms, itl_ms=None, e2e_ms=None)
        self.slo_windows = slo_windows
        self.prefill_tokens_per_step = prefill_tokens_per_step
        self.server: Optional[FabricServer] = None
        self.runtime: Optional[DistributedRuntime] = None
        self.router: Optional[PushRouter] = None
        #: KV-routed mode only (start(router="kv")): the real KvRouter
        #: whose choose() drives the PushRouter; carries the optional
        #: EconomyPolicy (ISSUE 18 — the KV economy plane)
        self.kv_router = None
        self.workers: list[Worker] = []
        self.stats = SimStats()
        self.rng = random.Random(7)
        self._rid = 0

    # -- lifecycle ---------------------------------------------------------

    async def start(
        self,
        replay: bool = True,
        router: str = "round_robin",
        economy=None,
    ) -> None:
        self.server = FabricServer(port=0)
        await self.server.start()
        # ONE runtime/fabric connection shared by every sim worker —
        # the wire protocol and watch planes are identical; only the
        # per-worker TCP connection count is collapsed, which is what
        # makes a 500-worker fleet fit one process
        self.runtime = await DistributedRuntime.create(self.server.address)
        ep = (
            self.runtime.namespace("dynamo")
            .component("backend")
            .endpoint("generate")
        )
        src = await ep.instance_source()
        if router == "kv":
            from dynamo_tpu.kv_router import KvRouter, KvRouterConfig

            self.kv_router = KvRouter(
                self.runtime.fabric, "backend", src,
                block_size=PAGE_SIZE, salt=MODEL,
                config=KvRouterConfig(temperature=0.0),
                economy=economy,
            )
            await self.kv_router.start()
            self.router = PushRouter(
                src, "generate", mode=RouterMode.KV,
                kv_chooser=self.kv_router.choose, replay=replay,
                retry_backoff_base_ms=5.0, retry_backoff_max_ms=50.0,
            )
            return
        self.router = PushRouter(
            src, "generate", mode=RouterMode.ROUND_ROBIN, replay=replay,
            # fast, bounded retries: the sim drives hundreds of streams
            retry_backoff_base_ms=5.0, retry_backoff_max_ms=50.0,
        )

    def _mock_args(self) -> MockEngineArgs:
        return MockEngineArgs(
            num_pages=self.num_pages,
            page_size=PAGE_SIZE,
            decode_s_per_step=self.decode_s_per_step,
            prefill_tokens_per_step=self.prefill_tokens_per_step,
            max_batch=self.max_batch,
            salt=MODEL,
        )

    async def add_worker(self, role: str = "decode") -> Worker:
        component, endpoint = (
            ("backend", "generate") if role == "decode" else
            ("prefill", "prefill")
        )
        w = Worker(
            self.runtime,
            _card(),
            engine_kind="mock",
            component=component,
            endpoint=endpoint,
            metrics_interval=self.metrics_interval,
            mock_args=self._mock_args(),
        )
        await w.start()
        # feed the planner's burn signal from measured latencies with a
        # short window so the sim's compressed time moves it
        w.mock.slo = type(w.mock.slo)(
            sla=self.sla, windows=self.slo_windows
        )
        self.workers.append(w)
        return w

    def alive(self, role: Optional[str] = None) -> list[Worker]:
        out = []
        for w in self.workers:
            if w.registration is None:
                continue
            if role is None or w.role == role:
                out.append(w)
        return out

    # -- chaos primitives --------------------------------------------------

    async def kill(self, w: Worker) -> None:
        """Abrupt death: live connections sever mid-stream, publishing
        stops, the registration is erased (lease-expiry stand-in)."""
        for t in w._tasks:
            t.cancel()
        await w.ingress.stop()
        try:
            await w._deregister()
        except Exception:
            pass

    def partition(self, w: Worker) -> None:
        """One network blip: every live connection drops; the worker
        stays registered and keeps serving new connections."""
        w.ingress.drop_connections()

    async def retire(self, w: Worker, drain_timeout: float = 5.0) -> None:
        """Graceful scale-down: deregister, finish in-flight, stop."""
        await w.stop(drain_timeout=drain_timeout)

    # -- traffic -----------------------------------------------------------

    def _request(self, isl: int, osl: int) -> dict:
        self._rid += 1
        prompt = [self.rng.randrange(1, 200) for _ in range(isl)]
        return {
            "request_id": f"sim-{self._rid}",
            "token_ids": prompt,
            "max_tokens": osl,
            "temperature": 0.0,
            "top_p": 1.0,
            "top_k": 0,
            "seed": None,
            "stop_token_ids": [],
            "stop_strings": [],
            "ignore_eos": True,
            "annotations": {},
        }

    async def one(self, isl: int = 24, osl: int = 8,
                  timeout: float = 30.0,
                  prompt: Optional[list] = None
                  ) -> tuple[list, Optional[str], float]:
        """Drive one stream to a terminal state. Returns (tokens,
        finish_reason, ttft_s); an exception IS a dropped stream and
        propagates to the caller's accounting. `prompt` overrides the
        random tokens (multi-turn chat sessions re-send their history)."""
        req = self._request(isl, osl)
        if prompt is not None:
            req["token_ids"] = list(prompt)
        self.stats.started += 1
        tokens: list = []
        finish = None
        t0 = time.monotonic()
        t_first = None

        async def drive():
            nonlocal finish, t_first
            async for item in self.router.generate(req, max_attempts=8):
                if not isinstance(item, dict):
                    continue
                got = item.get("token_ids") or ()
                if got and t_first is None:
                    t_first = time.monotonic()
                tokens.extend(got)
                if item.get("finish_reason"):
                    finish = item["finish_reason"]

        try:
            await asyncio.wait_for(drive(), timeout)
        except Exception:
            self.stats.errored += 1
            raise
        finally:
            if self.kv_router is not None:
                # router_pipeline does this in the frontend; the sim
                # drives PushRouter directly, so free the active-
                # sequence footprint here
                self.kv_router.on_complete(req["request_id"])
        if finish in ("length", "stop"):
            self.stats.completed += 1
            ttft = (t_first or time.monotonic()) - t0
            self.stats.ttfts.append((t0, ttft, True))
            self.stats.finishes[req["request_id"]] = finish
        else:
            self.stats.errored += 1
        return tokens, finish, (t_first or time.monotonic()) - t0

    async def drive_phase(
        self,
        seconds: float,
        rate_fn,
        isl: int = 24,
        osl: int = 8,
        timeout: float = 30.0,
    ) -> list:
        """Open-loop arrivals for `seconds`: at time t (phase-relative),
        requests arrive at rate_fn(t) req/s. Returns the list of stream
        tasks' results; every stream MUST reach a terminal state."""
        tasks: list[asyncio.Task] = []
        t0 = time.monotonic()
        while True:
            t = time.monotonic() - t0
            if t >= seconds:
                break
            rate = max(0.05, float(rate_fn(t)))
            tasks.append(
                asyncio.create_task(self.one(isl=isl, osl=osl,
                                             timeout=timeout))
            )
            await asyncio.sleep(1.0 / rate)
        return await asyncio.gather(*tasks, return_exceptions=True)

    @staticmethod
    def diurnal(base: float, amp: float, period_s: float):
        """Compressed day: rate(t) = base + amp * sin(2πt/period)."""
        return lambda t: base + amp * math.sin(2 * math.pi * t / period_s)

    # -- teardown ----------------------------------------------------------

    async def stop(self) -> None:
        if self.router is not None:
            self.router.close()
        if self.kv_router is not None:
            try:
                await self.kv_router.stop()
            except Exception:
                pass
        for w in list(self.workers):
            try:
                await w.stop(drain_timeout=0)
            except Exception:
                pass
        if self.runtime is not None:
            await self.runtime.close()
        if self.server is not None:
            await self.server.stop()


class SimConnector:
    """Planner Connector over the sim: spawn mock workers on scale-up,
    retire the youngest on scale-down (graceful drain). Records calls
    like RecordingConnector so tests can assert the actuation path."""

    def __init__(self, sim: FleetSim, max_spawn_per_call: int = 4):
        self.sim = sim
        self.max_spawn_per_call = max_spawn_per_call
        self.calls: list[tuple[str, int, int]] = []

    async def scale(self, role: str, target: int, observed: int) -> None:
        self.calls.append((role, target, observed))
        delta = target - observed
        if delta > 0:
            for _ in range(min(delta, self.max_spawn_per_call)):
                await self.sim.add_worker(role=role)
        elif delta < 0:
            victims = self.sim.alive(role)[delta:]
            for w in victims:
                await self.sim.retire(w)

"""Chaos scenario: live traffic under injected faults (the acceptance
gate of docs/operations.md "Overload & draining").

Two layers, matching the two failure planes:

- process-level (ChaosCluster over the FT harness's ManagedProc): real
  CLI fleet — fabric + frontend(--max-inflight) + echo workers — driven
  by concurrent clients while a worker is SIGKILLed, another is drained
  via SIGTERM (must exit 0 with its in-flight streams finished), the
  frontend saturates into 429s, and a deadline-carrying request 504s.
  The invariant throughout: EVERY request terminates with a real HTTP
  status inside its timeout — zero hung streams.

- in-process disagg (fabric server + decode Worker + PrefillWorker with
  the fault injector aimed at the KV transfer planes): repeated landing
  failures must dead-letter the prefill and error-finish the decode
  stream (never redeliver forever, never hang), a single transient drop
  must retry to the SAME tokens, and recovery after the faults clear
  must be bit-identical to a local reference run.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import signal
import time
import urllib.error
import urllib.request

import pytest

from dynamo_tpu.testing import faults

pytestmark = pytest.mark.slow


# -- process-level chaos ----------------------------------------------------


class ChaosCluster:
    """fabric + frontend with admission caps + echo workers, all real
    CLI processes (tests/fault_tolerance/harness.py shape, with the
    overload-plane flags wired in)."""

    def __init__(self, num_workers=2, max_inflight=4, echo_delay=0.05,
                 drain_budget=8.0, engine="echo", mock_step=None,
                 frontend_args=(), ha=False, detector_budget=1.0):
        from benchmarks._procs import free_port
        from tests.fault_tolerance.harness import ManagedProc, _cli

        self._cli = _cli
        self._ManagedProc = ManagedProc
        self.engine = engine
        self.mock_step = mock_step
        self.echo_delay = echo_delay
        self.drain_budget = drain_budget
        self.fabric_port = free_port()
        #: control-plane HA (docs/operations.md "Control-plane HA"):
        #: ha=True adds a warm standby broker and points every client at
        #: the comma list, so a primary SIGKILL fails over
        self.standby_port = free_port() if ha else None
        self.http_port = free_port()
        self.workers = []
        self.frontend = None
        self.fabric = None
        self.standby = None
        try:
            self.fabric = ManagedProc(
                "fabric", _cli("fabric", "--port", str(self.fabric_port))
            )
            self.fabric.wait_for("fabric server on|listening", timeout=20)
            if ha:
                self.standby = ManagedProc(
                    "fabric-standby",
                    _cli(
                        "fabric", "--port", str(self.standby_port),
                        "--standby-of", f"127.0.0.1:{self.fabric_port}",
                        "--detector-budget", str(detector_budget),
                    ),
                )
                self.standby.wait_for("fabric standby on", timeout=20)
            for _ in range(num_workers):
                self.add_worker()
            self.frontend = ManagedProc(
                "frontend",
                _cli(
                    "run", "in=http", "out=dyn",
                    "--fabric", self.fabric_addr(),
                    "--port", str(self.http_port),
                    "--max-inflight", str(max_inflight),
                    *frontend_args,
                ),
            )
            self.frontend.wait_for("listening on", timeout=30)
            self.wait_until_ready()
        except BaseException:
            self.stop()
            raise

    def fabric_addr(self) -> str:
        if self.standby_port is not None:
            return (
                f"127.0.0.1:{self.fabric_port},"
                f"127.0.0.1:{self.standby_port}"
            )
        return f"127.0.0.1:{self.fabric_port}"

    def add_worker(self):
        extra = (
            ("--mock-step", str(self.mock_step))
            if self.engine == "mock" and self.mock_step
            else ("--echo-delay", str(self.echo_delay))
            if self.engine == "echo"
            else ()
        )
        w = self._ManagedProc(
            f"worker{len(self.workers)}",
            self._cli(
                "run", "in=dyn", f"out={self.engine}", "--model", "tiny",
                "--fabric", self.fabric_addr(),
                "--drain-budget", str(self.drain_budget),
                *extra,
            ),
        )
        self.workers.append(w)
        w.wait_for(r"worker \w+ up", timeout=40)
        return w

    def request(self, text: str, timeout: float = 30.0,
                headers: dict | None = None) -> tuple[int, dict]:
        """One chat completion; ALWAYS returns a terminal status (an
        exception here is a protocol-level failure, counted by the
        caller — never a hang, urllib enforces the timeout)."""
        body = json.dumps({
            "model": "tiny",
            "messages": [{"role": "user", "content": text}],
            "max_tokens": 24,
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.http_port}/v1/chat/completions",
            data=body,
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except Exception:
                payload = {}
            return e.code, dict(e.headers) | payload

    def wait_until_ready(self, timeout: float = 30.0) -> None:
        deadline = time.time() + timeout
        last = None
        while time.time() < deadline:
            try:
                status, data = self.request("ping", timeout=5)
                if status == 200:
                    return
                last = (status, data)
            except Exception as e:
                last = e
            time.sleep(0.5)
        raise AssertionError(f"cluster never became ready: {last}")

    def stop(self) -> None:
        for p in [self.frontend, *self.workers, self.fabric, self.standby]:
            if p is None:
                continue
            try:
                p.stop()
            except Exception:
                pass


def _drive(cluster, n, tag, timeout=30.0, headers=None):
    """n concurrent requests; every one MUST terminate (status or
    protocol error) inside its timeout. Returns the status list —
    a worker-thread that never returns would trip the outer wait."""
    with concurrent.futures.ThreadPoolExecutor(max_workers=n) as pool:
        futs = [
            pool.submit(cluster.request, f"{tag} {i}", timeout, headers)
            for i in range(n)
        ]
        done, not_done = concurrent.futures.wait(futs, timeout=timeout + 30)
        assert not not_done, f"{len(not_done)} hung streams in phase {tag!r}"
        statuses = []
        for f in done:
            try:
                statuses.append(f.result()[0])
            except Exception:
                statuses.append(-1)  # connection reset etc. — terminal
        return statuses


def test_chaos_kill_drain_saturation_deadline():
    """The full process-level scenario: baseline -> worker SIGKILL ->
    replacement -> graceful SIGTERM drain (exit 0) -> queue saturation
    (429 + Retry-After) -> expired deadline (504). Zero hung streams in
    any phase; the fleet answers 200s again after every disruption."""
    cluster = ChaosCluster(num_workers=2, max_inflight=4)
    try:
        # phase 1: baseline under the inflight cap — all tokens
        statuses = _drive(cluster, 3, "baseline")
        assert statuses == [200, 200, 200], statuses

        # phase 2: SIGKILL one worker mid-traffic. In-flight streams on
        # the dead worker may error (that IS a terminal finish); the
        # survivor keeps serving, and a replacement restores capacity.
        with concurrent.futures.ThreadPoolExecutor(max_workers=3) as pool:
            futs = [
                pool.submit(cluster.request, f"kill {i}", 30.0)
                for i in range(3)
            ]
            time.sleep(0.15)
            cluster.workers[0].kill(signal.SIGKILL)
            done, not_done = concurrent.futures.wait(futs, timeout=60)
            assert not not_done, "hung streams across the worker kill"
        assert cluster.workers[0].proc.returncode not in (None, 0)
        cluster.add_worker()
        deadline = time.time() + 30
        while time.time() < deadline:
            if _drive(cluster, 3, "recovered").count(200) == 3:
                break
            time.sleep(0.5)
        else:
            raise AssertionError("fleet never recovered from the kill")

        # phase 3: graceful drain — SIGTERM mid-stream. The drained
        # worker finishes its in-flight requests within --drain-budget
        # and exits 0; traffic keeps flowing on the survivors.
        victim = cluster.workers[1]
        with concurrent.futures.ThreadPoolExecutor(max_workers=3) as pool:
            futs = [
                pool.submit(cluster.request, f"drain {i}", 30.0)
                for i in range(3)
            ]
            time.sleep(0.3)
            victim.proc.send_signal(signal.SIGTERM)
            done, not_done = concurrent.futures.wait(futs, timeout=60)
            assert not not_done, "hung streams across the drain"
            drain_statuses = [f.result()[0] for f in done]
        assert victim.proc.wait(timeout=30) == 0, (
            "drained worker must exit 0:\n" + open(victim.log_path).read()
        )
        with open(victim.log_path) as f:
            assert "drained; exiting" in f.read()
        # in-flight work finished or was retried on a survivor — every
        # stream terminated and the fleet still answers
        assert all(s != -1 for s in drain_statuses), drain_statuses
        assert _drive(cluster, 3, "post-drain").count(200) == 3

        # phase 4: saturation — 12 concurrent slow streams against
        # --max-inflight 4: excess answers 429 + Retry-After, admitted
        # work completes, nothing hangs
        statuses = _drive(cluster, 12, "saturate")
        assert statuses.count(429) >= 1, statuses
        assert statuses.count(200) >= 1, statuses
        assert all(s in (200, 429) for s in statuses), statuses
        status, payload = cluster.request("one more", timeout=30)
        if status == 429:
            assert int(payload.get("Retry-After", 0)) >= 1

        # phase 5: a request whose deadline can't be met 504s instead of
        # burning the engine (the echoed stream runs ~0.25s at 50ms per
        # token; a 0.12s deadline expires mid-stream)
        deadline_status, _ = cluster.request(
            "too slow", timeout=30, headers={"x-request-timeout": "0.12"}
        )
        assert deadline_status == 504, deadline_status

        # coda: the fleet is still healthy after the whole gauntlet
        assert _drive(cluster, 3, "coda").count(200) == 3
    finally:
        cluster.stop()


def _stream_content(port: int, prompt: str, max_tokens: int,
                    timeout: float = 60.0) -> str:
    """One STREAMING chat completion; returns the concatenated delta
    content (SSE parse). Raises on a dropped/errored stream."""
    body = json.dumps({
        "model": "tiny",
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": max_tokens,
        "temperature": 0.0,
        "stream": True,
        # the mock's deterministic token chain hits byte-EOS (token 0)
        # early on some prompts — the scenario needs the full-length
        # stream so the kill lands mid-way
        "ext": {"ignore_eos": True},
    }).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/chat/completions",
        data=body, headers={"Content-Type": "application/json"},
    )
    out = []
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        assert resp.status == 200
        for raw in resp:
            line = raw.decode().strip()
            if not line.startswith("data:"):
                continue
            payload = line[5:].strip()
            if payload == "[DONE]":
                break
            doc = json.loads(payload)
            for c in doc.get("choices", ()):
                delta = (c.get("delta") or {}).get("content")
                if delta:
                    out.append(delta)
    return "".join(out)


def test_chaos_midstream_sigkill_stream_replay_bit_identical():
    """Satellite 6: SIGKILL the worker serving a live stream, with
    --stream-replay on — the client's HTTP stream CONTINUES on the
    survivor and the final text is BIT-IDENTICAL to an undisturbed
    greedy run (the mock engine's token chain is a pure function of
    history, so one duplicated, missing, or diverged token changes the
    bytes). This is the process-level twin of
    tests/test_stream_replay.py's in-process pin."""
    # mock workers: deterministic greedy tokens, ~60ms per step so the
    # kill lands mid-stream; replay enabled at the frontend router
    cluster = ChaosCluster(
        num_workers=1, max_inflight=32, engine="mock", mock_step=0.08,
        frontend_args=("--stream-replay",),
    )
    try:
        prompt = "replay me, exactly"
        # ~10 s of stream at 80 ms/step: the mid-stream survivor spawn
        # (a full worker process boot, seconds) plus the kills must all
        # land well before the stream would finish on its own
        n_tok = 120
        # undisturbed reference on worker0
        ref = _stream_content(cluster.http_port, prompt, n_tok)
        assert len(ref) > 0

        # start a second candidate; the stream lands on one of the two
        # (round-robin makes which one ambiguous) — so after the stream
        # starts, spawn a FRESH survivor and SIGKILL every pre-stream
        # worker: the serving worker dies mid-stream by construction,
        # and the only place the stream can continue is the survivor.
        candidates = [cluster.workers[0], cluster.add_worker()]
        time.sleep(1.0)

        def frontend_replays() -> int:
            with open(cluster.frontend.log_path) as f:
                return f.read().count("replaying stream")

        with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
            fut = pool.submit(
                _stream_content, cluster.http_port, prompt, n_tok, 90.0
            )
            time.sleep(0.4)  # a handful of tokens in
            survivor = cluster.add_worker()
            time.sleep(0.8)  # frontend's watch sees the survivor
            for victim in candidates:
                victim.kill(signal.SIGKILL)
            text = fut.result(timeout=90)
        assert text == ref, (
            f"replayed stream diverged:\nref={ref!r}\ngot={text!r}"
        )
        for victim in candidates:
            assert victim.proc.returncode not in (None, 0)
        assert frontend_replays() >= 1, "no stream was ever severed"

        # the fleet still serves after the kills (replay did not poison
        # the router state)
        assert cluster.request("after", timeout=30)[0] == 200
    finally:
        cluster.stop()


# -- process-level worker handover (ISSUE 12) --------------------------------


def _worker_instance_id(worker) -> str:
    import re

    with open(worker.log_path) as f:
        m = re.search(r"worker (\w+) up", f.read())
    assert m, "worker never logged its instance id"
    return m.group(1)


def _admin_post(port: int, path: str, body: dict,
                timeout: float = 15.0) -> tuple[int, dict]:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}


def test_chaos_handover_process_exits_zero_stream_continues():
    """The tentpole's process-level acceptance: POST /v1/admin/handover
    retires the worker serving a LIVE stream — its KV (mock: the
    registered hash chains) migrates to the survivor, the client's
    stream continues BIT-IDENTICALLY via replay on the warm survivor,
    and the retiring process exits 0."""
    cluster = ChaosCluster(
        num_workers=1, max_inflight=32, engine="mock", mock_step=0.08,
        drain_budget=2.0, frontend_args=("--stream-replay",),
    )
    try:
        prompt = "hand me over, exactly"
        n_tok = 120
        ref = _stream_content(cluster.http_port, prompt, n_tok)
        assert len(ref) > 0
        victim = cluster.workers[0]
        victim_id = _worker_instance_id(victim)

        with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
            # the stream starts while the victim is the ONLY worker, so
            # it must be mid-flight there when the handover severs it
            fut = pool.submit(
                _stream_content, cluster.http_port, prompt, n_tok, 90.0
            )
            time.sleep(0.4)
            survivor = cluster.add_worker()  # boots while tokens flow
            time.sleep(0.8)  # frontend's watch sees the survivor
            status, reply = _admin_post(
                cluster.http_port, "/v1/admin/handover",
                {"instance_id": victim_id},
            )
            assert status == 200, reply
            assert reply.get("handing_over") is True
            text = fut.result(timeout=90)
        assert text == ref, (
            f"stream diverged across handover:\nref={ref!r}\ngot={text!r}"
        )
        # the stream really was severed and continued (not merely
        # finished on the victim before the handover landed)
        with open(cluster.frontend.log_path) as f:
            assert f.read().count("replaying stream") >= 1
        # the retiring process exits 0 on its own (drained fires)
        assert victim.proc.wait(timeout=60) == 0, (
            open(victim.log_path).read()[-2000:]
        )
        with open(victim.log_path) as f:
            assert "drained; exiting" in f.read()
        # the survivor keeps serving
        assert cluster.request("after handover", timeout=30)[0] == 200
        del survivor
    finally:
        cluster.stop()


def test_chaos_sigkill_mid_handover_degrades_to_replay():
    """Kill-at-phase, process level: the retiring worker is SIGKILLed
    MID-handover (a fault-injected delay pins it inside the offer
    phase). The in-flight stream still continues bit-identically on the
    survivor via plain crash replay — a dying handover can never hang or
    corrupt a stream."""
    import os

    # the initial worker carries a fault table that WEDGES its handover
    # in the offer phase, so the SIGKILL lands mid-handover
    os.environ["DYNTPU_FAULTS"] = "handover.offer:delay:1.0:delay_ms=10000"
    try:
        cluster = ChaosCluster(
            num_workers=1, max_inflight=32, engine="mock", mock_step=0.08,
            drain_budget=2.0, frontend_args=("--stream-replay",),
        )
    finally:
        del os.environ["DYNTPU_FAULTS"]
    try:
        victim = cluster.workers[0]
        victim_id = _worker_instance_id(victim)
        prompt = "kill me mid-migration"
        n_tok = 120
        ref = _stream_content(cluster.http_port, prompt, n_tok)

        with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
            fut = pool.submit(
                _stream_content, cluster.http_port, prompt, n_tok, 90.0
            )
            time.sleep(0.4)
            survivor = cluster.add_worker()
            time.sleep(0.8)
            status, _ = _admin_post(
                cluster.http_port, "/v1/admin/handover",
                {"instance_id": victim_id},
            )
            assert status == 200
            time.sleep(1.0)  # inside the injected offer-phase delay
            victim.kill(signal.SIGKILL)
            text = fut.result(timeout=90)
        assert text == ref, (
            f"stream diverged across mid-handover kill:\n"
            f"ref={ref!r}\ngot={text!r}"
        )
        assert victim.proc.returncode not in (None, 0)
        assert cluster.request("after kill", timeout=30)[0] == 200
        del survivor
    finally:
        cluster.stop()


# -- in-process disagg chaos: transfer faults -------------------------------


def _req(rid, prompt, n_out):
    return {
        "request_id": rid, "token_ids": prompt, "max_tokens": n_out,
        "temperature": 0.0, "top_p": 1.0, "top_k": 0, "seed": None,
        "stop_token_ids": [], "stop_strings": [], "ignore_eos": True,
        "annotations": {},
    }


def test_chaos_disagg_transfer_faults_dead_letter_and_recover():
    """Injected KV-landing failures exhaust the redelivery cap: the
    request dead-letters with an ERROR finish on the decode stream
    (bounded, fast — not a timeout, not an infinite redelivery). With
    the fault table cleared the same pipeline recovers to tokens that
    are bit-identical to a local reference run; a single transient
    send-drop self-heals through the bounded retry."""
    from dynamo_tpu.disagg.prefill_worker import PrefillWorker
    from dynamo_tpu.disagg.router import DisaggConfig
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.model_card import ModelDeploymentCard
    from dynamo_tpu.runtime import DistributedRuntime, RouterMode
    from dynamo_tpu.runtime.fabric import FabricServer
    from dynamo_tpu.worker import Worker

    cfg = EngineConfig.for_tests()
    #: distinct prompt per phase — a shared prompt would prefix-cache
    #: after the first successful transfer and later phases would prefill
    #: locally, never reaching the faulted transfer plane
    prompts = {
        "poison": [5, 17, 42, 99, 3, 8, 21, 60, 11, 2],
        "recover": [7, 19, 44, 101, 5, 10, 23, 62, 13, 4],
        "transient": [9, 21, 46, 103, 7, 12, 25, 64, 15, 6],
        "land-fault": [11, 23, 48, 105, 9, 14, 27, 66, 17, 8],
        "slow-transfer": [13, 25, 50, 107, 11, 16, 29, 68, 19, 10],
    }
    n_out = 5

    ref = JaxEngine(cfg)
    ref_tokens = {}
    for rid, prompt in prompts.items():  # solo runs: same shape as serving
        ref.add_request(
            rid, prompt,
            SamplingParams(temperature=0.0, max_tokens=n_out, ignore_eos=True),
        )
        ref_tokens.update(ref.run_to_completion())

    card = ModelDeploymentCard(
        name="tiny", kv_page_size=cfg.page_size,
        context_length=cfg.max_context,
    )

    async def main():
        inj = faults.install(seed=5)
        server = FabricServer(port=0)
        await server.start()
        rt_d = await DistributedRuntime.create(server.address)
        decode = Worker(
            rt_d, card, engine_config=cfg, engine_kind="jax",
            namespace="chaos", metrics_interval=0.1, enable_disagg=True,
            disagg_config=DisaggConfig(
                max_local_prefill_length=4, transfer_timeout_s=15.0
            ),
        )
        await decode.start()
        rt_p = await DistributedRuntime.create(server.address)
        prefill = PrefillWorker(rt_p, cfg, namespace="chaos")
        await prefill.start()
        rt_c = await DistributedRuntime.create(server.address)
        try:
            ep = (
                rt_c.namespace("chaos").component("backend")
                .endpoint("generate")
            )
            router = await ep.router(mode=RouterMode.ROUND_ROBIN)
            await router.source.wait_for_instances()

            async def stream(rid):
                tokens, finishes = [], []
                async for item in router.generate(
                    _req(rid, prompts[rid], n_out)
                ):
                    tokens.extend(item.get("token_ids", ()))
                    if item.get("finish_reason"):
                        finishes.append(item["finish_reason"])
                return tokens, finishes

            # 1) the prefill side dies before any byte moves, EVERY
            #    attempt (persistent partition at transfer.send): the
            #    redelivery cap dead-letters the request and the decode
            #    stream ERROR-finishes fast — no infinite redelivery,
            #    no burned transfer timeout, no hang
            rule = inj.add_rule("transfer.send", "partition")
            t0 = time.monotonic()
            tokens, finishes = await asyncio.wait_for(stream("poison"), 60)
            assert tokens == []
            assert finishes == ["error"]
            assert time.monotonic() - t0 < 15.0, "burned the timeout"
            assert inj.fired.get(("transfer.send", "drop"), 0) == 3
            assert prefill.dead_letters == 1

            # 2) fault cleared: the same pipeline recovers, bit-identical
            inj.remove_rule(rule)
            tokens, finishes = await asyncio.wait_for(stream("recover"), 60)
            assert tokens == ref_tokens["recover"]
            assert finishes[-1] in ("length", "stop")
            assert prefill.prefills_done >= 1

            # 3) one transient send-drop self-heals via the bounded retry
            done_before = prefill.prefills_done
            inj.add_rule("transfer.send", "drop", times=1)
            tokens, finishes = await asyncio.wait_for(stream("transient"), 60)
            assert tokens == ref_tokens["transient"]
            assert inj.fired.get(("transfer.send", "drop"), 0) == 4
            assert prefill.prefills_done > done_before

            # 4) a decode-side LANDING failure degrades gracefully: the
            #    waiter fails over to local prefill (landed bytes can't
            #    be retried into possibly-reused pages) and the client
            #    still gets the exact reference tokens
            inj.add_rule("transfer.land", "error", times=1)
            tokens, finishes = await asyncio.wait_for(stream("land-fault"), 60)
            assert tokens == ref_tokens["land-fault"]
            assert inj.fired.get(("transfer.land", "error"), 0) == 1

            # 5) the client's deadline lapses WHILE the transfer is in
            #    flight (injected send delay): the decode side error-
            #    finishes without ever admitting the request — no decode
            #    flops for a dead client, no pages leaked, no unwatched
            #    stream decoding to completion behind the error
            free_before = await decode.runner.submit(
                lambda e: e.allocator.num_free
            )
            inj.add_rule("transfer.send", "delay", times=1, delay_ms=1500.0)
            body = dict(
                _req("slow-transfer", prompts["slow-transfer"], n_out),
                deadline=time.time() + 0.5,
            )
            tokens, finishes = [], []
            async for item in router.generate(body):
                tokens.extend(item.get("token_ids", ()))
                if item.get("finish_reason"):
                    finishes.append(item["finish_reason"])
            assert tokens == []
            assert finishes == ["error"]
            deadline_chk = time.monotonic() + 10
            while time.monotonic() < deadline_chk:
                running, waiting, free = await decode.runner.submit(
                    lambda e: (len(e.scheduler.running),
                               len(e.scheduler.waiting),
                               e.allocator.num_free)
                )
                if (running, waiting, free) == (0, 0, free_before):
                    break
                await asyncio.sleep(0.1)
            assert (running, waiting, free) == (0, 0, free_before)
        finally:
            faults.uninstall()
            router.close()
            await prefill.stop()
            await decode.stop()
            await rt_c.close()
            await rt_p.close()
            await rt_d.close()
            await server.stop()

    asyncio.run(main())


def _frontend_metrics(cluster) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{cluster.http_port}/metrics", timeout=5
    ) as resp:
        return resp.read().decode()


def test_chaos_control_plane_failover_then_degraded_then_recovery():
    """ISSUE 15 acceptance, process level: (1) SIGKILL the primary
    broker mid-traffic -> the warm standby promotes inside the detector
    budget and the fleet recovers to all-200 (leases reattach on the new
    primary within the orphan grace, zero hung streams throughout);
    (2) resurrect the stale primary with --peer -> it starts DEMOTED
    (split-brain refusal pinned); (3) SIGKILL the remaining broker ->
    the DESIGNED degraded mode: cached-discovery traffic keeps serving
    200 over direct ingress and the frontend's Prometheus surface gauges
    dynamo_tpu_control_plane_degraded=1; (4) a broker returns -> clients
    re-establish sessions (leased registrations re-put, watches
    reset+replay) and the gauge drops back to 0."""
    cluster = ChaosCluster(
        num_workers=2, max_inflight=8, ha=True, detector_budget=1.0,
    )
    try:
        assert _drive(cluster, 3, "baseline") == [200, 200, 200]

        # phase 1: primary SIGKILL mid-traffic -> promotion + recovery
        with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
            futs = [
                pool.submit(cluster.request, f"mid {i}", 30.0)
                for i in range(4)
            ]
            time.sleep(0.2)
            cluster.fabric.kill(signal.SIGKILL)
            done, not_done = concurrent.futures.wait(futs, timeout=60)
            assert not not_done, "hung streams during broker failover"
            # chats ride direct ingress: the broker's death must not
            # terminate a single in-flight stream abnormally
            for f in done:
                assert f.result()[0] == 200, f.result()
        cluster.standby.wait_for("PROMOTED to primary", timeout=30)
        deadline = time.time() + 30
        statuses = []
        while time.time() < deadline:
            statuses = _drive(cluster, 3, "after-failover", timeout=20)
            if statuses == [200, 200, 200]:
                break
            time.sleep(1.0)
        assert statuses == [200, 200, 200], statuses

        # phase 2: the stale primary resurrects with --peer -> demoted
        # standby, never a second primary
        stale = cluster._ManagedProc(
            "fabric-stale",
            cluster._cli(
                "fabric", "--port", str(cluster.fabric_port),
                "--peer", f"127.0.0.1:{cluster.standby_port}",
            ),
        )
        try:
            stale.wait_for("fabric standby on", timeout=20)
            assert _drive(cluster, 2, "with-stale") == [200, 200]
        finally:
            stale.stop()

        # phase 3: kill the LAST broker -> designed degraded mode
        cluster.standby.kill(signal.SIGKILL)
        time.sleep(1.0)
        statuses = _drive(cluster, 4, "degraded", timeout=20)
        assert statuses == [200, 200, 200, 200], statuses
        deadline = time.time() + 25  # default DYNTPU_DEGRADED_AFTER=5s
        seen = False
        while time.time() < deadline:
            if "dynamo_tpu_control_plane_degraded 1" in (
                _frontend_metrics(cluster)
            ):
                seen = True
                break
            time.sleep(0.5)
        assert seen, "frontend never gauged degraded mode"
        # still serving while verifiably degraded
        assert _drive(cluster, 2, "degraded-still") == [200, 200]

        # phase 4: a broker returns (fresh state) -> sessions
        # re-establish and the fleet exits degraded mode
        revived = cluster._ManagedProc(
            "fabric-revived",
            cluster._cli("fabric", "--port", str(cluster.fabric_port)),
        )
        try:
            revived.wait_for("fabric server on|listening", timeout=20)
            deadline = time.time() + 45
            ok = False
            while time.time() < deadline:
                statuses = _drive(cluster, 3, "recovered", timeout=20)
                txt = _frontend_metrics(cluster)
                if statuses == [200, 200, 200] and (
                    "dynamo_tpu_control_plane_degraded 0" in txt
                ):
                    ok = True
                    break
                time.sleep(1.0)
            assert ok, (statuses, "degraded gauge never cleared")
        finally:
            revived.stop()
    finally:
        cluster.stop()

"""Fleet event timeline (ISSUE 14): process-local recording +
coalescing, the metrics service's bounded EventRing, query filters,
and the annotation-layer exposition."""

import threading

from dynamo_tpu.telemetry import events
from dynamo_tpu.telemetry.events import EVENT_TYPES, EventRing


def setup_function(_fn):
    events.reset()


def teardown_function(_fn):
    events.reset()


def test_record_and_drain_roundtrip():
    events.record("role_flip", source="w1", src="prefill", dst="decode")
    events.record(
        "handover", severity="warning", source="w2", phase="fallback"
    )
    assert events.pending() == 2
    evs = events.drain()
    assert events.pending() == 0
    assert [e["type"] for e in evs] == ["role_flip", "handover"]
    assert evs[0]["attrs"] == {"src": "prefill", "dst": "decode"}
    assert evs[1]["severity"] == "warning"
    assert all(e["count"] == 1 for e in evs)
    # garbage severity degrades to info, never raises
    events.record("drain", severity="shouting", source="w3")
    assert events.drain()[0]["severity"] == "info"


def test_coalescing_folds_bursts_into_episodes():
    for _ in range(50):
        events.record(
            "shed", severity="warning", source="frontend:burn",
            coalesce_s=60.0, reason="burn",
        )
    # a different source never folds into the episode
    events.record(
        "shed", severity="warning", source="frontend:inflight",
        coalesce_s=60.0, reason="frontend_inflight",
    )
    evs = events.drain()
    assert len(evs) == 2
    assert evs[0]["count"] == 50
    assert evs[1]["count"] == 1
    # coalescing never upgrades severity downward
    events.record("shed", severity="info", source="s", coalesce_s=60.0)
    events.record("shed", severity="critical", source="s", coalesce_s=60.0)
    assert events.drain()[0]["severity"] == "critical"


def test_buffer_is_bounded_oldest_dropped():
    for i in range(events.BUFFER_CAP + 100):
        events.record("drain", source=f"w{i}")
    evs = events.drain()
    assert len(evs) == events.BUFFER_CAP
    assert evs[0]["source"] == "w100"  # oldest 100 dropped


def test_record_is_thread_safe():
    def pump():
        for _ in range(200):
            events.record("kv_resync", source="t")

    threads = [threading.Thread(target=pump) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert events.pending() == min(800, events.BUFFER_CAP)


def test_ring_ids_counters_and_query_filters():
    ring = EventRing(capacity=4)
    for i, etype in enumerate(
        ("role_flip", "shed", "shed", "worker_lost", "drain")
    ):
        ring.add({
            "ts": 100.0 + i, "type": etype,
            "severity": "warning" if etype != "drain" else "info",
            "source": f"w{i}", "count": 2 if etype == "shed" else 1,
        })
    # bounded: 5 added, capacity 4 -> oldest evicted
    assert len(ring) == 4
    # but the counters stay monotonic across eviction
    assert ring.counters[("role_flip", "warning")] == 1
    assert ring.counters[("shed", "warning")] == 4  # 2 events x count 2
    # ids are monotonic; since_id tails
    evs = ring.query()
    ids = [e["id"] for e in evs]
    assert ids == sorted(ids)
    tail = ring.query(since_id=ids[-2])
    assert [e["id"] for e in tail] == ids[-1:]
    # filters compose (the evicted role_flip is gone from the ring but
    # not from the counters above)
    assert ring.query(etype="role_flip") == []
    assert [e["type"] for e in ring.query(etype="shed")] == ["shed", "shed"]
    assert ring.query(severity="info")[0]["type"] == "drain"
    assert ring.query(source="w3")[0]["type"] == "worker_lost"
    assert ring.query(since_ts=103.5)[0]["type"] == "drain"
    # limit=0 means none, not all
    assert ring.query(limit=0) == []
    assert ring.overlapping(0.0, 1000.0, limit=0) == []
    # garbage frames are rejected, not raised
    assert ring.add(None) is None
    assert ring.add({"no_type": 1}) is None
    assert ring.add({"type": "x", "ts": "yesterday"}) is not None


def test_overlapping_joins_by_time_window():
    ring = EventRing()
    ring.add({"ts": 10.0, "type": "role_flip", "source": "w1"})
    ring.add({"ts": 20.0, "type": "shed", "source": "f"})
    ring.add({"ts": 40.0, "type": "drain", "source": "w2"})
    hits = ring.overlapping(19.0, 21.0)
    assert [e["type"] for e in hits] == ["shed"]
    # the pad catches events just outside the trace window
    hits = ring.overlapping(20.4, 21.0, pad_s=0.5)
    assert [e["type"] for e in hits] == ["shed"]
    assert ring.overlapping(100.0, 101.0) == []


def test_exposition_matches_annotation_layer_contract():
    from dynamo_tpu.telemetry import promlint

    ring = EventRing()
    for etype in EVENT_TYPES:
        ring.add({"type": etype, "severity": "info", "source": "w"})
    lines = ring.expose_lines()
    text = "\n".join(lines) + "\n"
    assert promlint.lint(text) == []
    for etype in EVENT_TYPES:
        assert any(f'type="{etype}"' in l for l in lines)
    # empty ring: no family at all (the metrics service's exposition
    # stays lint-clean either way)
    assert EventRing().expose_lines() == []


def test_ship_once_requeues_events_while_broker_unreachable():
    """Degraded mode must not eat the timeline: events drained while no
    broker answers go BACK in the (bounded) buffer and ship on
    reconnect — the degraded/failover events are exactly the ones that
    must survive the outage they describe."""
    import asyncio

    from dynamo_tpu.telemetry import events, traceplane

    events.reset()
    try:
        events.record("degraded", severity="warning", source="w1")

        class _Offline:
            connected = False

            async def publish(self, *a, **k):
                raise AssertionError("must not publish while offline")

        asyncio.run(traceplane.ship_once(_Offline(), "w1"))
        assert events.pending() == 1  # requeued, not dropped

        class _Flaky:
            connected = True

            async def publish(self, *a, **k):
                raise ConnectionError("lost mid-publish")

        asyncio.run(traceplane.ship_once(_Flaky(), "w1"))
        assert events.pending() == 1  # failed publish requeues too

        sent = []

        class _Online:
            connected = True

            async def publish(self, subject, header, payload=b""):
                sent.append(subject)

        asyncio.run(traceplane.ship_once(_Online(), "w1"))
        assert events.pending() == 0
        assert any("fleet.events" in s for s in sent)
    finally:
        events.reset()

"""Perf-regression ledger (ISSUE 19): schema round-trip through
dynamo_tpu/telemetry/perf_ledger.py, the BENCH_r*.json back-fill
(every recorded round must parse into a valid row), and the
scripts/perf_diff.py CI contract (exit 0 clean / 1 data error / 2
regression)."""

import importlib.util
import json
import pathlib

import pytest

from dynamo_tpu.telemetry import perf_ledger

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_perf_diff():
    spec = importlib.util.spec_from_file_location(
        "perf_diff", REPO / "scripts" / "perf_diff.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- schema ----------------------------------------------------------------


def test_schema_round_trip(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    row = perf_ledger.make_row(
        "r42", "bench", {"tok_s": 651.55, "p50_ttft_s": 0.028},
        {"model": "tiny", "isl": 64}, platform="cpu",
    )
    perf_ledger.append_row(row, path)
    rows, problems = perf_ledger.read_rows(path, strict=True)
    assert problems == []
    assert rows == [row]
    assert rows[0]["schema"] == perf_ledger.SCHEMA_VERSION
    assert rows[0]["fingerprint"] == perf_ledger.config_fingerprint(
        {"model": "tiny", "isl": 64}
    )


def test_make_row_drops_unbandable_metrics():
    row = perf_ledger.make_row(
        "r1", "bench",
        {"tok_s": 100.0, "mfu": None, "bad": float("nan"), "flag": True},
        {},
    )
    assert set(row["metrics"]) == {"tok_s"}


def test_validate_row_failures():
    good = perf_ledger.make_row("r1", "bench", {"tok_s": 1.0}, {"m": 1})
    assert perf_ledger.validate_row(good) == []

    missing = {k: v for k, v in good.items() if k != "round"}
    assert any("round" in e for e in perf_ledger.validate_row(missing))

    stale = dict(good, schema=99)
    assert any("schema" in e for e in perf_ledger.validate_row(stale))

    bad_metric = dict(good, metrics={"tok_s": "fast"})
    assert any(
        "not a number" in e for e in perf_ledger.validate_row(bad_metric)
    )

    # a tampered config must not keep the old fingerprint
    forged = dict(good, config={"m": 2})
    assert any(
        "fingerprint" in e for e in perf_ledger.validate_row(forged)
    )


def test_append_row_rejects_invalid(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with pytest.raises(ValueError):
        perf_ledger.append_row({"round": "r1"}, path)
    assert not (tmp_path / "ledger.jsonl").exists()


def test_read_rows_tolerant_of_bad_lines(tmp_path):
    path = tmp_path / "ledger.jsonl"
    good = perf_ledger.make_row("r1", "bench", {"tok_s": 1.0}, {})
    path.write_text(
        json.dumps(good) + "\n"
        + "{not json\n"
        + json.dumps({"round": "r2"}) + "\n"
    )
    rows, problems = perf_ledger.read_rows(str(path))
    assert [r["round"] for r in rows] == ["r1"]
    assert len(problems) == 2
    with pytest.raises(ValueError):
        perf_ledger.read_rows(str(path), strict=True)


def test_rows_by_round_last_wins(tmp_path):
    a = perf_ledger.make_row("r1", "bench", {"tok_s": 1.0}, {})
    b = perf_ledger.make_row("r1", "bench", {"tok_s": 2.0}, {})
    by = perf_ledger.rows_by_round([a, b])
    assert by["r1"]["metrics"]["tok_s"] == 2.0


# -- comparison ------------------------------------------------------------


def _row(name, metrics, config=None, ok=True):
    return perf_ledger.make_row(
        name, "bench", metrics, config if config is not None else {"m": 1},
        ok=ok,
    )


def test_compare_rows_verdicts():
    res = perf_ledger.compare_rows(
        _row("a", {"tok_s": 600.0, "p50_ttft_s": 0.030}),
        _row("b", {"tok_s": 540.0, "p50_ttft_s": 0.029}),
    )
    assert res["comparable"] and not res["advisory"]
    # tok_s -10% past the 8% band; ttft -3.3% inside its 15% band
    assert res["regressions"] == ["tok_s"]
    verdicts = {r["metric"]: r["verdict"] for r in res["rows"]}
    assert verdicts["tok_s"] == "REGRESSION"
    assert verdicts["p50_ttft_s"] == "ok"

    # the same move the other way is an improvement, never flagged
    res = perf_ledger.compare_rows(
        _row("a", {"tok_s": 540.0}), _row("b", {"tok_s": 600.0})
    )
    assert res["regressions"] == []
    assert res["rows"][0]["verdict"] == "improved"


def test_compare_rows_direction_lower_is_better():
    res = perf_ledger.compare_rows(
        _row("a", {"ms_per_dispatch": 10.0}),
        _row("b", {"ms_per_dispatch": 13.0}),
    )
    assert res["regressions"] == ["ms_per_dispatch"]


def test_compare_rows_fingerprint_mismatch_is_advisory():
    res = perf_ledger.compare_rows(
        _row("a", {"tok_s": 600.0}, {"platform": "tpu"}),
        _row("b", {"tok_s": 100.0}, {"platform": "cpu"}),
    )
    assert res["advisory"]
    assert res["regressions"] == []  # different workloads can't regress
    assert "fingerprints differ" in res["note"]


def test_compare_rows_failed_round_not_comparable():
    res = perf_ledger.compare_rows(
        _row("a", {}, ok=False), _row("b", {"tok_s": 1.0})
    )
    assert not res["comparable"]
    assert "failed" in res["note"]


def test_compare_rows_one_sided_metrics_never_verdicted():
    res = perf_ledger.compare_rows(
        _row("a", {"tok_s": 1.0, "mfu": 0.2}), _row("b", {"tok_s": 1.0})
    )
    only = [r for r in res["rows"] if r["metric"] == "mfu"]
    assert only and only[0]["verdict"] == "only in a"
    assert res["regressions"] == []


def test_compare_rows_tolerance_override():
    res = perf_ledger.compare_rows(
        _row("a", {"tok_s": 600.0}), _row("b", {"tok_s": 580.0}),
        tolerance={"tok_s": 0.01},
    )
    assert res["regressions"] == ["tok_s"]


# -- producers: BENCH_r*.json back-fill ------------------------------------


def _backfill(tmp_path) -> str:
    """Back-fill r01..r05 from the recorded BENCH artifacts into a
    fresh ledger, returning its path."""
    path = str(tmp_path / "ledger.jsonl")
    for p in sorted(REPO.glob("BENCH_r*.json")):
        round_name = p.stem.split("_")[-1]
        with open(p) as f:
            row = perf_ledger.row_from_bench(json.load(f), round_name)
        perf_ledger.append_row(row, path)
    return path


def test_every_recorded_bench_round_parses_into_the_schema(tmp_path):
    """CI satellite: the repo's BENCH_r*.json history must keep
    back-filling into valid ledger rows — a schema change that orphans
    the recorded rounds fails here."""
    path = _backfill(tmp_path)
    rows, problems = perf_ledger.read_rows(path, strict=True)
    assert problems == []
    by = perf_ledger.rows_by_round(rows)
    assert set(by) >= {"r01", "r02", "r03", "r04", "r05"}
    # r01 predates bench.py: rc=1, parsed null -> honest failed row
    assert by["r01"]["ok"] is False
    assert by["r01"]["metrics"] == {}
    assert by["r01"]["note"]
    for name in ("r02", "r03", "r04", "r05"):
        assert by[name]["ok"] is True
        assert by[name]["metrics"]["tok_s"] > 0
        assert by[name]["config"].get("model") == "tiny"
    # r02/r03 measured the same workload -> diffable pair
    assert by["r02"]["fingerprint"] == by["r03"]["fingerprint"]


def test_row_from_decode_profile():
    doc = {
        "platform": "cpu", "k_steps": 8, "model": "tiny",
        "batches": {
            "8": {"full_xla": {"tok_s": 900.0},
                  "pure_xla": {"ms_per_dispatch": 1.0}},
            "64": {"full_xla": {"tok_s": 2634.3},
                   "pure_xla": {"ms_per_dispatch": 766.931},
                   "full_pallas": {"tok_s": 2000.0},
                   "pure_pallas": {"ms_per_dispatch": 900.0}},
        },
    }
    row = perf_ledger.row_from_decode_profile(doc, "r06/decode")
    assert row["ok"] and row["source"] == "decode_profile"
    # headline = the LARGEST batch's best impl
    assert row["metrics"]["tok_s"] == 2634.3
    assert row["metrics"]["ms_per_dispatch"] == 766.931
    assert row["metrics"]["pallas_tok_s"] == 2000.0
    assert row["config"]["batches"] == ["8", "64"]

    empty = perf_ledger.row_from_decode_profile({"batches": {}}, "r0")
    assert empty["ok"] is False and empty["note"]


def test_row_from_baseline_pseudo_row():
    with open(REPO / "BASELINE.json") as f:
        row = perf_ledger.row_from_baseline(json.load(f))
    assert row["round"] == "BASELINE"
    assert row["metrics"]["tok_s"] == pytest.approx(6919.8)
    assert row["metrics"]["mfu"] == pytest.approx(0.2549)
    assert perf_ledger.validate_row(row) == []


def test_cli_append_bench(tmp_path, capsys):
    path = str(tmp_path / "ledger.jsonl")
    rc = perf_ledger.main([
        "--append-bench", str(REPO / "BENCH_r03.json"),
        "--round", "r03", "--ledger", path,
    ])
    assert rc == 0
    assert "appended round=r03" in capsys.readouterr().out
    rows, _ = perf_ledger.read_rows(path, strict=True)
    assert rows[0]["metrics"]["tok_s"] == pytest.approx(651.55)


# -- scripts/perf_diff.py CI contract --------------------------------------


def test_perf_diff_exit_codes(tmp_path, capsys):
    pd = _load_perf_diff()
    path = _backfill(tmp_path)

    # r01 failed -> nothing comparable -> clean exit (acceptance)
    assert pd.main(["r01", "r05", "--ledger", path]) == 0
    assert "nothing comparable" in capsys.readouterr().out

    # same-workload rounds, both inside the band
    assert pd.main(["r02", "r03", "--ledger", path]) == 0
    assert "no regressions" in capsys.readouterr().out

    # missing round is a data error, not a pass
    assert pd.main(["r02", "r99", "--ledger", path]) == 1
    capsys.readouterr()

    # inject a 10% tok/s regression on the SAME fingerprint (acceptance)
    rows, _ = perf_ledger.read_rows(path)
    r05 = perf_ledger.rows_by_round(rows)["r05"]
    bad = perf_ledger.make_row(
        "r06", "bench",
        {"tok_s": r05["metrics"]["tok_s"] * 0.90}, r05["config"],
        platform=r05["platform"],
    )
    perf_ledger.append_row(bad, path)
    assert pd.main(["r05", "r06", "--ledger", path]) == 2
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "tok_s" in out

    # --tolerance widens the band back to passing
    assert pd.main(
        ["r05", "r06", "--ledger", path, "--tolerance", "tok_s=0.15"]
    ) == 0
    capsys.readouterr()


def test_perf_diff_baseline_and_list(tmp_path, capsys):
    pd = _load_perf_diff()
    path = _backfill(tmp_path)

    # BASELINE (TPU workload) vs a CPU round: fingerprints differ, the
    # whole diff is advisory -> exit 0 even though the delta is huge
    rc = pd.main([
        "BASELINE", "r05", "--ledger", path,
        "--baseline", str(REPO / "BASELINE.json"),
    ])
    assert rc == 0
    assert "advisory" in capsys.readouterr().out

    assert pd.main(["--list", "--ledger", path]) == 0
    out = capsys.readouterr().out
    for name in ("r01", "r02", "r03", "r04", "r05"):
        assert name in out

    # unreadable ledger is a data error
    assert pd.main(["r02", "r03", "--ledger",
                    str(tmp_path / "nope.jsonl")]) == 1


def test_perf_diff_json_output(tmp_path, capsys):
    pd = _load_perf_diff()
    path = _backfill(tmp_path)
    assert pd.main(["r02", "r03", "--ledger", path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["round_a"] == "r02" and doc["round_b"] == "r03"
    assert any(r["metric"] == "tok_s" for r in doc["rows"])

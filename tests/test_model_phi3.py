"""Phi-3 family (Llama architecture, FUSED qkv/gate_up checkpoint
projections split at load) vs HuggingFace Phi3ForCausalLM."""

from dataclasses import replace

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_kv_pages,
    params_from_torch_state_dict,
)

PAGE_SIZE = 4


def _tiny_phi3_cfg():
    # MHA (kv == q heads) like real Phi-3-mini
    return replace(
        LlamaConfig.tiny(), num_kv_heads=4, dtype=jnp.float32,
    )


def test_against_hf_phi3():
    torch = pytest.importorskip("torch")
    from transformers import Phi3Config, Phi3ForCausalLM

    cfg = _tiny_phi3_cfg()
    hf_cfg = Phi3Config(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps,
        tie_word_embeddings=False,
        pad_token_id=0,  # default 32000 exceeds the tiny vocab
        attn_implementation="eager",
    )
    torch.manual_seed(33)
    model = Phi3ForCausalLM(hf_cfg).eval()
    sd = dict(model.state_dict())
    assert "model.layers.0.self_attn.qkv_proj.weight" in sd  # really fused
    params = params_from_torch_state_dict(sd, cfg)

    rng = np.random.default_rng(14)
    toks = rng.integers(0, cfg.vocab_size, size=(2, 10)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()

    b, t = toks.shape
    kv = init_kv_pages(cfg, 64, PAGE_SIZE)
    n_pages = -(-t // PAGE_SIZE)
    pts = np.stack([
        np.arange(1 + i * n_pages, 1 + (i + 1) * n_pages) for i in range(b)
    ]).astype(np.int32)
    positions = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    logits, _ = forward(
        params, cfg, jnp.asarray(toks), jnp.asarray(positions),
        jnp.ones((b, t), bool), kv, jnp.asarray(pts),
    )
    ours = np.asarray(logits)
    np.testing.assert_allclose(ours, ref, rtol=5e-2, atol=5e-2)
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.95


def test_phi3_registry_and_longrope_refusal(tmp_path):
    import json

    from dynamo_tpu.models.registry import get_model

    c = get_model("phi3-mini", dtype="float32").config
    assert c.num_heads == c.num_kv_heads == 32  # MHA

    d = tmp_path / "p3"
    d.mkdir()
    (d / "config.json").write_text(json.dumps({
        "architectures": ["Phi3ForCausalLM"], "model_type": "phi3",
        "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "rope_scaling": {"rope_type": "longrope", "factor": 32},
    }))
    with pytest.raises(ValueError, match="rope_scaling"):
        get_model(str(d))

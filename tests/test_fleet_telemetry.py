"""Fleet-wide live telemetry plane (ISSUE 6 acceptance).

- e2e: >=2 JaxEngine workers + HTTP frontend under generated traffic
  produce a /v1/fleet snapshot whose MERGED TTFT/ITL percentiles sit
  within 1% rank of the exact offline percentiles of the raw worker
  observations, with compile counters, page-pool gauges, and a
  (0,1]-bounded MFU gauge present per worker; both Prometheus
  expositions (fleet + frontend SLO) pass the promlint gate.
- hardening: a worker vanishing between polls ages out of the snapshot
  (last_seen_s), malformed frames are logged-and-skipped, and the pump
  keeps serving later legitimate frames.
- scripts/fleet_top.py renders a recorded snapshot.
- --no-fleet-telemetry is bit-identical on the token path.
"""

import asyncio
import importlib.util
import json
import pathlib
import sys

import aiohttp
import numpy as np
import pytest

from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.frontend import HttpService, ModelManager
from dynamo_tpu.frontend.service import ModelWatcher
from dynamo_tpu.metrics_service import MetricsService
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.fabric import FabricServer
from dynamo_tpu.subjects import METRICS_SUBJECT
from dynamo_tpu.worker import Worker

REPO = pathlib.Path(__file__).resolve().parent.parent


def run(coro):
    return asyncio.run(coro)


def _card(name: str) -> ModelDeploymentCard:
    return ModelDeploymentCard(
        name=name, tokenizer={"kind": "byte"}, context_length=32,
        kv_page_size=4,
    )


def _rank_bracket(data, q: float, est: float, slack: float = 0.01):
    """`est` is within `slack` rank of the exact quantile iff it lies
    between the exact quantiles at q±slack (tiny float epsilon)."""
    lo = float(np.percentile(data, max(0.0, (q - slack)) * 100.0))
    hi = float(np.percentile(data, min(1.0, (q + slack)) * 100.0))
    eps = 1e-6 + 1e-3 * max(abs(lo), abs(hi))
    assert lo - eps <= est <= hi + eps, (
        f"q={q}: estimate {est} outside exact-rank bracket "
        f"[{lo}, {hi}] of n={len(data)}"
    )


def test_fleet_snapshot_e2e():
    async def main():
        from dynamo_tpu.telemetry import promlint

        server = FabricServer(port=0)
        await server.start()
        workers, runtimes = [], []
        recorded = {"ttft_ms": [], "itl_ms": [], "e2e_ms": []}
        try:
            for i in range(2):
                rt = await DistributedRuntime.create(server.address)
                runtimes.append(rt)
                w = Worker(
                    rt, _card("fleet-tiny"),
                    engine_config=EngineConfig.for_tests(),
                    engine_kind="jax", metrics_interval=0.15,
                )
                await w.start()
                workers.append(w)
                # spy on the worker-side SLO observations so the merged
                # fleet percentiles can be checked against the EXACT
                # offline percentiles of what the sketches ingested
                eng = w.runner.engine
                orig = eng.slo.observe

                def spy(metric, value_ms, _orig=orig):
                    recorded[metric].append(float(value_ms))
                    _orig(metric, value_ms)

                eng.slo.observe = spy

            rt_f = await DistributedRuntime.create(server.address)
            runtimes.append(rt_f)
            manager = ModelManager()
            watcher = ModelWatcher(rt_f, manager)
            await watcher.start()
            for _ in range(100):
                if manager.get("fleet-tiny"):
                    break
                await asyncio.sleep(0.05)
            assert manager.get("fleet-tiny") is not None
            svc = HttpService(manager, host="127.0.0.1", port=0)
            await svc.start()

            rt_m = await DistributedRuntime.create(server.address)
            runtimes.append(rt_m)
            metrics = MetricsService(rt_m.fabric, port=0)
            await metrics.start()

            base = f"http://127.0.0.1:{svc.port}"
            mbase = f"http://127.0.0.1:{metrics.port}"

            async def one(session, i):
                body = {
                    "model": "fleet-tiny",
                    "messages": [{"role": "user", "content": f"hi {i}"}],
                    "max_tokens": 6,
                    "temperature": 0.0,
                    "stream": True,
                }
                async with session.post(
                    f"{base}/v1/chat/completions", json=body
                ) as r:
                    assert r.status == 200
                    async for _ in r.content:
                        pass

            async with aiohttp.ClientSession() as s:
                for batch in range(10):
                    await asyncio.gather(
                        *[one(s, batch * 4 + j) for j in range(4)]
                    )

            n_ttft = len(recorded["ttft_ms"])
            assert n_ttft == 40
            assert len(recorded["itl_ms"]) >= 40

            # wait until both workers' published sketches carry every
            # observation (frames ship every 0.15 s)
            async with aiohttp.ClientSession() as s:
                snap = None
                for _ in range(100):
                    async with s.get(f"{mbase}/v1/fleet") as r:
                        assert r.status == 200
                        snap = await r.json()
                    fl = snap.get("fleet", {}).get("slo", {})
                    if (
                        len(snap.get("workers", {})) >= 2
                        and fl.get("ttft_ms", {}).get("n") == n_ttft
                        and fl.get("itl_ms", {}).get("n")
                        == len(recorded["itl_ms"])
                    ):
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError(
                        f"fleet snapshot never converged: {snap}"
                    )

                # merged percentiles within 1% rank of the exact offline
                # percentiles over the pooled raw observations
                for metric in ("ttft_ms", "itl_ms", "e2e_ms"):
                    data = np.asarray(recorded[metric])
                    pcts = snap["fleet"]["slo"][metric]
                    for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                        _rank_bracket(data, q, pcts[key])

                # per-worker engine internals
                assert len(snap["workers"]) == 2
                for iid, w in snap["workers"].items():
                    assert w["role"] == "decode"
                    assert w["compiles"] > 0, (iid, w)
                    assert sum(w["compiles_by_kind"].values()) == w["compiles"]
                    assert w["kv_free_pages"] >= 0
                    assert w["kv_pages_watermark"] > 0
                    assert w["kv_total_pages"] > 0
                    assert 0.0 < w["mfu"] <= 1.0, (iid, w.get("mfu"))
                    assert w["last_seen_s"] < 5.0
                    assert "slo" in w and w["slo"]["requests_total"] > 0
                    # debug plane (ISSUE 7): healthy workers report the
                    # watchdog counter at zero
                    assert w["stalls_total"] == 0, (iid, w)

                # the workers' flight windows + program cost rollups
                # rode the frames into the metrics service
                async with s.get(f"{mbase}/v1/debug/flight?n=8") as r:
                    assert r.status == 200
                    fdoc = await r.json()
                assert len(fdoc["workers"]) == 2
                for iid, fw in fdoc["workers"].items():
                    assert fw["records"], iid
                    assert fw["records"][-1]["kind"] in (
                        "prefill", "decode", "mixed"
                    )
                async with s.get(f"{mbase}/v1/debug/programs") as r:
                    assert r.status == 200
                    pdoc = await r.json()
                for iid, pw in pdoc["workers"].items():
                    assert any(
                        k.get("attainment") is not None
                        for k in pw["kinds"].values()
                    ), (iid, pw)
                role = snap["roles"]["decode"]
                assert role["workers"] == 2
                assert role["slo"]["requests_total"] == 40

                # both Prometheus surfaces pass the lint gate and carry
                # the new families
                async with s.get(f"{mbase}/metrics") as r:
                    fleet_text = await r.text()
                async with s.get(f"{base}/metrics") as r:
                    front_text = await r.text()
            assert promlint.lint(fleet_text) == [], promlint.lint(fleet_text)[:5]
            assert promlint.lint(front_text) == [], promlint.lint(front_text)[:5]
            assert 'dynamo_tpu_fleet_workers{role="decode"} 2' in fleet_text
            assert "dynamo_tpu_fleet_ttft_ms{" in fleet_text
            assert "dynamo_tpu_fleet_goodput_tokens_total{" in fleet_text
            assert "dynamo_tpu_fleet_burn_rate{" in fleet_text
            assert "dynamo_tpu_fleet_compile_total{" in fleet_text
            assert "dynamo_tpu_worker_mfu{" in fleet_text
            assert "dynamo_tpu_worker_compiles_total{" in fleet_text
            assert "dynamo_tpu_worker_kv_pages_watermark{" in fleet_text
            assert 'dynamo_tpu_slo_ttft_ms{endpoint="chat"' in front_text
            assert 'dynamo_tpu_slo_attainment{endpoint="chat"' in front_text

            await metrics.stop()
            await svc.stop()
            await watcher.stop()
        finally:
            for w in workers:
                await w.stop(drain_timeout=0)
            for rt in runtimes:
                await rt.close()
            await server.stop()

    run(main())


def test_worker_vanishes_and_malformed_frames_never_kill_the_pump():
    """Regression (satellite 1): a worker that stops publishing between
    polls ages out of the fleet snapshot; malformed frames (non-dict
    header, garbage slo wire, string-valued gauges) are skipped; the
    pump keeps serving frames that arrive after the garbage."""

    async def main():
        server = FabricServer(port=0)
        await server.start()
        try:
            rt_m = await DistributedRuntime.create(server.address)
            rt_w = await DistributedRuntime.create(server.address)
            svc = MetricsService(rt_m.fabric, component="backend", port=0)
            for agg in svc.aggregators:
                agg.stale_after = 0.6
            await svc.start()
            await asyncio.sleep(0.1)

            async def publish(iid, **extra):
                await rt_w.fabric.publish(
                    f"{METRICS_SUBJECT}.backend.{iid}",
                    {
                        "instance_id": iid,
                        "kv_usage": 0.5,
                        "requests_received": 3,
                        "generated_tokens": 12,
                        **extra,
                    },
                )

            await publish("w-stable")
            await publish(
                "w-vanishes", preemptions=5,
                compiles_by_kind={"prefill": 2},
            )
            # malformed traffic: non-dict header, garbage slo, junk gauge
            await rt_w.fabric.publish(
                f"{METRICS_SUBJECT}.backend.junk", ["not", "a", "dict"]
            )
            await publish("w-garbage", slo="not-a-wire", mfu="NaN-ish")
            await asyncio.sleep(0.2)

            snap = svc.fleet_snapshot()
            assert set(snap["workers"]) == {
                "w-stable", "w-vanishes", "w-garbage"
            }
            assert "slo" not in snap["workers"]["w-garbage"]
            assert "mfu" not in snap["workers"]["w-garbage"]
            assert snap["workers"]["w-stable"]["last_seen_s"] < 0.6

            def fleet_counter(text, name):
                for line in text.splitlines():
                    if line.startswith(f"dynamo_tpu_fleet_{name}"):
                        return float(line.rsplit(" ", 1)[1])
                return None

            before = svc.expose()
            assert fleet_counter(before, "preemptions_total") == 5.0
            assert 'compile_total{role="decode",kind="prefill"} 2' in before

            # w-vanishes dies between polls: only w-stable keeps
            # publishing; the stale entry ages out
            for _ in range(4):
                await asyncio.sleep(0.25)
                await publish("w-stable")
            snap = svc.fleet_snapshot()
            assert "w-vanishes" not in snap["workers"]
            assert "w-stable" in snap["workers"]

            # fleet counter families must stay monotonic across the
            # departure (Prometheus rate() would read a drop as a
            # counter reset and manufacture a spike), and the departed
            # worker's rate baseline must be pruned
            after = svc.expose()
            assert fleet_counter(after, "preemptions_total") == 5.0
            assert 'compile_total{role="decode",kind="prefill"} 2' in after
            assert "w-vanishes" not in svc._rate_state

            # the pump survived all of it: a brand-new worker lands
            await publish("w-late")
            await asyncio.sleep(0.2)
            snap = svc.fleet_snapshot()
            assert "w-late" in snap["workers"]

            # /metrics never corrupts
            from dynamo_tpu.telemetry import promlint

            text = svc.expose()
            assert promlint.lint(text) == []

            await svc.stop()
            await rt_m.close()
            await rt_w.close()
        finally:
            await server.stop()

    run(main())


def test_pump_survives_header_less_message():
    """Regression: a message object with NO .header attribute must be
    logged-and-skipped by the aggregator pump — the guard used to
    re-read msg.header inside its own except block, re-raising the very
    AttributeError it had just caught and killing the pump."""
    from dynamo_tpu.kv_router.metrics_aggregator import MetricsAggregator

    class _HeaderlessMsg:
        pass

    class _GoodMsg:
        header = {"instance_id": "w-after", "kv_usage": 0.1}

    class _FakeSub:
        def __init__(self):
            self._msgs = [_HeaderlessMsg(), _GoodMsg(), None]

        async def next(self):
            return self._msgs.pop(0)

    agg = MetricsAggregator.__new__(MetricsAggregator)
    agg._latest = {}
    agg._sub = _FakeSub()
    run(agg._pump())  # must NOT raise
    assert "w-after" in agg._latest


def test_transient_missing_slo_wire_does_not_double_count():
    """Regression: one frame with a transiently missing slo wire (the
    worker drops the key when to_wire() throws) used to read as a
    counter regression — the fold+restore cycle then permanently
    double-counted the monotonic dynamo_tpu_fleet_* families."""
    from dynamo_tpu.telemetry.slo import SloTracker

    async def main():
        server = FabricServer(port=0)
        await server.start()
        try:
            rt_m = await DistributedRuntime.create(server.address)
            rt_w = await DistributedRuntime.create(server.address)
            svc = MetricsService(rt_m.fabric, component="backend", port=0)
            await svc.start()
            await asyncio.sleep(0.1)

            tracker = SloTracker()
            tracker.observe("ttft_ms", 50.0)
            tracker.finish_request(ttft_ms=50.0, tokens=100)
            tracker.observe("ttft_ms", 60.0)
            tracker.finish_request(ttft_ms=60.0, tokens=100)

            async def publish(**extra):
                await rt_w.fabric.publish(
                    f"{METRICS_SUBJECT}.backend.w-flaky",
                    {
                        "instance_id": "w-flaky",
                        "preemptions": 3,
                        "compiles_by_kind": {"prefill": 2},
                        **extra,
                    },
                )

            def fleet_counter(name):
                for line in svc.expose().splitlines():
                    if line.startswith(f"dynamo_tpu_fleet_{name}"):
                        return float(line.rsplit(" ", 1)[1])
                return None

            # good -> degraded (slo + compiles_by_kind keys dropped,
            # exactly what worker.py does on a to_wire() failure) ->
            # good again; each expose() runs a fold pass
            await publish(slo=tracker.to_wire())
            await asyncio.sleep(0.2)
            assert fleet_counter("requests_total") == 2.0

            await publish()
            await asyncio.sleep(0.2)
            svc.expose()

            await publish(slo=tracker.to_wire())
            await asyncio.sleep(0.2)
            assert fleet_counter("requests_total") == 2.0
            assert fleet_counter("preemptions_total") == 3.0
            assert (
                'compile_total{role="decode",kind="prefill"} 2'
                in svc.expose()
            )

            await svc.stop()
            await rt_m.close()
            await rt_w.close()
        finally:
            await server.stop()

    run(main())


RECORDED_SNAPSHOT = {
    "workers": {
        "worker-decode-1": {
            "role": "decode", "component": "backend", "model": "llama3-1b",
            "last_seen_s": 0.4, "req_s": 12.5, "tok_s": 812.0,
            "kv_usage": 0.42, "kv_free_pages": 1187,
            "kv_pages_watermark": 1622, "preemptions": 3,
            "stalls_total": 2,
            "stalls_by_cause": {"stalled_stream": 1, "queue_wait": 1},
            "num_running": 9, "num_waiting": 1, "compiles": 14,
            "compiles_by_kind": {"prefill": 6, "decode_multi": 8},
            "mfu": 0.241, "tokens_per_s": 812.0,
            "kvbm_host_blocks": 12, "kvbm_disk_blocks": 3,
            "kvbm_demotions_total": 15, "kvbm_promotions_total": 6,
            "kvbm_host_hits_total": 5, "kvbm_disk_hits_total": 1,
            "hbm_weights_bytes": 2147483648, "hbm_kv_pool_bytes": 3435973836,
            "hbm_free_bytes": 25769803776, "hbm_peak_bytes": 6000000000,
            "host": 0, "dispatch_p95_ms": 7.2,
            "slo": {
                "requests_total": 400, "within_sla_total": 392,
                "tokens_total": 25600, "goodput_tokens_total": 25100,
                "attainment": 0.98,
                "ttft_ms": {"p50": 130.1, "p95": 410.2, "p99": 601.3,
                            "n": 400},
                "itl_ms": {"p50": 13.2, "p95": 21.8, "p99": 30.0,
                           "n": 25000},
                "windows": {"60": {"requests": 80, "attainment": 0.975,
                                   "burn_rate": 2.5}},
            },
        },
        "worker-prefill-1": {
            "role": "prefill", "component": "prefill", "model": "llama3-1b",
            "last_seen_s": 1.1, "req_s": 4.0, "tok_s": 4100.0,
            "kv_usage": 0.11, "compiles": 4, "mfu": 0.38,
        },
    },
    "roles": {
        "decode": {"workers": 1, "kv_usage": 0.42, "mfu": 0.241,
                   "tokens_per_s": 812.0, "preemptions": 3,
                   "compiles_by_kind": {"prefill": 6, "decode_multi": 8}},
        "prefill": {"workers": 1, "kv_usage": 0.11, "mfu": 0.38,
                    "tokens_per_s": 4100.0, "preemptions": 0,
                    "compiles_by_kind": {}},
    },
    "fleet": {
        "workers": 2,
        "slo": {
            "requests_total": 400, "within_sla_total": 392,
            "tokens_total": 25600, "goodput_tokens_total": 25100,
            "attainment": 0.98,
            "ttft_ms": {"p50": 130.1, "p95": 410.2, "p99": 601.3, "n": 400},
            "itl_ms": {"p50": 13.2, "p95": 21.8, "p99": 30.0, "n": 25000},
            "windows": {"60": {"requests": 80, "attainment": 0.975,
                               "burn_rate": 2.5},
                        "600": {"requests": 400, "attainment": 0.98,
                                "burn_rate": 2.0}},
        },
    },
}


def _load_fleet_top():
    spec = importlib.util.spec_from_file_location(
        "fleet_top", REPO / "scripts" / "fleet_top.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


#: recorded kept-trace summaries (metrics service GET /v1/traces) for
#: the WORST-TRACE column
RECORDED_TRACES = [
    {"trace_id": "aa11" * 8, "duration_ms": 4200.5,
     "workers": ["worker-decode-1"], "kept_reasons": ["slow_e2e"],
     "breakdown": {"total_ms": 4200.5, "dominant": "queue_wait",
                   "phases": {"queue_wait": 3000.0, "decode": 1200.5}}},
    {"trace_id": "bb22" * 8, "duration_ms": 900.0,
     "workers": ["worker-decode-1", "worker-prefill-1"],
     "kept_reasons": ["healthy_sample"],
     "breakdown": {"total_ms": 900.0, "dominant": "decode",
                   "phases": {"decode": 900.0}}},
]


def test_fleet_top_renders_events_timeline():
    ft = _load_fleet_top()
    events = [
        {"id": 1, "ts": 1754300000.0, "type": "role_flip",
         "severity": "info", "source": "worker-1", "count": 1,
         "attrs": {"src": "prefill", "dst": "decode"}},
        {"id": 2, "ts": 1754300011.0, "type": "shed",
         "severity": "warning", "source": "frontend:burn", "count": 37,
         "attrs": {"reason": "burn"}},
        {"id": 3, "ts": 1754300012.5, "type": "worker_lost",
         "severity": "critical", "source": "worker-9", "count": 1,
         "attrs": {"role": "decode"}},
    ]
    text = ft.render_events(events, color=True)
    lines = text.splitlines()
    assert len(lines) == 3
    assert "role_flip" in lines[0] and "dst=decode" in lines[0]
    assert "x37" in lines[1] and "\x1b[33m" in lines[1]  # warning color
    assert "\x1b[31m" in lines[2]  # critical color
    plain = ft.render_events(events, color=False)
    assert "\x1b[" not in plain
    assert "(no fleet events)" in ft.render_events([])


def test_fleet_top_hbm_column(tmp_path):
    """ISSUE 19 satellite: the HBM w/kv/free column renders the frame's
    hbm_* gauges compactly; workers without the plane degrade to a
    dash, not a crash."""
    ft = _load_fleet_top()
    assert ft._bshort(2147483648) == "2.0G"
    assert ft._bshort(3435973836) == "3.2G"
    assert ft._bshort(25769803776) == "24G"
    assert ft._bshort(427264) == "417K"  # binary units
    assert ft._bshort(0) == "0"
    assert ft._bshort(None) == "-"

    text = ft.render(RECORDED_SNAPSHOT)
    assert "HBM w/kv/free" in text
    decode_row = next(
        l for l in text.splitlines() if l.startswith("worker-decode-1")
    )
    assert "2.0G/3.2G/24G" in decode_row
    # prefill worker predates the plane: no hbm_* fields -> dash
    prefill_row = next(
        l for l in text.splitlines() if l.startswith("worker-prefill-1")
    )
    cols = prefill_row.split()
    assert "-" in cols


def test_fleet_top_renders_recorded_snapshot(tmp_path):
    ft = _load_fleet_top()
    text = ft.render(RECORDED_SNAPSHOT, traces=RECORDED_TRACES)
    # WORST-TRACE column: slowest kept trace touching each worker
    assert "WORST-TRACE" in text
    decode_row0 = next(
        l for l in text.splitlines() if l.startswith("worker-decode-1")
    )
    assert "aa11aa11 4200ms" in decode_row0
    prefill_row0 = next(
        l for l in text.splitlines() if l.startswith("worker-prefill-1")
    )
    assert "bb22bb22 900ms" in prefill_row0
    # without trace data the column degrades to dashes, not a crash
    text = ft.render(RECORDED_SNAPSHOT)
    assert "worker-decode-1" in text
    assert "decode" in text and "prefill" in text
    assert "0.2410" in text  # worker MFU
    assert "130.1" in text or "130/" in text  # ttft p50 in fleet footer
    assert "burn rate 2.50x" in text
    assert "goodput 25100/25600 tokens" in text
    # KV-economy TIER/HIT column: lower-tier residency + which tier
    # served the hits ("12h3d 5/1"); workers without KVBM show "-"
    assert "TIER/HIT" in text
    assert "12h3d 5/1" in decode_row0
    assert "12h3d" not in prefill_row0
    # stall-count + burn-rate columns (sourced from the watchdog's
    # stalls_total and the worker SLO windows)
    assert "STALLS" in text and "BURN" in text
    decode_row = next(
        l for l in text.splitlines() if l.startswith("worker-decode-1")
    )
    assert " 2 " in decode_row  # stalls_total
    assert "2.5x" in decode_row  # 60s-window burn rate
    prefill_row = next(
        l for l in text.splitlines() if l.startswith("worker-prefill-1")
    )
    assert " - " in prefill_row  # no stall/burn data: dashes, not zeros
    # the CLI one-shot path over a recorded file
    snap_file = tmp_path / "fleet.json"
    snap_file.write_text(json.dumps(RECORDED_SNAPSHOT))
    import subprocess

    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "fleet_top.py"),
         "--snapshot", str(snap_file)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "worker-prefill-1" in out.stdout


def test_no_fleet_telemetry_is_bit_identical():
    """--no-fleet-telemetry: same config/seed/prompts => identical token
    streams, no SLO tracker, zero throughput-window bookkeeping."""
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    import dataclasses

    outs = {}
    for on in (True, False):
        cfg = dataclasses.replace(
            EngineConfig.for_tests(), fleet_telemetry=on
        )
        eng = JaxEngine(cfg)
        for i in range(3):
            eng.add_request(
                f"r{i}", [1 + i, 2, 3, 4],
                SamplingParams(temperature=0.8, top_p=0.9, max_tokens=6),
            )
        outs[on] = eng.run_to_completion()
        if on:
            assert eng.slo is not None
            assert eng.metrics.mfu >= 0.0
        else:
            assert eng.slo is None
            assert len(eng._thru_window) == 0
            assert eng.metrics.mfu == 0.0
    assert outs[True] == outs[False]


def test_metrics_service_promlint_gate_with_fleet_families():
    """CI gate (satellite 5): a fully-populated exposition — worker
    frames with SLO wires + fleet families + phase histograms — lints
    clean, so future fleet metrics can't regress the format."""

    async def main():
        from dynamo_tpu.engine.engine import EngineMetrics
        from dynamo_tpu.telemetry import phases, promlint
        from dynamo_tpu.telemetry.slo import SloTracker

        server = FabricServer(port=0)
        await server.start()
        try:
            rt_m = await DistributedRuntime.create(server.address)
            rt_w = await DistributedRuntime.create(server.address)
            svc = MetricsService(rt_m.fabric, port=0)
            await svc.start()
            await asyncio.sleep(0.1)
            tr = SloTracker()
            tr.observe("ttft_ms", 100.0)
            tr.observe("itl_ms", 10.0)
            tr.observe("e2e_ms", 500.0)
            tr.finish_request(ttft_ms=100.0, itl_ms=10.0, e2e_ms=500.0,
                              tokens=64)
            frame = EngineMetrics().to_dict()
            frame.update(
                instance_id="w1", model="tiny", component="backend",
                role="decode", slo=tr.to_wire(),
                compiles_by_kind={"prefill": 2, "decode": 1},
                kv_transfer_shm_total=1, remote_prefills_total=1,
                ext_ready=1, ext_restarts_total=0,
            )
            await rt_w.fabric.publish(
                f"{METRICS_SUBJECT}.backend.w1", frame
            )
            prefill_frame = dict(frame)
            prefill_frame.update(
                instance_id="p1", component="prefill", role="prefill"
            )
            await rt_w.fabric.publish(
                f"{METRICS_SUBJECT}.prefill.p1", prefill_frame
            )
            await asyncio.sleep(0.2)
            for phase in phases.PHASES:
                phases.observe(phase, 1.5)
            text = svc.expose()
            assert promlint.lint(text) == [], promlint.lint(text)[:8]
            assert 'dynamo_tpu_fleet_workers{role="prefill"} 1' in text
            assert (
                'dynamo_tpu_fleet_sla_requests_total{role="decode"} 1'
                in text
            )
            assert "dynamo_tpu_phase_compile_ms_bucket" in text
            await svc.stop()
            await rt_m.close()
            await rt_w.close()
        finally:
            await server.stop()

    run(main())

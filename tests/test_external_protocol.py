"""Wire-protocol tests for the subprocess external-engine harness:
frame round-trips, corruption fuzz (truncation, bit flips, bad
checksums), handshake validation, and supervisor-side version refusal
with a live child."""

import asyncio
import random
import sys

import pytest

from dynamo_tpu.external import protocol
from dynamo_tpu.runtime.codec import (
    CodecError,
    decode_frame,
    encode_frame,
)


def test_frame_round_trip_all_types():
    """Every protocol frame shape survives encode -> decode bit-exact."""
    cases = [
        (protocol.hello_frame("m", {"embed": True}, card={"x": 1}), b""),
        (protocol.ready_frame(), b""),
        (
            {"type": "generate", "id": "r1"},
            protocol.pack({"request_id": "r1", "token_ids": [1, 2, 3]}),
        ),
        (
            {"type": "token", "id": "r1"},
            protocol.pack({"token_ids": [5], "finish_reason": None}),
        ),
        (
            {"type": "finish", "id": "r1", "finish_reason": "stop",
             "cancelled": False},
            b"",
        ),
        ({"type": "error", "id": "r1", "message": "boom"}, b""),
        ({"type": "cancel", "id": "r1"}, b""),
        (
            {"type": "kv_event"},
            protocol.pack(
                [
                    {
                        "kind": "stored",
                        "block_hashes": [123, 456],
                        "parent_hash": None,
                        "token_blocks": [[1, 2], [3, 4]],
                    }
                ]
            ),
        ),
        ({"type": "metrics"}, protocol.pack({"num_running": 2})),
        ({"type": "ping", "n": 7}, b""),
        ({"type": "shutdown"}, b""),
    ]
    for header, payload in cases:
        buf = encode_frame(header, payload)
        h, p, consumed = decode_frame(buf)
        assert h == header
        assert p == payload
        assert consumed == len(buf)


def test_truncated_frames_raise():
    buf = encode_frame(
        {"type": "token", "id": "r"}, protocol.pack({"token_ids": [1] * 64})
    )
    for cut in (0, 1, 7, 15, 16, len(buf) // 2, len(buf) - 1):
        with pytest.raises(CodecError):
            decode_frame(buf[:cut])


def test_bit_flip_fuzz_never_misparses():
    """Any single corrupted byte anywhere in the frame must surface as a
    CodecError — never as silently different data (the checksum
    discipline the harness inherits from the fabric codec)."""
    rng = random.Random(0)
    header = {"type": "token", "id": "req-42"}
    payload = protocol.pack(
        {"token_ids": list(range(32)), "finish_reason": None}
    )
    buf = encode_frame(header, payload)
    for _ in range(300):
        pos = rng.randrange(len(buf))
        flip = 1 << rng.randrange(8)
        corrupted = bytearray(buf)
        corrupted[pos] ^= flip
        try:
            h, p, _ = decode_frame(bytes(corrupted))
        except (CodecError, Exception) as e:
            # length corruption can also manifest as short-buffer/too-large
            assert isinstance(e, CodecError), (pos, flip, e)
            continue
        raise AssertionError(
            f"corrupted byte {pos} (flip {flip:#x}) parsed as {h!r}"
        )


def test_handshake_validation():
    protocol.check_hello(protocol.hello_frame("m"))
    protocol.check_ready(protocol.ready_frame())

    with pytest.raises(protocol.ProtocolError):
        protocol.check_hello({"type": "token", "id": "x"})
    with pytest.raises(protocol.ProtocolError):
        protocol.check_ready({"type": "hello", "v": protocol.PROTOCOL_VERSION})
    with pytest.raises(protocol.VersionMismatch):
        protocol.check_hello({"type": "hello", "v": 999, "model": "m"})
    with pytest.raises(protocol.VersionMismatch):
        protocol.check_ready({"type": "ready", "v": 0})


def test_unknown_frame_types_are_ignored():
    """Forward compatibility: the client routes unknown child frames to
    the void instead of dying."""
    from dynamo_tpu.external.client import SubprocessEngine

    eng = SubprocessEngine([sys.executable, "-c", "pass"], name="t")
    eng._on_frame({"type": "definitely-not-a-frame", "x": 1}, b"")
    eng._on_frame({"type": "token", "id": "nobody"}, protocol.pack({}))
    eng._on_frame({"type": "finish", "id": "nobody"}, b"")


def test_version_mismatch_refused_at_live_handshake():
    """A real child claiming protocol v99 is refused permanently: the
    supervisor circuit-opens (no restart loop — a version skew cannot be
    restarted away) and admission raises a retryable error."""
    from dynamo_tpu.external.client import (
        EngineUnavailableError,
        SubprocessEngine,
    )
    from dynamo_tpu.external.supervisor import SupervisorConfig
    from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
    from dynamo_tpu.runtime.context import Context

    async def main():
        eng = SubprocessEngine(
            [sys.executable, "-m", "dynamo_tpu.external.reference_worker",
             "--hello-version", "99"],
            name="vmm",
            config=SupervisorConfig(ready_timeout=30.0, backoff_initial=0.05),
            admission_timeout=1.0,
        )
        await eng.start(wait_ready=False)
        for _ in range(200):
            if eng.supervisor.state == "broken":
                break
            await asyncio.sleep(0.05)
        assert eng.supervisor.state == "broken"
        with pytest.raises(EngineUnavailableError):
            async for _ in eng.generate(
                Context(request_id="r"),
                PreprocessedRequest(request_id="r", token_ids=[1]),
            ):
                pass
        await eng.stop()

    asyncio.run(main())

"""Helm chart renders to valid, coherent Kubernetes manifests.

No helm binary ships in this environment, so the chart is written against
a DISCIPLINED template subset (documented in values.yaml) and validated by
a mini renderer implementing exactly that subset: `{{ .Values.* }}` /
`{{ .Release.Name }}` / `{{ .Release.Namespace }}` lookups, `| quote`,
`{{ include "name" . }}` of helpers defined with `{{- define }}`,
`{{- if }}/{{- else }}/{{- end }}` blocks, and `eq <lookup> "<literal>"`
conditions. Anything outside the subset fails the test loudly —
which is the guard that keeps the chart renderable by real `helm
template` (parity: deploy/cloud/helm/platform).
"""

from __future__ import annotations

import os
import re

import pytest
import yaml

CHART = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "deploy", "helm", "dynamo-tpu",
)

_TAG = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}")


class MiniHelm:
    """The template subset the chart is allowed to use."""

    def __init__(self, values: dict, release: str, namespace: str = "default"):
        self.values = values
        self.release = release
        self.namespace = namespace
        self.helpers: dict[str, str] = {}

    def load_helpers(self, text: str) -> None:
        for m in re.finditer(
            r'\{\{-\s*define\s+"([^"]+)"\s*-\}\}(.*?)\{\{-?\s*end\s*-\}\}',
            text, re.S,
        ):
            self.helpers[m.group(1)] = m.group(2).strip()

    # -- expression evaluation --------------------------------------------

    def _lookup(self, path: str):
        if path == ".Release.Name":
            return self.release
        if path == ".Release.Namespace":
            return self.namespace
        assert path.startswith(".Values."), f"unsupported lookup {path!r}"
        node = self.values
        for part in path[len(".Values."):].split("."):
            assert isinstance(node, dict) and part in node, (
                f"values key missing: {path}"
            )
            node = node[part]
        return node

    def _eval(self, expr: str):
        expr = expr.strip()
        eq = re.fullmatch(r'eq\s+(\S+)\s+"([^"]*)"', expr)
        if eq:
            return self._eval(eq.group(1)) == eq.group(2)
        inc = re.fullmatch(r'include\s+"([^"]+)"\s+\.', expr)
        if inc:
            name = inc.group(1)
            assert name in self.helpers, f"unknown helper {name!r}"
            return self.render_text(self.helpers[name])
        if "|" in expr:
            base, *filters = [p.strip() for p in expr.split("|")]
            val = self._eval(base)
            for f in filters:
                assert f == "quote", f"unsupported filter {f!r}"
                val = f'"{val}"'
            return val
        return self._lookup(expr)

    # -- block structure ---------------------------------------------------

    def render_text(self, text: str) -> str:
        """Handle if/else/end blocks, then inline tags."""
        out = []
        stack = [[True]]  # branch-taken stack

        def active():
            return all(s[-1] for s in stack)

        for line in text.split("\n"):
            m = _TAG.search(line)
            tag = m.group(1).strip() if m else None
            if tag and tag.startswith("if "):
                cond = bool(self._eval(tag[3:])) if active() else False
                stack.append([cond])
                continue
            if tag == "else":
                stack[-1][-1] = (
                    not stack[-1][-1] and all(s[-1] for s in stack[:-1])
                )
                continue
            if tag == "end":
                assert len(stack) > 1, "unbalanced end"
                stack.pop()
                continue
            if not active():
                continue
            out.append(_TAG.sub(lambda mm: str(self._eval(mm.group(1))), line))
        assert len(stack) == 1, "unbalanced if/end"
        return "\n".join(out)

    def render_chart(self) -> list[dict]:
        tpl_dir = os.path.join(CHART, "templates")
        helpers = os.path.join(tpl_dir, "_helpers.tpl")
        if os.path.exists(helpers):
            with open(helpers) as f:
                self.load_helpers(f.read())
        docs = []
        for name in sorted(os.listdir(tpl_dir)):
            if not name.endswith(".yaml"):
                continue
            with open(os.path.join(tpl_dir, name)) as f:
                rendered = self.render_text(f.read())
            for doc in yaml.safe_load_all(rendered):
                if doc:
                    docs.append(doc)
        # CRDs ship verbatim
        crds = os.path.join(CHART, "crds")
        if os.path.isdir(crds):
            for name in sorted(os.listdir(crds)):
                with open(os.path.join(crds, name)) as f:
                    docs.extend(d for d in yaml.safe_load_all(f.read()) if d)
        return docs


@pytest.fixture(scope="module")
def values():
    with open(os.path.join(CHART, "values.yaml")) as f:
        return yaml.safe_load(f)


def _render(values, release="dt", **overrides) -> list[dict]:
    import copy

    v = copy.deepcopy(values)
    for path, val in overrides.items():
        node = v
        *parents, last = path.split(".")
        for p in parents:
            node = node[p]
        node[last] = val
    return MiniHelm(v, release).render_chart()


def test_operator_mode_default_render(values):
    """Default mode: the chart renders the shared platform (fabric,
    metrics, operator, planner) plus ONE DynamoGraphDeployment CR; the
    worker fleet comes from the operator reconciling that CR — never from
    static chart Deployments that would double the fleet."""
    docs = _render(values)
    kinds = [(d["kind"], d["metadata"]["name"]) for d in docs]
    for expected in [
        ("Service", "dt-fabric"), ("Deployment", "dt-fabric"),
        ("PersistentVolumeClaim", "dt-fabric-wal"),
        ("DynamoGraphDeployment", "dt"),
        ("Deployment", "dt-planner"),
        ("Deployment", "dt-metrics"), ("Service", "dt-metrics"),
        ("Deployment", "dt-operator"),
        ("CustomResourceDefinition", "dynamographdeployments.dynamo.tpu"),
    ]:
        assert expected in kinds, f"missing {expected}"
    for absent in [
        ("Deployment", "dt-decode-worker"),
        ("Deployment", "dt-prefill-worker"),
        ("Deployment", "dt-frontend"),
        ("Deployment", "dt-router"),
    ]:
        assert absent not in kinds, f"unexpected static object {absent}"

    for d in docs:
        assert d.get("apiVersion") and d.get("kind")
        if d["kind"] == "Deployment":
            for c in d["spec"]["template"]["spec"]["containers"]:
                assert c["image"] == "dynamo-tpu:latest"
                assert c["command"][0:3] == [
                    "python", "-m", "dynamo_tpu.cli.run"
                ], c["command"]
                assert all("{{" not in str(a) for a in c["command"])


def test_operator_mode_cr_is_reconcilable(values):
    """The rendered CR must be one OUR reconciler accepts and must share
    the chart's fabric instead of spawning a second one."""
    from dynamo_tpu.operator.reconciler import desired_objects

    docs = _render(values, release="prod")
    cr = next(d for d in docs if d["kind"] == "DynamoGraphDeployment")
    assert cr["spec"]["fabricHost"] == "prod-fabric"
    assert cr["spec"]["fabricExternal"] is True
    names = {s["name"] for s in cr["spec"]["services"]}
    assert names == {"Frontend", "Worker", "PrefillWorker"}

    children = desired_objects(cr)
    child_names = {c["metadata"]["name"] for c in children}
    # no per-graph fabric: the CHART's persistent fabric is the rendezvous
    assert "prod-fabric" not in child_names
    for c in children:
        if c["kind"] == "Deployment":
            cmd = c["spec"]["template"]["spec"]["containers"][0]["command"]
            assert "prod-fabric:4222" in cmd
    # TPU scheduling flows CR -> reconciled worker pods
    worker = next(c for c in children if c["metadata"]["name"] == "worker")
    pod = worker["spec"]["template"]["spec"]
    assert pod["nodeSelector"] == {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice"
    }
    assert pod["containers"][0]["resources"]["limits"]["google.com/tpu"] == "4"

    # non-default fabric port flows through the whole chain
    docs2 = _render(values, release="p2", **{"fabric.port": 5000})
    cr2 = next(d for d in docs2 if d["kind"] == "DynamoGraphDeployment")
    assert cr2["spec"]["fabricPort"] == 5000
    for c in desired_objects(cr2):
        if c["kind"] == "Deployment":
            cmd = c["spec"]["template"]["spec"]["containers"][0]["command"]
            assert "p2-fabric:5000" in cmd

    # fabricExternal without a host must fail loudly, not render a
    # dangling '--fabric <name>-fabric' pointing at nothing
    import pytest as _pytest

    bad = {"metadata": {"name": "x"}, "spec": {
        "fabricExternal": True, "services": [],
    }}
    with _pytest.raises(ValueError, match="fabricHost"):
        desired_objects(bad)


def test_operator_and_planner_are_namespace_scoped(values):
    docs = _render(values, release="dt")
    # subset renderer defaults namespace to "default"; a real install's
    # .Release.Namespace flows through the same lookups
    by_name = {
        (d["kind"], d["metadata"]["name"]): d for d in docs
    }
    op_cmd = by_name[("Deployment", "dt-operator")]["spec"]["template"][
        "spec"
    ]["containers"][0]["command"]
    assert "--namespace" in op_cmd
    pl = by_name[("Deployment", "dt-planner")]["spec"]["template"]["spec"]
    pl_cmd = pl["containers"][0]["command"]
    assert "--k8s-namespace" in pl_cmd
    assert "--cr-name" in pl_cmd and "dt" in pl_cmd
    assert "decode=Worker" in pl_cmd and "prefill=PrefillWorker" in pl_cmd
    # planner RBAC covers the CRs it edits
    role = by_name[("Role", "dt-planner")]
    groups = {g for r in role["rules"] for g in r["apiGroups"]}
    assert "dynamo.tpu" in groups


def test_static_mode_renders_fleet_without_operator(values):
    docs = _render(
        values,
        **{"managed": "static", "router.enabled": True},
    )
    kinds = [(d["kind"], d["metadata"]["name"]) for d in docs]
    for expected in [
        ("Deployment", "dt-frontend"), ("Service", "dt-frontend"),
        ("Deployment", "dt-decode-worker"),
        ("Deployment", "dt-prefill-worker"),
        ("Deployment", "dt-router"),
    ]:
        assert expected in kinds, f"missing {expected}"
    for absent in [
        ("DynamoGraphDeployment", "dt"),
        ("Deployment", "dt-planner"),
        ("Deployment", "dt-operator"),
    ]:
        assert absent not in kinds, f"unexpected {absent}"
    by_name = {d["metadata"]["name"]: d for d in docs}
    cmd = by_name["dt-decode-worker"]["spec"]["template"]["spec"][
        "containers"
    ][0]["command"]
    assert "dt-fabric:4222" in cmd
    assert "--disagg" in cmd and "--kv-remote" in cmd
    rcmd = by_name["dt-router"]["spec"]["template"]["spec"]["containers"][0][
        "command"
    ]
    assert "--salt" in rcmd and values["model"] in rcmd


def test_fabric_persistence_toggle(values):
    docs = _render(values, **{"fabric.persistence.enabled": False})
    kinds = [(d["kind"], d["metadata"]["name"]) for d in docs]
    assert ("PersistentVolumeClaim", "dt-fabric-wal") not in kinds
    by_name = {d["metadata"]["name"]: d for d in docs if d["kind"] == "Deployment"}
    vols = by_name["dt-fabric"]["spec"]["template"]["spec"]["volumes"]
    assert vols == [{"name": "fabric-wal", "emptyDir": {}}]

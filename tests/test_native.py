"""Native C++ core (native/) parity with the pure-Python paths.

The native library is the production hot path (token-block chain hashing,
KV radix index); these tests pin it byte-for-byte / decision-for-decision
against the Python implementations, plus golden xxh3 values against the
python-xxhash C extension (the canonical reference for the hash).
"""

import os
import random

import numpy as np
import pytest
import xxhash

from dynamo_tpu.native import ensure_built, lib

pytestmark = pytest.mark.skipif(
    ensure_built() is None, reason="native library unavailable (no g++?)"
)


# -- xxh3 -------------------------------------------------------------------


@pytest.mark.parametrize(
    "n", [0, 1, 2, 3, 4, 7, 8, 9, 15, 16, 17, 64, 128, 129, 200, 240, 241,
          256, 512, 1023, 1024, 1025, 4096, 10000]
)
def test_xxh3_matches_python_xxhash(n):
    rng = random.Random(n)
    data = bytes(rng.getrandbits(8) for _ in range(n))
    for seed in (0, 1, 1337, rng.getrandbits(64)):
        got = lib().dyn_xxh3_64(data, n, seed)
        assert got == xxhash.xxh3_64_intdigest(data, seed=seed)


def test_xxh3_fuzz():
    rng = random.Random(42)
    for _ in range(500):
        n = rng.randrange(0, 3000)
        data = os.urandom(n)
        seed = rng.getrandbits(64)
        assert lib().dyn_xxh3_64(data, n, seed) == xxhash.xxh3_64_intdigest(
            data, seed=seed
        )


# -- token-block chain hashing ---------------------------------------------


def _python_chain(tokens, block_size, salt):
    """Ground-truth chain via the scalar Python primitives."""
    from dynamo_tpu.tokens.blocks import (
        compute_block_hash,
        compute_salt_hash,
        compute_seq_hash,
    )

    salt_hash = compute_salt_hash(salt)
    parent = None
    bhs, shs = [], []
    for i in range(len(tokens) // block_size):
        block = tokens[i * block_size : (i + 1) * block_size]
        bh = compute_block_hash(block, parent if parent is not None else salt_hash)
        sh = compute_seq_hash(parent, bh)
        bhs.append(bh)
        shs.append(sh)
        parent = sh
    return bhs, shs


@pytest.mark.parametrize("block_size,n", [(4, 0), (4, 3), (4, 4), (4, 17),
                                          (64, 64), (64, 257), (16, 1000)])
def test_token_block_sequence_native_bulk_parity(block_size, n):
    from dynamo_tpu.tokens import TokenBlockSequence

    rng = random.Random(n)
    tokens = [rng.randrange(0, 1 << 32) for _ in range(n)]
    seq = TokenBlockSequence(tokens, block_size=block_size, salt="model-x")
    bhs, shs = _python_chain(tokens, block_size, "model-x")
    assert seq.block_hashes() == bhs
    assert seq.sequence_hashes() == shs
    assert seq.tokens == tokens
    # Appending after a bulk init must continue the same chain.
    extra = [rng.randrange(0, 1 << 32) for _ in range(2 * block_size)]
    seq.extend(extra)
    bhs2, shs2 = _python_chain(tokens + extra, block_size, "model-x")
    assert seq.sequence_hashes() == shs2


def test_token_values_beyond_int64_mask_like_python(monkeypatch):
    from dynamo_tpu import native
    from dynamo_tpu.tokens import TokenBlockSequence

    toks = [2**63, 2**64 - 1, 5, 6]
    with_native = TokenBlockSequence(toks, block_size=4).sequence_hashes()
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_build_failed", True)
    without = TokenBlockSequence(toks, block_size=4).sequence_hashes()
    assert with_native == without


def test_hash_token_blocks_native_vs_forced_python(monkeypatch):
    from dynamo_tpu import native
    from dynamo_tpu.tokens import hash_token_blocks

    tokens = [random.Random(9).randrange(0, 1 << 32) for _ in range(300)]
    with_native = hash_token_blocks(tokens, block_size=32, salt="s")
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_build_failed", True)
    without = hash_token_blocks(tokens, block_size=32, salt="s")
    assert with_native == without


# -- radix index ------------------------------------------------------------


def test_native_radix_tree_matches_python():
    from dynamo_tpu.kv_router.indexer import NativeRadixTree, RadixTree

    rng = random.Random(7)
    native_tree, py_tree = NativeRadixTree(), RadixTree()
    workers = [f"w{i}" for i in range(6)]
    # Build some shared-prefix hash chains.
    chains = [[rng.getrandbits(64) for _ in range(10)] for _ in range(4)]
    chains.append(chains[0][:5] + [rng.getrandbits(64) for _ in range(5)])

    events = []
    for _ in range(400):
        w = rng.choice(workers)
        chain = rng.choice(chains)
        k = rng.randrange(1, len(chain) + 1)
        kind = "stored" if rng.random() < 0.7 else "removed"
        events.append((w, {"kind": kind, "block_hashes": chain[:k]}))
    for w, ev in events:
        native_tree.apply_event(w, ev)
        py_tree.apply_event(w, ev)

    assert native_tree.num_blocks == py_tree.num_blocks
    assert native_tree.events_applied == py_tree.events_applied
    for chain in chains:
        for k in (0, 1, 5, 10):
            a = native_tree.find_matches(chain[:k])
            b = py_tree.find_matches(chain[:k])
            assert a.scores == b.scores, (chain[:k], a.scores, b.scores)
            assert a.matched_blocks == b.matched_blocks

    gone = workers[0]
    assert native_tree.remove_worker(gone) == py_tree.remove_worker(gone)
    for chain in chains:
        a = native_tree.find_matches(chain)
        b = py_tree.find_matches(chain)
        assert a.scores == b.scores
    for w in workers:
        assert native_tree.blocks_for(w) == py_tree.blocks_for(w)

    native_tree.clear()
    py_tree.clear()
    assert native_tree.num_blocks == py_tree.num_blocks == 0
    assert native_tree.find_matches(chains[0]).scores == {}

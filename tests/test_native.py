"""Native C++ core (native/) parity with the pure-Python paths.

The native library is the production hot path (token-block chain hashing,
KV radix index); these tests pin it byte-for-byte / decision-for-decision
against the Python implementations, plus golden xxh3 values against the
python-xxhash C extension (the canonical reference for the hash).
"""

import os
import random

import numpy as np
import pytest
import xxhash

from dynamo_tpu.native import ensure_built, lib

pytestmark = pytest.mark.skipif(
    ensure_built() is None, reason="native library unavailable (no g++?)"
)


# -- xxh3 -------------------------------------------------------------------


@pytest.mark.parametrize(
    "n", [0, 1, 2, 3, 4, 7, 8, 9, 15, 16, 17, 64, 128, 129, 200, 240, 241,
          256, 512, 1023, 1024, 1025, 4096, 10000]
)
def test_xxh3_matches_python_xxhash(n):
    rng = random.Random(n)
    data = bytes(rng.getrandbits(8) for _ in range(n))
    for seed in (0, 1, 1337, rng.getrandbits(64)):
        got = lib().dyn_xxh3_64(data, n, seed)
        assert got == xxhash.xxh3_64_intdigest(data, seed=seed)


def test_xxh3_fuzz():
    rng = random.Random(42)
    for _ in range(500):
        n = rng.randrange(0, 3000)
        data = os.urandom(n)
        seed = rng.getrandbits(64)
        assert lib().dyn_xxh3_64(data, n, seed) == xxhash.xxh3_64_intdigest(
            data, seed=seed
        )


# -- token-block chain hashing ---------------------------------------------


def _python_chain(tokens, block_size, salt):
    """Ground-truth chain via the scalar Python primitives."""
    from dynamo_tpu.tokens.blocks import (
        compute_block_hash,
        compute_salt_hash,
        compute_seq_hash,
    )

    salt_hash = compute_salt_hash(salt)
    parent = None
    bhs, shs = [], []
    for i in range(len(tokens) // block_size):
        block = tokens[i * block_size : (i + 1) * block_size]
        bh = compute_block_hash(block, parent if parent is not None else salt_hash)
        sh = compute_seq_hash(parent, bh)
        bhs.append(bh)
        shs.append(sh)
        parent = sh
    return bhs, shs


@pytest.mark.parametrize("block_size,n", [(4, 0), (4, 3), (4, 4), (4, 17),
                                          (64, 64), (64, 257), (16, 1000)])
def test_token_block_sequence_native_bulk_parity(block_size, n):
    from dynamo_tpu.tokens import TokenBlockSequence

    rng = random.Random(n)
    tokens = [rng.randrange(0, 1 << 32) for _ in range(n)]
    seq = TokenBlockSequence(tokens, block_size=block_size, salt="model-x")
    bhs, shs = _python_chain(tokens, block_size, "model-x")
    assert seq.block_hashes() == bhs
    assert seq.sequence_hashes() == shs
    assert seq.tokens == tokens
    # Appending after a bulk init must continue the same chain.
    extra = [rng.randrange(0, 1 << 32) for _ in range(2 * block_size)]
    seq.extend(extra)
    bhs2, shs2 = _python_chain(tokens + extra, block_size, "model-x")
    assert seq.sequence_hashes() == shs2


def test_token_values_beyond_int64_mask_like_python(monkeypatch):
    from dynamo_tpu import native
    from dynamo_tpu.tokens import TokenBlockSequence

    toks = [2**63, 2**64 - 1, 5, 6]
    with_native = TokenBlockSequence(toks, block_size=4).sequence_hashes()
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_build_failed", True)
    without = TokenBlockSequence(toks, block_size=4).sequence_hashes()
    assert with_native == without


def test_hash_token_blocks_native_vs_forced_python(monkeypatch):
    from dynamo_tpu import native
    from dynamo_tpu.tokens import hash_token_blocks

    tokens = [random.Random(9).randrange(0, 1 << 32) for _ in range(300)]
    with_native = hash_token_blocks(tokens, block_size=32, salt="s")
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_build_failed", True)
    without = hash_token_blocks(tokens, block_size=32, salt="s")
    assert with_native == without


# -- radix index ------------------------------------------------------------


def test_native_radix_tree_matches_python():
    from dynamo_tpu.kv_router.indexer import NativeRadixTree, RadixTree

    rng = random.Random(7)
    native_tree, py_tree = NativeRadixTree(), RadixTree()
    workers = [f"w{i}" for i in range(6)]
    # Build some shared-prefix hash chains.
    chains = [[rng.getrandbits(64) for _ in range(10)] for _ in range(4)]
    chains.append(chains[0][:5] + [rng.getrandbits(64) for _ in range(5)])

    events = []
    for _ in range(400):
        w = rng.choice(workers)
        chain = rng.choice(chains)
        k = rng.randrange(1, len(chain) + 1)
        kind = "stored" if rng.random() < 0.7 else "removed"
        events.append((w, {"kind": kind, "block_hashes": chain[:k]}))
    for w, ev in events:
        native_tree.apply_event(w, ev)
        py_tree.apply_event(w, ev)

    assert native_tree.num_blocks == py_tree.num_blocks
    assert native_tree.events_applied == py_tree.events_applied
    for chain in chains:
        for k in (0, 1, 5, 10):
            a = native_tree.find_matches(chain[:k])
            b = py_tree.find_matches(chain[:k])
            assert a.scores == b.scores, (chain[:k], a.scores, b.scores)
            assert a.matched_blocks == b.matched_blocks

    gone = workers[0]
    assert native_tree.remove_worker(gone) == py_tree.remove_worker(gone)
    for chain in chains:
        a = native_tree.find_matches(chain)
        b = py_tree.find_matches(chain)
        assert a.scores == b.scores
    for w in workers:
        assert native_tree.blocks_for(w) == py_tree.blocks_for(w)

    native_tree.clear()
    py_tree.clear()
    assert native_tree.num_blocks == py_tree.num_blocks == 0
    assert native_tree.find_matches(chains[0]).scores == {}


# -- host tier slabs ---------------------------------------------------------


def test_host_tier_native_slab_roundtrip():
    import ml_dtypes

    from dynamo_tpu.kvbm.tiers import BlockEntry, HostTier

    shape = (2, 4, 8, 16)  # [L, Hkv, S, D]
    tier = HostTier(capacity_bytes=1 << 20)
    rng = np.random.default_rng(0)

    def mk(h, parent=None):
        k = rng.standard_normal(shape).astype(ml_dtypes.bfloat16)
        v = rng.standard_normal(shape).astype(ml_dtypes.bfloat16)
        return BlockEntry(seq_hash=h, parent_hash=parent, tokens=(1, 2), k=k, v=v)

    entries = {h: mk(h) for h in (10, 11, 12)}
    for e in entries.values():
        assert tier.put(e)
    assert tier._nh is not None, "native slab store should have activated"
    assert len(tier) == 3
    assert tier.used_bytes == 3 * entries[10].nbytes
    for h, e in entries.items():
        got = tier.get(h)
        assert got is not None and got.parent_hash == e.parent_hash
        np.testing.assert_array_equal(np.asarray(got.k), np.asarray(e.k))
        np.testing.assert_array_equal(np.asarray(got.v), np.asarray(e.v))
    popped = tier.pop(11)
    np.testing.assert_array_equal(np.asarray(popped.k), np.asarray(entries[11].k))
    assert 11 not in tier and len(tier) == 2
    tier.clear()
    assert len(tier) == 0 and tier.used_bytes == 0


def test_host_tier_native_lru_demote_chain():
    from dynamo_tpu.kvbm.tiers import BlockEntry, HostTier

    shape = (1, 1, 4, 8)
    demoted = []
    one = np.ones(shape, np.float32)
    nbytes = 2 * one.nbytes
    tier = HostTier(capacity_bytes=3 * nbytes, demote=lambda e: demoted.append(
        BlockEntry(e.seq_hash, e.parent_hash, e.tokens, e.k.copy(), e.v.copy())
    ))
    for h in range(5):
        tier.put(BlockEntry(h, None, (h,), one * h, one * (h + 10)))
    # capacity 3 blocks: 0 then 1 demoted, LRU-first
    assert [e.seq_hash for e in demoted] == [0, 1]
    assert len(tier) == 3
    # demoted copies carried the right bytes
    np.testing.assert_array_equal(demoted[1].k, one * 1)
    # get() refreshes recency: touch 2, then insert -> 3 is the next victim
    assert tier.get(2) is not None
    tier.put(BlockEntry(99, None, (99,), one, one))
    assert [e.seq_hash for e in demoted] == [0, 1, 3]


# -- frame codec -------------------------------------------------------------


def test_native_codec_matches_python_framing():
    import ctypes

    from dynamo_tpu.runtime.codec import decode_frame, encode_frame

    header = {"op": "generate", "id": "r1", "n": 7}
    payload = os.urandom(333)
    frame = encode_frame(header, payload)

    import msgpack

    hbytes = msgpack.packb(header, use_bin_type=True)
    prefix = (ctypes.c_uint8 * 24)()
    lib().dyn_frame_prefix(hbytes, len(hbytes), payload, len(payload), prefix)
    native_frame = bytes(prefix) + hbytes + payload
    assert native_frame == frame, "C++ and Python framing must be byte-identical"

    hlen = ctypes.c_uint64()
    plen = ctypes.c_uint64()
    rc = lib().dyn_frame_parse_prefix(
        bytes(frame[:24]), ctypes.byref(hlen), ctypes.byref(plen)
    )
    assert rc == 0 and hlen.value == len(hbytes) and plen.value == len(payload)
    assert lib().dyn_frame_check(
        bytes(frame[:24]), hbytes, len(hbytes), payload, len(payload)
    ) == 0
    # corruption detected
    bad = bytearray(payload)
    bad[0] ^= 0xFF
    assert lib().dyn_frame_check(
        bytes(frame[:24]), hbytes, len(hbytes), bytes(bad), len(payload)
    ) == 2
    # Python side decodes the native-framed bytes
    h2, p2, consumed = decode_frame(native_frame)
    assert h2 == header and p2 == payload and consumed == len(frame)


def test_write_frame_vectored_matches_encode_frame():
    """The vectored bulk write (streaming checksum, no concat copy) must
    produce byte-identical wire format to encode_frame."""
    import asyncio

    import numpy as np

    from dynamo_tpu.runtime.codec import (
        encode_frame,
        read_frame,
        write_frame,
    )

    header = {"op": "write", "request_id": "x", "page_ids": [1, 2]}
    k = np.arange(48, dtype=np.float32).reshape(2, 24)
    v = np.ones(16, dtype=np.uint8)
    expect = encode_frame(header, k.tobytes() + v.tobytes())

    async def main():
        server_got = {}

        async def handle(reader, writer):
            server_got["frame"] = await reader.readexactly(len(expect))
            h, p = b"", b""
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await write_frame(writer, header, [k.view(np.uint8), v])
        await asyncio.sleep(0.1)
        writer.close()
        server.close()
        return server_got["frame"]

    wire = asyncio.run(main())
    assert wire == expect

    # and the read side accepts it
    async def roundtrip():
        async def handle(reader, writer):
            h, p = await read_frame(reader)
            writer.write(repr((h, len(p))).encode())
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await write_frame(writer, header, [k.view(np.uint8), v])
        out = await reader.read(1 << 16)
        writer.close()
        server.close()
        return out

    out = asyncio.run(roundtrip())
    assert b"208" in out  # 192 + 16 payload bytes arrived intact

"""Cross-host SPMD serving, end to end (VERDICT r2 item 3).

Two REAL processes x 4 virtual CPU devices each join one jax.distributed
group, build one 8-device global mesh (dp=4, tp=2), and serve a greedy
workload through SpmdDriver's lockstep event broadcast. The leader's
outputs must match a single-process run of the SAME config on a local
8-device mesh exactly — proving replicated deterministic scheduling plus
XLA cross-host collectives implement the reference's multi-node serving
(MultiNodeConfig, engines.rs:43-50) without a head-node RPC plane.
"""

import os
import sys
from pathlib import Path

import pytest

HELPER = Path(__file__).parent / "helpers" / "spmd_host.py"

pytestmark = pytest.mark.skipif(
    bool(os.environ.get("DYNTPU_TEST_ON_TPU")),
    reason="CPU-mesh lockstep test: the subprocess hosts force the CPU "
    "platform, so an on-TPU reference run would compare greedy argmax "
    "across backends",
)

#: the fleet-spawning tests are `slow`: each spawns a 2-process
#: jax.distributed group (420 s spawn timeout, and a flaky gloo
#: rendezvous can wedge a collective with no timeout at all) — far past
#: the quick-suite budget. The fake-driver test below stays quick.
fleet = pytest.mark.slow


@pytest.fixture(scope="module")
def collective_plane():
    """Skip (not wedge) on hosts whose cross-process collective plane
    can't come up — a dead gloo rendezvous otherwise burns each fleet
    test's full spawn timeout, or hangs inside a timeout-less
    collective. Only the @fleet tests request this; the fake-driver
    test needs no plane and must not pay the probe."""
    sys.path.insert(0, str(HELPER.parent))
    from spmd_host import collective_plane_available

    if not collective_plane_available():
        pytest.skip("cross-process collective plane (gloo) unavailable")


@pytest.fixture(scope="module")
def spmd_outputs(collective_plane):
    sys.path.insert(0, str(HELPER.parent))
    from spmd_host import spawn_two_hosts

    outputs, _logs = spawn_two_hosts()
    return outputs


def _reference_outputs():
    """Same config + workload on this process's local 8-device mesh."""
    sys.path.insert(0, str(HELPER.parent))
    from spmd_host import spmd_test_config, spmd_test_workload

    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    eng = JaxEngine(spmd_test_config(dp=4, tp=2))
    for rid, toks, mt in spmd_test_workload():
        eng.add_request(
            rid, toks, SamplingParams(temperature=0.0, max_tokens=mt)
        )
    return eng.run_to_completion()


@fleet
def test_two_host_serving_matches_single_process(spmd_outputs):
    ref = _reference_outputs()
    assert set(spmd_outputs) == set(ref)
    for rid in ref:
        assert spmd_outputs[rid] == ref[rid], (
            f"{rid}: spmd={spmd_outputs[rid]} ref={ref[rid]}"
        )
    # every request actually generated tokens
    assert all(len(v) > 0 for v in ref.values())


def _tier_ab(devices_per_host: int, dp: int, tp: int):
    """2-process lockstep run with host tiering vs the identical
    single-process run; returns after asserting offload, onboard, and
    byte-identical outputs."""
    sys.path.insert(0, str(HELPER.parent))
    from spmd_host import (
        spawn_two_hosts,
        spmd_tier_config,
        spmd_tier_workload,
    )

    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    result, _logs = spawn_two_hosts(
        devices_per_host=devices_per_host, dp=dp, tp=tp, tier=True
    )
    assert result["offloaded"] > 0, "churn never reached the host tier"
    assert result["onboarded"] > 0, "re-served prompt never onboarded"

    ref_eng = JaxEngine(spmd_tier_config(dp=dp, tp=tp))
    ref = {}
    for phase in spmd_tier_workload():
        for rid, toks, mt in phase:
            ref_eng.add_request(
                rid, toks, SamplingParams(temperature=0.0, max_tokens=mt)
            )
        ref.update(ref_eng.run_to_completion())
    assert ref_eng.allocator.stats.onboarded_blocks > 0

    assert set(result["outputs"]) == set(ref)
    for rid in ref:
        assert result["outputs"][rid] == ref[rid], (
            f"{rid}: spmd={result['outputs'][rid]} ref={ref[rid]}"
        )


@fleet
def test_two_host_tiering_evicts_and_onboards_byte_identically(
    collective_plane,
):
    """G2 host tiering under a CROSS-HOST mesh (round-4 verdict item 6):
    each host tiers its own Hkv shard; eviction + onboard must reproduce
    the single-process run exactly — the re-served prompt's continuation
    is byte-identical, proving the reassembled KV is the KV. (dp=4 tp=2
    over 4 devices/host: both tp shards live on each host, so the local
    slice is full-width.)"""
    _tier_ab(devices_per_host=4, dp=4, tp=2)


@fleet
def test_two_host_tiering_with_tp_spanning_hosts(collective_plane):
    """The PARTIAL-slice path: 1 device/host, tp=2 — each host holds
    HALF the kv heads, so extract really returns a partial Hkv slice and
    inject really reassembles the global array from two processes'
    halves. A wrong shard offset would corrupt generations here."""
    _tier_ab(devices_per_host=1, dp=1, tp=2)


def test_broadcast_failure_fails_inflight_admissions():
    """A broadcast-layer step failure must error that round's admissions
    instead of leaving their clients waiting forever (their events were
    popped from the driver's pending queue but reached no replica)."""
    import asyncio

    from dynamo_tpu.engine.async_engine import SpmdEngineRunner
    from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
    from dynamo_tpu.runtime.context import Context

    class FakeEngine:
        has_work = False
        metrics = None

    class BrokenDriver:
        def __init__(self):
            self._pending = []
            self.submit_errors = []
            self.last_cleared = 0

        def submit(self, rid, tokens, sampling):
            self._pending.append(("submit", rid))

        def abort(self, rid):
            pass

        def clear_cache(self):
            self._pending.append(("clear",))

        def step(self):
            self._pending.clear()
            raise RuntimeError("fabric barrier lost")

        def shutdown(self):
            pass

    async def drive():
        runner = SpmdEngineRunner(FakeEngine(), BrokenDriver())
        runner.start()
        try:
            req = PreprocessedRequest(
                request_id="r0", token_ids=[1, 2, 3], max_tokens=4
            )
            with pytest.raises(RuntimeError, match="lockstep step failed"):
                async for _ in runner.generate(Context("r0"), req):
                    pass
        finally:
            runner.stop()

    asyncio.run(asyncio.wait_for(drive(), timeout=30))

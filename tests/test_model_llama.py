"""Llama model numerics: paged forward vs. HuggingFace torch reference, and
prefill/decode consistency through the paged KV cache.

Tolerances are loose (5e-2) because XLA-CPU (oneDNN) and torch use different
matmul accumulation orders in f32; a float64 run of the same checks gives
~1e-7 agreement, proving the paged-cache path is structurally exact. Argmax
agreement is asserted as the functional bar.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.models.llama import (
    KVPages,
    LlamaConfig,
    forward,
    init_kv_pages,
    init_params,
    params_from_torch_state_dict,
)

PAGE_SIZE = 4
NUM_PAGES = 32
MAX_PAGES = 6  # per-sequence page table length -> max context 24


def _make_page_table(start_page: int, n: int):
    """Allocate n contiguous pages (never page 0 — the null page)."""
    pt = np.zeros(MAX_PAGES, np.int32)
    pt[:n] = np.arange(start_page, start_page + n)
    return pt


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = LlamaConfig.tiny()
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _full_forward(cfg, params, tokens_batch):
    """Run a whole-prompt prefill for each row; returns logits [B,T,V]."""
    b, t = tokens_batch.shape
    kv = init_kv_pages(cfg, NUM_PAGES, PAGE_SIZE)
    n_pages = -(-t // PAGE_SIZE)
    pts = np.stack(
        [_make_page_table(1 + i * MAX_PAGES, n_pages) for i in range(b)]
    )
    positions = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    valid = np.ones((b, t), bool)
    logits, _ = forward(
        params, cfg, jnp.asarray(tokens_batch), jnp.asarray(positions),
        jnp.asarray(valid), kv, jnp.asarray(pts),
    )
    return np.asarray(logits)


def test_prefill_then_decode_matches_full_prefill(tiny_setup):
    """Prefill 8 tokens then decode 4 one-by-one == prefill of all 12."""
    cfg, params = tiny_setup
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, size=(1, 12)).astype(np.int32)

    full = _full_forward(cfg, params, toks)

    kv = init_kv_pages(cfg, NUM_PAGES, PAGE_SIZE)
    pt = jnp.asarray(_make_page_table(1, 3)[None])
    pos = jnp.arange(8, dtype=jnp.int32)[None]
    logits, kv = forward(
        params, cfg, jnp.asarray(toks[:, :8]), pos,
        jnp.ones((1, 8), bool), kv, pt,
    )
    np.testing.assert_allclose(
        np.asarray(logits)[0, :8], full[0, :8], rtol=5e-2, atol=5e-2
    )
    assert np.asarray(logits)[0, :8].argmax(-1).tolist() == full[0, :8].argmax(-1).tolist()
    for i in range(8, 12):
        logits, kv = forward(
            params, cfg, jnp.asarray(toks[:, i : i + 1]),
            jnp.full((1, 1), i, jnp.int32), jnp.ones((1, 1), bool), kv, pt,
        )
        got = np.asarray(logits)[0, 0]
        np.testing.assert_allclose(got, full[0, i], rtol=5e-2, atol=5e-2)
        assert got.argmax() == full[0, i].argmax()


def test_padding_and_null_page_isolation(tiny_setup):
    """Padded rows/cols must not corrupt other sequences' KV."""
    cfg, params = tiny_setup
    rng = np.random.default_rng(1)
    toks = rng.integers(0, cfg.vocab_size, size=(1, 8)).astype(np.int32)
    ref = _full_forward(cfg, params, toks)

    # Same tokens, but padded to T=12 with valid=False tail, batch padded to 2.
    kv = init_kv_pages(cfg, NUM_PAGES, PAGE_SIZE)
    toks_pad = np.zeros((2, 12), np.int32)
    toks_pad[0, :8] = toks[0]
    pts = np.stack([_make_page_table(1, 2), _make_page_table(10, 2)])
    positions = np.tile(np.arange(12, dtype=np.int32), (2, 1))
    valid = np.zeros((2, 12), bool)
    valid[0, :8] = True
    logits, _ = forward(
        params, cfg, jnp.asarray(toks_pad), jnp.asarray(positions),
        jnp.asarray(valid), kv, jnp.asarray(pts),
    )
    np.testing.assert_allclose(np.asarray(logits)[0, :8], ref[0, :8], rtol=5e-2, atol=5e-2)


def test_against_hf_transformers(tiny_setup):
    """Exact-architecture check: our forward vs transformers LlamaForCausalLM."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny()
    hf_cfg = HFConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps,
        tie_word_embeddings=False,
        attention_bias=False,
        mlp_bias=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    params = params_from_torch_state_dict(model.state_dict(), cfg)

    rng = np.random.default_rng(2)
    toks = rng.integers(0, cfg.vocab_size, size=(2, 11)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    ours = _full_forward(cfg, params, toks)
    np.testing.assert_allclose(ours, ref, rtol=5e-2, atol=5e-2)
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.95


def test_sharded_forward_on_mesh(tiny_setup, cpu_mesh_devices):
    """tp×dp-sharded forward == single-device forward (8 virtual devices)."""
    cfg, params = tiny_setup
    from dynamo_tpu.parallel import (
        MeshConfig, make_mesh, llama_param_specs, kv_cache_spec,
        batch_spec, shardings_for,
    )

    rng = np.random.default_rng(3)
    toks = rng.integers(0, cfg.vocab_size, size=(4, 8)).astype(np.int32)
    ref = _full_forward(cfg, params, toks)

    mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=1))
    p_sh = shardings_for(mesh, llama_param_specs(cfg))
    params_s = jax.device_put(params, p_sh)
    kv = init_kv_pages(cfg, NUM_PAGES, PAGE_SIZE)
    kv_sh = shardings_for(mesh, KVPages(k=kv_cache_spec(), v=kv_cache_spec()))
    kv = jax.device_put(kv, kv_sh)

    n_pages = 2
    pts = np.stack([_make_page_table(1 + i * MAX_PAGES, n_pages) for i in range(4)])
    positions = np.tile(np.arange(8, dtype=np.int32), (4, 1))
    b_sh = shardings_for(mesh, batch_spec(2))
    args = [
        jax.device_put(jnp.asarray(x), b_sh)
        for x in (toks, positions, np.ones((4, 8), bool), pts)
    ]
    fwd = jax.jit(lambda p, t, pos, val, kv, pt: forward(p, cfg, t, pos, val, kv, pt))
    logits, kv2 = fwd(params_s, args[0], args[1], args[2], kv, args[3])
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=5e-2, atol=5e-2)


def test_llama3_rope_scaling_against_hf():
    """NTK-by-parts (llama3) rope scaling must match HF across freq bands."""
    torch = pytest.importorskip("torch")
    from dataclasses import replace
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    cfg = replace(
        LlamaConfig.tiny(),
        rope_scaling_factor=8.0,
        rope_low_freq_factor=1.0,
        rope_high_freq_factor=4.0,
        rope_original_max_position=64,
        head_dim=32,
        num_heads=2,
        num_kv_heads=1,
    )
    hf_cfg = HFConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps,
        tie_word_embeddings=False,
        attention_bias=False,
        mlp_bias=False,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 64,
        },
        max_position_embeddings=512,
    )
    torch.manual_seed(1)
    model = LlamaForCausalLM(hf_cfg).eval()
    params = params_from_torch_state_dict(model.state_dict(), cfg)
    rng = np.random.default_rng(4)
    # Long enough (96 > original_max 64) to engage scaled frequencies.
    toks = rng.integers(0, cfg.vocab_size, size=(1, 96)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    b, t = toks.shape
    kv = init_kv_pages(cfg, 64, PAGE_SIZE)
    n_pages = -(-t // PAGE_SIZE)
    pts = np.zeros((1, n_pages), np.int32)
    pts[0] = np.arange(1, 1 + n_pages)
    positions = np.arange(t, dtype=np.int32)[None]
    logits, _ = forward(
        params, cfg, jnp.asarray(toks), jnp.asarray(positions),
        jnp.ones((1, t), bool), kv, jnp.asarray(pts),
    )
    ours = np.asarray(logits)
    np.testing.assert_allclose(ours, ref, rtol=5e-2, atol=5e-2)
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.95

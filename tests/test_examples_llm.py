"""The canonical examples/llm graphs serve end-to-end in-process.

Mirrors the reference's serve tests over its example graphs
(tests/serve/test_dynamo_serve.py parametrized over agg/agg_router/
disagg...). Echo engines keep it hardware-free; the graph wiring —
SDK services, fabric discovery, model watch, HTTP attach — is real.
"""

from __future__ import annotations

import asyncio

import pytest

from dynamo_tpu.sdk.serving import serve_graph


def _cfg(graph: str) -> dict:
    worker = {
        "model": "tiny",
        "engine": "echo",
        "page-size": 4,
        "num-pages": 64,
        "max-context": 64,
    }
    cfg = {"Frontend": {"port": 0}, "DisaggFrontend": {"port": 0},
           "Worker": dict(worker), "PrefillWorkerService": dict(worker)}
    if "router" in graph:
        cfg["Worker"]["router-mode"] = "kv"
    if "disagg" in graph:
        cfg["Worker"]["disagg"] = True
        cfg["Worker"]["max-local-prefill"] = 8
    return cfg


@pytest.mark.parametrize(
    "graph,root", [
        ("agg", "Frontend"),
        ("agg_router", "Frontend"),
        ("disagg", "DisaggFrontend"),
    ],
)
def test_graph_serves_chat(graph, root):
    import importlib

    import aiohttp

    mod = importlib.import_module(f"examples.llm.graphs.{graph}")
    root_cls = getattr(mod, root)

    async def run():
        handle = await serve_graph(root_cls, config=_cfg(graph), static=True)
        try:
            frontend = handle.instance_of(root_cls)
            await asyncio.sleep(0.3)  # model watch attach
            async with aiohttp.ClientSession() as sess:
                url = (
                    f"http://127.0.0.1:{frontend.port}/v1/chat/completions"
                )
                r = await sess.post(
                    url,
                    json={
                        "model": "tiny",
                        "messages": [{"role": "user", "content": "hey"}],
                        "max_tokens": 4,
                    },
                )
                assert r.status == 200, await r.text()
                body = await r.json()
                assert body["choices"][0]["message"]["content"]
                r2 = await sess.get(
                    f"http://127.0.0.1:{frontend.port}/v1/models"
                )
                assert "tiny" in (await r2.text())
        finally:
            await handle.stop()

    asyncio.run(run())


def test_worker_config_passes_engine_knobs():
    """YAML service config reaches EngineConfig: spec decode, quantization,
    KV tiers, and the parallel axes must not silently drop."""
    from examples.llm.components import _engine_config

    cfg = _engine_config({
        "model": "tiny", "spec-ngram": 3, "quantize": "int8",
        "host-kv-bytes": 1234, "dp": 2, "tp": 2, "sp": 1, "ep": 2,
    })
    assert cfg.spec_ngram == 3
    assert cfg.quantize == "int8"
    assert cfg.host_kv_cache_bytes == 1234
    assert (cfg.dp, cfg.tp, cfg.sp, cfg.ep) == (2, 2, 1, 2)

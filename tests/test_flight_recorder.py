"""Flight recorder (ISSUE 7): bounded per-step ring, counter deltas,
engine integration, wire shape, and the bit-identical-off guarantee."""

import dataclasses
import json

from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.engine.engine import EngineMetrics, JaxEngine
from dynamo_tpu.engine.request import SamplingParams
from dynamo_tpu.telemetry.flight import FlightRecorder


def test_ring_is_bounded_and_ordered():
    fl = FlightRecorder(capacity=4)
    m = EngineMetrics()
    for i in range(10):
        m.generated_tokens += 1
        fl.record_step(m, kind="decode", step_ms=1.0, n_decode=1)
    recs = fl.snapshot()
    assert len(recs) == 4 == len(fl)
    assert [r["seq"] for r in recs] == [6, 7, 8, 9]
    # n= trims from the newest end
    assert [r["seq"] for r in fl.snapshot(2)] == [8, 9]
    assert fl.to_wire(1)[0]["seq"] == 9
    # n=0 is an empty window, not the whole ring ([-0:] off-by-zero)
    assert fl.snapshot(0) == []


def test_records_carry_counter_deltas_not_cumulatives():
    fl = FlightRecorder()
    m = EngineMetrics()
    m.compiles = 3
    m.compile_ms = 120.0
    m.preemptions = 1
    fl.record_step(m, kind="prefill", step_ms=5.0)
    # first record sees the whole cumulative as its delta (boot window)
    r0 = fl.snapshot()[-1]
    assert r0["compiles"] == 3 and r0["preempted"] == 1
    # a quiet step records NO delta keys at all (compact records)
    fl.record_step(m, kind="decode", step_ms=1.0)
    r1 = fl.snapshot()[-1]
    assert "compiles" not in r1 and "preempted" not in r1
    m.compiles += 1
    m.overlap_hits += 2
    fl.record_step(m, kind="decode", step_ms=1.0)
    r2 = fl.snapshot()[-1]
    assert r2["compiles"] == 1 and r2["overlap_hits"] == 2


def test_engine_steps_append_records_with_buckets_and_compiles():
    eng = JaxEngine(EngineConfig.for_tests())
    for i in range(3):
        eng.add_request(
            f"r{i}", [1 + i, 2, 3, 4, 5],
            SamplingParams(temperature=0.0, max_tokens=4),
        )
    eng.run_to_completion()
    recs = eng.flight.snapshot()
    assert recs, "engine steps must append flight records"
    kinds = {r["kind"] for r in recs}
    assert "prefill" in kinds and ("decode" in kinds or "mixed" in kinds)
    pre = next(r for r in recs if r["kind"] == "prefill")
    assert pre["n_prefill"] == 3 and pre["t_bucket"] >= 5
    assert pre["prefill_tokens"] == 15
    dec = next(r for r in recs if r["kind"] in ("decode", "mixed"))
    assert dec["n_decode"] == 3 and dec["b_decode"] == 4  # bucket of 3
    # the first steps carry the jit-compile events
    assert sum(r.get("compiles", 0) for r in recs) == eng.metrics.compiles
    assert all(r["step_ms"] > 0 for r in recs)
    # records are json-safe (they ride the metrics frame wire)
    json.dumps(recs)


def test_flight_off_is_bit_identical_and_recorder_absent():
    outs = {}
    for on in (True, False):
        cfg = dataclasses.replace(
            EngineConfig.for_tests(), flight_recorder=on
        )
        eng = JaxEngine(cfg)
        for i in range(3):
            eng.add_request(
                f"r{i}", [1 + i, 2, 3, 4],
                SamplingParams(temperature=0.8, top_p=0.9, max_tokens=6),
            )
        outs[on] = eng.run_to_completion()
        if on:
            assert eng.flight is not None and len(eng.flight) > 0
        else:
            assert eng.flight is None
    assert outs[True] == outs[False]

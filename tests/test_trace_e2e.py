"""End-to-end distributed trace propagation (ISSUE 4 acceptance).

One chat completion through `in=http` + KV-routed worker + external
subprocess engine must produce ONE trace whose spans cover
frontend -> router -> engine -> subprocess child (>=6 spans), retrievable
at /v1/traces/{id} with a valid Chrome-trace export; a disagg variant
covers the prefill-handoff span crossing the prefill queue; and a
request WITHOUT any trace header still serves identically while minting
a fresh trace."""

import asyncio
import sys

import aiohttp
import pytest

from dynamo_tpu import telemetry
from dynamo_tpu.external.client import SubprocessEngine
from dynamo_tpu.frontend import HttpService, ModelManager
from dynamo_tpu.frontend.service import ModelWatcher
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.fabric import FabricServer
from dynamo_tpu.worker import Worker


def run(coro):
    return asyncio.run(coro)


@pytest.fixture()
def tracing():
    telemetry.configure(enabled=True, ring_size=64)
    telemetry.reset()
    yield
    telemetry.configure(enabled=False)
    telemetry.reset()


TRACE_ID = "ab" * 16
TRACEPARENT = f"00-{TRACE_ID}-{'cd' * 8}-01"


def _ref_cmd() -> list[str]:
    return [
        sys.executable, "-m", "dynamo_tpu.external.reference_worker",
        "--model", "ext-ref", "--block-size", "4",
        "--metrics-interval", "0.1",
    ]


async def _await_spans(trace_id: str, want_services: set, tries: int = 100):
    """Poll the ring until every wanted service contributed (the child's
    span frame arrives asynchronously after the finish frame)."""
    for _ in range(tries):
        spans = telemetry.get_trace(trace_id) or []
        if want_services <= {s["service"] for s in spans}:
            return spans
        await asyncio.sleep(0.05)
    raise AssertionError(
        f"trace {trace_id} never covered {want_services}; has "
        f"{[(s['service'], s['name']) for s in (telemetry.get_trace(trace_id) or [])]}"
    )


def test_http_kv_routed_subprocess_trace(tracing):
    """frontend -> kv router -> worker -> SubprocessEngine -> child, one
    trace, >=6 spans, parent links intact, served over /v1/traces."""

    async def main():
        server = FabricServer(port=0)
        await server.start()
        eng = SubprocessEngine(_ref_cmd(), name="ref")
        await eng.start()
        rt_w = await DistributedRuntime.create(server.address)
        card = ModelDeploymentCard(
            name="ext-ref", tokenizer={"kind": "byte"}, context_length=512,
            kv_page_size=4,
        )
        worker = Worker(
            rt_w, card, engine_kind="external", engine=eng,
            namespace="ns", router_mode="kv", metrics_interval=0.1,
        )
        await worker.start()
        rt_f = await DistributedRuntime.create(server.address)
        manager = ModelManager()
        watcher = ModelWatcher(rt_f, manager)
        await watcher.start()
        for _ in range(100):
            if manager.get("ext-ref"):
                break
            await asyncio.sleep(0.05)
        assert manager.get("ext-ref") is not None
        svc = HttpService(manager, host="127.0.0.1", port=0)
        await svc.start()
        base = f"http://127.0.0.1:{svc.port}"
        body = {
            "model": "ext-ref",
            "messages": [{"role": "user", "content": "trace me"}],
            "max_tokens": 6,
            "temperature": 0.0,
        }
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"{base}/v1/chat/completions", json=body,
                    headers={"traceparent": TRACEPARENT},
                ) as r:
                    assert r.status == 200
                    data = await r.json()
                assert data["usage"]["completion_tokens"] == 6

                spans = await _await_spans(
                    TRACE_ID,
                    {"frontend", "router", "worker", "engine", "ext-child"},
                )
                assert len(spans) >= 6, [s["name"] for s in spans]
                by_name = {s["name"]: s for s in spans}
                for name in (
                    "http.request", "preprocess", "router.dispatch",
                    "kv.choose", "worker.generate", "engine.generate",
                    "child.generate",
                ):
                    assert name in by_name, (name, sorted(by_name))
                # every span belongs to the ONE trace, and the stitch
                # chain holds across both the fabric hop and the wire
                assert all(s["trace_id"] == TRACE_ID for s in spans)
                ids = {s["span_id"] for s in spans}
                root = by_name["http.request"]
                assert root["parent_id"] == "cd" * 8  # traceparent span
                assert by_name["worker.generate"]["parent_id"] in ids
                assert (
                    by_name["engine.generate"]["parent_id"]
                    == by_name["worker.generate"]["span_id"]
                )
                assert (
                    by_name["child.generate"]["parent_id"]
                    == by_name["engine.generate"]["span_id"]
                )
                # the KV decision is attributed on the trace
                kv = by_name["kv.choose"]
                assert kv["attrs"]["chosen"] == worker.instance_id
                assert "matched_blocks" in kv["attrs"]
                assert "overlap_score" in kv["attrs"]

                # retrievable over HTTP (frontend serves the ring) ...
                async with s.get(f"{base}/v1/traces/{TRACE_ID}") as r:
                    assert r.status == 200
                    doc = await r.json()
                assert len(doc["spans"]) == len(spans)
                async with s.get(f"{base}/v1/traces?limit=5") as r:
                    listing = await r.json()
                assert listing["enabled"] is True
                assert any(
                    t["trace_id"] == TRACE_ID for t in listing["traces"]
                )
                # ... and the chrome export is valid, pid/tid/ts intact
                async with s.get(
                    f"{base}/v1/traces/{TRACE_ID}?format=chrome"
                ) as r:
                    chrome = await r.json()
                complete = [
                    e for e in chrome["traceEvents"] if e["ph"] == "X"
                ]
                assert len(complete) == len(spans)
                assert all(
                    isinstance(e["ts"], int)
                    and isinstance(e["pid"], int)
                    and isinstance(e["tid"], int)
                    for e in complete
                )
                async with s.get(f"{base}/v1/traces/{'9' * 32}") as r:
                    assert r.status == 404

                # absent trace header: same serving behavior, fresh trace
                n_before = len(telemetry.list_traces(64))
                async with s.post(
                    f"{base}/v1/chat/completions", json=body
                ) as r:
                    assert r.status == 200
                    data = await r.json()
                assert data["usage"]["completion_tokens"] == 6
                for _ in range(100):
                    fresh = [
                        t for t in telemetry.list_traces(64)
                        if t["trace_id"] != TRACE_ID
                    ]
                    if fresh and fresh[0]["spans"] >= 6:
                        break
                    await asyncio.sleep(0.05)
                assert len(telemetry.list_traces(64)) > n_before
                assert fresh[0]["trace_id"] != TRACE_ID
                assert len(fresh[0]["trace_id"]) == 32
        finally:
            await svc.stop()
            await watcher.stop()
            await rt_f.close()
            await worker.stop()
            await rt_w.close()
            await eng.stop()
            await server.stop()

    run(main())


def test_disagg_prefill_handoff_trace(tracing, monkeypatch):
    """The disagg variant: a long prompt's remote prefill contributes
    disagg.remote_prefill (decode side) and disagg.prefill (prefill
    worker, parented across the QUEUE hop) to the same trace. Host
    transfer plane: always available on CPU (the device plane needs
    jax.experimental.transfer, absent from the baked toolchain)."""
    monkeypatch.setenv("DYN_KV_TRANSFER", "host")
    from dynamo_tpu.disagg import DisaggConfig
    from dynamo_tpu.disagg.prefill_worker import PrefillWorker
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.runtime import RouterMode

    tiny_cfg = EngineConfig.for_tests()
    prompt = [5, 17, 42, 99, 3, 8, 21, 60, 11, 2]
    card = ModelDeploymentCard(
        name="tiny", kv_page_size=tiny_cfg.page_size,
        context_length=tiny_cfg.max_context,
    )

    async def main():
        server = FabricServer(port=0)
        await server.start()
        rt_d = await DistributedRuntime.create(server.address)
        decode = Worker(
            rt_d, card, engine_config=tiny_cfg, engine_kind="jax",
            namespace="test", metrics_interval=0.1, enable_disagg=True,
            disagg_config=DisaggConfig(
                max_local_prefill_length=4, transfer_timeout_s=20.0
            ),
        )
        await decode.start()
        rt_p = await DistributedRuntime.create(server.address)
        prefill = PrefillWorker(rt_p, tiny_cfg, namespace="test")
        await prefill.start()
        rt_c = await DistributedRuntime.create(server.address)
        try:
            ep = rt_c.namespace("test").component("backend").endpoint(
                "generate"
            )
            router = await ep.router(mode=RouterMode.ROUND_ROBIN)
            await router.source.wait_for_instances()

            with telemetry.span("test.root", service="frontend") as root:
                trace_id = root.trace_id
                tokens = []
                async for item in router.generate(
                    {
                        "request_id": "trace-disagg", "token_ids": prompt,
                        "max_tokens": 4, "temperature": 0.0, "top_p": 1.0,
                        "top_k": 0, "seed": None, "stop_token_ids": [],
                        "stop_strings": [], "ignore_eos": True,
                        "annotations": {},
                    }
                ):
                    tokens.extend(item.get("token_ids", ()))
            assert len(tokens) == 4
            assert decode.remote_prefills == 1

            spans = await _await_spans(
                trace_id, {"router", "worker", "disagg", "prefill"}
            )
            by_name = {s["name"]: s for s in spans}
            assert "disagg.remote_prefill" in by_name
            assert "disagg.prefill" in by_name
            # the handoff span crossed the prefill QUEUE with its parent
            # link intact: prefill-worker side hangs off the decode side
            assert (
                by_name["disagg.prefill"]["parent_id"]
                == by_name["disagg.remote_prefill"]["span_id"]
            )
            assert by_name["disagg.prefill"]["trace_id"] == trace_id
            events = {
                e["name"]
                for e in by_name["disagg.remote_prefill"]["events"]
            }
            assert {"pages_reserved", "kv_landed"} <= events
        finally:
            await rt_c.close()
            await prefill.stop()
            await rt_p.close()
            await decode.stop()
            await rt_d.close()
            await server.stop()

    run(main())

"""Cross-host SPMD serving through the PRODUCT CLI, end to end.

Four processes: fabric, leader worker (host 0 — owns the fabric
endpoint), follower worker (host 1 — lockstep replica, no fabric), and
the HTTP frontend. Each worker host gets 2 virtual CPU devices; the
engine's dp=2 x tp=2 mesh spans both processes, so every generated token
is the product of cross-host collectives. The test asserts a chat
completion arrives and that the follower actually joined and released.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import pytest

REPO = str(Path(__file__).resolve().parents[1])

pytestmark = pytest.mark.skipif(
    bool(os.environ.get("DYNTPU_TEST_ON_TPU")),
    reason="CPU-mesh lockstep test (subprocess hosts force the CPU "
    "platform)",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_for(log: Path, needle: str, timeout: float, procs) -> None:
    t0 = time.time()
    while time.time() - t0 < timeout:
        for p in procs:
            if p.poll() is not None:
                raise AssertionError(
                    f"process {p.args[-1]} exited rc={p.returncode} "
                    f"before {needle!r}; log:\n"
                    + "".join(
                        f.read_text()
                        for f in log.parent.glob("*.log")
                    )[-4000:]
                )
        if log.exists() and needle in log.read_text():
            return
        time.sleep(0.3)
    raise AssertionError(
        f"{needle!r} not seen in {log} after {timeout}s:\n"
        + (log.read_text()[-2000:] if log.exists() else "<missing>")
    )


def test_cli_spmd_serving(tmp_path):
    fport = _free_port()
    hport = _free_port()
    cport = _free_port()
    base_env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    base_env["PYTHONPATH"] = REPO

    def spawn(name, extra_args, jax_cpu=False, devices=0):
        env = dict(base_env)
        if jax_cpu:
            env["JAX_PLATFORMS"] = "cpu"
        if devices:
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count={devices}"
            )
        log = tmp_path / f"{name}.log"
        proc = subprocess.Popen(
            [sys.executable, "-u", "-m", "dynamo_tpu.cli.run", *extra_args],
            env=env,
            stdout=open(log, "w"),
            stderr=subprocess.STDOUT,
        )
        return proc, log

    worker_args = [
        "run", "in=dyn", "out=jax", "--model", "tiny",
        "--page-size", "4", "--num-pages", "64", "--max-context", "32",
        "--dtype", "float32", "--dp", "2", "--tp", "2",
        "--coordinator", f"127.0.0.1:{cport}", "--num-hosts", "2",
    ]
    procs = []
    try:
        fabric, _ = spawn(
            "fabric", ["fabric", "--port", str(fport)], jax_cpu=True
        )
        procs.append(fabric)
        time.sleep(1.5)
        leader, llog = spawn(
            "leader",
            [*worker_args, "--host-id", "0",
             "--fabric", f"127.0.0.1:{fport}"],
            jax_cpu=True, devices=2,
        )
        procs.append(leader)
        follower, wlog = spawn(
            "follower",
            [*worker_args, "--host-id", "1",
             "--fabric", f"127.0.0.1:{fport}"],
            jax_cpu=True, devices=2,
        )
        procs.append(follower)
        _wait_for(wlog, "spmd follower 1 up", 180, procs)
        _wait_for(llog, "worker", 180, procs)
        front, flog = spawn(
            "frontend",
            ["run", "in=http", "out=dyn",
             "--fabric", f"127.0.0.1:{fport}", "--port", str(hport)],
            jax_cpu=True,
        )
        procs.append(front)
        _wait_for(flog, "model attached", 120, procs)

        req = urllib.request.Request(
            f"http://127.0.0.1:{hport}/v1/chat/completions",
            data=json.dumps(
                {
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "Hi"}],
                    "max_tokens": 4,
                    "temperature": 0,
                }
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        body = json.loads(urllib.request.urlopen(req, timeout=90).read())
        assert body["choices"][0]["message"]["role"] == "assistant"
        assert body["usage"]["completion_tokens"] >= 1
    finally:
        # scoped kills by PID — a broad pkill pattern would hit unrelated
        # bench/test workers (see memory: pkill-kills-bench-workers)
        for p in reversed(procs):
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

"""Cross-host SPMD serving through the PRODUCT CLI, end to end.

Four processes: fabric, leader worker (host 0 — owns the fabric
endpoint), follower worker (host 1 — lockstep replica, no fabric), and
the HTTP frontend. Each worker host gets 2 virtual CPU devices; the
engine's dp=2 x tp=2 mesh spans both processes, so every generated token
is the product of cross-host collectives. The test asserts a chat
completion arrives and that the follower actually joined and released.
"""

import json
import os
import urllib.request

import pytest

from benchmarks._procs import ManagedProc, cli, free_port

pytestmark = pytest.mark.skipif(
    bool(os.environ.get("DYNTPU_TEST_ON_TPU")),
    reason="CPU-mesh lockstep test (subprocess hosts force the CPU "
    "platform)",
)


def _env(devices: int = 0) -> dict:
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))
    env["JAX_PLATFORMS"] = "cpu"
    if devices:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices}"
        )
    return env


@pytest.mark.slow  # 4-process fleet over cross-host gloo collectives:
# a flaky rendezvous can wedge past the quick-suite budget
def test_cli_spmd_serving():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent / "helpers"))
    from spmd_host import collective_plane_available

    if not collective_plane_available():
        pytest.skip("cross-process collective plane (gloo) unavailable")
    fport, hport, cport = free_port(), free_port(), free_port()
    worker_args = [
        "run", "in=dyn", "out=jax", "--model", "tiny",
        "--page-size", "4", "--num-pages", "64", "--max-context", "32",
        "--dtype", "float32", "--dp", "2", "--tp", "2",
        "--coordinator", f"127.0.0.1:{cport}", "--num-hosts", "2",
    ]
    procs: list[ManagedProc] = []
    try:
        fabric = ManagedProc(
            "fabric", cli("fabric", "--port", str(fport)), env=_env()
        )
        procs.append(fabric)
        fabric.wait_for("listening|fabric server on")
        leader = ManagedProc(
            "leader",
            cli(*worker_args, "--host-id", "0",
                "--fabric", f"127.0.0.1:{fport}"),
            env=_env(devices=2),
        )
        procs.append(leader)
        follower = ManagedProc(
            "follower",
            cli(*worker_args, "--host-id", "1",
                "--fabric", f"127.0.0.1:{fport}"),
            env=_env(devices=2),
        )
        procs.append(follower)
        follower.wait_for("spmd follower 1 up", timeout=180,
                          peers=[fabric, leader])
        leader.wait_for(r"worker \w+ up", timeout=180,
                        peers=[fabric, follower])
        front = ManagedProc(
            "frontend",
            cli("run", "in=http", "out=dyn",
                "--fabric", f"127.0.0.1:{fport}", "--port", str(hport)),
            env=_env(),
        )
        procs.append(front)
        front.wait_for("model attached", timeout=120)

        req = urllib.request.Request(
            f"http://127.0.0.1:{hport}/v1/chat/completions",
            data=json.dumps(
                {
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "Hi"}],
                    "max_tokens": 4,
                    "temperature": 0,
                }
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        body = json.loads(urllib.request.urlopen(req, timeout=90).read())
        assert body["choices"][0]["message"]["role"] == "assistant"
        assert body["usage"]["completion_tokens"] >= 1
    finally:
        # scoped kills by PID — a broad pkill pattern would hit unrelated
        # bench/test workers (see memory: pkill-kills-bench-workers)
        for p in reversed(procs):
            p.stop()

"""Fleet trace plane (ISSUE 14): span shipping over the fabric,
cross-process assembly at the metrics service, tail-based sampling,
timeline breakdowns, and the chaos-grade stitch-across-replay proof.

Unit layer: TailSampler determinism + anomaly coverage, TraceAssembler
window/eviction bounds, breakdown reconciliation, exemplar emission.
E2E layer: a multi-hop request (frontend -> kv router -> worker ->
subprocess child; disagg variant) assembles into ONE trace at the
metrics service's GET /v1/traces/{id} with an intact parent chain and
a reconciling breakdown; a SIGKILL-equivalent mid-stream kill stitches
both replay attempts under one trace_id, flagged incomplete, never
dropped."""

import asyncio
import sys
import time

import aiohttp
import pytest

from dynamo_tpu import telemetry
from dynamo_tpu.telemetry import phases, promlint, trace, traceplane
from dynamo_tpu.telemetry.traceplane import (
    TailSampler,
    TraceAssembler,
    breakdown,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture()
def tracing():
    telemetry.configure(enabled=True, ring_size=256)
    telemetry.reset()
    traceplane.ensure_shipping()
    traceplane.drain_spans()
    telemetry.events.reset()
    phases.phase_histograms.reset()
    yield
    telemetry.configure(enabled=False)
    telemetry.reset()
    traceplane.disable_shipping()
    telemetry.events.reset()
    phases.phase_histograms.reset()


def _span(
    name="http.request", service="frontend", trace_id="ab" * 16,
    span_id="11" * 8, parent_id=None, start_ts=1000.0, duration_ms=10.0,
    status="ok", attrs=None, events=None,
):
    return {
        "trace_id": trace_id, "span_id": span_id, "parent_id": parent_id,
        "name": name, "service": service, "start_ts": start_ts,
        "duration_ms": duration_ms, "status": status,
        "attrs": dict(attrs or {}), "events": list(events or []),
    }


def _healthy_trace(tid, dur_ms=10.0):
    return [
        _span(trace_id=tid, span_id="aa" * 8, duration_ms=dur_ms,
              attrs={"http_status": 200, "endpoint": "chat"}),
        _span(name="engine.generate", service="engine", trace_id=tid,
              span_id="bb" * 8, parent_id="aa" * 8,
              duration_ms=dur_ms * 0.8),
    ]


# -- tail sampler ----------------------------------------------------------


def test_sampler_keeps_every_anomaly_and_seeded_healthy_subset():
    sampler = TailSampler(healthy_rate=10, seed=42)
    anomalies = {
        "error status": [_span(status="error")],
        "http 504": [_span(attrs={"http_status": 504})],
        "http 429": [_span(attrs={"http_status": 429})],
        "replay event": [_span(events=[{"ts": 1.0, "name": "replay",
                                        "attrs": {}}])],
        "mark_down event": [_span(events=[{"ts": 1.0, "name": "mark_down",
                                           "attrs": {}}])],
        "overloaded event": [_span(events=[{"ts": 1.0, "name": "overloaded",
                                            "attrs": {}}])],
        "deadline event": [_span(events=[{"ts": 1.0,
                                          "name": "deadline_expired",
                                          "attrs": {}}])],
    }
    for label, spans in anomalies.items():
        keep, reasons = sampler.decide("cd" * 16, spans)
        assert keep, label
        assert reasons and reasons != ["healthy_sample"], (label, reasons)
    # incomplete assemblies are anomalous by definition
    keep, reasons = sampler.decide("cd" * 16, [_span()], incomplete=True)
    assert keep and "incomplete" in reasons

    # healthy traffic: the seeded 1-in-N decision is deterministic and
    # lands near the configured rate
    tids = ["%032x" % i for i in range(2000)]
    kept1 = {t for t in tids
             if sampler.decide(t, _healthy_trace(t))[0]}
    kept2 = {t for t in tids
             if TailSampler(healthy_rate=10, seed=42).decide(
                 t, _healthy_trace(t))[0]}
    assert kept1 == kept2  # same seed -> same decisions, restart-proof
    assert 100 < len(kept1) < 320  # ~1 in 10 of 2000
    other_seed = {t for t in tids
                  if TailSampler(healthy_rate=10, seed=7).decide(
                      t, _healthy_trace(t))[0]}
    assert other_seed != kept1  # the seed matters
    # rate 0: anomalies only
    none_kept = [t for t in tids[:100]
                 if TailSampler(healthy_rate=0).decide(
                     t, _healthy_trace(t))[0]]
    assert none_kept == []


def test_sampler_slow_thresholds_track_live_slo_p95():
    p95 = {"ttft_ms": 100.0, "e2e_ms": 1000.0}
    sampler = TailSampler(healthy_rate=0, slo_p95s=lambda: p95)
    slow_root = _span(attrs={"http_status": 200, "ttft_ms": 250.0},
                      duration_ms=300.0)
    keep, reasons = sampler.decide("ee" * 16, [slow_root])
    assert keep and "slow_ttft" in reasons
    slow_e2e = _span(attrs={"http_status": 200}, duration_ms=5000.0)
    keep, reasons = sampler.decide("ee" * 16, [slow_e2e])
    assert keep and "slow_e2e" in reasons
    fast = _span(attrs={"http_status": 200, "ttft_ms": 10.0},
                 duration_ms=50.0)
    assert not sampler.decide("ee" * 16, [fast])[0]
    # a cold fleet (empty p95s) must not flag everything slow
    cold = TailSampler(healthy_rate=0, slo_p95s=lambda: {})
    assert not cold.decide("ee" * 16, [slow_root])[0]
    # a crashing provider degrades to no thresholds, never raises
    broken = TailSampler(
        healthy_rate=0, slo_p95s=lambda: (_ for _ in ()).throw(ValueError)
    )
    assert not broken.decide("ee" * 16, [slow_root])[0]


# -- assembler bounds ------------------------------------------------------


def test_assembler_quiet_window_and_memory_bounds():
    clock = [0.0]
    asm = TraceAssembler(
        sampler=TailSampler(healthy_rate=1), window_s=1.0,
        max_age_s=30.0, max_open=8, keep=4, now_fn=lambda: clock[0],
    )
    asm.add_spans(_healthy_trace("aa" * 16))
    assert asm.sweep() == 0  # still inside the quiet window
    clock[0] = 0.5
    asm.add_spans([_span(name="preprocess", trace_id="aa" * 16,
                         span_id="cc" * 8, parent_id="aa" * 8)])
    clock[0] = 1.2
    assert asm.sweep() == 0  # the straggler reset the quiet clock
    clock[0] = 1.6
    assert asm.sweep() == 1
    doc = asm.get("aa" * 16)
    assert doc is not None and not doc["incomplete"]
    assert len(doc["spans"]) == 3

    # max_open: the 9th concurrent assembly evicts the oldest, which
    # finalizes (incomplete, kept) instead of vanishing
    for i in range(9):
        asm.add_spans(_healthy_trace("%032x" % (i + 1)))
    st = asm.stats()
    assert st["open"] <= 8
    assert st["evicted_total"] == 1
    evicted = asm.get("%032x" % 1)
    assert evicted is not None and evicted["incomplete"]
    # keep ring is bounded too (LRU)
    clock[0] = 10.0
    asm.sweep()
    assert asm.stats()["kept"] <= 4

    # under sustained load open assemblies stay bounded (the eviction
    # test of the acceptance criteria)
    for i in range(500):
        asm.add_spans(_healthy_trace("%032x" % (1000 + i)))
    st = asm.stats()
    assert st["open"] <= 8 and st["kept"] <= 4


def test_assembler_mixed_traffic_keeps_all_anomalies_at_rate():
    """Acceptance: mixed healthy/slow/error/replayed traffic -> 100% of
    anomalies kept, healthy kept at the deterministic seeded rate."""
    clock = [0.0]
    sampler = TailSampler(healthy_rate=5, seed=9)
    asm = TraceAssembler(sampler=sampler, window_s=0.1, keep=4096,
                         max_open=4096, now_fn=lambda: clock[0])
    anomalous, healthy = [], []
    for i in range(300):
        tid = "%032x" % (i + 1)
        if i % 3 == 0:
            anomalous.append(tid)
            spans = [_span(trace_id=tid, span_id="aa" * 8,
                           attrs={"http_status": 504})]
        elif i % 3 == 1:
            anomalous.append(tid)
            spans = [_span(trace_id=tid, span_id="aa" * 8,
                           events=[{"ts": 1.0, "name": "replay",
                                    "attrs": {}}])]
        else:
            healthy.append(tid)
            spans = _healthy_trace(tid)
        asm.add_spans(spans)
    clock[0] = 1.0
    asm.sweep()
    for tid in anomalous:
        assert asm.get(tid) is not None, "anomalous trace dropped"
    kept_healthy = [t for t in healthy if asm.get(t) is not None]
    expected = [
        t for t in healthy if sampler.decide(t, _healthy_trace(t))[0]
    ]
    assert kept_healthy == expected
    assert 0 < len(kept_healthy) < len(healthy)


def test_straggler_completes_an_early_finalized_trace():
    """A shipper on a slower cadence than the assembly window: the
    trace finalizes incomplete (kept), then the missing subtree's
    spans arrive — they attach AND clear the incomplete flag, because
    the stitch is now whole."""
    clock = [0.0]
    asm = TraceAssembler(sampler=TailSampler(healthy_rate=1),
                         window_s=0.5, now_fn=lambda: clock[0])
    tid = "cc" * 16
    # the EARLY-ENDING spans ship first (preprocess, kv.choose end in
    # microseconds; their parents — http.request, router.dispatch —
    # are still streaming, so they ship a cadence later): two dangling
    # subtrees -> incomplete at finalize
    asm.add_spans([
        _span(name="preprocess", trace_id=tid, span_id="bb" * 8,
              parent_id="aa" * 8),
        _span(name="kv.choose", service="router", trace_id=tid,
              span_id="dd" * 8, parent_id="ee" * 8),
    ])
    clock[0] = 1.0
    asm.sweep()
    doc = asm.get(tid)
    assert doc is not None and doc["incomplete"]
    # the late shipper fires: the roots arrive, the stitch is whole
    asm.add_spans([
        _span(trace_id=tid, span_id="aa" * 8,
              attrs={"http_status": 200, "endpoint": "chat"}),
        _span(name="router.dispatch", service="router", trace_id=tid,
              span_id="ee" * 8, parent_id="aa" * 8),
    ])
    doc = asm.get(tid)
    assert len(doc["spans"]) == 4
    assert not doc["incomplete"]
    assert not doc["summary"]["incomplete"]
    assert asm.stats()["incomplete_total"] == 0


def test_incomplete_trace_is_kept_and_flagged_not_dropped():
    """A subtree whose parent never shipped (SIGKILLed worker) and a
    mark_down-carrying trace both finalize as incomplete + kept."""
    clock = [0.0]
    asm = TraceAssembler(sampler=TailSampler(healthy_rate=0),
                         window_s=0.1, now_fn=lambda: clock[0])
    # dangling subtree: engine span whose parent id never arrives
    asm.add_spans([
        _span(trace_id="dd" * 16, span_id="aa" * 8,
              attrs={"http_status": 200}),
        _span(name="engine.generate", service="engine",
              trace_id="dd" * 16, span_id="bb" * 8,
              parent_id="99" * 8),
    ])
    clock[0] = 1.0
    asm.sweep()
    doc = asm.get("dd" * 16)
    assert doc is not None
    assert doc["incomplete"] and "incomplete" in doc["kept_reasons"]
    assert asm.stats()["incomplete_total"] == 1


# -- breakdown -------------------------------------------------------------


def test_breakdown_reconciles_and_attributes_phases():
    t0 = 1000.0
    spans = [
        _span(span_id="aa" * 8, start_ts=t0, duration_ms=100.0,
              attrs={"http_status": 200, "endpoint": "chat"}),
        _span(name="preprocess", span_id="bb" * 8, parent_id="aa" * 8,
              start_ts=t0 + 0.001, duration_ms=5.0),
        _span(name="router.dispatch", service="router",
              span_id="cc" * 8, parent_id="aa" * 8,
              start_ts=t0 + 0.006, duration_ms=90.0,
              events=[{"ts": t0 + 0.030, "name": "first_frame",
                       "attrs": {}}]),
        # attempt 1: killed after 20 ms of decode
        _span(name="engine.generate", service="engine",
              span_id="dd" * 8, parent_id="cc" * 8,
              start_ts=t0 + 0.010, duration_ms=30.0, status="cancelled",
              attrs={"queue_wait_ms": 4.0},
              events=[{"ts": t0 + 0.014, "name": "first_token",
                       "attrs": {}}]),
        # 10 ms replay gap, then attempt 2 with a disagg prefill hop
        _span(name="engine.generate", service="engine",
              span_id="ee" * 8, parent_id="cc" * 8,
              start_ts=t0 + 0.050, duration_ms=46.0,
              attrs={"queue_wait_ms": 2.0, "decode_stall_ms": 3.0},
              events=[{"ts": t0 + 0.070, "name": "first_token",
                       "attrs": {}}]),
        _span(name="disagg.remote_prefill", service="disagg",
              span_id="ff" * 8, parent_id="ee" * 8,
              start_ts=t0 + 0.052, duration_ms=14.0),
        _span(name="disagg.prefill", service="prefill",
              span_id="ab" * 8, parent_id="ff" * 8,
              start_ts=t0 + 0.054, duration_ms=9.0),
    ]
    bd = breakdown(spans)
    assert bd is not None
    ph = bd["phases"]
    # the partition invariant the acceptance pins at +-1 ms
    assert abs(sum(ph.values()) - bd["total_ms"]) < 1e-6
    assert bd["total_ms"] == 100.0
    assert bd["attempts"] == 2
    assert ph["preprocess"] == 5.0
    assert ph["queue_wait"] == 6.0       # 4 + 2
    assert ph["replay_gap"] == pytest.approx(10.0, abs=0.001)
    assert ph["transfer"] == pytest.approx(5.0, abs=0.001)  # 14 - 9
    assert ph["prefill"] > 0.0
    assert ph["decode_stall"] == 3.0
    assert ph["decode"] > 0.0
    assert ph["other"] >= 0.0
    # dispatch: router start -> first attempt start
    assert ph["dispatch"] == pytest.approx(4.0, abs=0.001)
    assert bd["dominant"] in ("decode", "prefill")

    # degenerate inputs never raise
    assert breakdown([]) is None
    garbage = breakdown([{"garbage": True}])
    assert garbage is None or garbage["total_ms"] == 0.0


# -- exemplars on both expositions ----------------------------------------


def test_exemplars_resolve_to_traces_and_lint_clean(tracing):
    """Acceptance: BOTH Prometheus surfaces carry OpenMetrics exemplars
    on their NEGOTIATED OpenMetrics rendering (trace ids resolving to
    kept traces), while the classic text/plain rendering stays
    exemplar-free — the 0.0.4 parser fails a whole scrape on exemplar
    syntax — and promlint passes over both, fully populated."""
    from dynamo_tpu.frontend.metrics import FrontendMetrics
    from dynamo_tpu.metrics_service import MetricsService

    with telemetry.span("http.request", service="frontend") as root:
        tid = root.trace_id
        phases.observe("queue_wait_ms", 3.0)          # contextvar path
        phases.observe("decode_step_ms", 0.7, trace_id=tid)
    fm = FrontendMetrics()

    class _F:
        pass

    svc = MetricsService(_F())
    for classic, om in (
        (fm.expose(), fm.expose(openmetrics=True)),
        (svc.expose(), svc.expose(openmetrics=True)),
    ):
        # classic surface: parseable by 0.0.4 scrapers, NO exemplars
        assert " # " not in classic
        assert promlint.lint(classic) == [], promlint.lint(classic)[:6]
        # OpenMetrics surface: exemplars + EOF, counters renamed
        assert om.rstrip().endswith("# EOF")
        ex_lines = [l for l in om.splitlines() if " # {" in l]
        assert ex_lines, "no exemplars on the OpenMetrics rendering"
        assert any(f'trace_id="{tid}"' in l for l in ex_lines)
        assert "# TYPE dynamo_tpu_phase_queue_wait_ms histogram" in om
        errs = promlint.lint(om, openmetrics=True)
        assert errs == [], errs[:6]
        # the classic linter REJECTS exemplar leakage (the regression
        # that would break production scrapes)
        assert any("classic" in e for e in promlint.lint(om))
    # the exemplar's trace is in the ring (resolvable via /v1/traces)
    assert telemetry.get_trace(tid)

    # tracing off: no exemplars anywhere, lint still clean
    phases.phase_histograms.reset()
    telemetry.configure(enabled=False)
    phases.observe("decode_step_ms", 0.7)
    off_text = FrontendMetrics().expose(openmetrics=True)
    assert " # {" not in off_text
    assert promlint.lint(off_text, openmetrics=True) == []


# -- default-off bit-identity (the PR 4/6 invariant) -----------------------


def test_token_path_identical_with_tracing_off_and_on():
    """Greedy streams through AsyncEngineRunner are bit-identical with
    the trace plane off and on; with it OFF the wire carries none of
    the enrichment keys."""
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.async_engine import AsyncEngineRunner
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
    from dynamo_tpu.runtime.context import Context

    async def drive(enable: bool):
        telemetry.configure(enabled=enable, ring_size=64 if enable else None)
        if enable:
            traceplane.ensure_shipping()
        eng = JaxEngine(EngineConfig.for_tests())
        runner = AsyncEngineRunner(eng)
        runner.start()
        try:
            streams = {}
            for i in range(3):
                req = PreprocessedRequest(
                    request_id=f"pin-{i}",
                    token_ids=[3 + i, 5, 7, 11, 13], max_tokens=8,
                    temperature=0.0, ignore_eos=True,
                )
                items = []
                async for item in runner.generate(Context(), req):
                    items.append(item)
                streams[i] = items
            return streams
        finally:
            runner.stop()
            telemetry.configure(enabled=False)
            traceplane.disable_shipping()

    off = run(drive(False))
    on = run(drive(True))
    for i in off:
        toks_off = [t for it in off[i] for t in it["token_ids"]]
        toks_on = [t for it in on[i] for t in it["token_ids"]]
        assert toks_off == toks_on
        # off: the enrichment keys never appear on the wire
        for item in off[i]:
            assert "queue_wait_ms" not in item
            assert "stall_ms" not in item
    # on: the first emission carried the measured queue wait
    assert any(
        "queue_wait_ms" in item for items in on.values() for item in items
    )


# -- e2e: multi-hop assembly at the metrics service ------------------------


def _ref_cmd() -> list[str]:
    return [
        sys.executable, "-m", "dynamo_tpu.external.reference_worker",
        "--model", "ext-ref", "--block-size", "4",
        "--metrics-interval", "0.1",
    ]


async def _await_assembled(base: str, trace_id: str, want_services: set,
                           tries: int = 240):
    async with aiohttp.ClientSession() as s:
        last = None
        for _ in range(tries):
            async with s.get(f"{base}/v1/traces/{trace_id}") as r:
                if r.status == 200:
                    last = await r.json()
                    have = {
                        sp.get("service") for sp in last.get("spans", ())
                    }
                    if want_services <= have and not last.get("assembling"):
                        return last
            await asyncio.sleep(0.05)
    raise AssertionError(
        f"trace {trace_id} never assembled {want_services}; last={last}"
    )


def test_multi_hop_assembly_proof(tracing):
    """Acceptance: one request (frontend -> kv router -> worker ->
    subprocess child) yields ONE assembled trace at the metrics
    service with an intact parent chain across every process boundary,
    a reconciling breakdown, and search API hits."""
    from dynamo_tpu.external.client import SubprocessEngine
    from dynamo_tpu.frontend import HttpService, ModelManager
    from dynamo_tpu.frontend.service import ModelWatcher
    from dynamo_tpu.metrics_service import MetricsService
    from dynamo_tpu.model_card import ModelDeploymentCard
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.fabric import FabricServer
    from dynamo_tpu.worker import Worker

    TRACE_ID = "fa" * 16
    TRACEPARENT = f"00-{TRACE_ID}-{'cd' * 8}-01"

    async def main():
        server = FabricServer(port=0)
        await server.start()
        eng = SubprocessEngine(_ref_cmd(), name="ref")
        await eng.start()
        rt_w = await DistributedRuntime.create(server.address)
        card = ModelDeploymentCard(
            name="ext-ref", tokenizer={"kind": "byte"},
            context_length=512, kv_page_size=4,
        )
        worker = Worker(
            rt_w, card, engine_kind="external", engine=eng,
            namespace="ns", router_mode="kv", metrics_interval=0.1,
        )
        await worker.start()
        rt_m = await DistributedRuntime.create(server.address)
        metrics = MetricsService(
            rt_m.fabric, host="127.0.0.1", port=0,
            trace_sample_rate=1, trace_window_s=1.5,
        )
        await metrics.start()
        rt_f = await DistributedRuntime.create(server.address)
        manager = ModelManager()
        watcher = ModelWatcher(rt_f, manager)
        await watcher.start()
        for _ in range(100):
            if manager.get("ext-ref"):
                break
            await asyncio.sleep(0.05)
        svc = HttpService(manager, host="127.0.0.1", port=0)
        await svc.start()
        base = f"http://127.0.0.1:{svc.port}"
        mbase = f"http://127.0.0.1:{metrics.port}"
        body = {
            "model": "ext-ref",
            "messages": [{"role": "user", "content": "assemble me"}],
            "max_tokens": 6, "temperature": 0.0,
        }
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"{base}/v1/chat/completions", json=body,
                    headers={"traceparent": TRACEPARENT},
                ) as r:
                    assert r.status == 200
                    data = await r.json()
                assert data["usage"]["completion_tokens"] == 6

            doc = await _await_assembled(
                mbase, TRACE_ID,
                {"frontend", "router", "worker", "engine", "ext-child"},
            )
            spans = doc["spans"]
            by_name = {sp["name"]: sp for sp in spans}
            ids = {sp["span_id"] for sp in spans}
            # the stitch chain holds across every process boundary
            assert by_name["http.request"]["parent_id"] == "cd" * 8
            assert by_name["worker.generate"]["parent_id"] in ids
            assert (
                by_name["engine.generate"]["parent_id"]
                == by_name["worker.generate"]["span_id"]
            )
            assert (
                by_name["child.generate"]["parent_id"]
                == by_name["engine.generate"]["span_id"]
            )
            assert all(sp["trace_id"] == TRACE_ID for sp in spans)
            assert not doc["incomplete"]
            # breakdown reconciles: phases partition the root wall time
            bd = doc["breakdown"]
            assert bd is not None
            assert abs(sum(bd["phases"].values()) - bd["total_ms"]) <= 1.0
            assert bd["phases"]["decode"] > 0.0
            # chrome export of the assembled trace
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"{mbase}/v1/traces/{TRACE_ID}?format=chrome"
                ) as r:
                    chrome = await r.json()
                assert len(
                    [e for e in chrome["traceEvents"] if e["ph"] == "X"]
                ) == len(spans)
                # search API facets
                async with s.get(
                    f"{mbase}/v1/traces?endpoint=chat&sort=duration"
                    f"&min_ms=0.1&worker={worker.instance_id}"
                ) as r:
                    listing = await r.json()
                assert any(
                    t["trace_id"] == TRACE_ID
                    for t in listing["traces"]
                )
                async with s.get(
                    f"{mbase}/v1/traces?worker=not-a-worker"
                ) as r:
                    assert (await r.json())["traces"] == []
                async with s.get(f"{mbase}/v1/traces?min_ms=bogus") as r:
                    assert r.status == 400
        finally:
            await svc.stop()
            await watcher.stop()
            await rt_f.close()
            await metrics.stop()
            await rt_m.close()
            await worker.stop()
            await rt_w.close()
            await eng.stop()
            await server.stop()

    run(main())


def test_disagg_prefill_hop_assembles(tracing, monkeypatch):
    """The disagg variant: decode + prefill workers' spans (crossing
    the prefill QUEUE) assemble into one trace at the metrics service
    with the hand-off chain intact and transfer attributed."""
    monkeypatch.setenv("DYN_KV_TRANSFER", "host")
    from dynamo_tpu.disagg import DisaggConfig
    from dynamo_tpu.disagg.prefill_worker import PrefillWorker
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.metrics_service import MetricsService
    from dynamo_tpu.model_card import ModelDeploymentCard
    from dynamo_tpu.runtime import DistributedRuntime, RouterMode
    from dynamo_tpu.runtime.fabric import FabricServer
    from dynamo_tpu.worker import Worker

    tiny_cfg = EngineConfig.for_tests()
    prompt = [5, 17, 42, 99, 3, 8, 21, 60, 11, 2]
    card = ModelDeploymentCard(
        name="tiny", kv_page_size=tiny_cfg.page_size,
        context_length=tiny_cfg.max_context,
    )

    async def main():
        server = FabricServer(port=0)
        await server.start()
        rt_d = await DistributedRuntime.create(server.address)
        decode = Worker(
            rt_d, card, engine_config=tiny_cfg, engine_kind="jax",
            namespace="test", metrics_interval=0.1, enable_disagg=True,
            disagg_config=DisaggConfig(
                max_local_prefill_length=4, transfer_timeout_s=20.0
            ),
        )
        await decode.start()
        rt_p = await DistributedRuntime.create(server.address)
        prefill = PrefillWorker(rt_p, tiny_cfg, namespace="test")
        await prefill.start()
        rt_m = await DistributedRuntime.create(server.address)
        metrics = MetricsService(
            rt_m.fabric, host="127.0.0.1", port=0,
            trace_sample_rate=1, trace_window_s=1.0,
        )
        await metrics.start()
        rt_c = await DistributedRuntime.create(server.address)
        try:
            ep = rt_c.namespace("test").component("backend").endpoint(
                "generate"
            )
            router = await ep.router(mode=RouterMode.ROUND_ROBIN)
            await router.source.wait_for_instances()
            with telemetry.span("test.root", service="frontend") as root:
                trace_id = root.trace_id
                tokens = []
                async for item in router.generate(
                    {
                        "request_id": "tp-disagg", "token_ids": prompt,
                        "max_tokens": 4, "temperature": 0.0,
                        "top_p": 1.0, "top_k": 0, "seed": None,
                        "stop_token_ids": [], "stop_strings": [],
                        "ignore_eos": True, "annotations": {},
                    }
                ):
                    tokens.extend(item.get("token_ids", ()))
            assert len(tokens) == 4
            # this client process has no shipper loop: ship explicitly
            # (the real frontend's ModelWatcher shipper does this)
            await traceplane.ship_once(rt_c.fabric, "client")
            mbase = f"http://127.0.0.1:{metrics.port}"
            doc = await _await_assembled(
                mbase, trace_id,
                {"frontend", "router", "worker", "disagg", "prefill"},
            )
            by_name = {sp["name"]: sp for sp in doc["spans"]}
            assert (
                by_name["disagg.prefill"]["parent_id"]
                == by_name["disagg.remote_prefill"]["span_id"]
            )
            bd = doc["breakdown"]
            assert abs(sum(bd["phases"].values()) - bd["total_ms"]) <= 1.0
            assert bd["phases"]["transfer"] >= 0.0
        finally:
            await rt_c.close()
            await metrics.stop()
            await rt_m.close()
            await prefill.stop()
            await rt_p.close()
            await decode.stop()
            await rt_d.close()
            await server.stop()

    run(main())


# -- chaos: SIGKILL-equivalent mid-stream, replay stitches one trace -------


def test_kill_midstream_replay_stitches_one_trace(tracing):
    """Chaos-grade assembly (satellite): kv-routed traffic through a
    2-worker fleet with stream replay; the serving worker dies
    (SIGKILL-equivalent: tasks cancelled, ingress severed, publishing
    stops) after the first tokens. The kept trace stitches BOTH
    attempts under one trace_id with a `replay` event, is flagged
    incomplete (a worker vanished mid-trace), and never vanishes."""
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from helpers.fleet_sim import FleetSim

    from dynamo_tpu.metrics_service import MetricsService
    from dynamo_tpu.runtime import DistributedRuntime

    async def main():
        sim = FleetSim(decode_s_per_step=0.03, metrics_interval=0.1)
        await sim.start(replay=True)
        rt_m = await DistributedRuntime.create(sim.server.address)
        metrics = MetricsService(
            rt_m.fabric, host="127.0.0.1", port=0,
            trace_sample_rate=1, trace_window_s=1.0,
        )
        await metrics.start()
        try:
            a = await sim.add_worker()
            b = await sim.add_worker()
            req = sim._request(isl=8, osl=12)
            tokens = []
            killed = None
            with telemetry.span("http.request", service="frontend",
                                attrs={"endpoint": "chat"}) as root:
                trace_id = root.trace_id
                async for item in sim.router.generate(
                    req, max_attempts=8
                ):
                    tokens.extend(item.get("token_ids") or ())
                    if len(tokens) >= 3 and killed is None:
                        killed = a if a.mock.active_requests else b
                        await sim.kill(killed)
            assert len(tokens) == 12  # the stream continued seamlessly
            # the dead worker's publish loop is gone — the survivor's
            # shipper (same process, shared buffer) and the client-side
            # ship below deliver what DID finish
            await traceplane.ship_once(
                sim.runtime.fabric, "test-client"
            )
            mbase = f"http://127.0.0.1:{metrics.port}"
            doc = await _await_assembled(
                mbase, trace_id, {"frontend", "router", "worker"},
            )
            spans = doc["spans"]
            assert all(sp["trace_id"] == trace_id for sp in spans)
            # both attempts stitched: two worker-side generate spans
            attempts = [
                sp for sp in spans if sp["name"] == "worker.generate"
            ]
            assert len(attempts) >= 2, [sp["name"] for sp in spans]
            # the dispatch span carries the replay + mark_down record
            dispatch = next(
                sp for sp in spans if sp["name"] == "router.dispatch"
            )
            ev_names = {e["name"] for e in dispatch["events"]}
            assert "replay" in ev_names and "mark_down" in ev_names
            # kept BECAUSE anomalous, and honestly flagged incomplete
            assert doc["incomplete"]
            reasons = set(doc["kept_reasons"])
            assert {"replay", "retry", "incomplete"} & reasons
            # the stream_replay fleet event landed on the timeline and
            # joins the trace by window
            async with aiohttp.ClientSession() as s:
                for _ in range(100):
                    async with s.get(
                        f"{mbase}/v1/fleet/events?type=stream_replay"
                    ) as r:
                        evs = (await r.json())["events"]
                    if evs:
                        break
                    await asyncio.sleep(0.05)
            assert evs and evs[-1]["source"] == killed.instance_id
            # eviction never blocked: the assembler is empty or bounded
            assert metrics.traces.stats()["open"] < 2048
        finally:
            await metrics.stop()
            await rt_m.close()
            await sim.stop()

    run(main())


# -- fleet events: worker-side emitters land on the timeline ---------------


def test_worker_drain_event_reaches_timeline(tracing):
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from helpers.fleet_sim import FleetSim

    from dynamo_tpu.metrics_service import MetricsService
    from dynamo_tpu.runtime import DistributedRuntime

    async def main():
        sim = FleetSim(metrics_interval=0.1)
        await sim.start(replay=False)
        rt_m = await DistributedRuntime.create(sim.server.address)
        metrics = MetricsService(rt_m.fabric, host="127.0.0.1", port=0)
        await metrics.start()
        try:
            a = await sim.add_worker()
            b = await sim.add_worker()
            await b.drain(budget_s=0.1)
            await a.flip_role("prefill", budget_s=0.1)
            mbase = f"http://127.0.0.1:{metrics.port}"
            async with aiohttp.ClientSession() as s:
                for _ in range(120):
                    async with s.get(f"{mbase}/v1/fleet/events") as r:
                        evs = (await r.json())["events"]
                    have = {e["type"] for e in evs}
                    if {"drain", "role_flip"} <= have:
                        break
                    await asyncio.sleep(0.05)
            assert {"drain", "role_flip"} <= {e["type"] for e in evs}
            flip = next(e for e in evs if e["type"] == "role_flip")
            assert flip["source"] == a.instance_id
            assert flip["attrs"]["dst"] == "prefill"
            # exposition: the annotation layer's counter family is live
            text = metrics.expose()
            assert 'dynamo_tpu_fleet_events_total{type="role_flip"' in text
            assert promlint.lint(text) == []
        finally:
            await metrics.stop()
            await rt_m.close()
            await sim.stop()

    run(main())

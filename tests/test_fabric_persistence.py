"""Fabric survival: WAL persistence + client session re-establishment.

The reference's control plane survives because etcd raft-persists writes
and clients re-establish leases/watches (transports/etcd.rs:78); these
tests pin the same story for the single fabric server: state outlives a
restart, orphaned leases give owners a reconnect window, and a client that
loses its connection reattaches leases, re-puts registrations, and resets
its watches.
"""

import asyncio

import pytest

from dynamo_tpu.runtime.fabric.persist import PersistentFabric


def run(coro):
    return asyncio.run(coro)


def test_wal_roundtrip(tmp_path):
    d = str(tmp_path)

    async def write():
        f = PersistentFabric(d, orphan_grace=60.0)
        await f.load_and_open()
        lease = await f.grant_lease(30.0)
        await f.put("v1/instances/a", b"inst-a", lease)
        await f.put("plain/key", b"value")
        await f.put("gone", b"bye")
        await f.delete("gone")
        await f.queue_push("q", {"n": 1}, b"p1")
        await f.queue_push("q", {"n": 2}, b"p2")
        item = await f.queue_pop("q")  # in flight, never acked
        await f.obj_put("card", b"model-card")
        await f.obj_put("tmp", b"x")
        await f.obj_delete("tmp")
        await f.close()
        return lease, item.header["n"]

    async def reload(lease):
        f = PersistentFabric(d, orphan_grace=60.0)
        await f.load_and_open()
        assert await f.get("v1/instances/a") == b"inst-a"
        assert await f.get("plain/key") == b"value"
        assert await f.get("gone") is None
        # both queue items pending again (the popped one was never acked)
        assert await f.queue_len("q") == 2
        assert await f.obj_get("card") == b"model-card"
        assert await f.obj_get("tmp") is None
        # the lease survived (orphaned) — keepalive under the old id works
        assert await f.keepalive(lease)
        await f.close()

    lease, popped_n = run(write())
    assert popped_n == 1
    run(reload(lease))


def test_orphaned_lease_expires_and_drops_keys(tmp_path):
    d = str(tmp_path)

    async def write():
        f = PersistentFabric(d)
        await f.load_and_open()
        lease = await f.grant_lease(0.2)
        await f.put("v1/instances/dead", b"x", lease)
        await f.close()

    async def reload():
        f = PersistentFabric(d, orphan_grace=0.3)
        await f.load_and_open()
        assert await f.get("v1/instances/dead") == b"x"  # grace window
        await asyncio.sleep(0.6)  # no reattach -> reaper revokes
        assert await f.get("v1/instances/dead") is None
        await f.close()

    run(write())
    run(reload())


def test_torn_wal_tail_is_dropped(tmp_path):
    d = str(tmp_path)

    async def write():
        f = PersistentFabric(d)
        await f.load_and_open()
        await f.put("k", b"v")
        await f.close()

    run(write())
    with open(str(tmp_path / "fabric.wal"), "ab") as fh:
        fh.write(b"\x13\x07torn-half-record")

    async def reload():
        f = PersistentFabric(d)
        await f.load_and_open()
        assert await f.get("k") == b"v"
        await f.close()

    run(reload())


def test_compaction_folds_wal(tmp_path):
    d = str(tmp_path)

    async def main():
        f = PersistentFabric(d)
        await f.load_and_open()
        for i in range(50):
            await f.put("hot", f"v{i}".encode())
        await f.close()
        size_before = (tmp_path / "fabric.wal").stat().st_size
        f2 = PersistentFabric(d)
        await f2.load_and_open()  # compacts: 50 puts fold into 1
        assert await f2.get("hot") == b"v49"
        await f2.close()
        assert (tmp_path / "fabric.wal").stat().st_size < size_before / 10

    run(main())


def test_client_session_reestablishes_after_server_restart(tmp_path):
    """Kill the fabric server under a live runtime; restart it on the same
    port (with its WAL); the client must reconnect, reattach its lease,
    re-put its registration, and watches must reset+replay."""
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.component import InstanceSource
    from dynamo_tpu.runtime.fabric import FabricServer

    d = str(tmp_path)

    async def main():
        server = FabricServer(port=0, persist_dir=d)
        await server.start()
        port = server.port

        rt = await DistributedRuntime.create(server.address)
        ep = rt.namespace("t").component("c").endpoint("e")
        reg = await ep.register("127.0.0.1", 9999, metadata={"m": 1})

        rt2 = await DistributedRuntime.create(server.address)
        src = InstanceSource(rt2.fabric, "t", "c", "e")
        await src.start()
        await src.wait_for_instances()
        assert len(src.list()) == 1

        sub = await rt2.fabric.subscribe("events.>")

        # hard-stop the server (connections drop; state is in the WAL)
        await server.stop()
        await asyncio.sleep(0.3)

        server2 = FabricServer(port=port, persist_dir=d)
        await server2.start()
        try:
            # both clients reconnect + re-establish within a few backoffs
            # generous: under a loaded CI box the client reconnect
            # backoff ladder can take tens of seconds
            deadline = asyncio.get_running_loop().time() + 30
            while True:
                items = await server2.fabric.get_prefix("v1/instances/")
                if items:
                    break
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("registration never re-put")
                await asyncio.sleep(0.2)
            # watcher saw reset + replayed put
            await src.wait_for_instances(timeout=30)
            assert len(src.list()) == 1
            # re-subscribed: a publish from rt reaches rt2's subscription.
            # Pub/sub has no replay, so a publish that lands BEFORE rt2's
            # re-subscribe completes is legitimately dropped (the suite-
            # context flake): publish repeatedly until one arrives.
            msg = None
            deadline2 = asyncio.get_running_loop().time() + 30
            while msg is None:
                assert (
                    asyncio.get_running_loop().time() < deadline2
                ), "re-subscribed message never arrived"
                try:
                    await rt.fabric.publish("events.x", {"ok": 1})
                except Exception:
                    pass  # rt may itself still be reconnecting
                try:
                    msg = await asyncio.wait_for(sub.next(), 1)
                except asyncio.TimeoutError:
                    pass
            assert msg.header == {"ok": 1}
            # lease keepalive still works under the ORIGINAL lease id
            assert await rt.fabric.keepalive(reg.lease_id)
        finally:
            await rt.close()
            await rt2.close()
            await server2.stop()

    run(main())

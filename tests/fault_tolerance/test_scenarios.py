"""Fault-tolerance scenarios: process-kill injection under live traffic.

Reference model (tests/fault_tolerance/scenarios.py:199-206): scenario
tables mapping names to timed process kills, asserting the serving plane
degrades gracefully and recovers. Covered here:

- decode_worker_kill: SIGKILL one of two workers mid-traffic; every
  subsequent request still succeeds (PushRouter fault detection retries +
  marks the instance down, SURVEY.md §5.3).
- all_workers_down_then_recover: kill the whole fleet -> requests fail
  fast (5xx, no hang); spawn a replacement -> traffic succeeds again
  (lease-based discovery attaches it automatically).
- frontend_restart: kill and restart the frontend; the model re-attaches
  from the fabric card registry with workers untouched.
"""

import json
import signal
import tempfile
import time
import urllib.request

import pytest

from tests.fault_tolerance.harness import (
    Cluster,
    DisaggCluster,
    ExtCluster,
    ManagedProc,
    PhaseMetrics,
    drive_phase,
)

pytestmark = pytest.mark.slow


@pytest.fixture()
def cluster():
    c = Cluster(num_workers=2)
    yield c
    c.stop()


def _drive(cluster, n, expect_ok=True):
    ok = 0
    for i in range(n):
        status, data = cluster.request(f"msg {i}")
        if status == 200:
            ok += 1
    if expect_ok:
        assert ok == n, f"only {ok}/{n} requests succeeded"
    return ok


def _write_metrics(name: str, metrics: PhaseMetrics) -> dict:
    path = tempfile.NamedTemporaryFile(
        suffix=f"-{name}-ft-metrics.json", delete=False
    ).name
    summary = metrics.write(path)
    print(f"[{name}] per-phase metrics -> {path}: {json.dumps(summary)}")
    return summary


def test_decode_worker_kill(cluster):
    """Kill one of two workers mid-traffic with per-phase latency
    accounting (reference: timed kill schedules + utils/metrics.py)."""
    m = PhaseMetrics()
    assert drive_phase(cluster, m, "baseline", 5) == 5
    cluster.workers[0].kill(signal.SIGKILL)
    # No settling time on purpose: the router must handle the dead
    # instance inline (retry + mark-down), not rely on lease expiry.
    assert drive_phase(cluster, m, "after_kill", 10) == 10
    _write_metrics("decode_worker_kill", m)  # the artifact is the point


def test_all_workers_down_then_recover(cluster):
    _drive(cluster, 3)
    for w in cluster.workers:
        w.kill(signal.SIGKILL)
    deadline = time.time() + 30
    saw_failure = False
    while time.time() < deadline:
        status, _ = cluster.request("into the void", timeout=15)
        if status != 200:
            saw_failure = True
            break
        time.sleep(0.5)
    assert saw_failure, "requests kept succeeding with zero workers"

    cluster.add_worker()
    deadline = time.time() + 30
    while time.time() < deadline:
        status, _ = cluster.request("back online")
        if status == 200:
            return
        time.sleep(0.5)
    raise AssertionError("replacement worker never took traffic")


def test_fabric_kill_and_restart():
    """SIGKILL the fabric (control plane) under traffic. The DATA plane
    must keep serving (push-router connections don't ride the fabric);
    after a restart on the same port + WAL, every client re-establishes
    its session (lease reattach + re-put + watch reset) and NEW components
    can still join — the cluster re-forms (etcd restart semantics,
    transports/etcd.rs:78)."""
    c = Cluster(num_workers=2, fabric_persist=True)
    try:
        _drive(c, 5)
        c.fabric.kill(signal.SIGKILL)

        # control plane down, data plane alive: requests still succeed
        _drive(c, 5)

        c.restart_fabric()
        # sessions re-establish within a few backoff rounds
        time.sleep(3.0)
        _drive(c, 5)

        # the re-formed control plane serves joins: a NEW worker registers
        # and a NEW frontend attaches the model from restored state
        c.add_worker()
        http2 = __import__(
            "tests.fault_tolerance.harness", fromlist=["_free_port"]
        )._free_port()
        from tests.fault_tolerance.harness import _cli

        f2 = ManagedProc(
            "frontend2",
            _cli(
                "run", "in=http", "out=dyn",
                "--fabric", f"127.0.0.1:{c.fabric_port}",
                "--port", str(http2),
            ),
        )
        try:
            f2.wait_for("model attached", timeout=30)
        finally:
            f2.stop()
        _drive(c, 5)
    finally:
        c.stop()


def test_prefill_worker_death_mid_transfer():
    """Disagg stack: SIGKILL the only prefill worker while remote prefills
    are in flight. Decode must local-fallback after the transfer timeout
    (requests succeed, slower), and a respawned prefill worker restores
    the remote path — with per-phase latency accounting."""
    c = DisaggCluster()
    try:
        m = PhaseMetrics()
        assert drive_phase(c, m, "baseline", 3) == 3
        assert c.remote_prefills_done() >= 1  # remote path really ran

        # Kill while a remote prefill is ACTUALLY in flight: submit a
        # fresh (uncached) request from a thread, then SIGKILL the prefill
        # worker a beat later — the kill lands while the request is
        # queued/prefilling/transferring, not between requests.
        import threading

        c.clear_kv()
        inflight: dict = {}

        def _one():
            t0 = time.time()
            try:
                status, _ = c.request("zq killme", timeout=60)
            except Exception:
                status = -1
            inflight["status"] = status
            m.record("inflight_kill", status == 200, time.time() - t0)

        t = threading.Thread(target=_one)
        t.start()
        time.sleep(0.3)
        c.prefill.kill(signal.SIGKILL)
        t.join(timeout=90)
        assert not t.is_alive(), "in-flight request hung after prefill kill"
        assert inflight["status"] == 200  # fallback completed it

        c.clear_kv()  # cached prompts would bypass the remote path
        # new requests with no prefill fleet: transfer waiters time out
        # (3s) and decode finishes locally — degraded but NOT failed
        assert drive_phase(c, m, "prefill_down", 3, timeout=60) == 3

        c.prefill = c.spawn_prefill()
        c.clear_kv()
        assert drive_phase(c, m, "recovered", 3) == 3
        assert c.remote_prefills_done() >= 1  # fresh worker served remotely

        s = _write_metrics("prefill_death", m)
        assert s["prefill_down"]["fail"] == 0
        # at least the first fallback pays the 3s transfer timeout (later
        # requests ride the cache and stay local-fast, so assert on max)
        assert s["prefill_down"]["max_ms"] > 2500
    finally:
        c.stop()


def test_worker_kill_during_stream():
    """SIGKILL the worker while a streaming response is mid-flight (the
    echo engine emits a token every 200ms, so the kill genuinely lands
    mid-stream): the stream must terminate promptly — never hang — and
    the fleet serves again after a replacement joins."""
    import http.client

    c = Cluster(num_workers=1, echo_delay=0.2)
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", c.http_port, timeout=30
        )
        body = json.dumps(
            {
                "model": c.model,
                "messages": [{"role": "user", "content": "stream me please"}],
                "max_tokens": 32,
                "stream": True,
            }
        )
        conn.request(
            "POST", "/v1/chat/completions", body=body,
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        first = resp.read(40)  # chunk-aware read: stream is live
        assert first
        c.workers[0].kill(signal.SIGKILL)
        t0 = time.time()
        try:
            while resp.read(256):  # must terminate, not hang
                pass
        except Exception:
            pass
        elapsed = time.time() - t0
        assert elapsed < 20, f"stream hung {elapsed:.1f}s after worker kill"
        conn.close()

        c.add_worker()
        c.wait_until_ready(30)  # exception-tolerant recovery poll
    finally:
        c.stop()


def test_subprocess_engine_kill_midstream_restart_markdown():
    """ISSUE 3 FT scenario: SIGKILL the supervised ENGINE subprocesses
    (not the workers) while a streaming response is mid-flight. The
    in-flight stream must error-finish promptly (never hang), the
    supervisors must backoff-restart the engines, and during the restart
    window pre-stream requests must ride the retryable-error mark-down
    onto whichever engine is back first — steady state recovers to 100%
    success with the ORIGINAL worker processes still up."""
    import http.client

    c = ExtCluster(num_workers=2, delay=0.05)
    try:
        m = PhaseMetrics()
        assert drive_phase(c, m, "baseline", 4) == 4
        # every worker has a live engine child before the kill
        engines_before = [c.engine_pids(w) for w in c.workers]
        assert all(engines_before), engines_before

        conn = http.client.HTTPConnection(
            "127.0.0.1", c.http_port, timeout=30
        )
        body = json.dumps(
            {
                "model": c.model,
                "messages": [{"role": "user", "content": "stream on"}],
                "max_tokens": 64,
                "stream": True,
            }
        )
        conn.request(
            "POST", "/v1/chat/completions", body=body,
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.read(40)  # the stream is live
        assert c.kill_engines() >= 2
        t0 = time.time()
        try:
            while resp.read(256):  # must terminate (error finish), not hang
                pass
        except Exception:
            pass
        elapsed = time.time() - t0
        assert elapsed < 15, f"stream hung {elapsed:.1f}s after engine kill"
        conn.close()

        # supervised restart: the SAME worker processes serve again
        c.wait_until_ready(30)
        assert drive_phase(c, m, "after_restart", 6) == 6

        # the workers never died — their engine children did and were
        # replaced by the supervisor
        for w, before in zip(c.workers, engines_before):
            assert w.proc.poll() is None, "worker process died with engine"
            after = c.engine_pids(w)
            assert after and set(after) != set(before), (before, after)

        s = _write_metrics("subprocess_engine_kill", m)
        assert s["after_restart"]["fail"] == 0
    finally:
        c.stop()


def test_frontend_restart(cluster):
    _drive(cluster, 3)
    http_port = cluster.http_port
    cluster.frontend.kill(signal.SIGKILL)
    from tests.fault_tolerance.harness import _cli

    cluster.frontend = ManagedProc(
        "frontend2",
        _cli(
            "run", "in=http", "out=dyn",
            "--fabric", f"127.0.0.1:{cluster.fabric_port}",
            "--port", str(http_port),
        ),
    )
    cluster.frontend.wait_for("listening on", timeout=30)
    cluster.wait_until_ready()
    _drive(cluster, 5)

"""Fault-tolerance scenarios: process-kill injection under live traffic.

Reference model (tests/fault_tolerance/scenarios.py:199-206): scenario
tables mapping names to timed process kills, asserting the serving plane
degrades gracefully and recovers. Covered here:

- decode_worker_kill: SIGKILL one of two workers mid-traffic; every
  subsequent request still succeeds (PushRouter fault detection retries +
  marks the instance down, SURVEY.md §5.3).
- all_workers_down_then_recover: kill the whole fleet -> requests fail
  fast (5xx, no hang); spawn a replacement -> traffic succeeds again
  (lease-based discovery attaches it automatically).
- frontend_restart: kill and restart the frontend; the model re-attaches
  from the fabric card registry with workers untouched.
"""

import signal
import time

import pytest

from tests.fault_tolerance.harness import Cluster, ManagedProc

pytestmark = pytest.mark.slow


@pytest.fixture()
def cluster():
    c = Cluster(num_workers=2)
    yield c
    c.stop()


def _drive(cluster, n, expect_ok=True):
    ok = 0
    for i in range(n):
        status, data = cluster.request(f"msg {i}")
        if status == 200:
            ok += 1
    if expect_ok:
        assert ok == n, f"only {ok}/{n} requests succeeded"
    return ok


def test_decode_worker_kill(cluster):
    _drive(cluster, 5)
    cluster.workers[0].kill(signal.SIGKILL)
    # No settling time on purpose: the router must handle the dead
    # instance inline (retry + mark-down), not rely on lease expiry.
    _drive(cluster, 10)


def test_all_workers_down_then_recover(cluster):
    _drive(cluster, 3)
    for w in cluster.workers:
        w.kill(signal.SIGKILL)
    deadline = time.time() + 30
    saw_failure = False
    while time.time() < deadline:
        status, _ = cluster.request("into the void", timeout=15)
        if status != 200:
            saw_failure = True
            break
        time.sleep(0.5)
    assert saw_failure, "requests kept succeeding with zero workers"

    cluster.add_worker()
    deadline = time.time() + 30
    while time.time() < deadline:
        status, _ = cluster.request("back online")
        if status == 200:
            return
        time.sleep(0.5)
    raise AssertionError("replacement worker never took traffic")


def test_fabric_kill_and_restart():
    """SIGKILL the fabric (control plane) under traffic. The DATA plane
    must keep serving (push-router connections don't ride the fabric);
    after a restart on the same port + WAL, every client re-establishes
    its session (lease reattach + re-put + watch reset) and NEW components
    can still join — the cluster re-forms (etcd restart semantics,
    transports/etcd.rs:78)."""
    c = Cluster(num_workers=2, fabric_persist=True)
    try:
        _drive(c, 5)
        c.fabric.kill(signal.SIGKILL)

        # control plane down, data plane alive: requests still succeed
        _drive(c, 5)

        c.restart_fabric()
        # sessions re-establish within a few backoff rounds
        time.sleep(3.0)
        _drive(c, 5)

        # the re-formed control plane serves joins: a NEW worker registers
        # and a NEW frontend attaches the model from restored state
        c.add_worker()
        http2 = __import__(
            "tests.fault_tolerance.harness", fromlist=["_free_port"]
        )._free_port()
        from tests.fault_tolerance.harness import _cli

        f2 = ManagedProc(
            "frontend2",
            _cli(
                "run", "in=http", "out=dyn",
                "--fabric", f"127.0.0.1:{c.fabric_port}",
                "--port", str(http2),
            ),
        )
        try:
            f2.wait_for("model attached", timeout=30)
        finally:
            f2.stop()
        _drive(c, 5)
    finally:
        c.stop()


def test_frontend_restart(cluster):
    _drive(cluster, 3)
    http_port = cluster.http_port
    cluster.frontend.kill(signal.SIGKILL)
    from tests.fault_tolerance.harness import _cli

    cluster.frontend = ManagedProc(
        "frontend2",
        _cli(
            "run", "in=http", "out=dyn",
            "--fabric", f"127.0.0.1:{cluster.fabric_port}",
            "--port", str(http_port),
        ),
    )
    cluster.frontend.wait_for("listening on", timeout=30)
    cluster.wait_until_ready()
    _drive(cluster, 5)

"""Fault-tolerance harness: real process clusters + kill injection.

The reference runs its FT suite by launching the full serve stack and
killing named processes on a schedule (tests/fault_tolerance/scenarios.py,
test_runner.py, utils/managed_process.py). Same shape here: ManagedProc
wraps a CLI process with log capture + pattern readiness; Cluster stands up
fabric + frontend + echo workers and exposes kill/spawn/request.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
ENV = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")


class ManagedProc:
    """Subprocess with a log file and wait-for-pattern readiness."""

    def __init__(self, name: str, argv: list[str]):
        self.name = name
        self.log_path = tempfile.NamedTemporaryFile(
            mode="w", suffix=f"-{name}.log", delete=False
        ).name
        self._log = open(self.log_path, "w")
        self.proc = subprocess.Popen(
            argv, cwd=REPO, env=ENV, stdout=self._log, stderr=subprocess.STDOUT
        )

    def wait_for(self, pattern: str, timeout: float = 30.0) -> None:
        rx = re.compile(pattern)
        deadline = time.time() + timeout
        while time.time() < deadline:
            with open(self.log_path) as f:
                if rx.search(f.read()):
                    return
            if self.proc.poll() is not None:
                raise AssertionError(
                    f"{self.name} exited {self.proc.returncode} before "
                    f"matching {pattern!r}:\n{open(self.log_path).read()}"
                )
            time.sleep(0.2)
        raise AssertionError(
            f"{self.name}: {pattern!r} not seen in {timeout}s:\n"
            + open(self.log_path).read()
        )

    def kill(self, sig=signal.SIGKILL) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(sig)
            self.proc.wait(timeout=10)

    def stop(self) -> None:
        self.kill(signal.SIGTERM)
        self._log.close()


def _cli(*args: str) -> list[str]:
    return [sys.executable, "-m", "dynamo_tpu.cli.run", *args]


class Cluster:
    """fabric + OpenAI frontend + N echo workers on one model."""

    def __init__(
        self, num_workers: int = 2, model: str = "tiny",
        fabric_persist: bool = False,
    ):
        self.model = model
        self.fabric_port = _free_port()
        self.http_port = _free_port()
        self.fabric = None
        self.frontend = None
        self.workers: list[ManagedProc] = []
        self.persist_dir = (
            tempfile.mkdtemp(prefix="fabric-wal-") if fabric_persist else None
        )
        try:
            self.fabric = ManagedProc("fabric", self._fabric_argv())
            self.fabric.wait_for("fabric server on|listening", timeout=20)
            for _ in range(num_workers):
                self.add_worker()
            self.frontend = ManagedProc(
                "frontend",
                _cli(
                    "run", "in=http", "out=dyn",
                    "--fabric", f"127.0.0.1:{self.fabric_port}",
                    "--port", str(self.http_port),
                ),
            )
            self.frontend.wait_for("listening on", timeout=30)
            self.wait_until_ready()
        except BaseException:
            # A failed bring-up must not leak the processes already started
            # (the fixture never gets a Cluster object to stop()).
            self.stop()
            raise

    def _fabric_argv(self) -> list[str]:
        argv = _cli("fabric", "--port", str(self.fabric_port))
        if self.persist_dir:
            argv += ["--persist-dir", self.persist_dir]
        return argv

    def restart_fabric(self) -> None:
        """Bring the fabric back on the SAME port (same WAL when
        persistent); clients re-establish their sessions on their own."""
        self.fabric = ManagedProc("fabric", self._fabric_argv())
        self.fabric.wait_for("fabric server on|listening", timeout=20)

    def add_worker(self) -> ManagedProc:
        w = ManagedProc(
            f"worker{len(self.workers)}",
            _cli(
                "run", "in=dyn", "out=echo", "--model", self.model,
                "--fabric", f"127.0.0.1:{self.fabric_port}",
            ),
        )
        w.wait_for(r"worker \w+ up", timeout=40)
        self.workers.append(w)
        return w

    def request(self, text: str, timeout: float = 10.0) -> tuple[int, dict]:
        body = json.dumps(
            {
                "model": self.model,
                "messages": [{"role": "user", "content": text}],
                "max_tokens": 32,
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.http_port}/v1/chat/completions",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except Exception:
                payload = {}
            return e.code, payload

    def wait_until_ready(self, timeout: float = 30.0) -> None:
        """Model attached + at least one worker reachable end-to-end."""
        deadline = time.time() + timeout
        last = None
        while time.time() < deadline:
            try:
                status, data = self.request("ping", timeout=5)
                if status == 200:
                    return
                last = (status, data)
            except Exception as e:  # conn refused while booting
                last = e
            time.sleep(0.5)
        raise AssertionError(f"cluster never became ready: {last}")

    def stop(self) -> None:
        for p in [self.frontend, *self.workers, self.fabric]:
            if p is None:
                continue
            try:
                p.stop()
            except Exception:
                pass


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]

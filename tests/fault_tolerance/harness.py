"""Fault-tolerance harness: real process clusters + kill injection.

The reference runs its FT suite by launching the full serve stack and
killing named processes on a schedule (tests/fault_tolerance/scenarios.py,
test_runner.py, utils/managed_process.py). Same shape here: ManagedProc
wraps a CLI process with log capture + pattern readiness; Cluster stands up
fabric + frontend + echo workers and exposes kill/spawn/request.
"""

from __future__ import annotations

import json
import signal
import tempfile
import time
import urllib.error
import urllib.request

from benchmarks._procs import ENV as _BASE_ENV
from benchmarks._procs import REPO, ManagedProc as _SharedProc
from benchmarks._procs import cli as _shared_cli

ENV = dict(_BASE_ENV, JAX_PLATFORMS="cpu")


class ManagedProc(_SharedProc):
    """Shared machinery pinned to the CPU platform for FT scenarios."""

    def __init__(self, name: str, argv: list[str]):
        super().__init__(name, argv, env=ENV)


def _cli(*args: str) -> list[str]:
    return _shared_cli(*args)


class Cluster:
    """fabric + OpenAI frontend + N echo workers on one model."""

    #: request-body knobs subclasses override (tiny-context engines)
    MAX_TOKENS = 32
    TEXT_LIMIT = None

    def __init__(
        self, num_workers: int = 2, model: str = "tiny",
        fabric_persist: bool = False, echo_delay: float = 0.0,
    ):
        self.model = model
        self.echo_delay = echo_delay
        self.fabric_port = _free_port()
        self.http_port = _free_port()
        self.fabric = None
        self.frontend = None
        self.workers: list[ManagedProc] = []
        self.persist_dir = (
            tempfile.mkdtemp(prefix="fabric-wal-") if fabric_persist else None
        )
        try:
            self.fabric = ManagedProc("fabric", self._fabric_argv())
            self.fabric.wait_for("fabric server on|listening", timeout=20)
            self._spawn_workers(num_workers)
            self.frontend = ManagedProc(
                "frontend",
                _cli(
                    "run", "in=http", "out=dyn",
                    "--fabric", f"127.0.0.1:{self.fabric_port}",
                    "--port", str(self.http_port),
                ),
            )
            self.frontend.wait_for("listening on", timeout=30)
            self.wait_until_ready()
        except BaseException:
            # A failed bring-up must not leak the processes already started
            # (the fixture never gets a Cluster object to stop()).
            self.stop()
            raise

    def _spawn_workers(self, n: int) -> None:
        for _ in range(n):
            self.add_worker()

    def _fabric_argv(self) -> list[str]:
        argv = _cli("fabric", "--port", str(self.fabric_port))
        if self.persist_dir:
            argv += ["--persist-dir", self.persist_dir]
        return argv

    def restart_fabric(self) -> None:
        """Bring the fabric back on the SAME port (same WAL when
        persistent); clients re-establish their sessions on their own."""
        self.fabric = ManagedProc("fabric", self._fabric_argv())
        self.fabric.wait_for("fabric server on|listening", timeout=20)

    def add_worker(self) -> ManagedProc:
        argv = _cli(
            "run", "in=dyn", "out=echo", "--model", self.model,
            "--fabric", f"127.0.0.1:{self.fabric_port}",
        )
        if self.echo_delay:
            argv += ["--echo-delay", str(self.echo_delay)]
        w = ManagedProc(f"worker{len(self.workers)}", argv)
        # append BEFORE readiness: a failed wait must not leak the process
        self.workers.append(w)
        w.wait_for(r"worker \w+ up", timeout=40)
        return w

    def request(self, text: str, timeout: float = 10.0) -> tuple[int, dict]:
        if self.TEXT_LIMIT:
            text = text[: self.TEXT_LIMIT]
        body = json.dumps(
            {
                "model": self.model,
                "messages": [{"role": "user", "content": text}],
                "max_tokens": self.MAX_TOKENS,
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.http_port}/v1/chat/completions",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except Exception:
                payload = {}
            return e.code, payload

    def wait_until_ready(self, timeout: float = 30.0) -> None:
        """Model attached + at least one worker reachable end-to-end."""
        deadline = time.time() + timeout
        last = None
        while time.time() < deadline:
            try:
                status, data = self.request("ping", timeout=5)
                if status == 200:
                    return
                last = (status, data)
            except Exception as e:  # conn refused while booting
                last = e
            time.sleep(0.5)
        raise AssertionError(f"cluster never became ready: {last}")

    def stop(self) -> None:
        for p in [self.frontend, *self.workers, self.fabric]:
            if p is None:
                continue
            try:
                p.stop()
            except Exception:
                pass


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class PhaseMetrics:
    """Per-phase success/latency accounting (the reference harness collects
    per-phase latency across its kill schedule — tests/fault_tolerance/
    utils/metrics.py + parse_results.py). Scenarios record every request
    under a named phase; the summary lands in a JSON artifact."""

    def __init__(self):
        self.phases: dict[str, dict] = {}

    def record(self, phase: str, ok: bool, latency_s: float) -> None:
        p = self.phases.setdefault(phase, {"ok": 0, "fail": 0, "lat": []})
        p["ok" if ok else "fail"] += 1
        if ok:
            p["lat"].append(latency_s)

    @staticmethod
    def _pct(values, q):
        if not values:
            return None
        v = sorted(values)
        return v[min(len(v) - 1, int(round(q * (len(v) - 1))))]

    def summary(self) -> dict:
        out = {}
        for name, p in self.phases.items():
            out[name] = {
                "requests": p["ok"] + p["fail"],
                "ok": p["ok"],
                "fail": p["fail"],
                "p50_ms": (
                    round(self._pct(p["lat"], 0.5) * 1e3, 1)
                    if p["lat"] else None
                ),
                "p95_ms": (
                    round(self._pct(p["lat"], 0.95) * 1e3, 1)
                    if p["lat"] else None
                ),
                "max_ms": (
                    round(max(p["lat"]) * 1e3, 1) if p["lat"] else None
                ),
            }
        return out

    def write(self, path: str) -> dict:
        s = self.summary()
        with open(path, "w") as f:
            json.dump(s, f, indent=1)
        return s


def drive_phase(
    cluster, metrics: PhaseMetrics, phase: str, n: int,
    text: str = "msg", timeout: float = 15.0,
) -> int:
    """n requests recorded under `phase`; returns successes."""
    ok = 0
    for i in range(n):
        t0 = time.time()
        try:
            status, _ = cluster.request(f"{text} {i}", timeout=timeout)
        except Exception:
            status = -1
        metrics.record(phase, status == 200, time.time() - t0)
        ok += status == 200
    return ok


class ExtCluster(Cluster):
    """fabric + frontend + N workers whose ENGINES are supervised
    subprocesses (the external-engine harness, docs/external_engines.md
    "Level 2"): every worker is `run in=dyn out=ext:<reference_worker>`,
    so kill injection can target the ENGINE process while the worker —
    its lease, ingress, and supervisor — stays up."""

    MAX_TOKENS = 16

    def __init__(self, num_workers: int = 2, delay: float = 0.05):
        self.delay = delay
        super().__init__(num_workers=num_workers)

    def add_worker(self) -> ManagedProc:
        import sys

        ext = (
            f"{sys.executable} -m dynamo_tpu.external.reference_worker "
            f"--block-size 4 --delay {self.delay}"
        )
        argv = _cli(
            "run", "in=dyn", "out=ext:" + ext, "--model", self.model,
            "--fabric", f"127.0.0.1:{self.fabric_port}",
        )
        w = ManagedProc(f"worker{len(self.workers)}", argv)
        self.workers.append(w)
        w.wait_for(r"worker \w+ up", timeout=60)
        return w

    def engine_pids(self, worker: ManagedProc) -> list[int]:
        """PIDs of the worker's supervised engine subprocess(es) —
        read from /proc so there's no pgrep/psutil dependency."""
        pid = worker.proc.pid
        try:
            with open(f"/proc/{pid}/task/{pid}/children") as f:
                return [int(x) for x in f.read().split()]
        except OSError:
            return []

    def kill_engines(self) -> int:
        """SIGKILL every worker's engine subprocess (not the workers);
        returns how many engines were killed."""
        import os

        n = 0
        for w in self.workers:
            for cpid in self.engine_pids(w):
                try:
                    os.kill(cpid, signal.SIGKILL)
                    n += 1
                except ProcessLookupError:
                    pass
        return n


class DisaggCluster(Cluster):
    """fabric + jax decode worker (remote prefill on) + prefill worker +
    frontend — the disagg serving stack for kill-injection scenarios.

    Context is 32 tokens (byte tokenizer + template ~17): prompts stay
    tiny, and any prompt with >4 uncached tokens goes to the prefill fleet
    (--max-local-prefill 4)."""

    ENGINE = [
        "--model", "tiny", "--page-size", "4", "--num-pages", "64",
        "--max-context", "32", "--dtype", "float32",
    ]
    MAX_TOKENS = 4
    TEXT_LIMIT = 8

    def __init__(self):
        self.prefill: ManagedProc | None = None
        super().__init__(num_workers=1)

    def _spawn_workers(self, n: int) -> None:
        decode = ManagedProc(
            "decode",
            _cli(
                "run", "in=dyn", "out=jax", *self.ENGINE,
                "--fabric", f"127.0.0.1:{self.fabric_port}",
                "--disagg", "--max-local-prefill", "4",
                "--transfer-timeout", "3",
            ),
        )
        self.workers.append(decode)
        decode.wait_for(r"worker \w+ up", timeout=60)
        self.prefill = self.spawn_prefill()

    @property
    def decode(self) -> ManagedProc:
        return self.workers[0]

    def spawn_prefill(self) -> ManagedProc:
        p = ManagedProc(
            "prefill",
            _cli(
                "run", "in=dyn", "out=jax", *self.ENGINE,
                "--role", "prefill",
                "--fabric", f"127.0.0.1:{self.fabric_port}",
            ),
        )
        # track BEFORE readiness so a failed wait can't leak the process
        self.prefill = p
        p.wait_for(r"prefill worker \w+ up", timeout=60)
        return p

    def remote_prefills_done(self) -> int:
        with open(self.prefill.log_path) as f:
            return f.read().count("compiled prefill")

    def clear_kv(self) -> None:
        """Flush every worker's prefix cache so the next prompts are fully
        uncached (and therefore eligible for remote prefill again)."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.http_port}/clear_kv_blocks", data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200

    def stop(self) -> None:
        if self.prefill is not None:
            try:
                self.prefill.stop()
            except Exception:
                pass
        super().stop()

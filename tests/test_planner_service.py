"""FleetObserver over a real fabric: discovery + metrics + queue -> FleetState."""

import asyncio

from dynamo_tpu.disagg.prefill_queue import PrefillQueue
from dynamo_tpu.disagg.protocol import RemotePrefillRequest
from dynamo_tpu.planner.service import FleetObserver
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.fabric import FabricServer
from dynamo_tpu.subjects import METRICS_SUBJECT


def test_fleet_observer_assembles_state():
    async def main():
        server = FabricServer(port=0)
        await server.start()
        try:
            rt_obs = await DistributedRuntime.create(server.address)
            rt_w = await DistributedRuntime.create(server.address)

            # two decode workers + one prefill worker register
            regs = []
            for i in range(2):
                ep = rt_w.namespace("dynamo").component("backend").endpoint("generate")
                regs.append(await ep.register("127.0.0.1", 9000 + i, metadata={}))
            epp = rt_w.namespace("dynamo").component("prefill").endpoint("prefill")
            regs.append(await epp.register("127.0.0.1", 0, metadata={}))

            observer = FleetObserver(rt_obs)
            await observer.start()
            await asyncio.sleep(0.2)  # watch deliveries

            # metrics from both decode workers
            for i, reg in enumerate(regs[:2]):
                iid = reg.instance.instance_id
                await rt_w.fabric.publish(
                    f"{METRICS_SUBJECT}.backend.{iid}",
                    {
                        "instance_id": iid,
                        "kv_usage": 0.4 + 0.2 * i,  # mean 0.5
                        "num_waiting": 2,
                        "requests_received": 10,
                    },
                )
            # one queued remote prefill
            q = PrefillQueue(rt_w.fabric)
            await q.push(
                RemotePrefillRequest(
                    request_id="r1", token_ids=[1, 2, 3], page_ids=[1],
                    transfer_host="h", transfer_port=1, sampling={},
                )
            )
            await asyncio.sleep(0.2)

            s1 = await observer.observe()
            assert s1.num_decode == 2
            assert s1.num_prefill == 1
            assert abs(s1.kv_usage - 0.5) < 1e-6
            assert s1.num_waiting == 4
            assert s1.prefill_queue_depth == 1
            assert s1.request_rate == 0.0  # first sample: no baseline yet

            # counters advance -> positive request rate
            await asyncio.sleep(0.05)
            for reg in regs[:2]:
                iid = reg.instance.instance_id
                await rt_w.fabric.publish(
                    f"{METRICS_SUBJECT}.backend.{iid}",
                    {
                        "instance_id": iid,
                        "kv_usage": 0.5,
                        "num_waiting": 0,
                        "requests_received": 15,
                    },
                )
            await asyncio.sleep(0.2)
            s2 = await observer.observe()
            assert s2.request_rate > 0.0

            # a dead worker disappears from the fleet
            await regs[0].deregister()
            await asyncio.sleep(0.2)
            s3 = await observer.observe()
            assert s3.num_decode == 1

            await observer.stop()
            await rt_obs.close()
            await rt_w.close()
        finally:
            await server.stop()

    asyncio.run(main())

"""MoE (Mixtral-style) model: gating properties, HF parity, ep-mesh run."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.models.llama import init_kv_pages
from dynamo_tpu.models.moe import (
    MoeConfig,
    forward,
    init_params,
    moe_param_specs,
    params_from_torch_state_dict,
    top_k_gating,
)

PAGE_SIZE = 4


def _run_paged(cfg, params, toks):
    b, t = toks.shape
    kv = init_kv_pages(cfg.base, 64, PAGE_SIZE)
    n_pages = -(-t // PAGE_SIZE)
    pts = np.zeros((b, n_pages), np.int32)
    for i in range(b):
        pts[i] = np.arange(1 + i * n_pages, 1 + (i + 1) * n_pages)
    positions = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    logits, _ = forward(
        params, cfg, jnp.asarray(toks), jnp.asarray(positions),
        jnp.ones((b, t), bool), kv, jnp.asarray(pts),
    )
    return np.asarray(logits)


# -- gating -----------------------------------------------------------------


def test_gating_dispatch_properties():
    rng = np.random.default_rng(0)
    n, e, k, cap = 12, 4, 2, 12  # cap=n: no assignment can ever drop
    logits = jnp.asarray(rng.normal(size=(n, e)), jnp.float32)
    dispatch, combine = top_k_gating(logits, k, cap)
    dispatch = np.asarray(dispatch)
    combine = np.asarray(combine)
    # every token goes to exactly k slots when capacity is ample
    assert (dispatch.sum(axis=(1, 2)) == k).all()
    # no expert slot double-booked
    assert (dispatch.sum(axis=0) <= 1).all()
    # combine weights per token sum to 1 (renormalized top-k)
    np.testing.assert_allclose(combine.sum(axis=(1, 2)), 1.0, rtol=1e-5)
    # combine only where dispatched
    assert (combine[dispatch == 0] == 0).all()


def test_gating_capacity_drops_weakest():
    # all tokens pick expert 0 first; capacity 2 keeps only the first two
    logits = jnp.asarray(
        [[10.0, 0.0, 1.0], [10.0, 0.0, 1.0], [10.0, 0.0, 1.0]], jnp.float32
    )
    dispatch, combine = top_k_gating(logits, 1, 2)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == 2  # expert 0 full
    assert d[2].sum() == 0  # third token dropped entirely


# -- full model -------------------------------------------------------------


def test_against_hf_mixtral():
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig, MixtralForCausalLM

    cfg = MoeConfig.tiny()
    # capacity >= all assignments -> exact (no drops), matches HF routing
    from dataclasses import replace

    cfg = replace(cfg, capacity_factor=float(cfg.num_experts))
    b = cfg.base
    hf_cfg = MixtralConfig(
        vocab_size=b.vocab_size,
        hidden_size=b.hidden_size,
        intermediate_size=b.intermediate_size,
        num_hidden_layers=b.num_layers,
        num_attention_heads=b.num_heads,
        num_key_value_heads=b.num_kv_heads,
        head_dim=b.head_dim,
        rope_theta=b.rope_theta,
        rms_norm_eps=b.rms_norm_eps,
        num_local_experts=cfg.num_experts,
        num_experts_per_tok=cfg.top_k,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = MixtralForCausalLM(hf_cfg).eval()
    params = params_from_torch_state_dict(model.state_dict(), cfg)

    rng = np.random.default_rng(2)
    toks = rng.integers(0, b.vocab_size, size=(2, 9)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    ours = _run_paged(cfg, params, toks)
    np.testing.assert_allclose(ours, ref, rtol=5e-2, atol=5e-2)
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.95


def test_moe_on_ep_mesh(cpu_mesh_devices):
    """ep-sharded experts: sharded forward == single-device forward."""
    from dynamo_tpu.models.llama import KVPages
    from dynamo_tpu.parallel import MeshConfig, make_mesh, shardings_for
    from dynamo_tpu.parallel.shardings import batch_spec, kv_cache_spec

    cfg = MoeConfig.tiny()
    params = init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(3)
    toks = rng.integers(0, cfg.base.vocab_size, size=(2, 8)).astype(np.int32)
    ref = _run_paged(cfg, params, toks)

    mesh = make_mesh(
        MeshConfig(dp=2, ep=4, tp=1), devices=cpu_mesh_devices[:8]
    )
    params_s = jax.device_put(params, shardings_for(mesh, moe_param_specs(cfg)))
    kv = init_kv_pages(cfg.base, 64, PAGE_SIZE)
    kv = jax.device_put(
        kv, shardings_for(mesh, KVPages(k=kv_cache_spec(), v=kv_cache_spec()))
    )
    n_pages = 2
    pts = np.stack(
        [np.arange(1 + i * n_pages, 1 + (i + 1) * n_pages) for i in range(2)]
    ).astype(np.int32)
    positions = np.tile(np.arange(8, dtype=np.int32), (2, 1))
    bsh = shardings_for(mesh, batch_spec(2))
    args = [
        jax.device_put(jnp.asarray(x), bsh)
        for x in (toks, positions, np.ones((2, 8), bool), pts)
    ]
    fwd = jax.jit(
        lambda p, t, pos, val, kv, pt: forward(p, cfg, t, pos, val, kv, pt)
    )
    logits, _ = fwd(params_s, args[0], args[1], args[2], kv, args[3])
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=5e-2, atol=5e-2)


def test_registry_moe_adapter():
    from dynamo_tpu.models.registry import get_model

    adapter = get_model("moe-tiny", dtype="float32")
    assert adapter.config.num_experts == 4
    params = adapter.init_params(jax.random.key(0))
    assert "we_gate" in params["layers"]
    kv = adapter.init_kv(16, 4)
    toks = jnp.ones((1, 4), jnp.int32)
    pos = jnp.arange(4, dtype=jnp.int32)[None]
    pt = jnp.asarray([[1, 2]], jnp.int32)
    logits, _ = adapter.forward(params, toks, pos, jnp.ones((1, 4), bool), kv, pt)
    assert logits.shape == (1, 4, adapter.vocab_size)


def test_moe_engine_with_ep_from_config(cpu_mesh_devices):
    """EngineConfig.ep reaches the mesh: a MoE model serves with experts
    sharded over ep devices, matching the single-device output."""
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams

    base = EngineConfig.for_tests()
    over = dict(model="moe-tiny", dtype="float32")
    single = JaxEngine(EngineConfig(**{**base.__dict__, **over}))
    sharded = JaxEngine(EngineConfig(**{**base.__dict__, **over, "ep": 2}))
    assert sharded.mesh is not None and sharded.mesh.shape["ep"] == 2
    prompt = [3, 5, 7, 9]
    for eng, rid in ((single, "a"), (sharded, "b")):
        eng.add_request(rid, prompt, SamplingParams(temperature=0.0, max_tokens=4))
    assert single.run_to_completion()["a"] == sharded.run_to_completion()["b"]


def test_qwen3_moe_against_hf():
    """Qwen3-MoE: Mixtral block + qk-norm attention + separate expert
    width + norm_topk_prob-gated renormalization, vs HF."""
    import pytest as _pytest

    torch = _pytest.importorskip("torch")
    from dataclasses import replace as _replace

    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.models.moe import (
        MoeConfig,
        forward,
        params_from_torch_state_dict,
    )

    cfg = MoeConfig(
        base=_replace(
            LlamaConfig.tiny(), rms_norm_eps=1e-6, qk_norm=True,
        ),
        num_experts=4, top_k=2, norm_topk_prob=True,
        expert_intermediate_size=32, hf_naming="qwen3_moe",
        capacity_factor=4.0,  # no drops: exactness vs HF
    )
    bc = cfg.base
    hf_cfg = Qwen3MoeConfig(
        vocab_size=bc.vocab_size, hidden_size=bc.hidden_size,
        intermediate_size=bc.intermediate_size,
        num_hidden_layers=bc.num_layers,
        num_attention_heads=bc.num_heads,
        num_key_value_heads=bc.num_kv_heads,
        head_dim=bc.head_dim, rope_theta=bc.rope_theta,
        rms_norm_eps=bc.rms_norm_eps, tie_word_embeddings=False,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        moe_intermediate_size=32, decoder_sparse_step=1,
        mlp_only_layers=[], attn_implementation="eager",
    )
    torch.manual_seed(27)
    model = Qwen3MoeForCausalLM(hf_cfg).eval()
    params = params_from_torch_state_dict(model.state_dict(), cfg)
    assert "q_norm" in params["layers"]

    rng = np.random.default_rng(12)
    toks = rng.integers(0, bc.vocab_size, size=(2, 9)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks.astype(np.int64))).logits.numpy()

    kv = init_kv_pages(bc, 64, 4)
    pts = np.stack([np.arange(1, 4), np.arange(4, 7)]).astype(np.int32)
    positions = np.tile(np.arange(9, dtype=np.int32), (2, 1))
    logits, _ = forward(
        params, cfg, jnp.asarray(toks), jnp.asarray(positions),
        jnp.ones((2, 9), bool), kv, jnp.asarray(pts),
    )
    ours = np.asarray(logits)
    np.testing.assert_allclose(ours, ref, rtol=5e-2, atol=5e-2)
    assert (ours.argmax(-1) == ref.argmax(-1)).mean() > 0.9


def test_qwen3_moe_from_hf_config_enables_qk_norm():
    """A real qwen3_moe config.json must map to qk_norm=True: the HF
    checkpoint carries per-head q/k RMSNorm weights, and loading them
    with qk_norm=False silently drops the norms (wrong logits)."""
    from dynamo_tpu.models.moe import MoeConfig

    hf = {
        "model_type": "qwen3_moe",
        "architectures": ["Qwen3MoeForCausalLM"],
        "vocab_size": 151936, "hidden_size": 2048,
        "intermediate_size": 6144, "num_hidden_layers": 48,
        "num_attention_heads": 32, "num_key_value_heads": 4,
        "head_dim": 128, "rope_theta": 1000000.0,
        "rms_norm_eps": 1e-6, "hidden_act": "silu",
        "num_experts": 128, "num_experts_per_tok": 8,
        "norm_topk_prob": True, "moe_intermediate_size": 768,
        "decoder_sparse_step": 1, "mlp_only_layers": [],
        "tie_word_embeddings": False,
    }
    cfg = MoeConfig.from_hf_config(hf)
    assert cfg.base.qk_norm is True
    assert cfg.hf_naming == "qwen3_moe"
    assert cfg.num_experts == 128 and cfg.top_k == 8
    # arch-only detection (model_type absent) must also work
    cfg2 = MoeConfig.from_hf_config(
        {k: v for k, v in hf.items() if k != "model_type"}
    )
    assert cfg2.base.qk_norm is True


def test_moe_int8_quantized_serving(cpu_mesh_devices):
    """Weight-only int8 over the MoE layout serves (single-chip AND on a
    tp x ep mesh: scale leaves need matching PartitionSpecs) and stays
    close to the fp forward."""
    import jax

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.models.moe import (
        MoeConfig,
        forward,
        init_params as moe_init,
        quantize_params_int8,
    )

    cfg = MoeConfig.tiny()
    params = moe_init(jax.random.key(4), cfg)
    qparams = quantize_params_int8(params)
    assert qparams["layers"]["we_gate"].dtype == jnp.int8

    toks = np.arange(1, 9, dtype=np.int32)[None]
    pts = np.asarray([[1, 2]], np.int32)
    pos = np.arange(8, dtype=np.int32)[None]
    kv1 = init_kv_pages(cfg.base, 8, 4)
    kv2 = init_kv_pages(cfg.base, 8, 4)
    a, _ = forward(params, cfg, jnp.asarray(toks), jnp.asarray(pos),
                   jnp.ones((1, 8), bool), kv1, jnp.asarray(pts))
    b, _ = forward(qparams, cfg, jnp.asarray(toks), jnp.asarray(pos),
                   jnp.ones((1, 8), bool), kv2, jnp.asarray(pts))
    assert (np.asarray(a).argmax(-1) == np.asarray(b).argmax(-1)).mean() > 0.7

    for tp, ep in ((1, 1), (2, 2)):
        eng = JaxEngine(
            EngineConfig(
                model="moe-tiny", tp=tp, ep=ep, num_pages=32, page_size=4,
                max_pages_per_seq=8, decode_buckets=(2,), prefill_chunk=8,
                max_seqs=2, dtype="float32", quantize="int8",
            )
        )
        rng = np.random.default_rng(5)
        eng.add_request(
            "r0", [int(x) for x in rng.integers(1, 250, 6)],
            SamplingParams(temperature=0.0, max_tokens=3),
        )
        assert len(eng.run_to_completion()["r0"]) == 3

"""Control-plane HA (docs/operations.md "Control-plane HA"): warm-standby
replication, epoch-fenced promotion, client failover, split-brain
refusal, replication-wire fuzz, and the designed degraded mode — all
in-process (the subprocess CLI variant lives in tests/test_chaos.py)."""

import asyncio
import random

import pytest

from dynamo_tpu.runtime.fabric import (
    FabricNode,
    FabricServer,
    RemoteFabric,
    fabric_state_digest,
)


def run(coro):
    return asyncio.run(coro)


async def _drain_lag(primary: FabricServer, timeout: float = 5.0) -> None:
    """Wait until every replication subscriber acked the whole stream."""
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        st = primary.stats()
        if st["repl_subscribers"] > 0 and st["repl_lag_records"] == 0:
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"replication lag never drained: {primary.stats()}")


async def _standby(primary: FabricServer, **kw) -> FabricNode:
    node = FabricNode(
        port=0, standby_of=primary.address,
        detector_budget_s=kw.pop("detector_budget_s", 0.4),
        orphan_grace=kw.pop("orphan_grace", 10.0), **kw,
    )
    await node.start()
    return node


def test_standby_bootstraps_and_converges_digest_exact():
    async def main():
        primary = FabricServer(port=0)
        await primary.start()
        c = await RemoteFabric.connect(primary.address)
        lease = await c.grant_lease(30.0)
        await c.put("v1/instances/a", b"worker-a", lease_id=lease)
        await c.put("plain/k", b"v0")
        await c.obj_put("card/m", b"{}")
        await c.queue_push("prefill_queue", {"n": 1}, b"item")
        for i in range(20):
            await c.publish("kv_events.w1", {"i": i}, f"e{i}".encode())

        node = await _standby(primary, auto_promote=False)
        try:
            # live tail after bootstrap: keep mutating
            await c.put("plain/k", b"v1")
            await c.delete("v1/instances/a")
            await c.put("v1/instances/b", b"worker-b", lease_id=lease)
            for i in range(20, 35):
                await c.publish("kv_events.w1", {"i": i}, f"e{i}".encode())
            await _drain_lag(primary)
            assert fabric_state_digest(primary.fabric) == (
                fabric_state_digest(node.fabric)
            )
            # standby redirects data ops
            assert node.role == "standby"
            st = primary.stats()
            assert st["repl_subscribers"] == 1
            assert st["is_primary"] == 1
            assert node.server.stats()["is_primary"] == 0
        finally:
            await c.close()
            await node.stop()
            await primary.stop()

    run(main())


def test_failover_client_follows_exactly_once_and_leases_reattach():
    """The tentpole proof, in-process: SIGKILL-equivalent primary death
    mid-traffic -> the standby promotes inside the detector budget, the
    multi-address client fails over, ringed subjects deliver exactly
    once ACROSS the failover, and leased keys survive via reattach
    inside the orphan grace."""

    async def main():
        primary = FabricServer(port=0)
        await primary.start()
        node = await _standby(primary, detector_budget_s=0.3)
        try:
            addrs = f"{primary.address},{node.address}"
            sub_fab = await RemoteFabric.connect(addrs)
            pub_fab = await RemoteFabric.connect(addrs)
            lease = await pub_fab.grant_lease(2.0)
            await pub_fab.put("v1/instances/w1", b"meta", lease_id=lease)

            sub = await sub_fab.subscribe("kv_events.>")
            got: list[int] = []

            async def consume():
                async for m in sub:
                    got.append(m.header["i"])

            consumer = asyncio.get_running_loop().create_task(consume())
            for i in range(10):
                await pub_fab.publish("kv_events.w1", {"i": i}, b"x")
            await _drain_lag(primary)

            primary.kill()  # SIGKILL-equivalent: no cleanup, no goodbyes
            await asyncio.wait_for(node.promoted.wait(), timeout=10.0)
            assert node.role == "primary"
            assert node.fabric.fence == 2

            # publish THROUGH the failover: first calls may fail while
            # the client reconnects — retry like any fabric caller
            for i in range(10, 20):
                for _ in range(100):
                    try:
                        await pub_fab.publish("kv_events.w1", {"i": i}, b"x")
                        break
                    except (ConnectionError, RuntimeError):
                        await asyncio.sleep(0.05)
                else:
                    raise AssertionError(f"publish {i} never landed")

            deadline = asyncio.get_event_loop().time() + 10.0
            while len(got) < 20 and asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
            # exactly once across the failover: every message, no dups
            assert got == list(range(20)), got

            # leased key reattached on the new primary within grace
            check = await RemoteFabric.connect(node.address)
            deadline = asyncio.get_event_loop().time() + 5.0
            val = None
            while asyncio.get_event_loop().time() < deadline:
                val = await check.get("v1/instances/w1")
                if val == b"meta":
                    break
                await asyncio.sleep(0.05)
            assert val == b"meta"
            # both clients failed over to the standby's address
            assert pub_fab.address == node.address
            assert pub_fab.failovers_total >= 1
            consumer.cancel()
            await check.close()
            await sub_fab.close()
            await pub_fab.close()
        finally:
            await node.stop()
            await primary.stop()

    run(main())


def test_stale_primary_demotes_and_redirects_split_brain_refused(tmp_path):
    """Restart the dead primary from its WAL after a failover: the
    promoted broker's fencer (plus the startup peer probe) demotes it,
    and a client pointed ONLY at the old address is transparently
    redirected — its write lands on the new primary."""

    async def main():
        d = str(tmp_path / "wal-a")
        primary = FabricServer(port=0, persist_dir=d)
        await primary.start()
        port_a = primary.port
        c = await RemoteFabric.connect(primary.address)
        await c.put("k", b"v")
        node = await _standby(primary, detector_budget_s=0.3)
        try:
            await _drain_lag(primary)
            await c.close()
            primary.kill()
            await primary.stop()
            await asyncio.wait_for(node.promoted.wait(), timeout=10.0)

            # resurrect the stale primary on its old port with its WAL
            # and its standby as --peer: the startup probe sees the
            # higher fence and it starts DEMOTED (standby of the new
            # primary) instead of accepting writes
            stale = FabricNode(
                port=port_a, persist_dir=d, peers=(node.address,),
                detector_budget_s=30.0,
            )
            await stale.start()
            assert stale.role == "standby"
            assert stale.server.primary_address == node.address

            # a client configured ONLY with the old address follows the
            # NotPrimary redirect transparently
            c2 = await RemoteFabric.connect(f"127.0.0.1:{port_a}")
            await c2.put("after-failover", b"yes")
            assert c2.address == node.address
            assert await c2.get("k") == b"v"  # replicated state intact
            direct = await RemoteFabric.connect(node.address)
            assert await direct.get("after-failover") == b"yes"
            # ... and the resurrected broker re-converges as a standby
            await _drain_lag(node.server)
            assert fabric_state_digest(node.fabric) == (
                fabric_state_digest(stale.fabric)
            )
            await direct.close()
            await c2.close()
            await stale.stop()
        finally:
            await node.stop()

    run(main())


def test_fencer_demotes_peerless_stale_primary(tmp_path):
    """A stale primary restarted WITHOUT --peer config is still fenced:
    the promoted broker's fencer loop actively delivers repl.fence to
    the old address."""

    async def main():
        d = str(tmp_path / "wal")
        primary = FabricServer(port=0, persist_dir=d)
        await primary.start()
        port_a = primary.port
        node = await _standby(primary, detector_budget_s=0.25)
        node.fence_interval_s = 0.2
        try:
            await _drain_lag(primary)
            primary.kill()
            await primary.stop()
            await asyncio.wait_for(node.promoted.wait(), timeout=10.0)

            stale = FabricServer(port=port_a, persist_dir=d)
            await stale.start()
            assert stale.role == "primary"  # resurrection, no peer info
            deadline = asyncio.get_event_loop().time() + 5.0
            while (
                stale.role == "primary"
                and asyncio.get_event_loop().time() < deadline
            ):
                await asyncio.sleep(0.05)
            assert stale.role == "standby"
            assert stale.primary_address == node.address
            assert stale.demotions_total == 1
            await stale.stop()
        finally:
            await node.stop()

    run(main())


def test_promotion_seq_skip_flags_client_ahead_cursor_as_gap():
    """A resume cursor pointing into the promotion's skipped seq range
    (messages only the dead primary ever delivered) resumes with
    gap=True — sequencing consumers resync instead of silently missing
    the tail."""
    from dynamo_tpu.runtime.fabric.local import LocalFabric

    async def main():
        f = LocalFabric()
        for i in range(5):
            await f.publish("kv_events.w", {"i": i}, b"")
        assert f.pub_seq == 5
        # standby only replicated up to seq 3, then promoted
        f.pub_seq = 3
        f.promote_state(seq_skip=1000)
        assert f.fence == 2
        # cursor 5 (the client saw seqs the standby never had) -> gap
        sub = await f.subscribe("kv_events.>", from_seq=5)
        assert sub.resume_gap is True
        # cursor 3 (exactly the watermark) -> lossless resume, no gap
        sub2 = await f.subscribe("kv_events.>", from_seq=3)
        assert sub2.resume_gap is False
        # new publishes land past the skip: no collision with seqs <= 5
        await f.publish("kv_events.w", {"i": 99}, b"")
        assert f.pub_seq == 1004

    run(main())


def test_repl_wire_fuzz_never_a_diverged_standby():
    """Bit-flip fuzz over the replication stream (a corrupting proxy
    between primary and standby): every corrupt frame is a CodecError
    -> session drop -> fresh snapshot bootstrap, and once the wire
    heals the standby is digest-EXACT — never silently diverged."""

    async def main():
        rng = random.Random(7)
        primary = FabricServer(port=0)
        await primary.start()
        phost, pport = primary.address.rsplit(":", 1)

        corrupting = True

        async def proxy(reader, writer):
            try:
                up_r, up_w = await asyncio.open_connection(phost, int(pport))
            except OSError:
                writer.close()
                return

            async def pump(src, dst, corrupt):
                try:
                    while True:
                        chunk = await src.read(4096)
                        if not chunk:
                            break
                        if corrupt and corrupting and rng.random() < 0.10:
                            b = bytearray(chunk)
                            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
                            chunk = bytes(b)
                        dst.write(chunk)
                        await dst.drain()
                except (ConnectionError, OSError, asyncio.CancelledError):
                    pass
                finally:
                    try:
                        dst.close()
                    except Exception:
                        pass

            await asyncio.gather(
                pump(reader, up_w, False),     # standby -> primary clean
                pump(up_r, writer, True),      # primary -> standby fuzzed
            )

        proxy_srv = await asyncio.start_server(proxy, "127.0.0.1", 0)
        proxy_addr = "127.0.0.1:%d" % proxy_srv.sockets[0].getsockname()[1]

        node = FabricNode(
            port=0, standby_of=proxy_addr, auto_promote=False,
        )
        await node.start()
        # tight liveness window: a wedged torn read (bit-flipped length
        # prefix) must die fast enough for the convergence budget below
        node.tail.idle_timeout_s = 0.4
        c = await RemoteFabric.connect(primary.address)
        try:
            for i in range(120):
                await c.put(f"k/{i % 17}", f"v{i}".encode())
                await c.publish("kv_events.w", {"i": i}, b"p" * 32)
                await asyncio.sleep(0.002)
            # the fuzz MUST have bitten at least once at 10%/chunk
            deadline = asyncio.get_event_loop().time() + 10.0
            while (
                node.tail.codec_errors == 0
                and asyncio.get_event_loop().time() < deadline
            ):
                await c.put("k/poke", b"x")
                await asyncio.sleep(0.01)
            assert node.tail.codec_errors > 0
            assert node.tail.bootstraps >= 2  # re-bootstrapped after poison

            corrupting = False  # heal the wire
            await c.put("k/final", b"done")
            deadline = asyncio.get_event_loop().time() + 10.0
            while asyncio.get_event_loop().time() < deadline:
                if (
                    node.tail.snapshot_applied
                    and fabric_state_digest(primary.fabric)
                    == fabric_state_digest(node.fabric)
                ):
                    break
                await asyncio.sleep(0.05)
            assert fabric_state_digest(primary.fabric) == (
                fabric_state_digest(node.fabric)
            ), "standby diverged after wire corruption"
        finally:
            await c.close()
            await node.stop()
            proxy_srv.close()
            await primary.stop()

    run(main())


def test_explicit_promote_admin_op():
    async def main():
        from dynamo_tpu.runtime.fabric.replica import promote_standby

        primary = FabricServer(port=0)
        await primary.start()
        node = await _standby(primary, auto_promote=False)
        try:
            await _drain_lag(primary)
            reply = await promote_standby(node.address)
            assert reply.get("ok") is True
            assert reply.get("role") == "primary"
            assert node.role == "primary"
            # primary refuses the promote op (no hook): explicit error
            reply2 = await promote_standby(primary.address)
            assert reply2.get("ok") is False
        finally:
            await node.stop()
            await primary.stop()

    run(main())


def test_multi_address_parse_and_single_broker_unchanged():
    f = RemoteFabric("a:1,b:2, c:3")
    assert f.addresses == ["a:1", "b:2", "c:3"]
    assert f.address == "a:1"
    g = RemoteFabric("127.0.0.1:4222")
    assert g.addresses == ["127.0.0.1:4222"]
    with pytest.raises(ValueError):
        RemoteFabric(" , ")

    async def main():
        # single-broker path: no standby -> no repl subscribers, role
        # primary, zero lag — the pre-HA wire pinned by the rest of
        # tests/test_fabric_e2e.py
        s = FabricServer(port=0)
        await s.start()
        c = await RemoteFabric.connect(s.address)
        await c.put("k", b"v")
        st = s.stats()
        assert st["repl_subscribers"] == 0
        assert st["repl_lag_records"] == 0
        assert st["is_primary"] == 1
        assert st["fence"] == 1
        await c.close()
        await s.stop()

    run(main())


def test_worker_degraded_mode_buffers_and_burns_seqs_on_overflow():
    """Designed broker-less mode at the worker: KV events buffer
    UNSTAMPED while no broker answers (a short outage loses nothing),
    overflow is stamped-and-burned (detectable seq gap), and the buffer
    ships on reconnect — the indexer sees [1..3, gap, 6..10]."""
    from dynamo_tpu.engine.page_table import KvEvent
    from dynamo_tpu.model_card import ModelDeploymentCard
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.worker import Worker

    async def main():
        server = FabricServer(port=0)
        await server.start()
        port = server.port
        fabric = await RemoteFabric.connect(server.address)
        fabric.degraded_after_s = 0.05
        rt = DistributedRuntime(fabric)
        worker = Worker(
            rt, ModelDeploymentCard(name="tiny"), engine_kind="echo",
        )
        worker.instance_id = "w-ha"
        worker.kv_pending_cap = 5

        sub = await fabric.subscribe("kv_events.>")
        got: list[list[int]] = []

        async def consume():
            import msgpack as _mp

            async for m in sub:
                got.append(
                    [e["seq"] for e in _mp.unpackb(m.payload, raw=False)]
                )

        task = asyncio.get_running_loop().create_task(consume())

        def ev(i):
            return KvEvent("stored", (1000 + i,), None, ((i,),))

        worker._kv_event_buffer.extend(ev(i) for i in range(3))
        await worker._publish_once(fabric)
        assert worker._kv_seq == 3  # stamped + published

        server.kill()
        await server.stop()
        deadline = asyncio.get_event_loop().time() + 5.0
        while fabric.connected and (
            asyncio.get_event_loop().time() < deadline
        ):
            await asyncio.sleep(0.02)
        assert not fabric.connected

        worker._kv_event_buffer.extend(ev(10 + i) for i in range(4))
        await worker._publish_once(fabric)
        assert len(worker._kv_pending) == 4
        assert worker._kv_seq == 3  # pending events stay UNSTAMPED
        assert worker.kv_events_dropped == 0

        worker._kv_event_buffer.extend(ev(20 + i) for i in range(3))
        await worker._publish_once(fabric)
        # 7 > cap 5: the 2 oldest were stamped (seqs 4,5 burned) and
        # dropped — an honest, detectable gap
        assert len(worker._kv_pending) == 5
        assert worker._kv_seq == 5
        assert worker.kv_events_dropped == 2

        # frames carry the accounting once a broker is back
        server2 = FabricServer(port=port)
        await server2.start()
        deadline = asyncio.get_event_loop().time() + 10.0
        while not fabric.connected and (
            asyncio.get_event_loop().time() < deadline
        ):
            await asyncio.sleep(0.02)
        assert fabric.connected
        await worker._publish_once(fabric)
        assert worker._kv_pending == []
        assert worker._kv_seq == 10  # 5 pending stamped 6..10

        deadline = asyncio.get_event_loop().time() + 5.0
        while sum(len(b) for b in got) < 8 and (
            asyncio.get_event_loop().time() < deadline
        ):
            await asyncio.sleep(0.02)
        seqs = [s for batch in got for s in batch]
        assert seqs == [1, 2, 3, 6, 7, 8, 9, 10]  # the gap IS 4,5
        assert fabric.degraded_total >= 1  # outage was marked + cleared
        task.cancel()
        await fabric.close()
        await server2.stop()

    run(main())


def test_planner_holds_while_control_plane_degraded():
    from dynamo_tpu.planner.planner import (
        Actions,
        ControlConfig,
        ControlRunner,
        FleetState,
    )

    class _Planner:
        config = ControlConfig(interval_s=1.0)

        def tick(self, state):
            return Actions(
                target_decode=8, target_prefill=4, reason="burn high"
            )

    scaled = []

    class _Conn:
        async def scale(self, role, target, observed):
            scaled.append((role, target))

    async def observe():
        return FleetState(
            num_decode=2, num_prefill=1, kv_usage=0.5, num_waiting=0,
            prefill_queue_depth=0,
        )

    async def main():
        degraded = {"on": True}
        r = ControlRunner(
            _Planner(), _Conn(), observe,
            degraded_fn=lambda: degraded["on"],
        )
        acts = await r.step()
        assert scaled == []  # actuation suspended
        assert r.decisions["hold"] == 1
        assert r.degraded_holds == 1
        assert acts.reason.startswith("hold")
        assert acts.target_decode == 2  # frozen at observed

        degraded["on"] = False
        await r.step()
        assert scaled  # broker back -> the loop actuates again

    run(main())

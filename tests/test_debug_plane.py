"""Deep-profiling / debug plane (ISSUE 7): GET /v1/debug/programs on a
real compiled engine reports per-program-kind cost-model %-attainment;
/v1/debug/flight serves the ring over HTTP; POST /v1/debug/profile arms
a step-bounded jax.profiler capture (and 501s gracefully without an
engine); the metrics service serves the fleet's windows from frames."""

import asyncio
import time

import aiohttp
import pytest

from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.engine.engine import JaxEngine
from dynamo_tpu.engine.request import SamplingParams
from dynamo_tpu.telemetry import debug as debug_mod


@pytest.fixture
def engine():
    eng = JaxEngine(EngineConfig.for_tests())
    for i in range(3):
        eng.add_request(
            f"r{i}", [1 + i, 2, 3, 4],
            SamplingParams(temperature=0.0, max_tokens=6),
        )
    eng.run_to_completion()
    return eng


def test_programs_report_has_cost_model_attainment(engine):
    """Acceptance: /v1/debug/programs reports measured step time vs
    cost-model roofline %-attained per program kind on a REAL compiled
    engine."""
    rep = engine.programs_report()
    assert rep["peak_flops"] > 0 and rep["peak_bytes_per_s"] > 0
    assert rep["programs"], "compiled programs must be recorded"
    for p in rep["programs"]:
        assert p["compile_ms"] > 0
        # cost_analysis is available on the CPU backend in this image —
        # every compiled program carries flops + bytes
        assert p["flops"] and p["flops"] > 0, p
        assert p["bytes"] and p["bytes"] > 0, p
        assert p["roofline_ms"] and p["roofline_ms"] > 0, p
    kinds = rep["kinds"]
    assert "prefill" in kinds
    decode_kind = "decode_multi" if "decode_multi" in kinds else "decode"
    for kind in ("prefill", decode_kind):
        k = kinds[kind]
        assert k["compiles"] >= 1
        assert k["measured_ms_per_dispatch"] > 0
        assert k["attainment"] is not None
        assert 0.0 < k["attainment"] <= 1.0, (kind, k)
    # the wire rollup is exactly the kinds table (rides metrics frames)
    assert set(engine.programs_wire()) == set(kinds)


def test_debug_payloads_list_the_engine(engine):
    body, status = debug_mod.programs_payload()
    assert status == 200
    assert engine.debug_name in body["engines"]
    assert "kinds" in body["engines"][engine.debug_name]

    body, status = debug_mod.flight_payload("2")
    assert status == 200
    mine = body["engines"][engine.debug_name]
    assert mine["enabled"] and len(mine["records"]) <= 2
    assert debug_mod.flight_payload("x")[1] == 400

    body, status = debug_mod.stalls_payload()
    assert status == 200
    assert "stalls_by_cause" in body and "diagnoses" in body


def test_debug_endpoints_over_frontend_http(engine):
    """The single-process topology serves its engines' debug plane on
    the OpenAI frontend port."""
    from dynamo_tpu.frontend import HttpService, ModelManager

    async def main():
        svc = HttpService(ModelManager(), host="127.0.0.1", port=0)
        await svc.start()
        base = f"http://127.0.0.1:{svc.port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{base}/v1/debug/programs") as r:
                    assert r.status == 200
                    doc = await r.json()
                assert engine.debug_name in doc["engines"]
                kinds = doc["engines"][engine.debug_name]["kinds"]
                assert any(
                    k.get("attainment") is not None for k in kinds.values()
                )
                async with s.get(f"{base}/v1/debug/flight?n=4") as r:
                    assert r.status == 200
                    doc = await r.json()
                recs = doc["engines"][engine.debug_name]["records"]
                assert recs and recs[-1]["kind"] in ("decode", "mixed")
                async with s.get(f"{base}/v1/debug/stalls") as r:
                    assert r.status == 200
        finally:
            await svc.stop()

    asyncio.run(main())


def test_profile_capture_brackets_k_steps(engine, monkeypatch):
    """request_profile arms; the engine thread starts the trace on the
    next step and stops after K dispatched steps (profiler faked so the
    test pins the choreography, not XLA's tracer)."""
    import jax

    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    out = engine.request_profile(2, outdir="artifacts/profile/test-cap")
    assert out == {"dir": "artifacts/profile/test-cap", "steps": 2}
    # double-arm refused while one is pending
    with pytest.raises(RuntimeError):
        engine.request_profile(1)
    with pytest.raises(ValueError):
        engine._profile = None
        engine.request_profile(0)
    engine.request_profile(2, outdir="artifacts/profile/test-cap")
    engine.add_request(
        "p0", [9, 8, 7], SamplingParams(temperature=0.0, max_tokens=6)
    )
    engine.run_to_completion()
    assert calls[0] == ("start", "artifacts/profile/test-cap")
    assert calls[-1] == ("stop",)
    assert engine._profile is None  # capture complete, re-armable


def test_profile_payload_501_without_engines(monkeypatch):
    debug_mod._clear_registry()
    body, status = debug_mod.profile_payload({"steps": 4})
    assert status == 501
    assert "no profilable engine" in body["error"]
    assert debug_mod.profile_payload({"steps": "x"})[1] == 400
    assert debug_mod.profile_payload({"steps": -1})[1] == 400


def test_profile_payload_confines_client_dirs(engine, monkeypatch):
    """HTTP-supplied 'dir' is confined under artifacts/profile — the
    unauthenticated endpoint must not become an arbitrary-path write
    primitive (absolute paths and .. escapes are 400s)."""
    import os

    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    for bad in ("/etc/cron.d/x", "../outside", "a/../../outside"):
        body, status = debug_mod.profile_payload({"steps": 1, "dir": bad})
        assert status == 400, (bad, body)
        assert "relative" in body["error"]
    body, status = debug_mod.profile_payload(
        {"steps": 1, "dir": "my-capture"}
    )
    assert status == 200, body
    armed = next(iter(body["armed"].values()))
    assert armed["dir"] == os.path.join("artifacts", "profile", "my-capture")
    engine._profile = None  # disarm for other tests


def test_metrics_service_serves_fleet_flight_and_programs():
    """The metrics service answers /v1/debug/{flight,programs} for the
    whole fleet from the windows shipped in metrics frames, and its
    /v1/debug/profile honestly 501s (no engine in that process)."""
    from dynamo_tpu.metrics_service import MetricsService
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.fabric import FabricServer
    from dynamo_tpu.subjects import METRICS_SUBJECT

    async def main():
        server = FabricServer(port=0)
        await server.start()
        try:
            rt_m = await DistributedRuntime.create(server.address)
            rt_w = await DistributedRuntime.create(server.address)
            svc = MetricsService(rt_m.fabric, port=0)
            await svc.start()
            await asyncio.sleep(0.1)
            frame = {
                "instance_id": "w1",
                "kv_usage": 0.4,
                "stalls_total": 1,
                "stalls_by_cause": {"stalled_stream": 1},
                "flight": [
                    {"seq": 0, "kind": "prefill", "step_ms": 4.0},
                    {"seq": 1, "kind": "decode", "step_ms": 1.0},
                ],
                "programs_by_kind": {
                    "decode": {"attainment": 0.2, "roofline_ms": 0.5},
                },
            }
            await rt_w.fabric.publish(
                f"{METRICS_SUBJECT}.backend.w1", frame
            )
            await asyncio.sleep(0.2)
            base = f"http://127.0.0.1:{svc.port}"
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{base}/v1/debug/flight?n=1") as r:
                    assert r.status == 200
                    doc = await r.json()
                assert doc["workers"]["w1"]["records"] == [
                    {"seq": 1, "kind": "decode", "step_ms": 1.0}
                ]
                async with s.get(f"{base}/v1/debug/programs") as r:
                    assert r.status == 200
                    doc = await r.json()
                assert (
                    doc["workers"]["w1"]["kinds"]["decode"]["attainment"]
                    == 0.2
                )
                # per-worker stall counter + cause split in the fleet
                snap = svc.fleet_snapshot()
                w = snap["workers"]["w1"]
                assert w["stalls_total"] == 1
                assert w["stalls_by_cause"] == {"stalled_stream": 1}
                text = svc.expose()
                assert (
                    'dynamo_tpu_worker_stalls_total{component="backend",'
                    'instance="w1"} 1' in text
                )
                from dynamo_tpu.telemetry import promlint

                assert promlint.lint(text) == [], promlint.lint(text)[:5]
                async with s.post(
                    f"{base}/v1/debug/profile", json={"steps": 2}
                ) as r:
                    assert r.status == 501
            await svc.stop()
            await rt_m.close()
            await rt_w.close()
        finally:
            await server.stop()

    asyncio.run(main())

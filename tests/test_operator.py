"""Operator: reconcile DynamoGraphDeployments into Deployments/Services.

Reference parity: deploy/cloud/operator's reconcilers tested via envtest
(suite_test.go); here the in-memory kube double plays the API server."""

import copy

from dynamo_tpu.operator import Controller, InMemoryKube, reconcile
from dynamo_tpu.operator.reconciler import (
    LABEL_OWNER,
    desired_objects,
    garbage_collect,
)


def make_cr(name="demo", services=None, generation=1):
    return {
        "apiVersion": "dynamo.tpu/v1alpha1",
        "kind": "DynamoGraphDeployment",
        "metadata": {"name": name, "namespace": "default",
                     "generation": generation},
        "spec": {
            "image": "dynamo-tpu:test",
            "services": services
            if services is not None
            else [
                {
                    "name": "Frontend",
                    "class": "examples.llm.graphs.agg:Frontend",
                    "replicas": 1,
                    "endpoints": [],
                    "depends": ["Worker"],
                    "config": {"port": 8000},
                },
                {
                    "name": "Worker",
                    "class": "examples.llm.graphs.agg:Worker",
                    "replicas": 2,
                    "endpoints": ["generate"],
                    "depends": [],
                    "config": {},
                },
            ],
        },
    }


def test_desired_objects_labeled_and_namespaced():
    objs = desired_objects(make_cr())
    assert objs, "renderer produced nothing"
    kinds = sorted(o["kind"] for o in objs)
    # fabric Deployment+Service, frontend Deployment+Service (has port),
    # worker Deployment
    assert kinds.count("Deployment") == 3
    assert kinds.count("Service") == 2
    for o in objs:
        assert o["metadata"]["namespace"] == "default"
        assert o["metadata"]["labels"][LABEL_OWNER] == "demo"


def test_reconcile_creates_then_idempotent():
    kube = InMemoryKube()
    cr = make_cr()
    kube.create("DynamoGraphDeployment", "default", cr)
    status = reconcile(kube, cr)
    # 2 component CRs + fabric Dep+Svc + frontend Dep+Svc + worker Dep
    assert status["lastAction"] == {"created": 7, "replaced": 0, "deleted": 0}
    assert status["conditions"][0]["status"] == "True"
    # the component layer exists and replicas made it all the way through
    dcd = kube.get("DynamoComponentDeployment", "default", "demo-worker")
    assert dcd["spec"]["replicas"] == 2
    worker = kube.get("Deployment", "default", "worker")
    assert worker["spec"]["replicas"] == 2
    # Second pass: no changes.
    kube.actions.clear()
    status = reconcile(kube, cr)
    assert status["lastAction"] == {"created": 0, "replaced": 0, "deleted": 0}
    assert kube.actions == []


def test_reconcile_scales_on_spec_change():
    kube = InMemoryKube()
    cr = make_cr()
    reconcile(kube, cr)
    cr2 = copy.deepcopy(cr)
    cr2["spec"]["services"][1]["replicas"] = 5
    status = reconcile(kube, cr2)
    # both levels converge: the component CR and its Deployment
    assert status["lastAction"]["replaced"] == 2
    assert kube.get("Deployment", "default", "worker")["spec"]["replicas"] == 5


def test_reconcile_deletes_removed_service():
    kube = InMemoryKube()
    cr = make_cr()
    reconcile(kube, cr)
    assert kube.get("Deployment", "default", "worker") is not None
    cr2 = copy.deepcopy(cr)
    cr2["spec"]["services"] = cr2["spec"]["services"][:1]  # drop Worker
    status = reconcile(kube, cr2)
    # the component CR and its Deployment both go
    assert status["lastAction"]["deleted"] == 2
    assert kube.get("DynamoComponentDeployment", "default", "demo-worker") is None
    assert kube.get("Deployment", "default", "worker") is None
    # frontend + fabric untouched
    assert kube.get("Deployment", "default", "frontend") is not None


def test_reconcile_heals_manual_drift():
    kube = InMemoryKube()
    cr = make_cr()
    reconcile(kube, cr)
    # Someone kubectl-edits the replica count behind the operator's back.
    obj = kube.get("Deployment", "default", "worker")
    obj["spec"]["replicas"] = 0
    kube.replace("Deployment", "default", "worker", obj)
    reconcile(kube, cr)
    assert kube.get("Deployment", "default", "worker")["spec"]["replicas"] == 2


def test_garbage_collect_orphans():
    kube = InMemoryKube()
    cr = make_cr(name="gone")
    reconcile(kube, cr)
    n = garbage_collect(kube, "default", live_owners=set())
    assert n == 7  # incl. the two component CRs
    assert kube.list("Deployment", "default") == []


def test_controller_pass_updates_status_and_gc():
    kube = InMemoryKube()
    kube.create("DynamoGraphDeployment", "default", make_cr(name="a"))
    ctl = Controller(kube, namespace="default")
    statuses = ctl.reconcile_once()
    assert statuses["a"]["conditions"][0]["status"] == "True"
    cr = kube.get("DynamoGraphDeployment", "default", "a")
    assert cr["status"]["observedGeneration"] == 1
    # Delete the CR; next pass GCs its children.
    kube.delete("DynamoGraphDeployment", "default", "a")
    ctl.reconcile_once()
    assert kube.list("Deployment", "default") == []


def test_two_crs_do_not_interfere():
    kube = InMemoryKube()
    a = make_cr(name="a", services=[{
        "name": "OnlyA", "class": "x:A", "replicas": 1,
        "endpoints": [], "depends": [], "config": {},
    }])
    b = make_cr(name="b", services=[{
        "name": "OnlyB", "class": "x:B", "replicas": 1,
        "endpoints": [], "depends": [], "config": {},
    }])
    a["spec"]["fabricHost"] = "fabric-a"
    b["spec"]["fabricHost"] = "fabric-b"
    reconcile(kube, a)
    reconcile(kube, b)
    # Removing all of a's services must not touch b's objects.
    a2 = copy.deepcopy(a)
    a2["spec"]["services"] = []
    reconcile(kube, a2)
    assert kube.get("Deployment", "default", "onlyb") is not None
    assert kube.get("Deployment", "default", "onlya") is None


def test_planner_kube_connector_closes_the_loop():
    """Planner scale() edits the CR; the operator reconciles the edit into
    the Deployment — the reference's planner->CRD->operator division of
    labor, end to end with no cluster."""
    import asyncio

    from dynamo_tpu.planner.kube_connector import KubeConnector

    kube = InMemoryKube()
    kube.create("DynamoGraphDeployment", "default", make_cr(name="fleet"))
    ctl = Controller(kube, namespace="default")
    ctl.reconcile_once()
    assert kube.get("Deployment", "default", "worker")["spec"]["replicas"] == 2

    conn = KubeConnector(
        kube, cr_name="fleet", role_services={"decode": "Worker"}
    )
    asyncio.run(conn.scale("decode", target=5, observed=2))
    # the /scale subresource path: the component CR scaled, the graph CR
    # NEVER rewritten (no read-modify-write conflicts with the operator)
    dcd = kube.get("DynamoComponentDeployment", "default", "fleet-worker")
    assert dcd["spec"]["replicas"] == 5
    cr = kube.get("DynamoGraphDeployment", "default", "fleet")
    assert cr["spec"]["services"][1]["replicas"] == 2  # untouched

    ctl.reconcile_once()
    assert kube.get("Deployment", "default", "worker")["spec"]["replicas"] == 5

    # idempotent: same target again writes nothing
    kube.actions.clear()
    asyncio.run(conn.scale("decode", target=5, observed=5))
    assert kube.actions == []

    # a later no-op graph reconcile must NOT clobber the planner's scale
    ctl.reconcile_once()
    assert kube.get("Deployment", "default", "worker")["spec"]["replicas"] == 5

    # unknown role/service and missing CR degrade to no-ops
    asyncio.run(conn.scale("nonexistent-role", target=3, observed=0))
    conn2 = KubeConnector(kube, cr_name="ghost")
    asyncio.run(conn2.scale("decode", target=1, observed=0))


def test_kube_connector_retries_on_write_conflict():
    """A 409 between get and replace (operator status churn) must retry,
    not fail the planner tick."""
    import asyncio
    import urllib.error

    from dynamo_tpu.planner.kube_connector import KubeConnector

    kube = InMemoryKube()
    kube.create("DynamoGraphDeployment", "default", make_cr(name="fleet"))

    real_replace = kube.replace
    fails = {"n": 2}

    def flaky_replace(kind, ns, name, obj):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise urllib.error.HTTPError("u", 409, "conflict", {}, None)
        return real_replace(kind, ns, name, obj)

    kube.replace = flaky_replace
    conn = KubeConnector(kube, cr_name="fleet",
                         role_services={"decode": "Worker"})
    asyncio.run(conn.scale("decode", target=7, observed=2))
    cr = kube.get("DynamoGraphDeployment", "default", "fleet")
    assert cr["spec"]["services"][1]["replicas"] == 7
    assert fails["n"] == 0


def test_kube_connector_detects_cr_vanishing_mid_write(caplog):
    """A replace that 404s (CR deleted between get and put) must warn, not
    log a successful scale."""
    import asyncio
    import logging

    from dynamo_tpu.planner.kube_connector import KubeConnector

    kube = InMemoryKube()
    kube.create("DynamoGraphDeployment", "default", make_cr(name="fleet"))
    kube.replace = lambda *a, **k: None  # InClusterKube's 404 behavior
    conn = KubeConnector(kube, cr_name="fleet",
                         role_services={"decode": "Worker"})
    with caplog.at_level(logging.INFO, "dynamo_tpu.planner.kube_connector"):
        asyncio.run(conn.scale("decode", target=9, observed=2))
    assert any("disappeared" in r.message for r in caplog.records)
    assert not any("->" in r.message for r in caplog.records)


def test_graph_edit_wins_over_stale_scale():
    """Replica ownership: the planner's /scale survives no-op graph
    reconciles, but an explicit graph-spec replica CHANGE propagates
    (the dynamo.tpu/graph-replicas annotation records what the graph
    last stated)."""
    kube = InMemoryKube()
    cr = make_cr(name="own")
    reconcile(kube, cr)
    # planner scales the component to 6
    kube.patch_scale("DynamoComponentDeployment", "default", "own-worker", 6)
    reconcile(kube, cr)  # no-op graph pass: scale preserved
    assert (
        kube.get("DynamoComponentDeployment", "default", "own-worker")
        ["spec"]["replicas"] == 6
    )
    assert kube.get("Deployment", "default", "worker")["spec"]["replicas"] == 6
    # the graph author now explicitly changes replicas: graph wins
    cr2 = copy.deepcopy(cr)
    cr2["spec"]["services"][1]["replicas"] = 3
    reconcile(kube, cr2)
    assert (
        kube.get("DynamoComponentDeployment", "default", "own-worker")
        ["spec"]["replicas"] == 3
    )
    assert kube.get("Deployment", "default", "worker")["spec"]["replicas"] == 3


def test_component_status_has_scale_read_path():
    """The DCD status carries .status.replicas (the CRD's
    statusReplicasPath) after a controller pass."""
    kube = InMemoryKube()
    kube.create("DynamoGraphDeployment", "default", make_cr(name="s"))
    Controller(kube, namespace="default").reconcile_once()
    dcd = kube.get("DynamoComponentDeployment", "default", "s-worker")
    assert dcd["status"]["replicas"] == 2
    assert dcd["status"]["conditions"][0]["status"] == "True"


def test_annotation_updates_when_graph_aligns_with_scale():
    """If the graph author edits replicas to the exact value the planner
    already scaled to, the annotation must still advance — else every
    LATER planner scale gets clobbered by the stale annotation."""
    kube = InMemoryKube()
    cr = make_cr(name="al")
    reconcile(kube, cr)  # graph says 2
    kube.patch_scale("DynamoComponentDeployment", "default", "al-worker", 6)
    cr2 = copy.deepcopy(cr)
    cr2["spec"]["services"][1]["replicas"] = 6  # author aligns with scale
    reconcile(kube, cr2)
    dcd = kube.get("DynamoComponentDeployment", "default", "al-worker")
    assert dcd["metadata"]["annotations"][
        "dynamo.tpu/graph-replicas"] == "6"
    # planner scales again; a no-op graph pass must NOT revert it
    kube.patch_scale("DynamoComponentDeployment", "default", "al-worker", 10)
    reconcile(kube, cr2)
    assert (
        kube.get("DynamoComponentDeployment", "default", "al-worker")
        ["spec"]["replicas"] == 10
    )
    assert kube.get("Deployment", "default", "worker")["spec"]["replicas"] == 10

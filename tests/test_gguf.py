"""GGUF reader: header/metadata/tensor round-trip, tokenizer + config
extraction (reference: lib/llm/src/gguf/)."""

import numpy as np
import pytest

from dynamo_tpu.gguf import GgufFile, read_gguf, write_gguf


@pytest.fixture()
def gguf_path(tmp_path):
    path = str(tmp_path / "tiny.gguf")
    md = {
        "general.architecture": "llama",
        "general.name": "tiny-test",
        "llama.block_count": 2,
        "llama.embedding_length": 64,
        "llama.feed_forward_length": 128,
        "llama.attention.head_count": 4,
        "llama.attention.head_count_kv": 2,
        "llama.attention.key_length": 16,
        "llama.attention.layer_norm_rms_epsilon": 1e-5,
        "llama.rope.freq_base": 10000.0,
        "llama.context_length": 256,
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": ["<unk>", "<s>", "</s>", "a", "b"],
        "tokenizer.ggml.bos_token_id": 1,
        "tokenizer.ggml.eos_token_id": 2,
        "tokenizer.chat_template": "{{messages}}",
        "truthy": True,
    }
    rng = np.random.default_rng(0)
    tensors = {
        "token_embd.weight": rng.normal(size=(5, 64)).astype(np.float32),
        "blk.0.attn_q.weight": rng.normal(size=(64, 64)).astype(np.float16),
    }
    write_gguf(path, md, tensors)
    return path, md, tensors


def test_roundtrip_metadata_and_tensors(gguf_path):
    path, md, tensors = gguf_path
    g = read_gguf(path)
    assert g.version == 3
    assert g.metadata["general.name"] == "tiny-test"
    assert g.metadata["llama.block_count"] == 2
    assert g.metadata["truthy"] is True
    assert g.metadata["tokenizer.ggml.tokens"] == md["tokenizer.ggml.tokens"]

    emb = g.load_tensor("token_embd.weight")
    np.testing.assert_allclose(emb, tensors["token_embd.weight"])
    q = g.load_tensor("blk.0.attn_q.weight")
    assert q.dtype == np.float16
    np.testing.assert_allclose(q, tensors["blk.0.attn_q.weight"])

    with pytest.raises(KeyError):
        g.load_tensor("missing")


def test_tokenizer_and_config_extraction(gguf_path):
    path, _, _ = gguf_path
    g = read_gguf(path)
    tok = g.tokenizer_vocab()
    assert tok["model"] == "llama"
    assert tok["bos_token_id"] == 1 and tok["eos_token_id"] == 2
    assert tok["chat_template"] == "{{messages}}"

    cfg = g.to_llama_config()
    assert cfg.num_layers == 2
    assert cfg.hidden_size == 64
    assert cfg.num_heads == 4 and cfg.num_kv_heads == 2
    assert cfg.head_dim == 16
    assert cfg.vocab_size == 5
    assert g.context_length() == 256


def test_rejects_non_gguf(tmp_path):
    bad = tmp_path / "bad.gguf"
    bad.write_bytes(b"NOTGGUF0")
    with pytest.raises(ValueError, match="not a GGUF"):
        read_gguf(str(bad))


def test_gguf_end_to_end_generation(tmp_path):
    """A .gguf file is directly servable: registry builds the config,
    params load from the file, the engine generates deterministically,
    and the embedded-vocab tokenizer round-trips text."""
    import jax

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.engine.request import SamplingParams
    from dynamo_tpu.models.llama import LlamaConfig, init_params
    from dynamo_tpu.models.registry import get_model
    from dynamo_tpu.preprocessor.tokenizer import load_tokenizer

    cfg = LlamaConfig.tiny(vocab_size=16)
    params = init_params(jax.random.key(0), cfg)

    md = {
        "general.architecture": "llama",
        "llama.block_count": cfg.num_layers,
        "llama.embedding_length": cfg.hidden_size,
        "llama.feed_forward_length": cfg.intermediate_size,
        "llama.attention.head_count": cfg.num_heads,
        "llama.attention.head_count_kv": cfg.num_kv_heads,
        "llama.attention.key_length": cfg.head_dim,
        "llama.attention.layer_norm_rms_epsilon": float(cfg.rms_norm_eps),
        "llama.rope.freq_base": float(cfg.rope_theta),
        "llama.vocab_size": cfg.vocab_size,
        "llama.context_length": 64,
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": ["<unk>", "<s>", "</s>", "▁hi", "▁the"]
        + [f"<0x{i:02X}>" for i in range(8)]
        + ["abc", "de", "f"],
        "tokenizer.ggml.eos_token_id": 2,
    }
    tensors = {
        "token_embd.weight": np.asarray(params["embed"], np.float32),
        "output_norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    def gguf_permute(w_out_in, n_head):
        # llama.cpp converter's rope permutation (HF -> interleaved order);
        # the loader must undo this for arch "llama".
        out, inn = w_out_in.shape
        d = out // n_head
        return (
            w_out_in.reshape(n_head, 2, d // 2, inn)
            .swapaxes(1, 2)
            .reshape(out, inn)
        )

    lp = params["layers"]
    for l in range(cfg.num_layers):
        tensors[f"blk.{l}.attn_norm.weight"] = np.asarray(lp["attn_norm"][l], np.float32)
        tensors[f"blk.{l}.attn_q.weight"] = gguf_permute(
            np.asarray(lp["wq"][l], np.float32).T, cfg.num_heads
        )
        tensors[f"blk.{l}.attn_k.weight"] = gguf_permute(
            np.asarray(lp["wk"][l], np.float32).T, cfg.num_kv_heads
        )
        tensors[f"blk.{l}.attn_v.weight"] = np.asarray(lp["wv"][l], np.float32).T
        tensors[f"blk.{l}.attn_output.weight"] = np.asarray(lp["wo"][l], np.float32).T
        tensors[f"blk.{l}.ffn_norm.weight"] = np.asarray(lp["mlp_norm"][l], np.float32)
        tensors[f"blk.{l}.ffn_gate.weight"] = np.asarray(lp["w_gate"][l], np.float32).T
        tensors[f"blk.{l}.ffn_up.weight"] = np.asarray(lp["w_up"][l], np.float32).T
        tensors[f"blk.{l}.ffn_down.weight"] = np.asarray(lp["w_down"][l], np.float32).T
    if "lm_head" in params:
        tensors["output.weight"] = np.asarray(params["lm_head"], np.float32).T
    path = str(tmp_path / "model.gguf")
    write_gguf(path, md, tensors)

    adapter = get_model(path, dtype="float32")
    assert adapter.config.num_layers == cfg.num_layers
    assert adapter.default_checkpoint == path

    eng = JaxEngine(
        EngineConfig(
            model=path, num_pages=32, page_size=4, max_pages_per_seq=8,
            prefill_chunk=16, max_seqs=4, dtype="float32",
        )
    )
    eng.add_request("g", [3, 4, 5], SamplingParams(temperature=0.0, max_tokens=4))
    out = eng.run_to_completion()["g"]
    assert len(out) >= 1

    # Forward with GGUF-loaded params must match the ORIGINAL params
    # exactly (proves the tensor round-trip is lossless).
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import forward, init_kv_pages

    kv1 = init_kv_pages(cfg, 8, 4)
    kv2 = init_kv_pages(cfg, 8, 4)
    toks = jnp.asarray([[3, 4, 5]], jnp.int32)
    pos = jnp.asarray([[0, 1, 2]], jnp.int32)
    val = jnp.ones((1, 3), bool)
    pt = jnp.asarray([[1, 0]], jnp.int32)
    gguf_params = adapter.load_params(path)
    l1, _ = forward(params, cfg, toks, pos, val, kv1, pt)
    l2, _ = forward(gguf_params, cfg, toks, pos, val, kv2, pt)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-5)

    tok = load_tokenizer({"kind": "gguf", "path": path})
    ids = tok.encode("hi the")
    assert ids and all(0 <= i < 16 for i in ids)
    assert "hi" in tok.decode(tok.encode("hi"))


def test_gguf_tokenizer_gpt2_style(tmp_path):
    """Byte-level BPE vocabs (qwen2-family GGUFs) encode/decode through the
    GPT-2 byte alphabet (Ġ = space), with no silent drops."""
    from dynamo_tpu.preprocessor.tokenizer import load_tokenizer

    path = str(tmp_path / "bpe.gguf")
    write_gguf(
        path,
        {
            "general.architecture": "qwen2",
            "tokenizer.ggml.model": "gpt2",
            "tokenizer.ggml.tokens": ["<unk>", "hello", "Ġworld", "Ġ", "h",
                                       "e", "l", "o", "w", "r", "d"],
            "tokenizer.ggml.eos_token_id": 0,
        },
        {},
    )
    tok = load_tokenizer({"kind": "gguf", "path": path})
    assert tok.kind == "gpt2"
    ids = tok.encode("hello world")
    assert ids[0] == 1  # "hello"
    assert 2 in ids  # "Ġworld"
    assert tok.decode(ids) == "hello world"
    # unknown char -> unk, not dropped
    ids2 = tok.encode("é")
    assert ids2 and all(i == 0 for i in ids2)


def test_gguf_tokenizer_preserves_generated_whitespace(tmp_path):
    """Only the sentencepiece dummy-prefix space is stripped — leading
    newlines a model generates survive decode."""
    from dynamo_tpu.preprocessor.tokenizer import load_tokenizer

    path = str(tmp_path / "spm.gguf")
    write_gguf(
        path,
        {
            "general.architecture": "llama",
            "tokenizer.ggml.model": "llama",
            "tokenizer.ggml.tokens": ["<unk>", "\n\n", "▁hi", "hi"],
            "tokenizer.ggml.eos_token_id": 0,
        },
        {},
    )
    tok = load_tokenizer({"kind": "gguf", "path": path})
    assert tok.decode([1, 3]) == "\n\nhi"  # newlines survive
    assert tok.decode([2]) == "hi"  # dummy prefix stripped


def _blk_tensors(cfg, params):
    lp = params["layers"]
    tensors = {
        "token_embd.weight": np.asarray(params["embed"], np.float32),
        "output_norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    for l in range(cfg.num_layers):
        tensors[f"blk.{l}.attn_norm.weight"] = np.asarray(
            lp["attn_norm"][l], np.float32
        )
        tensors[f"blk.{l}.attn_q.weight"] = np.asarray(lp["wq"][l], np.float32).T
        tensors[f"blk.{l}.attn_k.weight"] = np.asarray(lp["wk"][l], np.float32).T
        tensors[f"blk.{l}.attn_v.weight"] = np.asarray(lp["wv"][l], np.float32).T
        tensors[f"blk.{l}.attn_output.weight"] = np.asarray(
            lp["wo"][l], np.float32
        ).T
        tensors[f"blk.{l}.ffn_norm.weight"] = np.asarray(
            lp["mlp_norm"][l], np.float32
        )
        tensors[f"blk.{l}.ffn_gate.weight"] = np.asarray(
            lp["w_gate"][l], np.float32
        ).T
        tensors[f"blk.{l}.ffn_up.weight"] = np.asarray(lp["w_up"][l], np.float32).T
        tensors[f"blk.{l}.ffn_down.weight"] = np.asarray(
            lp["w_down"][l], np.float32
        ).T
    if "lm_head" in params:
        tensors["output.weight"] = np.asarray(params["lm_head"], np.float32).T
    return tensors


def _family_md(arch, cfg):
    return {
        "general.architecture": arch,
        f"{arch}.block_count": cfg.num_layers,
        f"{arch}.embedding_length": cfg.hidden_size,
        f"{arch}.feed_forward_length": cfg.intermediate_size,
        f"{arch}.attention.head_count": cfg.num_heads,
        f"{arch}.attention.head_count_kv": cfg.num_kv_heads,
        f"{arch}.attention.key_length": cfg.head_dim,
        f"{arch}.attention.layer_norm_rms_epsilon": float(cfg.rms_norm_eps),
        f"{arch}.rope.freq_base": float(cfg.rope_theta),
        f"{arch}.vocab_size": cfg.vocab_size,
        f"{arch}.context_length": 64,
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": [f"<t{i}>" for i in range(cfg.vocab_size)],
        "tokenizer.ggml.eos_token_id": 2,
    }


def test_gguf_qwen2_biases_load(tmp_path):
    """qwen2-arch GGUFs carry qkv biases; the loaded model must match the
    in-memory params exactly (biases silently dropped would diverge)."""
    from dataclasses import replace

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LlamaConfig, forward, init_kv_pages, init_params
    from dynamo_tpu.models.registry import get_model

    cfg = replace(LlamaConfig.tiny(vocab_size=32), attention_bias=True)
    params = init_params(jax.random.key(1), cfg)
    # non-zero biases: a loader that drops them must fail the comparison
    params["layers"]["bq"] = params["layers"]["bq"] + 0.1
    params["layers"]["bk"] = params["layers"]["bk"] - 0.2
    params["layers"]["bv"] = params["layers"]["bv"] + 0.3

    tensors = _blk_tensors(cfg, params)
    for l in range(cfg.num_layers):
        for ours, g in (("bq", "attn_q"), ("bk", "attn_k"), ("bv", "attn_v")):
            tensors[f"blk.{l}.{g}.bias"] = np.asarray(
                params["layers"][ours][l], np.float32
            )
    path = str(tmp_path / "q2.gguf")
    write_gguf(path, _family_md("qwen2", cfg), tensors)

    adapter = get_model(path, dtype="float32")
    assert adapter.config.attention_bias
    loaded = adapter.load_params(path)

    toks = np.arange(1, 9, dtype=np.int32)[None]
    pts = np.asarray([[1, 2]], np.int32)
    pos = np.arange(8, dtype=np.int32)[None]
    kv1 = init_kv_pages(cfg, 8, 4)
    kv2 = init_kv_pages(cfg, 8, 4)
    a, _ = forward(params, cfg, jnp.asarray(toks), jnp.asarray(pos),
                   jnp.ones((1, 8), bool), kv1, jnp.asarray(pts))
    b, _ = forward(loaded, cfg, jnp.asarray(toks), jnp.asarray(pos),
                   jnp.ones((1, 8), bool), kv2, jnp.asarray(pts))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-5)


def test_gguf_qwen3_qk_norms_load(tmp_path):
    """qwen3-arch GGUFs carry per-head q/k RMSNorm weights."""
    from dataclasses import replace

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LlamaConfig, forward, init_kv_pages, init_params
    from dynamo_tpu.models.registry import get_model

    cfg = replace(LlamaConfig.tiny(vocab_size=32), qk_norm=True)
    params = init_params(jax.random.key(2), cfg)
    params["layers"]["q_norm"] = params["layers"]["q_norm"] * 1.5
    params["layers"]["k_norm"] = params["layers"]["k_norm"] * 0.5

    tensors = _blk_tensors(cfg, params)
    for l in range(cfg.num_layers):
        tensors[f"blk.{l}.attn_q_norm.weight"] = np.asarray(
            params["layers"]["q_norm"][l], np.float32
        )
        tensors[f"blk.{l}.attn_k_norm.weight"] = np.asarray(
            params["layers"]["k_norm"][l], np.float32
        )
    path = str(tmp_path / "q3.gguf")
    write_gguf(path, _family_md("qwen3", cfg), tensors)

    adapter = get_model(path, dtype="float32")
    assert adapter.config.qk_norm
    loaded = adapter.load_params(path)

    toks = np.arange(1, 9, dtype=np.int32)[None]
    pts = np.asarray([[1, 2]], np.int32)
    pos = np.arange(8, dtype=np.int32)[None]
    kv1 = init_kv_pages(cfg, 8, 4)
    kv2 = init_kv_pages(cfg, 8, 4)
    a, _ = forward(params, cfg, jnp.asarray(toks), jnp.asarray(pos),
                   jnp.ones((1, 8), bool), kv1, jnp.asarray(pts))
    b, _ = forward(loaded, cfg, jnp.asarray(toks), jnp.asarray(pos),
                   jnp.ones((1, 8), bool), kv2, jnp.asarray(pts))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-5)


def test_gemma3_gguf_roundtrip_equivalence(tmp_path):
    """Gemma-3 GGUFs serve correctly under llama.cpp's conventions: norm
    tensors store (1+w) folded in (so the config clears the unit offset),
    q/k norms + sandwich norms load, the head is tied, and the dual-theta
    sliding config maps from the gemma3.* metadata — logits must equal
    the HF-convention engine's exactly."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import (
        LlamaConfig,
        forward,
        init_kv_pages,
        init_params,
        params_from_gguf,
    )

    cfg_hf = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_layers=6, num_heads=4, num_kv_heads=2, head_dim=16,
        rope_theta=1_000_000.0, rope_local_theta=10_000.0,
        rope_linear_factor=8.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, hidden_act="gelu_tanh",
        rms_norm_unit_offset=True, scale_embeddings=True, qk_norm=True,
        sliding_window=8, sliding_global_every=6,
        post_block_norms=True, dtype=jnp.float32,
    )
    P = init_params(jax.random.key(3), cfg_hf)

    def fold(x):  # llama.cpp stores gemma norms as (1 + w)
        return np.asarray(x, np.float32) + 1.0

    L = cfg_hf.num_layers
    lp = P["layers"]
    tensors = {
        "token_embd.weight": np.asarray(P["embed"], np.float32),
        "output_norm.weight": fold(P["final_norm"]),
    }
    for l in range(L):
        tensors[f"blk.{l}.attn_norm.weight"] = fold(lp["attn_norm"][l])
        tensors[f"blk.{l}.ffn_norm.weight"] = fold(lp["mlp_norm"][l])
        tensors[f"blk.{l}.post_attention_norm.weight"] = fold(
            lp["post_attn_norm"][l]
        )
        tensors[f"blk.{l}.post_ffw_norm.weight"] = fold(lp["post_mlp_norm"][l])
        tensors[f"blk.{l}.attn_q_norm.weight"] = fold(lp["q_norm"][l])
        tensors[f"blk.{l}.attn_k_norm.weight"] = fold(lp["k_norm"][l])
        for ours, theirs in (
            ("wq", "attn_q"), ("wk", "attn_k"), ("wv", "attn_v"),
            ("wo", "attn_output"), ("w_gate", "ffn_gate"),
            ("w_up", "ffn_up"), ("w_down", "ffn_down"),
        ):
            tensors[f"blk.{l}.{theirs}.weight"] = np.asarray(
                lp[ours][l], np.float32
            ).T  # GGUF stores [out, in]
    md = {
        "general.architecture": "gemma3",
        "gemma3.block_count": L,
        "gemma3.embedding_length": cfg_hf.hidden_size,
        "gemma3.feed_forward_length": cfg_hf.intermediate_size,
        "gemma3.attention.head_count": cfg_hf.num_heads,
        "gemma3.attention.head_count_kv": cfg_hf.num_kv_heads,
        "gemma3.attention.key_length": cfg_hf.head_dim,
        "gemma3.attention.layer_norm_rms_epsilon": cfg_hf.rms_norm_eps,
        "gemma3.attention.sliding_window": cfg_hf.sliding_window,
        "gemma3.rope.freq_base": cfg_hf.rope_theta,
        "gemma3.rope.local.freq_base": cfg_hf.rope_local_theta,
        "gemma3.rope.scaling.factor": cfg_hf.rope_linear_factor,
        "gemma3.vocab_size": cfg_hf.vocab_size,
        "gemma3.context_length": 256,
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": ["<pad>"] * 256,
        "tokenizer.ggml.eos_token_id": 1,
    }
    path = str(tmp_path / "gemma3.gguf")
    write_gguf(path, md, tensors)

    g = read_gguf(path)
    import dataclasses

    cfg = dataclasses.replace(g.to_llama_config(), dtype=jnp.float32)
    assert not cfg.rms_norm_unit_offset  # folded into the stored norms
    assert cfg.qk_norm and cfg.post_block_norms and cfg.tie_word_embeddings
    assert cfg.sliding_global_every == 6
    assert cfg.rope_local_theta == 10_000.0
    assert cfg.rope_linear_factor == 8.0
    assert cfg.query_pre_attn_scalar is None  # non-27B: scale by head_dim

    # 27B-class shapes are the one case where the attention scale is NOT
    # head_dim; GGUF has no key for it, so it is derived by model type
    # (layer count), the way llama.cpp special-cases it
    md27 = dict(md)
    md27.update({
        "gemma3.block_count": 62,
        "gemma3.embedding_length": 5376,
        "gemma3.attention.head_count": 32,
        "gemma3.attention.key_length": 128,
    })
    p27 = str(tmp_path / "g27.gguf")
    write_gguf(p27, md27, {"token_embd.weight": np.zeros((4, 8), np.float32)})
    cfg27 = read_gguf(p27).to_llama_config()
    assert cfg27.query_pre_attn_scalar == 5376 / 32  # 168
    gp = params_from_gguf(g, cfg)
    assert "lm_head" not in gp  # tied

    rng = np.random.default_rng(7)
    toks = rng.integers(0, 256, size=(1, 12)).astype(np.int32)
    positions = np.arange(12, dtype=np.int32)[None]
    pts = np.arange(1, 4, dtype=np.int32)[None]

    def run(c, p):
        kv = init_kv_pages(c, 16, 4)
        logits, _ = forward(
            p, c, jnp.asarray(toks), jnp.asarray(positions),
            jnp.ones((1, 12), bool), kv, jnp.asarray(pts),
        )
        return np.asarray(logits)

    np.testing.assert_allclose(
        run(cfg, gp), run(cfg_hf, P), rtol=1e-4, atol=1e-4
    )

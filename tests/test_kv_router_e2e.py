"""KV-aware routing end to end: two mock workers over a real fabric server,
KV events feeding the router's index, prefix-affinity + load-aware choice.

Mirrors the reference's mocker-driven router tests (SURVEY.md §4: the mocker
emits real KV events so routing is testable with zero hardware)."""

import asyncio

import pytest

from dynamo_tpu.kv_router import KvRouter, KvRouterConfig
from dynamo_tpu.kv_router.recorder import KvRecorder, replay
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.runtime import DistributedRuntime, RouterMode
from dynamo_tpu.runtime.fabric import FabricServer
from dynamo_tpu.runtime.push_router import PushRouter
from dynamo_tpu.tokens import hash_token_blocks
from dynamo_tpu.worker import Worker

PAGE = 16


def run(coro):
    return asyncio.run(coro)


def _card():
    return ModelDeploymentCard(name="mock-model", kv_page_size=PAGE)


async def _spawn_mock_worker(addr):
    rt = await DistributedRuntime.create(addr)
    w = Worker(
        rt, _card(), engine_kind="mock", namespace="test",
        component="backend", endpoint="generate",
        metrics_interval=0.05, router_mode="kv",
    )
    await w.start()
    return rt, w


async def _kv_setup(addr):
    rt = await DistributedRuntime.create(addr)
    ep = rt.namespace("test").component("backend").endpoint("generate")
    src = await ep.instance_source()
    kv = KvRouter(
        rt.fabric, "backend", src, block_size=PAGE, salt="mock-model",
        config=KvRouterConfig(temperature=0.0),
    )
    await kv.start()
    router = PushRouter(src, "generate", mode=RouterMode.KV, kv_chooser=kv.choose)
    return rt, src, kv, router


def _req(rid, tokens, max_tokens=2 * PAGE):
    return {
        "request_id": rid, "token_ids": tokens, "max_tokens": max_tokens,
        "temperature": 0.0, "top_p": 1.0, "top_k": 0, "seed": None,
        "stop_token_ids": [], "stop_strings": [], "ignore_eos": True,
        "annotations": {},
    }


async def _drain(router, req):
    out = []
    async for item in router.generate(req):
        out.append(item)
    return out


def test_kv_routing_prefix_affinity_and_load():
    async def main():
        server = FabricServer(port=0)
        await server.start()
        rt1, w1 = await _spawn_mock_worker(server.address)
        rt2, w2 = await _spawn_mock_worker(server.address)
        rtc, src, kv, router = await _kv_setup(server.address)
        try:
            await src.wait_for_instances()
            assert len(src.list()) == 2

            prompt_a = list(range(100, 100 + 4 * PAGE))
            out = await _drain(router, _req("r1", prompt_a))
            assert out, "no output from mock worker"
            kv.on_complete("r1")

            # wait for the worker's KV events to land in the index
            hashes = hash_token_blocks(prompt_a, block_size=PAGE, salt="mock-model")
            for _ in range(100):
                if kv.indexer.find_matches(hashes).scores:
                    break
                await asyncio.sleep(0.05)
            scores = kv.indexer.find_matches(hashes).scores
            assert scores, "KV events never reached the router index"
            (first_worker,) = scores
            assert scores[first_worker] >= 3  # prompt blocks are indexed

            # same prefix again → must go to the same worker
            choice, overlap = await kv.find_best_match(prompt_a, request_id="r2")
            assert choice == first_worker
            assert overlap >= 3
            kv.on_complete("r2")

            # a cold prompt should prefer the other (less-loaded) worker:
            # saturate first_worker's local bookkeeping to force the tilt
            kv.active.add(first_worker, "pin", 100)
            prompt_b = list(range(5000, 5000 + 4 * PAGE))
            other, _ = await kv.find_best_match(prompt_b, request_id="r3")
            assert other != first_worker
        finally:
            await kv.stop()
            await rtc.close()
            await w1.stop(); await rt1.close()
            await w2.stop(); await rt2.close()
            await server.stop()

    run(main())


def test_kv_router_prunes_dead_worker():
    async def main():
        server = FabricServer(port=0)
        await server.start()
        rt1, w1 = await _spawn_mock_worker(server.address)
        rtc, src, kv, router = await _kv_setup(server.address)
        try:
            await src.wait_for_instances()
            prompt = list(range(4 * PAGE))
            await _drain(router, _req("r1", prompt))
            hashes = hash_token_blocks(prompt, block_size=PAGE, salt="mock-model")
            for _ in range(100):
                if kv.indexer.find_matches(hashes).scores:
                    break
                await asyncio.sleep(0.05)
            assert kv.indexer.find_matches(hashes).scores

            # worker dies: registration goes, prune loop must clear the index
            await w1.stop()
            await rt1.close()
            for _ in range(100):
                if not kv.indexer.find_matches(hashes).scores:
                    break
                await asyncio.sleep(0.1)
            assert not kv.indexer.find_matches(hashes).scores
        finally:
            await kv.stop()
            await rtc.close()
            await server.stop()

    run(main())


def test_kv_recorder_and_replay(tmp_path):
    async def main():
        server = FabricServer(port=0)
        await server.start()
        rt1, w1 = await _spawn_mock_worker(server.address)
        rtc, src, kv, router = await _kv_setup(server.address)
        rec_path = tmp_path / "kv_events.jsonl"
        recorder = KvRecorder(rtc.fabric, str(rec_path))
        await recorder.start()
        try:
            await src.wait_for_instances()
            prompt = list(range(4 * PAGE))
            await _drain(router, _req("rr", prompt))
            for _ in range(100):
                if recorder.event_count:
                    break
                await asyncio.sleep(0.05)
            assert recorder.event_count > 0

            # replay the recording into a fresh index on a fresh fabric
            from dynamo_tpu.kv_router.indexer import KvIndexer
            from dynamo_tpu.runtime.fabric import LocalFabric

            fab2 = LocalFabric()
            idx2 = KvIndexer(fab2)
            await idx2.start()
            n = await replay(fab2, str(rec_path))
            assert n == recorder.event_count
            await asyncio.sleep(0.05)
            hashes = hash_token_blocks(prompt, block_size=PAGE, salt="mock-model")
            assert idx2.find_matches(hashes).scores
            await idx2.stop()
        finally:
            await recorder.stop()
            await kv.stop()
            await rtc.close()
            await w1.stop(); await rt1.close()
            await server.stop()

    run(main())

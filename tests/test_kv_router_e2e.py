"""KV-aware routing end to end: two mock workers over a real fabric server,
KV events feeding the router's index, prefix-affinity + load-aware choice.

Mirrors the reference's mocker-driven router tests (SURVEY.md §4: the mocker
emits real KV events so routing is testable with zero hardware)."""

import asyncio

import pytest

from dynamo_tpu.kv_router import KvRouter, KvRouterConfig
from dynamo_tpu.kv_router.recorder import KvRecorder, replay
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.runtime import DistributedRuntime, RouterMode
from dynamo_tpu.runtime.fabric import FabricServer
from dynamo_tpu.runtime.push_router import PushRouter
from dynamo_tpu.tokens import hash_token_blocks
from dynamo_tpu.worker import Worker

PAGE = 16


def run(coro):
    return asyncio.run(coro)


def _card():
    return ModelDeploymentCard(name="mock-model", kv_page_size=PAGE)


async def _spawn_mock_worker(addr):
    rt = await DistributedRuntime.create(addr)
    w = Worker(
        rt, _card(), engine_kind="mock", namespace="test",
        component="backend", endpoint="generate",
        metrics_interval=0.05, router_mode="kv",
    )
    await w.start()
    return rt, w


async def _kv_setup(addr):
    rt = await DistributedRuntime.create(addr)
    ep = rt.namespace("test").component("backend").endpoint("generate")
    src = await ep.instance_source()
    kv = KvRouter(
        rt.fabric, "backend", src, block_size=PAGE, salt="mock-model",
        config=KvRouterConfig(temperature=0.0),
    )
    await kv.start()
    router = PushRouter(src, "generate", mode=RouterMode.KV, kv_chooser=kv.choose)
    return rt, src, kv, router


def _req(rid, tokens, max_tokens=2 * PAGE):
    return {
        "request_id": rid, "token_ids": tokens, "max_tokens": max_tokens,
        "temperature": 0.0, "top_p": 1.0, "top_k": 0, "seed": None,
        "stop_token_ids": [], "stop_strings": [], "ignore_eos": True,
        "annotations": {},
    }


async def _drain(router, req):
    out = []
    async for item in router.generate(req):
        out.append(item)
    return out


def test_kv_routing_prefix_affinity_and_load():
    async def main():
        server = FabricServer(port=0)
        await server.start()
        rt1, w1 = await _spawn_mock_worker(server.address)
        rt2, w2 = await _spawn_mock_worker(server.address)
        rtc, src, kv, router = await _kv_setup(server.address)
        try:
            await src.wait_for_instances()
            assert len(src.list()) == 2

            prompt_a = list(range(100, 100 + 4 * PAGE))
            out = await _drain(router, _req("r1", prompt_a))
            assert out, "no output from mock worker"
            kv.on_complete("r1")

            # wait for the worker's KV events to land in the index
            hashes = hash_token_blocks(prompt_a, block_size=PAGE, salt="mock-model")
            for _ in range(100):
                if kv.indexer.find_matches(hashes).scores:
                    break
                await asyncio.sleep(0.05)
            scores = kv.indexer.find_matches(hashes).scores
            assert scores, "KV events never reached the router index"
            (first_worker,) = scores
            assert scores[first_worker] >= 3  # prompt blocks are indexed

            # same prefix again → must go to the same worker
            choice, overlap = await kv.find_best_match(prompt_a, request_id="r2")
            assert choice == first_worker
            assert overlap >= 3
            kv.on_complete("r2")

            # a cold prompt should prefer the other (less-loaded) worker:
            # saturate first_worker's local bookkeeping to force the tilt
            kv.active.add(first_worker, "pin", 100)
            prompt_b = list(range(5000, 5000 + 4 * PAGE))
            other, _ = await kv.find_best_match(prompt_b, request_id="r3")
            assert other != first_worker
        finally:
            await kv.stop()
            await rtc.close()
            await w1.stop(); await rt1.close()
            await w2.stop(); await rt2.close()
            await server.stop()

    run(main())


def test_kv_router_prunes_dead_worker():
    async def main():
        server = FabricServer(port=0)
        await server.start()
        rt1, w1 = await _spawn_mock_worker(server.address)
        rtc, src, kv, router = await _kv_setup(server.address)
        try:
            await src.wait_for_instances()
            prompt = list(range(4 * PAGE))
            await _drain(router, _req("r1", prompt))
            hashes = hash_token_blocks(prompt, block_size=PAGE, salt="mock-model")
            for _ in range(100):
                if kv.indexer.find_matches(hashes).scores:
                    break
                await asyncio.sleep(0.05)
            assert kv.indexer.find_matches(hashes).scores

            # worker dies: registration goes, prune loop must clear the index
            await w1.stop()
            await rt1.close()
            for _ in range(100):
                if not kv.indexer.find_matches(hashes).scores:
                    break
                await asyncio.sleep(0.1)
            assert not kv.indexer.find_matches(hashes).scores
        finally:
            await kv.stop()
            await rtc.close()
            await server.stop()

    run(main())


def test_kv_recorder_and_replay(tmp_path):
    async def main():
        server = FabricServer(port=0)
        await server.start()
        rt1, w1 = await _spawn_mock_worker(server.address)
        rtc, src, kv, router = await _kv_setup(server.address)
        rec_path = tmp_path / "kv_events.jsonl"
        recorder = KvRecorder(rtc.fabric, str(rec_path))
        await recorder.start()
        try:
            await src.wait_for_instances()
            prompt = list(range(4 * PAGE))
            await _drain(router, _req("rr", prompt))
            for _ in range(100):
                if recorder.event_count:
                    break
                await asyncio.sleep(0.05)
            assert recorder.event_count > 0

            # replay the recording into a fresh index on a fresh fabric
            from dynamo_tpu.kv_router.indexer import KvIndexer
            from dynamo_tpu.runtime.fabric import LocalFabric

            fab2 = LocalFabric()
            idx2 = KvIndexer(fab2)
            await idx2.start()
            n = await replay(fab2, str(rec_path))
            assert n == recorder.event_count
            await asyncio.sleep(0.05)
            hashes = hash_token_blocks(prompt, block_size=PAGE, salt="mock-model")
            assert idx2.find_matches(hashes).scores
            await idx2.stop()
        finally:
            await recorder.stop()
            await kv.stop()
            await rtc.close()
            await w1.stop(); await rt1.close()
            await server.stop()

    run(main())


def test_standalone_router_service():
    """Routing-as-a-service (reference: components/router): a dedicated
    RouterService answers choose/feedback/state over its ingress, with
    its placement following KV events from workers."""
    import asyncio

    import msgpack

    from dynamo_tpu.kv_router.service import RouterService
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.fabric.local import LocalFabric
    from dynamo_tpu.runtime.push_router import PushRouter
    from dynamo_tpu.subjects import KV_EVENT_SUBJECT
    from dynamo_tpu.tokens import hash_token_blocks

    async def run():
        fabric = LocalFabric()

        async def rt_for():
            lease = await fabric.grant_lease(1e12)
            return DistributedRuntime(fabric, primary_lease=lease)

        rt = await rt_for()
        # two fake workers registered on the routed component
        regs = []
        for host_port in ((("127.0.0.1", 9001)), ("127.0.0.1", 9002)):
            wrt = await rt_for()
            ep = wrt.namespace("dynamo").component("backend").endpoint("generate")
            regs.append(await ep.register(host_port[0], host_port[1]))

        svc = RouterService(rt, block_size=4, salt="m")
        await svc.start()
        try:
            # worker A announces cached blocks for a prompt prefix
            prompt = [1, 2, 3, 4, 5, 6, 7, 8]
            hashes = hash_token_blocks(prompt, block_size=4, salt="m")
            a_id = regs[0].instance.instance_id
            await fabric.publish(
                f"{KV_EVENT_SUBJECT}.{a_id}",
                {"instance_id": a_id, "count": 1},
                msgpack.packb(
                    [{
                        "kind": "stored",
                        "block_hashes": list(hashes),
                        "parent_hash": None,
                        "token_blocks": [prompt[:4], prompt[4:]],
                    }],
                    use_bin_type=True,
                ),
            )
            await asyncio.sleep(0.3)

            # query the service through its OWN registered endpoint
            router_ep = (
                rt.namespace("dynamo").component("router").endpoint("choose")
            )
            src = await router_ep.instance_source()
            client = PushRouter(src, "choose")
            replies = [
                r async for r in client.generate(
                    {"token_ids": prompt, "request_id": "q1"}
                )
            ]
            assert replies[0]["instance_id"] == a_id
            assert replies[0]["matched_blocks"] == 2

            state_client = PushRouter(src, "state")
            state = [r async for r in state_client.generate({})][0]
            assert a_id in state["workers"]

            fb = PushRouter(src, "feedback")
            assert [
                r async for r in fb.generate(
                    {"request_id": "q1", "complete": True}
                )
            ][0]["ok"]
            client.close(); state_client.close(); fb.close()
        finally:
            await svc.stop()

    asyncio.run(run())
